"""Euclidean loss as a user-defined Python layer — the sparknet_tpu twin
of reference examples/pycaffe/layers/pyloss.py, consumed unchanged by the
stock examples/pycaffe/linreg.prototxt (python_param {module: 'pyloss'
layer: 'EuclideanLossLayer'}).

Where the reference class mutates blob .data/.diff buffers and hand-writes
backward(), here forward is one pure jnp expression and the gradient is
jax autodiff — nothing else to write (ops/python_layer.py docstring)."""

import jax.numpy as jnp


class EuclideanLossLayer:
    """sum((x - y)^2) / num / 2 — identical math to the C++
    EuclideanLossLayer (and the reference pyloss.py)."""

    def setup(self, bottom_shapes):
        if len(bottom_shapes) != 2:
            raise ValueError("Need two inputs to compute distance.")

    def reshape(self, bottom_shapes):
        import numpy as np
        if np.prod(bottom_shapes[0]) != np.prod(bottom_shapes[1]):
            raise ValueError("Inputs must have the same dimension.")
        return (1,)

    def forward(self, params, bottoms):
        diff = (bottoms[0] - bottoms[1]).astype(jnp.float32)
        return jnp.sum(diff * diff).reshape(1) / bottoms[0].shape[0] / 2.0
