#!/usr/bin/env bash
# CI entry point: byte-compile the whole package (catches syntax/import-time
# breakage in files no test imports), then run the tier-1 test command from
# ROADMAP.md verbatim. Exits non-zero on either failure.
set -uo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q sparknet_tpu || exit 1
echo "compileall OK"

# static analysis: JAX hazard rules + lock-discipline checker, strict
# mode (any non-baselined finding fails the build — scripts/lint.sh)
bash scripts/lint.sh || exit 1
echo "sparknet lint OK"

# multi-host fault domains, end to end: a real 2-process run where one
# host is SIGKILLed mid-run — the survivor must evict it on lease
# expiry, finish, and exit 0 (the fast stage of scripts/smoke.sh)
bash scripts/smoke.sh multihost || exit 1
echo "multihost smoke OK"

# async bounded staleness, end to end: the chaos slow-worker run must
# finish under a wall-clock budget the synchronous barrier cannot meet,
# with the straggler parked+readmitted and the staleness section in
# `sparknet report` (scripts/smoke.sh stage h)
bash scripts/smoke.sh async || exit 1
echo "async smoke OK"

# elastic world resizing, end to end: a 2-process world's checkpoint
# resumes at N-1 and N+1 under --reshard auto (strict refusal names
# the remedy), a preempted host rejoins through the rendezvous, and a
# live run admits a late-started --grow host with zero recompiles
bash scripts/smoke.sh resize || exit 1
echo "resize smoke OK"

# serving tier, end to end: serve a snapshot, bench it across a live
# hot reload with zero rejects/errors, drain on SIGTERM with exit 0,
# and render the serving section (scripts/smoke.sh stage i)
bash scripts/smoke.sh serve || exit 1
echo "serve smoke OK"

# serving fleet, end to end: a real 3-replica fleet behind `sparknet
# route` — SIGKILLed replica evicted on lease expiry with the
# availability dip bounded (asserted from the metrics stream), grow
# admission under load, canary auto-rollback of a corrupt checkpoint,
# router drained on SIGTERM with exit 0 (scripts/smoke.sh stage n)
bash scripts/smoke.sh routefleet || exit 1
echo "routefleet smoke OK"

# input pipeline, end to end: a real 2-process run whose per-host
# `ingest` events stay inside each host's owned record shard, and a
# --echo 2 run beating the no-echo wall clock under chaos slow_h2d
# (scripts/smoke.sh stage j)
bash scripts/smoke.sh ingest || exit 1
echo "ingest smoke OK"

# FSDP one-big-model, end to end: a d-small LM under --fsdp on
# --precision bf16 whose metrics stream proves the sharded update
# executed (fsdp kind=exec off the live arrays), SIGTERM + resume from
# the gathered manifest, and the same checkpoint consumed by plain DP
# (scripts/smoke.sh stage k)
bash scripts/smoke.sh fsdp || exit 1
echo "fsdp smoke OK"

# fleet simulation, end to end: replay validation of a recorded real
# multi-coordinator crash run must match membership-event-exactly,
# then a 1,000-host x 200-round chaos cell under the 60 s CPU wall
# budget with report/monitor rendering (scripts/smoke.sh stage l)
bash scripts/smoke.sh simfleet || exit 1
echo "simfleet smoke OK"

# fleet observability, end to end: a real 2-process run with a chaos
# slow_host straggler merged into one clock-aligned Chrome trace, the
# critical path naming the straggler from the metrics alone, and the
# simfleet cell rendering through the same path (scripts/smoke.sh
# stage m)
bash scripts/smoke.sh trace || exit 1
echo "trace smoke OK"

# perf-regression gate: the committed bench_details.json rows must sit
# within their own noise tolerance of the committed medians (pure JSON
# compare, no accelerator; a fresh bench run's rows are gated the same
# way by `python bench.py --check --details <new rows>`)
python bench.py --check || exit 1
echo "bench --check OK"

set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
