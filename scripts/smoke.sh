#!/usr/bin/env bash
# Observability smoke test (ISSUE 1 acceptance, CI-runnable on CPU):
# a 5-step synthetic train with metrics + trace enabled must produce
#   (a) a JSONL with step/span/comms/recompile events (host/device split)
#   (b) a well-formed Chrome trace_event span file
#   (c) a `sparknet report` that renders and writes valid JSON.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"

tmp=$(mktemp -d)
trap 'rm -rf "$tmp"' EXIT

cat > "$tmp/net.prototxt" <<'EOF'
name: "smoke_cifar_synth"
layer { name: "data" type: "JavaData" top: "data"
        java_data_param { shape { dim: 8 dim: 3 dim: 32 dim: 32 } } }
layer { name: "label" type: "JavaData" top: "label"
        java_data_param { shape { dim: 8 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 8 kernel_size: 5 stride: 2
                            weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "fc" type: "InnerProduct" bottom: "conv1" top: "fc"
        inner_product_param { num_output: 10
                              weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc" bottom: "label"
        top: "loss" }
EOF

cat > "$tmp/solver.prototxt" <<'EOF'
net: "net.prototxt"
base_lr: 0.01
lr_policy: "fixed"
display: 2
max_iter: 5
random_seed: 0
EOF

python -m sparknet_tpu train --solver "$tmp/solver.prototxt" \
    --iterations 5 --metrics "$tmp/run.jsonl" --profile "$tmp/trace"

python - "$tmp" <<'EOF'
import json, sys, os
tmp = sys.argv[1]
lines = open(os.path.join(tmp, "run.jsonl")).read().splitlines()
events = [json.loads(l) for l in lines]         # every line must parse
kinds = {e["event"] for e in events}
missing = {"step", "span", "comms", "recompile"} - kinds
assert not missing, f"missing event kinds: {missing} (got {sorted(kinds)})"
step = next(e for e in events if e["event"] == "step")
assert "host_ms" in step and "device_ms" in step, step
chrome = json.load(open(os.path.join(tmp, "trace", "spans.trace.json")))
assert chrome["traceEvents"], "empty chrome trace"
print(f"JSONL OK: {len(events)} events, kinds {sorted(kinds)}")
print(f"Chrome trace OK: {len(chrome['traceEvents'])} span events")
EOF

python -m sparknet_tpu report "$tmp/run.jsonl" --json "$tmp/report.json"

python - "$tmp" <<'EOF'
import json, sys, os
rep = json.load(open(os.path.join(sys.argv[1], "report.json")))
assert rep["steps"]["steps"] == 5, rep.get("steps")
assert rep["comms"]["h2d_bytes_total"] > 0
assert rep["phases"], "no per-phase breakdown"
print("report JSON OK")
EOF

echo "SMOKE OK"
