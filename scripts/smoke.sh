#!/usr/bin/env bash
# Smoke tests (CI-runnable on CPU):
# Observability (ISSUE 1): a 5-step synthetic train with metrics + trace
# enabled must produce
#   (a) a JSONL with step/span/comms/recompile events (host/device split)
#   (b) a well-formed Chrome trace_event span file
#   (c) a `sparknet report` that renders and writes valid JSON.
# Resilience (ISSUE 2):
#   (d) SIGTERM mid-run snapshots-then-stops cleanly, and a relaunch with
#       --resume auto continues the iter counter and loss curve
#   (e) a chaos-injected NaN rolls back, the run completes to target, and
#       the report surfaces the recovery events.
# Elasticity (ISSUE 4):
#   (f) a chaos-killed worker is evicted, the run completes on the
#       survivors, the eviction (and readmission) appear in `sparknet
#       report`, and dropping below --quorum exits with code 4.
# Multi-host fault domains (ISSUE 6):
#   (g) a REAL 2-process run with leased heartbeats: chaos SIGKILLs one
#       host mid-run, the survivor evicts it on lease expiry, completes
#       every round, exits 0, and `sparknet report` shows the host
#       eviction + fault-domain section.
# Async bounded staleness (ISSUE 7):
#   (h) the same chaos slow_worker run twice: the SYNCHRONOUS barrier
#       waits out the straggler's injected stall every round, while the
#       async `--staleness` run must finish under a wall-clock budget
#       the synchronous mode cannot meet (its injected stall alone
#       exceeds the gap), with the straggler parked+readmitted and the
#       staleness section rendered by `sparknet report`.
#
# Serving tier (ISSUE 11):
#   (i) `sparknet serve` over a trained snapshot answers a closed-loop
#       `serve-bench` with zero rejects/errors and a sane p99,
#       hot-reloads a newer snapshot mid-load without dropping a
#       request, drains on SIGTERM with exit 0, and `sparknet report`
#       renders the serving section from the same metrics stream.
#
# Input pipeline (ISSUE 13):
#   (j) a REAL 2-process run with sharded ingest: each host's `ingest`
#       events in the metrics stream must stay inside its owned half of
#       the record space (disjointness), both halves together must cover
#       the dataset, and under chaos slow_h2d (a per-transfer stall on
#       the simulated wire) a --echo 2 run must beat the no-echo wall
#       clock — echoes reuse the transferred batch, so they skip the
#       stall.
#
# FSDP one-big-model (ISSUE 14):
#   (k) a d-small transformer LM under --fsdp on --precision bf16 over
#       the 8-virtual-device CPU mesh: the metrics stream must carry
#       the fsdp kind=plan AND kind=exec events (exec measures the
#       per-device resident bytes off the live arrays — proof the
#       sharded update really executed), the run is SIGTERMed after its
#       first committed snapshot and resumes from the gathered manifest
#       with the iter counter continuing, and the same checkpoint
#       restores into the replicated DP path (fsdp off) — the
#       world-portable format.
#
# Fleet simulation (ISSUE 15):
#   (l) replay validation — a recorded REAL multi-coordinator crash run
#       (real threads, wall clock, on-disk rendezvous) must be
#       reproduced membership-event-exactly by the discrete-event
#       simulator — then a 1,000-host x 200-round chaos cell must
#       finish under a 60 s CPU wall budget with `sparknet report` and
#       `monitor` rendering the simulated metrics stream.
#
# Fleet observability (ISSUE 16):
#   (m) a REAL 2-process relay run with a chaos slow_host straggler
#       writes per-host metrics streams; `sparknet trace` must merge
#       them into ONE Chrome trace with a track per host and solved
#       clock offsets in the metadata, `--critpath` must name the
#       injected straggler host from the metrics alone, and the same
#       verb must render a critical-path summary for a simulated
#       fleet cell (zero special cases between real and simfleet).
#
# Serving fleet (ISSUE 17):
#   (n) a REAL 3-replica fleet behind `sparknet route`: chaos SIGKILLs
#       replica 1 mid-load — evicted on lease expiry with the
#       availability dip bounded (both asserted from the metrics
#       stream); the SLO autoscaler emits a grow decision under load
#       and the admitted 4th replica serves a corrupt checkpoint that
#       the canary controller auto-rolls back, pinning the baseline;
#       the router drains on SIGTERM with exit 0.
#
# Usage: smoke.sh
#   [all|multihost|async|serve|routefleet|ingest|fsdp|simfleet|trace]
# — the named stages run alone (the fast CI wiring; scripts/ci.sh
# invokes them individually).
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"

stage="${1:-all}"

tmp=$(mktemp -d)
trap 'rm -rf "$tmp"' EXIT

# ------------------------------------------ multi-host fault domains ----
# 2 real processes (jax.distributed, one fault domain each), hierarchical
# local SGD with the heartbeat runtime; chaos SIGKILLs host 1 at the gate
# of round 2. Host 0 must evict it (lease_expired), finish all 5 rounds,
# and exit 0; the report must render the host eviction.
run_multihost_stage() {
    mh="$tmp/mh"
    mkdir -p "$mh"
    port=$(python -c "import socket; s=socket.socket(); \
s.bind(('localhost',0)); print(s.getsockname()[1])")
    pids=()
    for i in 0 1; do
        SPARKNET_COORDINATOR="localhost:$port" \
        SPARKNET_NUM_PROCESSES=2 SPARKNET_PROCESS_ID=$i \
        SPARKNET_CHAOS="kill_host=1,kill_host_round=2" \
        XLA_FLAGS="--xla_force_host_platform_device_count=4" \
        python -m sparknet_tpu cifar --workers 4 --hosts 2 --tau 2 \
            --rounds 5 --test-every 100 --metrics "$mh/run$i.jsonl" \
            --heartbeat-dir "$mh/rdv" --lease-s 1.5 \
            --heartbeat-interval 0.2 \
            --quorum 1 --evict-after 1 --readmit-after 0 \
            > "$mh/out$i.txt" 2>&1 &
        pids+=($!)
    done
    rc0=0; wait "${pids[0]}" || rc0=$?
    rc1=0; wait "${pids[1]}" || rc1=$?
    test "$rc0" -eq 0 || { echo "survivor host failed (rc=$rc0):"
                           cat "$mh/out0.txt"; exit 1; }
    test "$rc1" -ne 0 || { echo "chaos target was supposed to die"
                           exit 1; }
    grep -q "EVICTED host 1" "$mh/out0.txt"
    grep -qE "round 4: loss = [0-9.]+" "$mh/out0.txt"
    python -m sparknet_tpu report "$mh/run0.jsonl" | tee "$mh/rep.txt" \
        > /dev/null
    grep -q "multi-host fault domains" "$mh/rep.txt"
    grep -q "evicted host 1" "$mh/rep.txt"
    grep -q "lease_expired" "$mh/rep.txt"
    echo "multihost stage OK: SIGKILLed host evicted on lease expiry," \
         "survivor completed and exited 0"
}

# ------------------------------------------ async bounded staleness ----
# The acceptance demonstration: a chaos slow_worker (2 s extra per round,
# every round) under the SYNCHRONOUS barrier stalls every round — 6
# rounds pay >= 12 s of pure injected stall. The async --staleness run
# of the SAME workload never waits for the straggler (its seconds land
# on its virtual version clock), so it must beat the synchronous wall
# clock by most of that stall; the straggler must be parked and
# readmitted with membership events, and `sparknet report` must render
# the staleness section.
run_async_stage() {
    as="$tmp/async"
    mkdir -p "$as"
    t0=$SECONDS
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m sparknet_tpu cifar --workers 4 --tau 2 --rounds 6 \
        --test-every 100 --metrics "$as/sync.jsonl" \
        --chaos "slow_worker=1,slow_s=2" --quorum 1 \
        > "$as/sync.out" 2>&1
    sync_s=$((SECONDS - t0))
    test "$sync_s" -ge 12 || { echo "sync baseline did not stall on the"\
                                    "straggler (${sync_s}s)"; exit 1; }
    t0=$SECONDS
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m sparknet_tpu cifar --workers 4 --tau 2 --rounds 6 \
        --test-every 100 --metrics "$as/async.jsonl" \
        --chaos "slow_worker=1,slow_s=2" --staleness 1 \
        --health-cooldown 1 > "$as/async.out" 2>&1
    async_s=$((SECONDS - t0))
    # the budget the synchronous mode cannot meet: its injected stall
    # alone (12 s) exceeds the allowed gap to its own wall clock
    budget=$((sync_s - 6))
    test "$async_s" -lt "$budget" || {
        echo "async run did not beat the barrier: ${async_s}s vs" \
             "sync ${sync_s}s (budget ${budget}s)"; exit 1; }
    grep -q "PARKED worker 1" "$as/async.out"
    grep -q "unparked worker 1" "$as/async.out"

    python - "$as" <<'EOF'
import json, sys, os
as_dir = sys.argv[1]
def rounds_t(path):
    evs = [json.loads(l) for l in open(path)]
    return [e["t"] for e in evs if e["event"] == "round"], evs
sync_t, _ = rounds_t(os.path.join(as_dir, "sync.jsonl"))
async_t, evs = rounds_t(os.path.join(as_dir, "async.jsonl"))
gaps = lambda ts: sorted(b - a for a, b in zip(ts, ts[1:]))
med = lambda g: g[len(g) // 2]
sync_med, async_med = med(gaps(sync_t)), med(gaps(async_t))
# per-round latency: the sync barrier tracks the straggler (>= the 2 s
# stall), the async round tracks the median worker (well under it)
assert sync_med >= 2.0, f"sync rounds did not stall: {sync_med:.2f}s"
assert async_med <= sync_med - 1.0, \
    f"async round latency tracks the straggler: {async_med:.2f}s " \
    f"vs sync {sync_med:.2f}s"
st = [e for e in evs if e["event"] == "staleness"]
assert st and any(max(e["lag"]) >= 2 for e in st), "no staleness events"
assert any(e["event"] == "parked" and e["worker"] == 1 for e in evs)
assert any(e["event"] == "unparked" and e["worker"] == 1 for e in evs)
assert not any(e["event"] == "eviction" for e in evs), \
    "the parked straggler must not be evicted"
print(f"async stage OK: sync {sync_med:.2f}s/round (tracks the "
      f"straggler) vs async {async_med:.2f}s/round (tracks the median)")
EOF

    python -m sparknet_tpu report "$as/async.jsonl" | tee "$as/async.rep" \
        > /dev/null
    grep -q "async staleness" "$as/async.rep"
    grep -q "parks by worker: w1" "$as/async.rep"
    grep -q "drift attribution" "$as/async.rep"
    echo "async stage OK: straggler parked+readmitted, round latency" \
         "tracked the median (async ${async_s}s < budget ${budget}s <" \
         "sync ${sync_s}s)"
}

# --------------------------------------------------- serving tier ----
# Train a tiny MLP to a snapshot, serve it, and exercise the full
# supervisor contract: bench under load (zero rejects at nominal
# load), hot reload mid-load, SIGTERM drain -> exit 0, report renders.
run_serve_stage() {
    sv="$tmp/serve"
    mkdir -p "$sv"

    python - "$sv" <<'EOF'
import sys
import numpy as np
from sparknet_tpu.proto import Message
from sparknet_tpu.solver import Solver

def mlp():
    net = Message("NetParameter", name="mlp")
    net.add("layer", name="d", type="JavaData", top=["data"],
            java_data_param=dict(shape=dict(dim=[16, 8])))
    net.add("layer", name="l", type="JavaData", top=["label"],
            java_data_param=dict(shape=dict(dim=[16])))
    net.add("layer", name="fc1", type="InnerProduct", bottom=["data"],
            top=["fc1"], inner_product_param=dict(
                num_output=16, weight_filler=dict(type="xavier")))
    net.add("layer", name="r1", type="ReLU", bottom=["fc1"], top=["fc1"])
    net.add("layer", name="fc2", type="InnerProduct", bottom=["fc1"],
            top=["fc2"], inner_product_param=dict(
                num_output=4, weight_filler=dict(type="xavier")))
    net.add("layer", name="loss", type="SoftmaxWithLoss",
            bottom=["fc2", "label"], top=["loss"])
    return net

sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
             momentum=0.9, random_seed=7)
s = Solver(sp, net_param=mlp(), log_fn=None)
rs = np.random.RandomState(0)
for _ in range(3):
    s.train_step({"data": rs.randn(16, 8).astype(np.float32),
                  "label": rs.randint(0, 4, 16).astype(np.int32)})
s.snapshot(sys.argv[1] + "/snap")
print("serve stage: snapshot at iter 3")
EOF

    python -m sparknet_tpu serve --prefix "$sv/snap" --port 0 \
        --metrics "$sv/serve.jsonl" --reload_poll 0.5 \
        > "$sv/serve.out" 2>&1 &
    serve_pid=$!
    for _ in $(seq 1 120); do
        grep -q "listening on" "$sv/serve.out" && break
        kill -0 "$serve_pid" || { echo "server died during startup:"
                                  cat "$sv/serve.out"; exit 1; }
        sleep 0.5
    done
    url=$(sed -n 's/.*listening on \(http:\/\/[^ ]*\).*/\1/p' \
          "$sv/serve.out" | head -1)
    test -n "$url" || { echo "server never announced:"
                        cat "$sv/serve.out"; exit 1; }

    # closed-loop bench under load; the snapshot advances mid-run so
    # the hot reload happens with requests in flight
    python -m sparknet_tpu serve-bench --url "$url" --mode closed \
        --concurrency 4 --duration 6 --json "$sv/bench.json" \
        > "$sv/bench.out" 2>&1 &
    bench_pid=$!
    sleep 1
    python - "$sv" <<'EOF'
import json, os, sys
import numpy as np
from sparknet_tpu.proto import Message
from sparknet_tpu.solver import Solver
from sparknet_tpu.resilience import load_manifest

sv = sys.argv[1]
man = load_manifest(sv + "/snap")

def mlp():
    net = Message("NetParameter", name="mlp")
    net.add("layer", name="d", type="JavaData", top=["data"],
            java_data_param=dict(shape=dict(dim=[16, 8])))
    net.add("layer", name="l", type="JavaData", top=["label"],
            java_data_param=dict(shape=dict(dim=[16])))
    net.add("layer", name="fc1", type="InnerProduct", bottom=["data"],
            top=["fc1"], inner_product_param=dict(
                num_output=16, weight_filler=dict(type="xavier")))
    net.add("layer", name="r1", type="ReLU", bottom=["fc1"], top=["fc1"])
    net.add("layer", name="fc2", type="InnerProduct", bottom=["fc1"],
            top=["fc2"], inner_product_param=dict(
                num_output=4, weight_filler=dict(type="xavier")))
    net.add("layer", name="loss", type="SoftmaxWithLoss",
            bottom=["fc2", "label"], top=["loss"])
    return net

sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
             momentum=0.9, random_seed=7)
s = Solver(sp, net_param=mlp(), log_fn=None)
s.restore(os.path.join(sv, man["latest"]["state"]))
rs = np.random.RandomState(1)
for _ in range(2):
    s.train_step({"data": rs.randn(16, 8).astype(np.float32),
                  "label": rs.randint(0, 4, 16).astype(np.int32)})
s.snapshot(sv + "/snap")
print("serve stage: advanced snapshot to iter 5 under load")
EOF
    wait "$bench_pid" || { echo "serve-bench failed:"
                           cat "$sv/bench.out"; exit 1; }

    python - "$sv" <<'EOF'
import json, sys
rows = json.load(open(sys.argv[1] + "/bench.json"))
b = next(r for r in rows if r["mode"] == "closed")
assert b["ok"] > 0, b
assert b["rejected"] == 0, f"rejects at nominal load: {b}"
assert b["errors"] == 0, f"errors under hot reload: {b}"
assert b["latency_ms_p99"] < 2000, f"p99 blown: {b}"
print(f"serve bench OK: {b['ok']} ok, p50={b['latency_ms_p50']}ms "
      f"p99={b['latency_ms_p99']}ms, 0 rejects/errors across a reload")
EOF
    grep -q "hot-reloaded iter 5" "$sv/serve.out" || {
        echo "no hot reload observed:"; cat "$sv/serve.out"; exit 1; }
    curl -sf "$url/healthz" 2>/dev/null | grep -q '"iter": 5' || \
    python -c "
import json, urllib.request
h = json.loads(urllib.request.urlopen('$url/healthz').read())
assert h['iter'] == 5, h"

    kill -TERM "$serve_pid"
    rc=0; wait "$serve_pid" || rc=$?
    test "$rc" -eq 0 || { echo "SIGTERM drain exited $rc:"
                          cat "$sv/serve.out"; exit 1; }
    grep -q "drained cleanly" "$sv/serve.out"

    # the unservable-checkpoint path: documented exit 3, before binding
    rc=0
    python -m sparknet_tpu serve --prefix "$sv/definitely-missing" \
        --port 0 > "$sv/bad.out" 2>&1 || rc=$?
    test "$rc" -eq 3 || { echo "expected exit 3 on a bad checkpoint," \
                               "got $rc"; cat "$sv/bad.out"; exit 1; }

    python -m sparknet_tpu report "$sv/serve.jsonl" | tee "$sv/serve.rep" \
        > /dev/null
    grep -q "serving" "$sv/serve.rep"
    grep -q "latency ms" "$sv/serve.rep"
    grep -q "drained cleanly" "$sv/serve.rep"
    python -m sparknet_tpu monitor "$sv/serve.jsonl" --once \
        | grep -q "serving: requests"
    echo "serve stage OK: bench clean across a live hot reload," \
         "SIGTERM drained with exit 0, report rendered the section"
}

# ------------------------------------------------- serving fleet ----
# (n) routing tier over a REAL 3-replica fleet (ISSUE 17): replicas
#     lease into the rendezvous, `sparknet route` spreads POST /predict
#     by queue depth. Chaos SIGKILLs replica 1 after its 25th request —
#     the router must evict it on lease expiry with the availability
#     dip bounded, both asserted FROM THE METRICS STREAM. The SLO
#     autoscaler must emit a grow decision under load; the script
#     (acting as the orchestrator) launches replica 3 — admitted via
#     the grow path — serving a CORRUPT canary checkpoint (wrong feed
#     width, so canary-routed requests 400): the canary controller
#     must auto-rollback, pin traffic to the baseline sha, and a
#     post-rollback bench must run clean on the old weights. SIGTERM
#     drains the router with exit 0; report/monitor render the
#     routing section from the same stream.
run_routefleet_stage() {
    rf="$tmp/routefleet"
    rdv="$rf/rdv"
    mkdir -p "$rf" "$rdv"

    python - "$rf" <<'EOF'
# snapshot A (8-wide feeds, the baseline) and snapshot B (6-wide
# feeds: the "corrupt" canary — requests shaped for A get 400 from it)
import sys
import numpy as np
from sparknet_tpu.proto import Message
from sparknet_tpu.solver import Solver

def mlp(feat):
    net = Message("NetParameter", name="mlp")
    net.add("layer", name="d", type="JavaData", top=["data"],
            java_data_param=dict(shape=dict(dim=[16, feat])))
    net.add("layer", name="l", type="JavaData", top=["label"],
            java_data_param=dict(shape=dict(dim=[16])))
    net.add("layer", name="fc1", type="InnerProduct", bottom=["data"],
            top=["fc1"], inner_product_param=dict(
                num_output=16, weight_filler=dict(type="xavier")))
    net.add("layer", name="r1", type="ReLU", bottom=["fc1"], top=["fc1"])
    net.add("layer", name="fc2", type="InnerProduct", bottom=["fc1"],
            top=["fc2"], inner_product_param=dict(
                num_output=4, weight_filler=dict(type="xavier")))
    net.add("layer", name="loss", type="SoftmaxWithLoss",
            bottom=["fc2", "label"], top=["loss"])
    return net

for name, feat in (("snapA", 8), ("snapB", 6)):
    sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
                 momentum=0.9, random_seed=7)
    s = Solver(sp, net_param=mlp(feat), log_fn=None)
    rs = np.random.RandomState(0)
    for _ in range(3):
        s.train_step({"data": rs.randn(16, feat).astype(np.float32),
                      "label": rs.randint(0, 4, 16).astype(np.int32)})
    s.snapshot(sys.argv[1] + "/" + name)
print("routefleet stage: snapshots A (baseline) + B (corrupt canary)")
EOF

    rpids=()
    for i in 0 1 2; do
        chaos=""
        [ "$i" = 1 ] && chaos="kill_replica=1,kill_req=25"
        # replica 2 is chaos-slowed: the per-stage decomposition must
        # attribute its milliseconds to the FORWARD (infer) stage — a
        # slow accelerator, not queue wait — in `sparknet report`
        [ "$i" = 2 ] && chaos="slow_replica=2,slow_ms=120"
        python -m sparknet_tpu serve --prefix "$rf/snapA" --port 0 \
            --fleet_dir "$rdv" --replica "$i" --replicas 3 \
            --lease 2 --heartbeat_interval 0.3 \
            ${chaos:+--chaos "$chaos"} --trace_tail_ms 60 \
            --metrics "$rf/rep$i.jsonl" > "$rf/rep$i.out" 2>&1 &
        rpids+=($!)
    done
    for i in 0 1 2; do
        for _ in $(seq 1 120); do
            grep -q "listening on" "$rf/rep$i.out" && break
            kill -0 "${rpids[$i]}" || { echo "replica $i died at start:"
                                        cat "$rf/rep$i.out"; exit 1; }
            sleep 0.5
        done
    done

    python -m sparknet_tpu route --fleet_dir "$rdv" --replicas 3 \
        --lease 2 --window_s 0.5 --slo_p99_ms 1 --breach_windows 3 \
        --idle_windows 9999 --max_replicas 4 \
        --canary_pct 25 --canary_min_requests 8 \
        --trace_tail_ms 60 --slo_ms 60 --burn_scale 0.01 \
        --metrics "$rf/route.jsonl" > "$rf/route.out" 2>&1 &
    route_pid=$!
    for _ in $(seq 1 120); do
        grep -q "sparknet route: listening on" "$rf/route.out" && break
        kill -0 "$route_pid" || { echo "router died during startup:"
                                  cat "$rf/route.out"; exit 1; }
        sleep 0.5
    done
    url=$(sed -n 's/.*listening on \(http:\/\/[^ ]*\).*/\1/p' \
          "$rf/route.out" | head -1)
    test -n "$url" || { echo "router never announced:"
                        cat "$rf/route.out"; exit 1; }

    # phase 1: closed-loop load through the router; chaos SIGKILLs
    # replica 1 after its 25th served request, mid-load
    python -m sparknet_tpu serve-bench --url "$url" --mode closed \
        --concurrency 4 --duration 8 --json "$rf/bench1.json" \
        > "$rf/bench1.out" 2>&1 || { echo "phase-1 bench failed:"
                                     cat "$rf/bench1.out"; exit 1; }
    rc=0; wait "${rpids[1]}" 2>/dev/null || rc=$?
    test "$rc" -ne 0 || { echo "chaos target replica 1 was supposed" \
                               "to die"; exit 1; }
    for _ in $(seq 1 60); do
        grep -q "EVICTED replica 1" "$rf/route.out" && break
        sleep 0.5
    done
    grep -q "EVICTED replica 1" "$rf/route.out" || {
        echo "replica 1 never evicted:"; cat "$rf/route.out"; exit 1; }

    # the failover contract, asserted FROM THE METRICS STREAM: the
    # eviction record names lease_expired, and the availability dip is
    # bounded — in-flight casualties were retried on the survivors
    python - "$rf" <<'EOF'
import json, sys
evs = [json.loads(l) for l in open(sys.argv[1] + "/route.jsonl")]
ev = [e for e in evs if e["event"] == "eviction"]
assert any(e["worker"] == 1 and e["reason"] == "lease_expired"
           for e in ev), ev
routes = [e for e in evs if e["event"] == "route"]
ok = sum(1 for e in routes if e["code"] == 200)
hard = sum(1 for e in routes if e["code"] not in (200, 429))
assert routes, "no route events in the metrics stream"
assert ok / len(routes) >= 0.95, (ok, len(routes))
assert hard <= 8, f"availability dip not bounded: {hard} hard failures"
retried = sum(1 for e in routes if e.get("retried"))
print(f"routefleet failover OK: {len(routes)} dispatches, {ok} ok, "
      f"{hard} hard failures, {retried} retried, eviction in stream")
EOF

    # phase 2: the autoscaler's grow decision is the orchestrator
    # contract — wait for it, then launch replica 3 (the 4th), which
    # serves the CORRUPT snapshot B: admission via the grow path AND
    # the canary split start in one move
    for _ in $(seq 1 60); do
        grep -q "route: scale grow" "$rf/route.out" && break
        sleep 0.5
    done
    grep -q "route: scale grow" "$rf/route.out" || {
        echo "no grow decision:"; cat "$rf/route.out"; exit 1; }
    python -m sparknet_tpu serve --prefix "$rf/snapB" --port 0 \
        --fleet_dir "$rdv" --replica 3 --replicas 4 \
        --lease 2 --heartbeat_interval 0.3 \
        --metrics "$rf/rep3.jsonl" > "$rf/rep3.out" 2>&1 &
    rep3_pid=$!
    for _ in $(seq 1 60); do
        grep -q "ADMITTED replica 3" "$rf/route.out" && break
        sleep 0.5
    done
    grep -q "ADMITTED replica 3" "$rf/route.out" || {
        echo "replica 3 never admitted:"; cat "$rf/route.out"; exit 1; }

    # load with the canary live: every 4th request routes to snapshot
    # B and 400s — the bench SEES those errors (non-zero exit is
    # expected here); the controller must roll back and pin the
    # baseline
    python -m sparknet_tpu serve-bench --url "$url" --mode closed \
        --concurrency 4 --duration 8 --json "$rf/bench2.json" \
        > "$rf/bench2.out" 2>&1 || true
    grep -q "serve-bench\[closed\]" "$rf/bench2.out" || {
        echo "phase-2 bench never ran:"; cat "$rf/bench2.out"; exit 1; }
    for _ in $(seq 1 60); do
        grep -q "canary_rollback" "$rf/route.out" && break
        sleep 0.5
    done
    grep -q "canary_rollback" "$rf/route.out" || {
        echo "no canary rollback:"; cat "$rf/route.out"; exit 1; }

    # phase 3: post-rollback the fleet serves the OLD weights clean —
    # zero errors, zero rejects
    python -m sparknet_tpu serve-bench --url "$url" --mode closed \
        --concurrency 4 --duration 4 --json "$rf/bench3.json" \
        > "$rf/bench3.out" 2>&1 || { echo "phase-3 bench failed:"
                                     cat "$rf/bench3.out"; exit 1; }
    python - "$rf" <<'EOF'
import json, sys
rf = sys.argv[1]
b = next(r for r in json.load(open(rf + "/bench3.json"))
         if r["mode"] == "closed")
assert b["ok"] > 0 and b["errors"] == 0 and b["rejected"] == 0, b
evs = [json.loads(l) for l in open(rf + "/route.jsonl")]
scale = [e for e in evs if e["event"] == "scale"]
assert any(e["action"] == "grow" for e in scale), scale
adm = [e for e in evs if e["event"] == "membership"
       and e.get("kind") == "admission"]
assert any(e["worker"] == 3 and e.get("via") == "grow" for e in adm), adm
can = [e for e in evs if e["event"] == "canary"]
assert any(e["action"] == "start" for e in can), can
rb = [e for e in can if e["action"] == "rollback"]
assert len(rb) == 1 and rb[0]["sha"] != rb[0]["baseline_sha"], can
print(f"routefleet canary OK: rollback of {rb[0]['sha'][:12]} pinned "
      f"baseline {rb[0]['baseline_sha'][:12]}; post-rollback bench "
      f"{b['ok']} ok / 0 errors")
EOF

    # phase 4: open-loop load (honest tail) — the bench reads the
    # echoed X-Sparknet-Stages header and splits server-attributed
    # milliseconds from network/client time
    python -m sparknet_tpu serve-bench --url "$url" --mode open \
        --rate 30 --duration 6 --json "$rf/bench4.json" \
        > "$rf/bench4.out" 2>&1 || true
    grep -q "serve-bench\[open\]" "$rf/bench4.out" || {
        echo "phase-4 bench never ran:"; cat "$rf/bench4.out"; exit 1; }
    grep -q "server share" "$rf/bench4.out" || {
        echo "phase-4 bench missing the server/network split:"
        cat "$rf/bench4.out"; exit 1; }

    kill -TERM "$route_pid"
    rc=0; wait "$route_pid" || rc=$?
    test "$rc" -eq 0 || { echo "router SIGTERM drain exited $rc:"
                          cat "$rf/route.out"; exit 1; }
    grep -q "route: drained cleanly" "$rf/route.out"
    for p in "${rpids[0]}" "${rpids[2]}" "$rep3_pid"; do
        kill -TERM "$p" 2>/dev/null || true
    done
    for p in "${rpids[0]}" "${rpids[2]}" "$rep3_pid"; do
        rc=0; wait "$p" || rc=$?
        test "$rc" -eq 0 || { echo "replica SIGTERM drain exited $rc"
                              exit 1; }
    done

    python -m sparknet_tpu report "$rf/route.jsonl" \
        --json "$rf/route.repjson" | tee "$rf/route.rep" > /dev/null
    grep -q "routing fleet" "$rf/route.rep"
    grep -q "canary" "$rf/route.rep"
    grep -q "p99 attribution" "$rf/route.rep"
    grep -q "slo error budget" "$rf/route.rep"
    python -m sparknet_tpu monitor "$rf/route.jsonl" --once \
        > "$rf/route.mon"
    grep -q "routing: dispatches" "$rf/route.mon"
    grep -q "tracing: traces" "$rf/route.mon"

    # "where did the p99 go": the decomposition must name the chaos-
    # slowed replica's FORWARD stage as the top tail contributor (not
    # queue wait), sum to the tail-cohort total within 10%, and the
    # error-budget ledger must have seen the burn
    python - "$rf" <<'EOF'
import json, sys
rf = sys.argv[1]
rep = json.load(open(rf + "/route.repjson"))
tr = rep["tracing"]
assert tr["traces"] > 0 and tr["tails"] >= 1, tr
assert tr["top_stage"] == "infer", \
    f"p99 misattributed: {tr.get('top_stage')} {tr.get('p99_attribution')}"
attr = tr["p99_attribution"]
s = sum(attr.values())
assert abs(s - tr["p99_cohort_ms"]) <= 0.1 * tr["p99_cohort_ms"], \
    (s, tr["p99_cohort_ms"], attr)
bn = rep["slo_burn"]
assert bn["evaluations"] > 0, bn
b = next(r for r in json.load(open(rf + "/bench4.json"))
         if r["mode"] == "open")
assert "server_ms_p99" in b and "net_ms_p99" in b, sorted(b)
print(f"routefleet tracing OK: top tail stage infer "
      f"({attr['infer']:.1f} of {tr['p99_cohort_ms']:.1f} ms), "
      f"{tr['tails']} tail exemplar(s), burn evaluated "
      f"{bn['evaluations']}x, bench server p99 {b['server_ms_p99']}ms "
      f"/ net p99 {b['net_ms_p99']}ms")
EOF

    # the merged Chrome timeline carries the traced request end to
    # end: router + replica tracks share one trace id, and the tail
    # exemplar is flagged in the span name
    python -m sparknet_tpu trace "$rf/route.jsonl" "$rf/rep0.jsonl" \
        "$rf/rep2.jsonl" "$rf/rep3.jsonl" --chrome "$rf/fleet.json" \
        > "$rf/trace.out" 2>&1 || { echo "trace merge failed:"
                                    cat "$rf/trace.out"; exit 1; }
    python - "$rf" <<'EOF'
import json, sys
rf = sys.argv[1]
doc = json.load(open(rf + "/fleet.json"))
evs = doc["traceEvents"] if isinstance(doc, dict) else doc
names = {e["pid"]: e["args"]["name"] for e in evs
         if e.get("ph") == "M" and e.get("name") == "process_name"}
router_pids = {p for p, n in names.items() if "router" in n}
rep_pids = set(names) - router_pids   # replica streams align as hosts
assert router_pids and rep_pids, names
spans = [e for e in evs if e.get("ph") == "X"
         and (e.get("args") or {}).get("trace")]
rtr = {e["args"]["trace"] for e in spans if e["pid"] in router_pids}
prt = {e["args"]["trace"] for e in spans if e["pid"] in rep_pids}
shared = rtr & prt
assert shared, (len(rtr), len(prt))
tails = [e for e in spans if "[tail]" in e.get("name", "")]
assert tails, "no tail exemplar span in the merged timeline"
print(f"routefleet timeline OK: {len(shared)} trace id(s) span the "
      f"router and replica tracks, {len(tails)} tail exemplar "
      f"span(s) flagged")
EOF
    echo "routefleet stage OK: lease eviction + bounded-availability" \
         "failover from the metrics stream, grow admission under load," \
         "canary auto-rollback to the baseline, p99 attributed to the" \
         "slow replica's forward stage, traced request end to end in" \
         "the merged timeline, router drained exit 0"
}

# --------------------------------------- elastic world resizing ----
# (j) cross-world checkpoint resharding + grow-mid-run (ISSUE 12):
#     a 2-process run writes world-stamped snapshots and loses host 1
#     to chaos SIGKILL; the survivor completes. A single-process
#     relaunch under --reshard strict is REFUSED with the actionable
#     WorldMismatch; --reshard auto resumes the same checkpoint at
#     N-1 (1 process) and N+1 (3 processes). Finally a live 2-process
#     run ADMITS a late-started --grow host through the heartbeat
#     rendezvous with zero recompiles, and `sparknet report` renders
#     the eviction, the reshard, and the join.
run_resize_stage() {
    rz="$tmp/resize"
    mkdir -p "$rz"
    # shared persistent compile cache: the joiner replays the
    # incumbents' XLA executables instead of re-tracing for minutes
    export JAX_COMPILATION_CACHE_DIR="$rz/jaxcache"

    # virtual preempt/rejoin cycle (chaos grammar satellite): the
    # preempted host drops its lease, is evicted, and is ADMITTED back
    # through the rendezvous rejoin_after rounds later
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m sparknet_tpu cifar --workers 4 --hosts 2 --tau 1 \
        --rounds 6 --test-every 100 --metrics "$rz/pre.jsonl" \
        --chaos "preempt_host=1,preempt_round=2,rejoin_after=2" \
        --quorum 1 --evict-after 1 --readmit-after 0 \
        > "$rz/pre.out" 2>&1
    grep -q "EVICTED host 1" "$rz/pre.out"
    grep -q "ADMITTED host 1" "$rz/pre.out"
    python -m sparknet_tpu report "$rz/pre.jsonl" | tee "$rz/pre.rep" \
        > /dev/null
    grep -q "joined host 1" "$rz/pre.rep"

    # 2-process training fleet: world-stamped snapshots every 2 rounds;
    # chaos SIGKILLs host 1 at round 3, the survivor finishes all 6
    port=$(python -c "import socket; s=socket.socket(); \
s.bind(('localhost',0)); print(s.getsockname()[1])")
    pids=()
    for i in 0 1; do
        SPARKNET_COORDINATOR="localhost:$port" \
        SPARKNET_NUM_PROCESSES=2 SPARKNET_PROCESS_ID=$i \
        SPARKNET_CHAOS="kill_host=1,kill_host_round=3" \
        XLA_FLAGS="--xla_force_host_platform_device_count=4" \
        python -m sparknet_tpu cifar --workers 4 --hosts 2 --tau 2 \
            --rounds 6 --test-every 100 --metrics "$rz/w2-$i.jsonl" \
            --snapshot-prefix "$rz/snap" --snapshot-every 2 \
            --heartbeat-dir "$rz/rdv1" --lease-s 1.5 \
            --heartbeat-interval 0.2 \
            --quorum 1 --evict-after 1 --readmit-after 0 \
            > "$rz/w2-$i.out" 2>&1 &
        pids+=($!)
    done
    rc0=0; wait "${pids[0]}" || rc0=$?
    rc1=0; wait "${pids[1]}" || rc1=$?
    test "$rc0" -eq 0 || { echo "resize: survivor failed (rc=$rc0):"
                           cat "$rz/w2-0.out"; exit 1; }
    test "$rc1" -ne 0 || { echo "resize: chaos target was supposed to die"
                           exit 1; }
    grep -q "EVICTED host 1" "$rz/w2-0.out"
    python - "$rz" <<'EOF'
from sparknet_tpu.resilience import checkpoint
import sys
man = checkpoint.load_manifest(sys.argv[1] + "/snap")
w = man["latest"]["world"]
assert w["processes"] == 2, f"snapshot not stamped 2-process: {w}"
print(f"resize: snapshot stamped for world {w}")
EOF

    # strict refusal: the single-process relaunch must name both
    # worlds and the exact remedy, and exit nonzero
    rc=0
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m sparknet_tpu cifar --workers 4 --hosts 1 --tau 2 \
        --rounds 2 --test-every 100 \
        --snapshot-prefix "$rz/snap" --resume auto --reshard strict \
        > "$rz/strict.out" 2>&1 || rc=$?
    test "$rc" -ne 0 || { echo "resize: strict resume was supposed to"\
                               "refuse the 2-process snapshot"
                          cat "$rz/strict.out"; exit 1; }
    grep -q "different world" "$rz/strict.out"
    grep -qe "--reshard auto" "$rz/strict.out"

    # N-1: the 2-process world's checkpoint resumes on ONE process
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m sparknet_tpu cifar --workers 4 --hosts 1 --tau 2 \
        --rounds 2 --test-every 100 --metrics "$rz/w1.jsonl" \
        --snapshot-prefix "$rz/snap" --resume auto --reshard auto \
        > "$rz/w1.out" 2>&1 || { echo "resize: N-1 resume failed:"
                                 cat "$rz/w1.out"; exit 1; }
    grep -q "reshard: snapshot" "$rz/w1.out"
    grep -qE "round 1: loss = [0-9.]+" "$rz/w1.out"
    python -m sparknet_tpu report "$rz/w1.jsonl" | tee "$rz/w1.rep" \
        > /dev/null
    grep -q "resharded snapshot for this world" "$rz/w1.rep"

    # N+1: the same checkpoint resumes on THREE processes. Generous
    # lease + no eviction pressure: this phase tests the reshard
    # resume, and round-0 compile skew between the processes must not
    # read as death (a spuriously-dead peer skips the jax.distributed
    # shutdown barrier and aborts the survivor)
    port=$(python -c "import socket; s=socket.socket(); \
s.bind(('localhost',0)); print(s.getsockname()[1])")
    pids=()
    for i in 0 1 2; do
        SPARKNET_COORDINATOR="localhost:$port" \
        SPARKNET_NUM_PROCESSES=3 SPARKNET_PROCESS_ID=$i \
        XLA_FLAGS="--xla_force_host_platform_device_count=4" \
        python -m sparknet_tpu cifar --workers 4 --hosts 3 --tau 2 \
            --rounds 2 --test-every 100 --metrics "$rz/w3-$i.jsonl" \
            --snapshot-prefix "$rz/snap" --resume auto --reshard auto \
            --heartbeat-dir "$rz/rdv3" --lease-s 6 \
            --heartbeat-interval 0.2 --quorum 1 --evict-after 999 \
            > "$rz/w3-$i.out" 2>&1 &
        pids+=($!)
    done
    for i in 0 1 2; do
        rc=0; wait "${pids[$i]}" || rc=$?
        test "$rc" -eq 0 || { echo "resize: N+1 process $i failed"\
                                   "(rc=$rc):"; cat "$rz/w3-$i.out"
                              exit 1; }
    done
    grep -q "reshard: snapshot" "$rz/w3-0.out"

    # grow-mid-run: 2 incumbents train; a LATE-STARTED third process
    # leases itself into the rendezvous with --grow and is admitted
    # with zero recompiles
    port=$(python -c "import socket; s=socket.socket(); \
s.bind(('localhost',0)); print(s.getsockname()[1])")
    pids=()
    for i in 0 1; do
        SPARKNET_COORDINATOR="localhost:$port" \
        SPARKNET_NUM_PROCESSES=2 SPARKNET_PROCESS_ID=$i \
        XLA_FLAGS="--xla_force_host_platform_device_count=4" \
        python -m sparknet_tpu cifar --workers 4 --hosts 2 --tau 1 \
            --rounds 40 --test-every 100 --metrics "$rz/g-$i.jsonl" \
            --snapshot-prefix "$rz/gsnap" --snapshot-every 3 \
            --heartbeat-dir "$rz/grdv" --lease-s 6 \
            --heartbeat-interval 0.2 \
            --quorum 1 --evict-after 999 --readmit-after 0 \
            > "$rz/g-$i.out" 2>&1 &
        pids+=($!)
    done
    # the joiner bootstraps its weights from the fleet's snapshots:
    # wait for the first manifest commit before launching it
    python - "$rz" <<'EOF'
from sparknet_tpu.resilience import checkpoint
import sys
entry = checkpoint.wait_for_manifest(sys.argv[1] + "/gsnap", timeout=240)
assert entry is not None, "incumbents never committed a snapshot"
print(f"resize: fleet snapshot at iter {entry['iter']}; growing")
EOF
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m sparknet_tpu cifar --workers 4 --hosts 1 --tau 1 \
        --rounds 3 --test-every 100 --metrics "$rz/g-join.jsonl" \
        --snapshot-prefix "$rz/gsnap" --resume auto --reshard auto \
        --grow --heartbeat-dir "$rz/grdv" --lease-s 6 \
        --heartbeat-interval 0.2 --quorum 1 --evict-after 999 \
        > "$rz/g-join.out" 2>&1 &
    jpid=$!
    rc0=0; wait "${pids[0]}" || rc0=$?
    rc1=0; wait "${pids[1]}" || rc1=$?
    rcj=0; wait "$jpid" || rcj=$?
    test "$rc0" -eq 0 || { echo "resize: grow incumbent 0 failed"\
                                "(rc=$rc0):"; cat "$rz/g-0.out"; exit 1; }
    test "$rc1" -eq 0 || { echo "resize: grow incumbent 1 failed"\
                                "(rc=$rc1):"; cat "$rz/g-1.out"; exit 1; }
    test "$rcj" -eq 0 || { echo "resize: joiner failed (rc=$rcj):"
                           cat "$rz/g-join.out"; exit 1; }
    grep -q "joining a running world of 2 host(s) \[0, 1\] as host 2" \
        "$rz/g-join.out"
    grep -q "host 2 joined the rendezvous" "$rz/g-0.out"
    grep -q "ADMITTED host 2" "$rz/g-0.out"
    python - "$rz" <<'EOF'
import json, sys
evs = [json.loads(l) for l in open(sys.argv[1] + "/g-0.jsonl")]
hj = [e for e in evs if e.get("event") == "host_joined"]
assert hj, "no host_joined event in the incumbent's stream"
t_join = hj[0]["t"]
recompiles = [e for e in evs if e.get("event") == "recompile"
              and not e.get("first") and e["t"] > t_join]
assert not recompiles, f"admission recompiled: {recompiles}"
print(f"resize: host {hj[0]['host']} admitted at round "
      f"{hj[0]['round']} with zero recompiles")
EOF
    python -m sparknet_tpu report "$rz/g-0.jsonl" | tee "$rz/g.rep" \
        > /dev/null
    grep -q "joined host 2" "$rz/g.rep"
    echo "resize stage OK: 2-process checkpoint resumed at N-1 and" \
         "N+1 under --reshard auto, strict refusal names the remedy," \
         "and a live run admitted a late --grow host with zero" \
         "recompiles"
}

# ------------------------------------------------ input pipeline ----
# (1) 2 real processes with sharded ingest (the default in multi-process
# worlds): every host's throttled `ingest` read events must stay inside
# the half of the record space it owns, and the two halves must tile the
# dataset — the owned-records assertion straight from the metrics
# stream. (2) chaos slow_h2d stalls every FRESH batch at the prefetch
# hand-off; --echo 2 halves the fresh-batch count for the same round
# count, so it must win wall clock by most of the skipped stall.
run_ingest_stage() {
    ig="$tmp/ingest"
    mkdir -p "$ig"
    port=$(python -c "import socket; s=socket.socket(); \
s.bind(('localhost',0)); print(s.getsockname()[1])")
    pids=()
    for i in 0 1; do
        SPARKNET_COORDINATOR="localhost:$port" \
        SPARKNET_NUM_PROCESSES=2 SPARKNET_PROCESS_ID=$i \
        XLA_FLAGS="--xla_force_host_platform_device_count=4" \
        python -m sparknet_tpu cifar --workers 4 --hosts 2 --tau 2 \
            --rounds 4 --test-every 100 --metrics "$ig/run$i.jsonl" \
            --heartbeat-dir "$ig/rdv" --lease-s 5 \
            --heartbeat-interval 0.2 --quorum 2 \
            > "$ig/out$i.txt" 2>&1 &
        pids+=($!)
    done
    for i in 0 1; do
        rc=0; wait "${pids[$i]}" || rc=$?
        test "$rc" -eq 0 || { echo "ingest host $i failed (rc=$rc):"
                              cat "$ig/out$i.txt"; exit 1; }
    done
    grep -q "sharded ingest: host 0 owns" "$ig/out0.txt"
    grep -q "sharded ingest: host 1 owns" "$ig/out1.txt"

    python - "$ig" <<'EOF'
import json, sys, os
ig = sys.argv[1]
own, spans = {}, {}
for i in (0, 1):
    evs = [json.loads(l) for l in open(os.path.join(ig, f"run{i}.jsonl"))]
    ing = [e for e in evs if e.get("event") == "ingest"]
    assert ing, f"host {i}: no ingest events in the metrics stream"
    init = [e for e in ing if e["kind"] == "init"]
    reads = [e for e in ing if e["kind"] == "read"]
    assert len(init) == 1 and init[0]["host"] == i \
        and init[0]["hosts"] == 2, f"host {i}: bad init {init}"
    assert reads, f"host {i}: no throttled read events"
    own[i] = init[0]["records"]
    spans[i] = (min(e["lo"] for e in reads), max(e["hi"] for e in reads))
    pf = [e for e in evs if e.get("event") == "prefetch"]
    assert pf and pf[-1]["ingest_hosts"] == 2 \
        and pf[-1]["ingest_records"] == own[i], \
        f"host {i}: prefetch gauge missing ingest fields: {pf[-1:]}"
# partitions are contiguous: host 0 owns [0, n0), host 1 [n0, n0+n1)
n0, n1 = own[0], own[1]
assert abs(n0 - n1) <= 1, f"lopsided split: {own}"
assert 0 <= spans[0][0] and spans[0][1] < n0, \
    f"host 0 read outside its shard: {spans[0]} vs [0, {n0})"
assert n0 <= spans[1][0] and spans[1][1] < n0 + n1, \
    f"host 1 read outside its shard: {spans[1]} vs [{n0}, {n0 + n1})"
print(f"ingest: host 0 read {spans[0]} of [0, {n0}), "
      f"host 1 read {spans[1]} of [{n0}, {n0 + n1}) — disjoint, "
      f"{n0 + n1} records covered")
EOF
    python -m sparknet_tpu report "$ig/run0.jsonl" | tee "$ig/rep.txt" \
        > /dev/null
    grep -q "input pipeline" "$ig/rep.txt"
    grep -q "sharded ingest" "$ig/rep.txt"

    # -- data echoing vs the slowed wire --------------------------------
    # the stall must exceed the ~7.6 s/round CPU compute or the depth-2
    # prefetch hides it entirely: at 12 s/transfer the no-echo run is
    # producer-bound (4 fresh batches = 48 s on the wire) while --echo 2
    # ships only 2 fresh batches (24 s) and goes back to compute-bound
    t0=$SECONDS
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m sparknet_tpu cifar --workers 2 --tau 1 --rounds 4 \
        --test-every 100 --metrics "$ig/noecho.jsonl" \
        --chaos "slow_h2d=12" > "$ig/noecho.out" 2>&1
    noecho_s=$((SECONDS - t0))
    t0=$SECONDS
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m sparknet_tpu cifar --workers 2 --tau 1 --rounds 4 \
        --test-every 100 --metrics "$ig/echo.jsonl" \
        --chaos "slow_h2d=12" --echo 2 > "$ig/echo.out" 2>&1
    echo_s=$((SECONDS - t0))
    # echo halves the wire time (24 s saved); demand a solid chunk of
    # it back after pipeline overlap
    test "$echo_s" -le "$((noecho_s - 8))" || {
        echo "echo run did not beat the slowed wire: ${echo_s}s vs" \
             "no-echo ${noecho_s}s"; exit 1; }
    python - "$ig" <<'EOF'
import json, sys, os
ig = sys.argv[1]
evs = [json.loads(l) for l in open(os.path.join(ig, "echo.jsonl"))]
pf = [e for e in evs if e.get("event") == "prefetch"]
assert pf and pf[-1].get("echo") == 2, f"echo gauge missing: {pf[-1:]}"
assert any(e.get("event") == "chaos" and e.get("kind") == "slow_h2d"
           for e in evs), "slow_h2d chaos event missing"
EOF
    echo "ingest stage OK: per-host reads stayed inside owned shards," \
         "and --echo 2 beat the slowed wire (${echo_s}s vs" \
         "${noecho_s}s)"
}

# --------------------------------------- FSDP one-big-model stage ----
# Sharded training end to end (ISSUE 14): --fsdp on --precision bf16
# on the 8-virtual-device CPU mesh. The exec event (logged after the
# first train_step off the LIVE addressable shards, not the plan) is
# the sharded-update-executed assertion; the kill/resume cycle proves
# the gathered manifest round-trips, and the final leg restores the
# same checkpoint into the replicated DP path — snapshots stay
# world-portable across sharding modes.
run_fsdp_stage() {
    fz="$tmp/fsdp"
    mkdir -p "$fz"
    lm_args="--vocab 256 --seq-len 64 --batch 8 --d-model 64 --layers 2
             --heads 4 --no-flash --display 5 --lr 0.01"

    # long run, preempted: SIGTERM after the first committed snapshot
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m sparknet_tpu lm $lm_args --steps 100000 \
        --fsdp on --precision bf16 \
        --metrics "$fz/run1.jsonl" --snapshot-prefix "$fz/snap" \
        --snapshot-every 10 > "$fz/run1.out" 2>&1 &
    fpid=$!
    python - "$fz" <<'EOF'
from sparknet_tpu.resilience import checkpoint
import sys
entry = checkpoint.wait_for_manifest(sys.argv[1] + "/snap", timeout=300)
assert entry is not None, "fsdp run never committed a snapshot"
print(f"fsdp: gathered snapshot committed at iter {entry['iter']}")
EOF
    kill -TERM "$fpid" 2>/dev/null || true
    wait "$fpid" || true

    resume_iter=$(python -c "
import json
print(json.load(open('$fz/snap.latest.json'))['latest']['iter'])")
    test "$resume_iter" -gt 0
    state=$(python -c "
import json
print(json.load(open('$fz/snap.latest.json'))['latest']['state'])")

    # the sharded update really executed, with bf16 mixed precision on
    python - "$fz" <<'EOF'
import json, sys
evs = [json.loads(l) for l in open(sys.argv[1] + "/run1.jsonl")]
cfg = next(e for e in evs if e["event"] == "config")
assert cfg["fsdp"] == 1 and cfg["precision"] == "bf16", cfg
fs = [e for e in evs if e.get("event") == "fsdp"]
plan = [e for e in fs if e["kind"] == "plan"]
ex = [e for e in fs if e["kind"] == "exec"]
assert plan and plan[0]["world"] == 8, f"bad fsdp plan: {plan}"
assert plan[0]["sharded_leaves"] > 0, plan
assert plan[0]["hist_bytes_per_device"] \
    < plan[0]["hist_bytes_replicated"], plan
assert ex, "no fsdp exec event: the sharded update never ran"
e = ex[0]
assert e["param_bytes_per_device"] < e["param_bytes_replicated"], e
print(f"fsdp: exec OK — {plan[0]['sharded_leaves']}/"
      f"{plan[0]['total_leaves']} leaves sharded, "
      f"{e['param_bytes_per_device']}/{e['param_bytes_replicated']} "
      f"param bytes resident per device")
EOF

    # resume the SAME sharded mode from the gathered manifest
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m sparknet_tpu lm $lm_args --steps $((resume_iter + 10)) \
        --fsdp on --precision bf16 --resume "$fz/$state" \
        --metrics "$fz/run2.jsonl" > "$fz/run2.out" 2>&1 || {
        echo "fsdp resume failed:"; cat "$fz/run2.out"; exit 1; }
    grep -q "done: 10 steps" "$fz/run2.out"
    python - "$fz" "$resume_iter" <<'EOF'
import json, sys
evs = [json.loads(l) for l in open(sys.argv[1] + "/run2.jsonl")]
it0 = int(sys.argv[2])
train = [e for e in evs if e["event"] == "train"]
assert train and all(e["iter"] >= it0 for e in train), \
    f"loss curve restarted below iter {it0}"
assert any(e.get("event") == "fsdp" and e["kind"] == "exec"
           for e in evs), "resumed run lost the sharded layout"
print(f"fsdp: resume OK — curve continued from iter {it0}")
EOF

    # world-portability: the replicated DP path (fsdp off) consumes the
    # same gathered checkpoint
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m sparknet_tpu lm $lm_args --steps $((resume_iter + 5)) \
        --fsdp off --resume "$fz/$state" \
        --metrics "$fz/run3.jsonl" > "$fz/run3.out" 2>&1 || {
        echo "DP consume of fsdp snapshot failed:"
        cat "$fz/run3.out"; exit 1; }
    grep -q "done: 5 steps" "$fz/run3.out"
    python - "$fz" <<'EOF'
import json, sys
evs = [json.loads(l) for l in open(sys.argv[1] + "/run3.jsonl")]
assert not any(e.get("event") == "fsdp" for e in evs), \
    "fsdp events in an fsdp=off run"
cfg = next(e for e in evs if e["event"] == "config")
assert cfg["fsdp"] == 0, cfg
EOF
    # the stream renders (fsdp is a registered event kind)
    python -m sparknet_tpu report "$fz/run1.jsonl" > /dev/null
    echo "fsdp stage OK: sharded update executed (exec event), SIGTERM" \
         "snapshot resumed at iter $resume_iter, and the gathered" \
         "checkpoint restored into plain DP"
}

# --------------------------------------------- fleet simulation stage ----
# Fleet-scale chaos simulation (ISSUE 15). First the replay gate: a
# recorded REAL multi-coordinator crash run (real threads, real wall
# clock, an on-disk rendezvous dir, the default seam) must be
# reproduced membership-event-exactly by the simulator — a mismatch
# means either the simulator drifted from the protocol or a protocol
# change altered membership behavior unnoticed. Then the scale proof:
# a 1,000-host x 200-round chaos cell (fail_rate failures + repair)
# must finish under a 60 s CPU wall budget, and `sparknet report` /
# `monitor` must render the simulated stream with zero special cases.
run_simfleet_stage() {
    sf="$tmp/sf"
    mkdir -p "$sf"
    python -m sparknet_tpu simfleet --record_real "$sf/rec.json" \
        --hosts 3 --rounds 7 --interval 0.1 --lease 0.5 \
        --round_s 0.12 --readmit_after 3 | tee "$sf/rec.out"
    grep -q "membership events" "$sf/rec.out"
    python -m sparknet_tpu simfleet --replay "$sf/rec.json" \
        | tee "$sf/replay.out"
    grep -q "REPLAY MATCH" "$sf/replay.out"

    start=$(date +%s)
    timeout -k 5 90 python -m sparknet_tpu simfleet \
        --hosts 1000 --rounds 200 --interval 0.2 --lease 0.6 \
        --round_s 0.15 --quorum 800 --recover_after 5 \
        --chaos "fail_rate=0.0002,fail_seed=7" \
        --metrics "$sf/fleet.jsonl" --json "$sf/fleet.json" \
        | tee "$sf/fleet.out"
    took=$(( $(date +%s) - start ))
    test "$took" -le 60 || { echo "1000x200 cell took ${took}s (> 60s)"
                             exit 1; }
    grep -q "fleet: 1000 hosts x 200 rounds" "$sf/fleet.out"
    python - "$sf" <<'EOF'
import json, sys, os
s = json.load(open(os.path.join(sys.argv[1], "fleet.json")))
assert s["rounds"] == 200 and not s["quorum_lost"], s
assert s["evictions"] > 0 and s["readmissions"] > 0, s
print(f"sim cell OK: {s['evictions']} evictions, "
      f"{s['readmissions']} readmissions, live {s['live_final']}/1000")
EOF
    python -m sparknet_tpu report "$sf/fleet.jsonl" | tee "$sf/rep.txt" \
        > /dev/null
    grep -q "fleet simulation" "$sf/rep.txt"
    grep -q "1000 virtual hosts x 200 rounds" "$sf/rep.txt"
    python -m sparknet_tpu monitor "$sf/fleet.jsonl" --once \
        | tee "$sf/mon.txt" > /dev/null
    grep -q "sim: 1000 hosts" "$sf/mon.txt"
    echo "simfleet stage OK: real run replayed event-exactly," \
         "1000x200 chaos cell in ${took}s, report+monitor rendered"
}

# ---------------------------------------------- fleet observability ----
# Cross-host trace correlation (ISSUE 16): the per-host metrics streams
# of a real 2-process run merge into one clock-aligned timeline via the
# heartbeat trace_align beacons, and the critical-path decomposition
# names the chaos-injected straggler from the metrics alone.
run_trace_stage() {
    tr="$tmp/trace"
    mkdir -p "$tr"
    port=$(python -c "import socket; s=socket.socket(); \
s.bind(('localhost',0)); print(s.getsockname()[1])")
    pids=()
    for i in 0 1; do
        SPARKNET_COORDINATOR="localhost:$port" \
        SPARKNET_NUM_PROCESSES=2 SPARKNET_PROCESS_ID=$i \
        SPARKNET_CHAOS="slow_host=1,slow_host_s=3,slow_host_round=2" \
        XLA_FLAGS="--xla_force_host_platform_device_count=4" \
        python -m sparknet_tpu cifar --workers 4 --hosts 2 --tau 2 \
            --rounds 4 --test-every 100 --metrics "$tr/run$i.jsonl" \
            --heartbeat-dir "$tr/rdv" --lease-s 5 \
            --heartbeat-interval 0.2 --quorum 2 \
            > "$tr/out$i.txt" 2>&1 &
        pids+=($!)
    done
    for i in 0 1; do
        rc=0; wait "${pids[$i]}" || rc=$?
        test "$rc" -eq 0 || { echo "trace host $i failed (rc=$rc):"
                              cat "$tr/out$i.txt"; exit 1; }
    done

    # one merged Chrome trace: a track per host, solved clock offsets
    python -m sparknet_tpu trace "$tr/run0.jsonl" "$tr/run1.jsonl" \
        --chrome "$tr/fleet.json" | tee "$tr/chrome.out"
    grep -q "2 host track(s)" "$tr/chrome.out"
    python - "$tr" <<'EOF'
import json, sys, os
doc = json.load(open(os.path.join(sys.argv[1], "fleet.json")))
names = [e for e in doc["traceEvents"]
         if e.get("ph") == "M" and e["name"] == "process_name"]
assert len(names) == 2, f"expected 2 host tracks, got {len(names)}"
offs = doc["otherData"]["clock_offsets"]
assert set(offs) == {"0", "1"}, offs
assert all(o["aligned"] for o in offs.values()), offs
gates = [e for e in doc["traceEvents"]
         if e.get("ph") == "X" and e["name"].startswith("gate")]
assert gates, "no gate events on the merged timeline"
print(f"chrome OK: 2 aligned host tracks, offsets "
      f"{[o['offset_s'] for o in offs.values()]}")
EOF

    # critical path: the chaos slow_host straggler named from metrics
    python -m sparknet_tpu trace "$tr/run0.jsonl" "$tr/run1.jsonl" \
        --critpath | tee "$tr/crit.out"
    grep -q "blocked on host 1" "$tr/crit.out"
    grep -q "chaos slow_host" "$tr/crit.out"
    grep -q "host 1: blocked" "$tr/crit.out"

    # report/monitor fleet sections render from the same stream, and
    # the JSON report carries the machine-readable alignment summary
    python -m sparknet_tpu report "$tr/run0.jsonl" | tee "$tr/rep.txt" \
        > /dev/null
    grep -q "fleet timeline" "$tr/rep.txt"
    python -m sparknet_tpu report "$tr/run0.jsonl" --format json \
        | python -c "
import json, sys
rep = json.load(sys.stdin)
assert rep['fleet']['beacons'] > 0, rep.get('fleet')
assert '0' in rep['fleet']['offsets'], rep['fleet']"

    # a simulated fleet cell flows through the SAME beacon path
    python -m sparknet_tpu simfleet --hosts 200 --rounds 30 \
        --interval 0.2 --lease 0.6 --round_s 0.15 \
        --chaos "slow_worker=7,slow_s=2,slow_round=10" \
        --metrics "$tr/sim.jsonl" > "$tr/sim.out" 2>&1
    python -m sparknet_tpu trace "$tr/sim.jsonl" --critpath \
        | tee "$tr/simcrit.out"
    grep -q "critical path (30 round(s)" "$tr/simcrit.out"
    echo "trace stage OK: merged Chrome trace with per-host clock" \
         "offsets, critpath named the chaos straggler"
}

if [ "$stage" = "trace" ]; then
    run_trace_stage
    echo "SMOKE OK (trace)"
    exit 0
fi
if [ "$stage" = "simfleet" ]; then
    run_simfleet_stage
    echo "SMOKE OK (simfleet)"
    exit 0
fi
if [ "$stage" = "fsdp" ]; then
    run_fsdp_stage
    echo "SMOKE OK (fsdp)"
    exit 0
fi
if [ "$stage" = "ingest" ]; then
    run_ingest_stage
    echo "SMOKE OK (ingest)"
    exit 0
fi
if [ "$stage" = "resize" ]; then
    run_resize_stage
    echo "SMOKE OK (resize)"
    exit 0
fi
if [ "$stage" = "serve" ]; then
    run_serve_stage
    echo "SMOKE OK (serve)"
    exit 0
fi
if [ "$stage" = "routefleet" ]; then
    run_routefleet_stage
    echo "SMOKE OK (routefleet)"
    exit 0
fi
if [ "$stage" = "multihost" ]; then
    run_multihost_stage
    echo "SMOKE OK (multihost)"
    exit 0
fi
if [ "$stage" = "async" ]; then
    run_async_stage
    echo "SMOKE OK (async)"
    exit 0
fi

cat > "$tmp/net.prototxt" <<'EOF'
name: "smoke_cifar_synth"
layer { name: "data" type: "JavaData" top: "data"
        java_data_param { shape { dim: 8 dim: 3 dim: 32 dim: 32 } } }
layer { name: "label" type: "JavaData" top: "label"
        java_data_param { shape { dim: 8 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 8 kernel_size: 5 stride: 2
                            weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "fc" type: "InnerProduct" bottom: "conv1" top: "fc"
        inner_product_param { num_output: 10
                              weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc" bottom: "label"
        top: "loss" }
EOF

cat > "$tmp/solver.prototxt" <<'EOF'
net: "net.prototxt"
base_lr: 0.01
lr_policy: "fixed"
display: 2
max_iter: 5
random_seed: 0
EOF

python -m sparknet_tpu train --solver "$tmp/solver.prototxt" \
    --iterations 5 --metrics "$tmp/run.jsonl" --profile "$tmp/trace"

python - "$tmp" <<'EOF'
import json, sys, os
tmp = sys.argv[1]
lines = open(os.path.join(tmp, "run.jsonl")).read().splitlines()
events = [json.loads(l) for l in lines]         # every line must parse
kinds = {e["event"] for e in events}
missing = {"step", "span", "comms", "recompile"} - kinds
assert not missing, f"missing event kinds: {missing} (got {sorted(kinds)})"
step = next(e for e in events if e["event"] == "step")
assert "host_ms" in step and "device_ms" in step, step
chrome = json.load(open(os.path.join(tmp, "trace", "spans.trace.json")))
assert chrome["traceEvents"], "empty chrome trace"
print(f"JSONL OK: {len(events)} events, kinds {sorted(kinds)}")
print(f"Chrome trace OK: {len(chrome['traceEvents'])} span events")
EOF

python -m sparknet_tpu report "$tmp/run.jsonl" --json "$tmp/report.json"

python - "$tmp" <<'EOF'
import json, sys, os
rep = json.load(open(os.path.join(sys.argv[1], "report.json")))
assert rep["steps"]["steps"] == 5, rep.get("steps")
assert rep["comms"]["h2d_bytes_total"] > 0
assert rep["phases"], "no per-phase breakdown"
print("report JSON OK")
EOF

# ------------------------------------------------- kill-and-resume stage ----
# Start a long run, SIGTERM it mid-run (the preemption notice): the default
# --sigterm_effect snapshot_stop must write an atomic snapshot and exit 0.
mkdir -p "$tmp/kr"
python -m sparknet_tpu train --solver "$tmp/solver.prototxt" \
    --iterations 200000 --metrics "$tmp/kr1.jsonl" \
    --snapshot-prefix "$tmp/kr/snap" &
pid=$!
sleep 12
kill -TERM "$pid"
wait "$pid"

resume_iter=$(python -c "
import json, sys
print(json.load(open('$tmp/kr/snap.latest.json'))['latest']['iter'])")
test "$resume_iter" -gt 0
echo "preempted at iter $resume_iter with a committed snapshot"

# Relaunch with --resume auto: the iter counter and loss curve continue.
python -m sparknet_tpu train --solver "$tmp/solver.prototxt" \
    --iterations $((resume_iter + 100)) --metrics "$tmp/kr2.jsonl" \
    --snapshot-prefix "$tmp/kr/snap" --resume auto | tee "$tmp/kr2.out"
grep -q "resume auto: restored iter $resume_iter" "$tmp/kr2.out"
grep -q "Optimization done, iter=$((resume_iter + 100))" "$tmp/kr2.out"

python - "$tmp" "$resume_iter" <<'EOF'
import json, sys, os
tmp, it0 = sys.argv[1], int(sys.argv[2])
evs = [json.loads(l) for l in open(os.path.join(tmp, "kr2.jsonl"))]
train = [e for e in evs if e["event"] == "train"]
assert train, "resumed run logged no train events"
assert all(e["iter"] >= it0 for e in train), \
    f"loss curve restarted below iter {it0}"
print(f"kill/resume OK: curve continued from iter {it0}")
EOF

# ------------------------------------------------------------ chaos stage ----
# An injected NaN at step 20 must roll back to last-known-good and the run
# must still complete to the target iter, with the recovery in the report.
python -m sparknet_tpu train --solver "$tmp/solver.prototxt" \
    --iterations 60 --metrics "$tmp/chaos.jsonl" \
    --snapshot-prefix "$tmp/chaos/snap" \
    --chaos "nan_step=20,seed=3" --recover 3 | tee "$tmp/chaos.out"
grep -q "Optimization done, iter=60" "$tmp/chaos.out"

python - "$tmp" <<'EOF'
import json, sys, os
evs = [json.loads(l) for l in open(os.path.join(sys.argv[1], "chaos.jsonl"))]
kinds = {(e["event"], e.get("kind")) for e in evs}
assert ("chaos", "nan") in kinds, kinds
assert ("recovery", "rollback") in kinds, kinds
print("chaos OK: injected NaN, observed rollback, run completed")
EOF
# (no -q: grep must drain the pipe, or report dies on BrokenPipeError)
python -m sparknet_tpu report "$tmp/chaos.jsonl" | grep "resilience" \
    > /dev/null

# ----------------------------------------------------- health stage ----
# Observability (ISSUE 3): a local-SGD run with a chaos stall pinned to
# worker 1 must produce metrics from which `sparknet report` renders a
# "training health" section with per-round divergence, the named
# straggler, and at least one health alarm; `sparknet monitor --once`
# must render the same stream; report/monitor on a missing file must be
# a one-line error, exit 2.
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m sparknet_tpu cifar --workers 2 --tau 3 --rounds 5 \
    --test-every 100 --metrics "$tmp/health.jsonl" \
    --chaos "stall_step=5,stall_s=3,stall_worker=1,stall_repeat=1" \
    --health-straggler-factor 1.25 --health-cooldown 1 \
    | tee "$tmp/health.out"

python -m sparknet_tpu report "$tmp/health.jsonl" | tee "$tmp/health.rep"
grep -q "training health" "$tmp/health.rep"
grep -q "per-round mean divergence" "$tmp/health.rep"
grep -q "straggler: worker 1" "$tmp/health.rep"
grep -qE "health alarms: [1-9]" "$tmp/health.rep"
python -m sparknet_tpu monitor "$tmp/health.jsonl" --once \
    | grep -q "divergence: mean"
echo "health stage OK: divergence measured, straggler named"

if python -m sparknet_tpu report "$tmp/does-not-exist.jsonl" \
    2> "$tmp/report.err"; then
    echo "report on a missing file should exit non-zero"; exit 1
fi
test "$(wc -l < "$tmp/report.err")" -eq 1
if python -m sparknet_tpu monitor "$tmp/does-not-exist.jsonl" --once \
    2> /dev/null; then
    echo "monitor on a missing file should exit non-zero"; exit 1
fi

# ------------------------------------------------- elasticity stage ----
# Robustness (ISSUE 4): chaos-kill worker 1 at round 2 of a 4-worker
# local-SGD run armed with --quorum 2: the run must COMPLETE on the
# survivors with finite losses, the per-worker eviction (and the
# cooldown readmission) must land in the metrics JSONL and render in
# `sparknet report`; a kill that breaks the quorum must exit 4.
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m sparknet_tpu cifar --workers 4 --tau 2 --rounds 8 \
    --test-every 100 --metrics "$tmp/elastic.jsonl" \
    --chaos "kill_worker=1,kill_round=2" \
    --quorum 2 --evict-after 1 --readmit-after 3 | tee "$tmp/elastic.out"
grep -q "EVICTED worker 1" "$tmp/elastic.out"

python - "$tmp" <<'EOF'
import json, math, sys, os
evs = [json.loads(l) for l in open(os.path.join(sys.argv[1],
                                                "elastic.jsonl"))]
ev = [e for e in evs if e["event"] == "eviction"]
assert ev and ev[0]["worker"] == 1 and ev[0]["reason"] == "chaos_kill", ev
rd = [e for e in evs if e["event"] == "readmission"]
assert rd and rd[0]["worker"] == 1, rd
rounds = [e for e in evs if e["event"] == "round"]
assert len(rounds) == 8, f"run did not complete: {len(rounds)}/8 rounds"
assert all(math.isfinite(e["loss"]) for e in rounds), \
    "a dead worker poisoned a round loss"
print("elastic OK: eviction + readmission recorded, run completed")
EOF

python -m sparknet_tpu report "$tmp/elastic.jsonl" | tee "$tmp/elastic.rep"
grep -q "elastic membership: " "$tmp/elastic.rep"
grep -q "evicted worker 1" "$tmp/elastic.rep"

# below-quorum must abort with the documented exit code 4 (DEPLOY.md)
rc=0
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m sparknet_tpu cifar --workers 2 --tau 2 --rounds 6 \
    --test-every 100 --chaos "kill_worker=0,kill_round=1" \
    --quorum 2 --evict-after 1 > "$tmp/quorum.out" 2>&1 || rc=$?
test "$rc" -eq 4 || { echo "expected exit 4 on quorum loss, got $rc"
                      cat "$tmp/quorum.out"; exit 1; }
grep -q "QUORUM LOST" "$tmp/quorum.out"
echo "elasticity stage OK: eviction survived, quorum loss exits 4"

run_async_stage

run_multihost_stage

run_serve_stage

run_ingest_stage

run_fsdp_stage

run_simfleet_stage

run_trace_stage

echo "SMOKE OK"
