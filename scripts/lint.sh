#!/usr/bin/env bash
# Static analysis gate: `sparknet lint --strict` over the package
# source with the committed baseline. Exits non-zero on ANY
# non-baselined finding, stale baseline entry, or baseline entry
# without a written justification (see README "Static analysis").
# jax-free: runs on any checkout, no accelerator stack needed.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m sparknet_tpu lint --strict \
    --baseline .sparknet-lint-baseline.json \
    --root . sparknet_tpu
