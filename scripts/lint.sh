#!/usr/bin/env bash
# Static analysis gate (see README "Static analysis"):
#
#   1. schema freshness — the committed event registry
#      (sparknet_tpu/obs/event_schema.py) must match what the repo
#      actually emits, or SPK401/402 are checking against stale truth
#   2. `sparknet lint --strict` over the package source with the
#      committed baseline: exits non-zero on ANY non-baselined
#      finding, stale baseline entry, or entry without a written
#      justification
#   3. relaxed per-tree passes: tests/ under the @tests profile
#      (parse + file-protocol + exit-code rules), scripts/ and
#      experiments/ under @tools (those plus the JAX host-sync
#      hazards)
#
# Every pass shares the content-hash result cache and a small worker
# pool. When $LINT_JSON_OUT is set, the strict pass's findings are
# also written there as JSON (CI uploads it as an artifact).
# jax-free: runs on any checkout, no accelerator stack needed.
set -euo pipefail
cd "$(dirname "$0")/.."

JOBS="${LINT_JOBS:-4}"

# 1. event-schema freshness: regenerate and diff
python -m sparknet_tpu lint --write-event-schema --root . >/dev/null
if ! git diff --quiet -- sparknet_tpu/obs/event_schema.py; then
    echo "lint.sh: sparknet_tpu/obs/event_schema.py is stale —" \
         "commit the regenerated file" >&2
    git --no-pager diff -- sparknet_tpu/obs/event_schema.py >&2
    exit 1
fi

# 2. the strict, baseline-gated package pass
if [ -n "${LINT_JSON_OUT:-}" ]; then
    python -m sparknet_tpu lint --json \
        --baseline .sparknet-lint-baseline.json \
        --root . sparknet_tpu > "$LINT_JSON_OUT" || true
fi
python -m sparknet_tpu lint --strict --cache --jobs "$JOBS" \
    --baseline .sparknet-lint-baseline.json \
    --root . sparknet_tpu

# 3. donation guard: SPK105 (missing buffer donation on an update jit)
#    must stay at ZERO findings repo-wide — every solver family donates
#    params/state/history, and new code keeps it that way. No baseline:
#    a single regression fails CI. (tests/fixtures holds the rule's own
#    intentional positive and is excluded everywhere.)
python -m sparknet_tpu lint --strict --cache --jobs "$JOBS" \
    --select SPK105 --exclude fixtures \
    --root . sparknet_tpu tests scripts experiments bench.py

# 4. relaxed per-tree profiles (the shared baseline stays empty)
python -m sparknet_tpu lint --strict --cache --jobs "$JOBS" \
    --select @tests --exclude fixtures \
    --baseline .sparknet-lint-baseline.json \
    --root . tests
python -m sparknet_tpu lint --strict --cache --jobs "$JOBS" \
    --select @tools \
    --baseline .sparknet-lint-baseline.json \
    --root . scripts experiments bench.py
