"""Local-SGD vs per-step DP to plateau — the SparkNet paper's core claim.

Runs ONE (strategy, tau, workers) configuration of the CifarApp comparison
(CifarApp.scala:92-135; paper arXiv:1511.06051 fig. 4) on the virtual CPU
mesh until the test-accuracy curve flattens, with test points matched in
IMAGES SEEN across configurations so curves are directly comparable.

Beyond the round-3 version (CONVERGENCE.md section 2, stopped at 216k images
with both curves still climbing) this driver:
  * stops on a plateau rule (last --flat-window test points within
    --flat-eps accuracy points of each other) instead of a fixed round count;
  * logs images_seen and cumulative communication volume with every record:
    DP pays one gradient allreduce per STEP, local SGD one weight average
    per ROUND — the 10x saving the paper claims, here measured in actual
    allreduce payload bytes (param_bytes each, identical payload per event
    since grads and weights are the same pytree).

Usage (the sweep driver experiments/run_plateau_sweep.sh runs the matrix):
    python experiments/plateau_cifar.py --strategy local_sgd --tau 10 \
        --workers 4 --data _work/cifar20k --metrics results/plateau_t10_w4.jsonl
"""

import argparse
import os
import sys

# Virtual CPU mesh: must win before any jax import (sitecustomize
# force-registers the axon TPU otherwise).

def _pre_jax(n_devices):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--strategy", choices=("local_sgd", "dp"), required=True)
    ap.add_argument("--tau", type=int, default=10)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--data", default="_work/cifar20k")
    ap.add_argument("--metrics", required=True)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--max-images", type=int, default=1_600_000)
    ap.add_argument("--min-images", type=int, default=400_000,
                    help="never declare plateau before this many images")
    ap.add_argument("--test-every-images", type=int, default=24_000)
    ap.add_argument("--flat-window", type=int, default=5)
    ap.add_argument("--flat-eps", type=float, default=0.6,
                    help="accuracy-percentage-point spread that counts "
                         "as flat over the window")
    args = ap.parse_args()

    if not os.path.isdir(args.data):
        sys.exit(f"--data {args.data} does not exist; CifarApp would fall "
                 f"back to gaussian noise and the curves would be "
                 f"meaningless. Create it: python -m sparknet_tpu "
                 f"make_synth_cifar {args.data} --train 20000 --test 2000")

    _pre_jax(args.workers)
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from sparknet_tpu.apps.cifar_app import CifarApp, TRAIN_BATCH
    from sparknet_tpu.utils.metrics import MetricsLogger

    app = CifarApp(num_workers=args.workers, data_dir=args.data,
                   strategy=args.strategy, tau=args.tau, seed=args.seed)
    solver = app.solver
    if os.path.exists(args.metrics):
        # MetricsLogger appends; a stale series under the same path would
        # interleave two runs into one unreadable curve
        os.rename(args.metrics, args.metrics + ".old")
    metrics = MetricsLogger(path=args.metrics)

    steps_per_round = args.tau if args.strategy == "local_sgd" else 1
    imgs_per_round = TRAIN_BATCH * app.num_workers * steps_per_round
    param_bytes = sum(np.prod(v.shape) * v.dtype.itemsize
                      for v in jax.tree_util.tree_leaves(solver.params))
    # one allreduce per round in both strategies; a DP "round" is one step
    # (gradient pmean), a local-SGD round is tau steps (weight pmean)
    events_per_round = 1
    app.log(f"plateau driver: {args.strategy} tau={args.tau} "
            f"workers={app.num_workers} imgs/round={imgs_per_round} "
            f"test every {args.test_every_images} images "
            f"param_bytes={param_bytes}")

    accs = []           # (images_seen, accuracy)
    images_seen = 0
    rounds = 0
    import time
    t0 = time.time()

    scores = None
    next_test_at = 0    # test when images_seen first crosses k*test_every
    plateaued = False
    while images_seen < args.max_images:
        if images_seen >= next_test_at:
            next_test_at = (images_seen // args.test_every_images + 1) \
                * args.test_every_images
            scores = app.run_test()
            acc = next((v for k, v in scores.items() if "accuracy" in k),
                       None)
            comm = rounds * events_per_round * param_bytes
            metrics.log("test", round=rounds, images_seen=images_seen,
                        allreduces=rounds * events_per_round,
                        comm_bytes=int(comm), **scores)
            acc_s = f"{acc:.4f}" if acc is not None else "?"
            app.log(f"[{images_seen}] acc={acc_s} "
                    f"allreduces={rounds * events_per_round} "
                    f"({time.time() - t0:.0f}s)")
            if acc is not None:
                accs.append((images_seen, acc))
            w = args.flat_window
            if (len(accs) >= w and images_seen >= args.min_images
                    and (max(a for _, a in accs[-w:])
                         - min(a for _, a in accs[-w:])) * 100
                    <= args.flat_eps):
                app.log(f"PLATEAU at {images_seen} images: last {w} points "
                        f"within {args.flat_eps} pts")
                plateaued = True
                break
        if args.strategy == "local_sgd":
            loss = solver.train_round(app._tau_batches(solver.tau))
        else:
            imgs, labs = app._train_arrays(TRAIN_BATCH * app.num_workers)
            loss = solver.train_step({"data": imgs, "label": labs})
        loss = float(loss)
        rounds += 1
        images_seen += imgs_per_round
        if rounds % 10 == 0:
            metrics.log("round", round=rounds, images_seen=images_seen,
                        loss=loss, iter=solver.iter,
                        images_per_s=round(images_seen
                                           / max(time.time() - t0, 1e-9), 1))

    final = scores if plateaued and scores is not None else app.run_test()
    metrics.log("final", round=rounds, images_seen=images_seen,
                allreduces=rounds * events_per_round,
                comm_bytes=int(rounds * events_per_round * param_bytes),
                param_bytes=int(param_bytes), plateau=plateaued, **final)
    metrics.close()
    app.log(f"done: {images_seen} images, {rounds} rounds, "
            f"{rounds * events_per_round} allreduces, final {final}")


if __name__ == "__main__":
    main()
