#!/bin/bash
# Round-5 hard-mode plateau sweep (VERDICT r4 item 4): label_noise=0.3
# surrogate caps attainable accuracy at 0.73, so DP-vs-local-SGD runs in a
# contested band. Sequential on purpose (one core); tau=1 runs to the
# plateau RULE like every other row (no special budget cap).
#
# flat-eps 1.75: the 0.3 label noise keeps test accuracy oscillating
# ~+-1.5pt at the plateau, which a 1.0pt flatness rule cannot see (the
# round-5 dp_w4 row was launched at eps 1.0 before this was measured and
# ran to the image cap; its curve is still the full record).
cd "$(dirname "$0")/.."
P=experiments/plateau_cifar.py
L=_work/plateau
mkdir -p results $L
COMMON="--data _work/cifar20k_hard --min-images 360000 --max-images 1200000 --flat-window 5 --flat-eps 1.75"
run() {
    name=$1; shift
    echo "=== $name: $* ==="
    python $P "$@" $COMMON --metrics results/plateau_hard_${name}.jsonl \
        > $L/hard_${name}.log 2>&1
    echo "=== $name done rc=$? ==="
}
run dp_w4  --strategy dp --workers 4
run t10_w4 --strategy local_sgd --tau 10 --workers 4
run t50_w4 --strategy local_sgd --tau 50 --workers 4
run t1_w4  --strategy local_sgd --tau 1 --workers 4
echo "HARD SWEEP COMPLETE"
