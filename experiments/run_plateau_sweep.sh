#!/bin/bash
# Local-SGD vs DP plateau sweep (VERDICT r3 item 1). Sequential on purpose:
# the box has one core; parallel runs would just contend. Headline pair
# first so partial results are already meaningful.
cd "$(dirname "$0")/.."
mkdir -p results _work
P=experiments/plateau_cifar.py
L=_work/plateau
mkdir -p $L
run() {
    name=$1; shift
    echo "=== $name: $* ==="
    python $P "$@" --metrics results/plateau_${name}.jsonl \
        > $L/${name}.log 2>&1
    echo "=== $name done rc=$? ==="
}
run t10_w4 --strategy local_sgd --tau 10 --workers 4
run dp_w4  --strategy dp --workers 4
run t50_w4 --strategy local_sgd --tau 50 --workers 4
run t10_w8 --strategy local_sgd --tau 10 --workers 8
run t1_w4  --strategy local_sgd --tau 1 --workers 4 --max-images 800000
echo "SWEEP COMPLETE"
