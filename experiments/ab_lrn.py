"""Interleaved A/B: SPARKNET_LRN=xla vs pallas fused LRN (CaffeNet/GoogLeNet)."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/sparknet_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

from sparknet_tpu.models import zoo
from sparknet_tpu.proto import Message
from sparknet_tpu.solver.solver import Solver

MODEL = sys.argv[1] if len(sys.argv) > 1 else "caffenet"
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 256
ITERS = 20
ROUNDS = 6

side = 227 if MODEL == "caffenet" else 224
rs = np.random.RandomState(0)
batch = {"data": jnp.asarray(rs.randn(BATCH, 3, side, side), jnp.bfloat16),
         "label": jnp.asarray(rs.randint(0, 1000, BATCH), jnp.int32)}

solvers = {}
for v in ("xla", "pallas"):
    os.environ["SPARKNET_LRN"] = v
    sp = Message("SolverParameter", base_lr=0.01, lr_policy="fixed",
                 momentum=0.9, weight_decay=0.0005, display=0,
                 random_seed=0)
    net = getattr(zoo, MODEL)(batch_size=BATCH, num_classes=1000)
    s = Solver(sp, net_param=net)
    for _ in range(3):
        loss = s.train_step(batch)
    float(loss)
    solvers[v] = s
    print("compiled lrn", v, "loss", float(loss), file=sys.stderr)

dts = {v: [] for v in solvers}
for r in range(ROUNDS):
    for v in solvers:
        s = solvers[v]
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = s.train_step(batch)
        float(loss)
        dts[v].append(time.perf_counter() - t0)

out = {}
for v, ds in dts.items():
    rates = sorted(BATCH * ITERS / dt for dt in ds)
    out[v] = {"best": round(rates[-1], 1),
              "median": round(rates[len(rates) // 2], 1),
              "worst": round(rates[0], 1)}
print(json.dumps({"model": MODEL, "knob": "lrn", "batch": BATCH, "img_per_sec": out}))
