"""tau x s sweep: bounded-staleness async local SGD vs the synchronous
barrier, under a straggler (ISSUE 7; ROADMAP item 5; CONVERGENCE.md
section 6 is the writeup).

The SparkNet paper positions synchronous tau-interval averaging against
downpour-style async SGD but never ships the comparison. This driver
settles it at experiment scale: every (workload, tau, mode) cell trains
the SAME model on the SAME data for the SAME total number of local
steps, with a chaos ``slow_worker`` making worker 1 pay ``--slow-s``
extra seconds per round — the persistent straggler both update rules
must live with:

  * mode "sync"  — the paper's barrier: the collect & average waits for
    the straggler every round, so wall clock tracks the MAX worker.
  * mode "s=K"   — bounded staleness: the round proceeds at the median
    worker's pace; the straggler's push is discounted by decay**lag and
    parked past the bound (resync = readmission from the consensus).

Measured per cell: wall clock (post-compile), mean round latency, final
eval (accuracy for the CIFAR surrogate, CE nats for the LM), parks /
unparks, and the straggler's max version lag. Rows land as ``sweep``
events in results/tau_s_<workload>.jsonl; a markdown table prints at
the end for CONVERGENCE.md.

Usage:
    python experiments/tau_s_sweep.py --workload cifar \
        --metrics results/tau_s_cifar.jsonl
    python experiments/tau_s_sweep.py --workload lm \
        --metrics results/tau_s_lm.jsonl
"""

import argparse
import json
import os
import sys
import time


def _pre_jax(n_devices):
    # must win before any jax import (sitecustomize force-registers the
    # axon TPU otherwise) — the tests/conftest.py discipline
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()


sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_cifar(workers, batch):
    """CIFAR-surrogate workload: a compact conv net (conv-pool-conv-fc,
    the cifar10_quick shape at experiment scale) on the shape-texture
    3x32x32 surrogate — the learnable zero-egress stand-in the repo's
    convergence artifacts use throughout (CONVERGENCE.md)."""
    import numpy as np
    from sparknet_tpu.proto import Message
    from sparknet_tpu.data.synthetic import shape_texture_images

    def net(b):
        n = Message("NetParameter", name="cifar_sweep")
        n.add("layer", name="data", type="JavaData", top=["data"],
              java_data_param=dict(shape=dict(dim=[b, 3, 32, 32])))
        n.add("layer", name="label", type="JavaData", top=["label"],
              java_data_param=dict(shape=dict(dim=[b])))
        n.add("layer", name="conv1", type="Convolution", bottom=["data"],
              top=["conv1"], convolution_param=dict(
                  num_output=16, kernel_size=[5], stride=[2],
                  weight_filler=dict(type="xavier")))
        n.add("layer", name="relu1", type="ReLU", bottom=["conv1"],
              top=["conv1"])
        n.add("layer", name="pool1", type="Pooling", bottom=["conv1"],
              top=["pool1"], pooling_param=dict(pool="MAX", kernel_size=3,
                                                stride=2))
        n.add("layer", name="conv2", type="Convolution", bottom=["pool1"],
              top=["conv2"], convolution_param=dict(
                  num_output=16, kernel_size=[3],
                  weight_filler=dict(type="xavier")))
        n.add("layer", name="relu2", type="ReLU", bottom=["conv2"],
              top=["conv2"])
        n.add("layer", name="ip1", type="InnerProduct", bottom=["conv2"],
              top=["ip1"], inner_product_param=dict(
                  num_output=10, weight_filler=dict(type="xavier")))
        n.add("layer", name="acc", type="Accuracy",
              bottom=["ip1", "label"], top=["accuracy"])
        n.add("layer", name="loss", type="SoftmaxWithLoss",
              bottom=["ip1", "label"], top=["loss"])
        return n

    ti, tl = shape_texture_images(4096, seed=0)
    vi, vl = shape_texture_images(512, seed=1)
    ti = np.asarray(ti, np.float32)
    vi = np.asarray(vi, np.float32)
    # mean-subtract + scale to ~unit range (the 0-255 pixel scale with
    # xavier init and momentum diverges at any useful lr)
    mean = ti.mean(0)
    ti = (ti - mean) / 64.0
    vi = (vi - mean) / 64.0
    tl, vl = np.asarray(tl, np.int32), np.asarray(vl, np.int32)

    def batch_fn(tau, seed):
        r = np.random.RandomState(seed)
        idx = r.randint(0, len(ti), tau * workers * batch)
        return {"data": ti[idx].reshape(tau, workers * batch, 3, 32, 32),
                "label": tl[idx].reshape(tau, workers * batch)}

    def eval_fn(solver):
        it = iter({"data": vi[i:i + batch], "label": vl[i:i + batch]}
                  for i in range(0, 512 - batch + 1, batch))
        scores = solver.test(it, num_iters=512 // batch)
        return {"accuracy": float(np.mean(scores["accuracy"])),
                "eval_loss": float(np.mean(scores["loss"]))}

    sp = dict(base_lr=0.02, momentum=0.9, lr_policy="fixed",
              random_seed=0, display=0)
    return net(batch), sp, batch_fn, eval_fn, "accuracy"


def build_lm(workers, batch):
    """LM workload: a tiny decoder-only transformer on the synthetic
    bigram corpus (floor = corpus bigram entropy, logged in the row)."""
    import numpy as np
    from sparknet_tpu.models import zoo
    from sparknet_tpu.data.synthetic import bigram_corpus

    seq = 32
    net = zoo.transformer_lm(vocab_size=64, seq_len=seq,
                             batch_size=batch, d_model=64, num_layers=2,
                             num_heads=4, flash=False)
    # ONE bigram corpus for train and eval (each lm_batch_stream seed
    # would draw a different transition matrix — a train/eval
    # distribution mismatch, not a held-out set)
    sample, floor = bigram_corpus(64, seed=0)

    def draw(n, rng):
        toks = sample(n, seq, rng)
        return {"data": toks[:, :-1].astype(np.int32),
                "label": toks[:, 1:].astype(np.int32)}

    cache = {}

    def batch_fn(tau, seed):
        # deterministic per (tau, seed): every mode sees identical data
        key = (tau, seed)
        if key not in cache:
            rng = np.random.RandomState(1000 + seed)
            ds = [draw(workers * batch, rng) for _ in range(tau)]
            cache[key] = {k: np.stack([d[k] for d in ds])
                          for k in ds[0]}
        return cache[key]

    probe_rng = np.random.RandomState(9)
    probe_batches = [draw(batch, probe_rng) for _ in range(8)]

    def eval_fn(solver):
        scores = solver.test(iter(list(probe_batches)), num_iters=8)
        return {"eval_ce": float(np.mean(scores["loss"])),
                "floor": round(floor, 4)}

    sp = dict(base_lr=3e-3, lr_policy="fixed", type="Adam",
              random_seed=0, display=0)
    return net, sp, batch_fn, eval_fn, "eval_ce"


def run_cell(workload, tau, mode, args, metrics):
    import numpy as np
    from sparknet_tpu.proto import Message
    from sparknet_tpu.parallel import LocalSGDSolver, make_mesh
    from sparknet_tpu.resilience.chaos import ChaosMonkey

    builder = build_cifar if workload == "cifar" else build_lm
    net, sp_kw, batch_fn, eval_fn, metric = builder(args.workers,
                                                    args.batch)
    sp = Message("SolverParameter", **sp_kw)
    s = LocalSGDSolver(sp, net_param=net, tau=tau,
                       mesh=make_mesh({"data": args.workers}),
                       log_fn=None)
    if mode != "sync":
        s.arm_staleness(int(mode.split("=")[1]), decay=args.s_decay)
    chaos = ChaosMonkey(slow_worker=1, slow_s=args.slow_s, log_fn=None)
    s.chaos = chaos
    if s.elastic is not None:
        s.elastic.chaos = chaos
    rounds = args.steps // tau
    s.train_round(batch_fn(tau, 0))            # warm-up (compile) round
    t0 = time.perf_counter()
    lat = []
    for r in range(1, rounds):
        r0 = time.perf_counter()
        s.train_round(batch_fn(tau, r))
        lat.append(time.perf_counter() - r0)
    wall = time.perf_counter() - t0
    ev = eval_fn(s)
    el = s.elastic
    row = {"workload": workload, "tau": tau, "mode": mode,
           "workers": args.workers, "batch_per_worker": args.batch,
           "local_steps": rounds * tau, "rounds": rounds,
           "slow_s": args.slow_s, "s_decay": args.s_decay,
           "wall_s": round(wall, 2),
           "round_s_mean": round(float(np.mean(lat)), 3) if lat else None,
           "parks": len(el.parks) if el is not None else 0,
           "unparks": len(el.unparks) if el is not None else 0,
           "straggler_max_lag": int(max(
               (p["lag"] or 0) for p in el.parks)) if el is not None
           and el.parks else 0,
           **{k: round(v, 4) for k, v in ev.items()}}
    s.close()
    metrics.log("sweep", **row)
    print(json.dumps(row))
    return row, metric


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workload", choices=("cifar", "lm"),
                    default="cifar")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--batch", type=int, default=32,
                    help="per-worker batch size")
    ap.add_argument("--steps", type=int, default=48,
                    help="total LOCAL steps per cell (rounds = steps/tau "
                         "— every cell sees the same optimization "
                         "budget)")
    ap.add_argument("--taus", default="2,8")
    ap.add_argument("--modes", default="sync,s=0,s=1,s=3")
    ap.add_argument("--slow-s", type=float, default=0.5,
                    help="chaos slow_worker: worker 1's extra seconds "
                         "per round")
    ap.add_argument("--s-decay", type=float, default=0.5)
    ap.add_argument("--metrics", default=None)
    args = ap.parse_args()
    _pre_jax(args.workers * 2)

    from sparknet_tpu.utils.metrics import MetricsLogger
    metrics = MetricsLogger(args.metrics) if args.metrics \
        else MetricsLogger(stream=sys.stderr)
    rows, metric = [], None
    for tau in [int(t) for t in args.taus.split(",")]:
        for mode in args.modes.split(","):
            row, metric = run_cell(args.workload, tau, mode.strip(),
                                   args, metrics)
            rows.append(row)
    metrics.close()

    # the CONVERGENCE.md table
    print(f"\n| tau | mode | wall s | round s | {metric} | parks |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['tau']} | {r['mode']} | {r['wall_s']} | "
              f"{r['round_s_mean']} | {r[metric]} | {r['parks']} |")


if __name__ == "__main__":
    main()
