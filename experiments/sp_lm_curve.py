"""Ring-attention LM training curve: d=512 LM, sequence sharded 4-way.

The trained-curve evidence for sequence parallelism at real model width
(VERDICT r3 item 7): tests/test_seq_parallel.py proves curve-equality at
toy size; this runs the d=512 x 6-layer LM (the bench toy config) on the
virtual 4-device CPU mesh with S sharded over a "seq" axis, against the
IDENTICAL single-device run, on the synthetic bigram corpus whose
entropy floor makes the curve checkable. Writes both curves + the final
comparison to results/sp_lm_curve.jsonl and exits nonzero if the curves
diverge beyond tolerance.

    nice -n 19 python experiments/sp_lm_curve.py
"""

import argparse
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--rtol", type=float, default=0.02,
                    help="per-step relative tolerance between curves")
    ap.add_argument("--out", default="results/sp_lm_curve.jsonl")
    args = ap.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        f" --xla_force_host_platform_device_count={args.sp}"
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from sparknet_tpu.proto import Message
    from sparknet_tpu.models import zoo
    from sparknet_tpu.solver.solver import Solver
    from sparknet_tpu.parallel import make_mesh, SeqParallelSolver
    from sparknet_tpu.data.synthetic import bigram_corpus, lm_batch_stream
    from sparknet_tpu.utils.metrics import MetricsLogger

    if os.path.exists(args.out):
        os.rename(args.out, args.out + ".old")
    metrics = MetricsLogger(path=args.out)
    _, floor = bigram_corpus(args.vocab, seed=3)
    metrics.log("config", steps=args.steps, batch=args.batch,
                seq_len=args.seq_len, d_model=args.d_model,
                layers=args.layers, vocab=args.vocab, sp=args.sp,
                entropy_floor=round(float(floor), 4))

    def batches():
        stream, _ = lm_batch_stream(args.vocab, args.batch, args.seq_len,
                                    seed=3)
        return [next(stream) for _ in range(args.steps)]

    def run(tag, solver):
        import time
        t0 = time.time()
        curve = []
        for i, b in enumerate(batches()):
            loss = float(solver.train_step(b))
            curve.append(loss)
            if (i + 1) % 10 == 0:
                metrics.log("step", run=tag, step=i + 1, loss=round(loss, 5),
                            elapsed=round(time.time() - t0, 1))
                print(f"{tag} step {i+1}: {loss:.4f} "
                      f"({time.time()-t0:.0f}s)", flush=True)
        return curve

    def sp_msg():
        return Message("SolverParameter", base_lr=0.2, lr_policy="fixed",
                       momentum=0.9, display=0, random_seed=0)

    def net(ring):
        return zoo.transformer_lm(
            vocab_size=args.vocab, seq_len=args.seq_len,
            batch_size=args.batch, d_model=args.d_model,
            num_layers=args.layers, num_heads=4, flash=False, ring=ring)

    ref = run("single_device", Solver(sp_msg(), net_param=net(False)))
    got = run(f"seq_sharded_{args.sp}way",
              SeqParallelSolver(sp_msg(),
                                mesh=make_mesh({"data": 1, "seq": args.sp}),
                                net_param=net(True)))

    err = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(ref, got))
    ok = bool(err <= args.rtol and got[-1] < got[0] - 0.5)
    metrics.log("final", max_rel_err=round(float(err), 5),
                final_single=round(ref[-1], 5), final_sp=round(got[-1], 5),
                first=round(ref[0], 5),
                entropy_floor=round(float(floor), 4), ok=ok)
    metrics.close()
    print(f"max rel err {err:.4%}; single {ref[-1]:.4f} vs sp {got[-1]:.4f} "
          f"(floor {floor:.4f}) -> {'OK' if ok else 'DIVERGED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
