"""conv1-only fwd+bwd microbench: s2d on vs off (why the end-to-end lost)."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/sparknet_jax_cache")

from tests.test_layers import make_layer

BATCH = 256
ITERS = 50
ROUNDS = 5

cases = {
    "caffenet_conv1": ((BATCH, 3, 227, 227), 96, 11, 4, 0),
    "googlenet_conv1": ((BATCH, 3, 224, 224), 64, 7, 2, 3),
}

out = {}
for name, (shape, o, k, s, p) in cases.items():
    layer, _ = make_layer(
        "Convolution", [shape],
        convolution_param=dict(num_output=o, kernel_size=[k], stride=[s],
                               pad=[p]))
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(*layer.weight_shape) * 0.01, jnp.bfloat16)
    b = jnp.zeros((o,), jnp.bfloat16)
    x = jnp.asarray(rs.randn(*shape), jnp.bfloat16)

    fns = {}
    for v in ("off", "on"):
        os.environ["SPARKNET_CONV_S2D"] = v

        def step(wv, xv):
            def f(wv):
                (y,) = layer.apply([wv, b], [xv], True, None)
                return (y.astype(jnp.float32) ** 2).sum()
            l, g = jax.value_and_grad(f)(wv)
            return l, g
        fns[v] = jax.jit(step)
        l, g = fns[v](w, x)
        float(l)
    res = {v: [] for v in fns}
    for r in range(ROUNDS):
        for v in fns:
            t0 = time.perf_counter()
            for _ in range(ITERS):
                l, g = fns[v](w, x)
            float(l)
            res[v].append((time.perf_counter() - t0) / ITERS * 1000)
    out[name] = {v: round(sorted(ds)[len(ds) // 2], 3)
                 for v, ds in res.items()}
print(json.dumps({"batch": BATCH, "median_ms_per_step": out}))
