"""Headline benchmark: CaffeNet (AlexNet-class) training throughput.

Reference baseline (BASELINE.md): stock Caffe trains CaffeNet at 256-image
batches in 26.5 s / 20 iters on a K40 (~193 img/s), 19.2 s with cuDNN
(~267 img/s). We time the same workload — batch 256, 227x227, full
forward+backward+momentum-SGD update — as ONE jitted XLA step, mixed
precision (fp32 params, bf16 activations driving the MXU).

stdout: ONE JSON line {"metric", "value", "unit", "vs_baseline"} — the
synthetic-fed headline number (input pipeline excluded, like the reference's
in-memory LMDB page cache).
stderr: supplementary rows ("#BENCH {...}"): host-fed throughput (uint8
source batches shipped raw; crop/mirror/mean runs INSIDE the jitted step —
the honest end-to-end number, with a transfer-vs-compute breakdown), a
batch-512 variant, GoogLeNet, and transformer-LM rows at toy and real
scale. All rows also land in bench_details.json.

Every timed row runs N windows (default 5, --windows N): the headline value
is the BEST window (the shared tunneled chip varies ~2x run to run and the
best window is the least-contended estimate of chip capability), and each
row carries min/median/max across windows so the spread is part of the
record, not a caveat.
"""

import json
import sys
import time

import numpy as np

BASELINE_IMG_PER_SEC = 267.0   # K40 + cuDNN, caffe/docs/performance_hardware.md:19-25
WARMUP = 3
ITERS = 20
WINDOWS = 5

# bf16 peak FLOP/s by device kind (public TPU specs; MFU denominators)
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v5": 459e12, "TPU v5p": 459e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def model_train_flops_per_image(solver):
    """Analytic MXU FLOPs: 2*MACs forward for conv/fc, x3 for training
    (grad wrt activations + grad wrt weights each re-run the matmuls).
    Elementwise/LRN/pool FLOPs are excluded — this is the standard MFU
    numerator, so the reported MFU slightly *understates* utilization."""
    net = solver.net
    fwd = 0
    batch = None
    for lp, impl, bottoms, tops in net.layers:
        if lp.type == "Convolution":
            out = net.blob_shapes[tops[0]]
            n, co, ho, wo = out
            batch = batch or n
            ci = net.blob_shapes[bottoms[0]][1]
            cp = lp.convolution_param
            ks = [int(x) for x in cp.kernel_size]
            if ks:
                kh = kw = ks[0]
            else:                        # DSL nets use kernel_h/kernel_w
                kh = int(cp.kernel_h)
                kw = int(cp.kernel_w)
            group = int(cp.group) if cp.has("group") else 1
            fwd += 2 * n * co * ho * wo * (ci // group) * kh * kw
        elif lp.type == "InnerProduct":
            out = net.blob_shapes[tops[0]]
            n = out[0]
            batch = batch or n
            cin = int(np.prod(net.blob_shapes[bottoms[0]][1:]))
            fwd += 2 * n * out[1] * cin
    return 3 * fwd // (batch or 1)


def _time_windows(step, sync, iters=ITERS, windows=None):
    """Time `iters` steps per window, `windows` times. -> (best_dt, [dts]).
    Best-of-N is the headline (least-contended window on a shared chip);
    the full list feeds the min/median/max spread in each row."""
    dts = []
    for _ in range(windows or WINDOWS):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step()
        sync(out)   # value fetch = true sync (block_until_ready returns
        # immediately under the axon TPU tunnel, inflating throughput ~200x)
        dts.append(time.perf_counter() - t0)
    return min(dts), dts


def _rate_stats(unit_per_window, dts):
    """Per-window rates -> {"min","median","max","windows"} (rounded)."""
    rates = sorted(unit_per_window / dt for dt in dts)
    n = len(rates)
    med = rates[n // 2] if n % 2 else 0.5 * (rates[n // 2 - 1]
                                             + rates[n // 2])
    return {"min": round(rates[0], 1), "median": round(med, 1),
            "max": round(rates[-1], 1), "windows": n}


def _mem_cols(solver, batch):
    """Peak-HBM columns from the compiled step's memory_analysis —
    XLA's own accounting of what the step RESIDES in, per device (the
    number that decides whether a model fits, where throughput only
    says how fast it runs). Empty when the backend has no analysis."""
    try:
        ms = solver.compiled_memory_stats(batch)
    except Exception:
        return {}
    if not ms:
        return {}
    mb = 1.0 / 2 ** 20
    return {"peak_hbm_mb": round(ms["peak_bytes"] * mb, 2),
            "hbm_argument_mb": round(ms["argument_bytes"] * mb, 2),
            "hbm_temp_mb": round(ms["temp_bytes"] * mb, 2)}


def _mk_solver(net_param, base_lr=0.01, compute_dtype=None):
    from sparknet_tpu.proto import Message
    from sparknet_tpu.solver.solver import Solver
    sp = Message("SolverParameter", base_lr=base_lr, lr_policy="fixed",
                 momentum=0.9, weight_decay=0.0005, display=0, random_seed=0)
    return Solver(sp, net_param=net_param, compute_dtype=compute_dtype)


def bench_synthetic(name, net_param, batch_size, shape, classes, peak):
    import jax.numpy as jnp
    solver = _mk_solver(net_param)
    rs = np.random.RandomState(0)
    data = jnp.asarray(rs.randn(batch_size, *shape), jnp.bfloat16)
    label = jnp.asarray(rs.randint(0, classes, batch_size), jnp.int32)
    batch = {"data": data, "label": label}
    for _ in range(WARMUP):
        loss = solver.train_step(batch)
    float(loss)
    dt, dts = _time_windows(lambda: solver.train_step(batch), float)
    img_s = batch_size * ITERS / dt
    flops = model_train_flops_per_image(solver)
    row = {"model": name, "mode": "synthetic", "batch": batch_size,
           "images_per_sec": round(img_s, 2),
           "images_per_sec_spread": _rate_stats(batch_size * ITERS, dts),
           "train_gflops_per_image": round(flops / 1e9, 2),
           "model_tflops_per_sec": round(img_s * flops / 1e12, 2)}
    if peak:
        row["mfu"] = round(img_s * flops / peak, 4)
    return row, solver


def bench_hostfed(name, net_param, batch_size, src_size, crop, classes,
                  peak):
    """The honest end-to-end row, transfer-minimal by design: the host
    ships the RAW uint8 source batch (src_size^2*3 bytes/img — 3.2x fewer
    than float32 crops) plus per-image crop/mirror draws, and the jitted
    step crops/mirrors/mean-subtracts on-chip (data/device_transform.py,
    semantics of reference data_transformer.cpp:42-51). A prefetch worker
    device_puts ahead of the step, so transfer overlaps compute.

    Also measures the two legs separately — pure H2D transfer of one
    uint8 batch, and the device step with a resident batch — so the row
    records *why* end-to-end lands where it does: good overlap means
    end-to-end ~= max(transfer, step).

    The input-pipeline levers (PERF.md "Input pipeline") are read from
    their SPARKNET_* env vars, so one env var flips this row between the
    raw baseline and any lever arm: SPARKNET_WIRE re-encodes the shipped
    batch (data/wire.py — h2d_kb_per_image reports the ACTUAL shipped
    bytes), SPARKNET_STAGING=on routes the feed through the rotating-slot
    H2DStager, SPARKNET_ECHO=E serves each transferred batch E times with
    fresh crop/mirror draws."""
    import os
    import jax
    import jax.numpy as jnp
    from sparknet_tpu.data.prefetch import (PrefetchIterator, H2DStager,
                                            EchoIterator)
    from sparknet_tpu.data.device_transform import DeviceTransformer
    from sparknet_tpu.data.transforms import DataTransformer
    from sparknet_tpu.data.wire import (WireCodec, wire_mode_from_env,
                                        wire_bits_from_env)
    from sparknet_tpu.proto import Message

    solver = _mk_solver(net_param)
    tp = Message("TransformationParameter", crop_size=crop, mirror=1)
    tp.mean_value.extend([104.0, 117.0, 123.0])
    host_t = DataTransformer(tp, phase=0, rng=np.random.RandomState(1))
    devt = DeviceTransformer(host_t)
    rec_shape = (3, src_size, src_size)

    rs = np.random.RandomState(0)
    pool = rs.randint(0, 256, (batch_size * 2, 3, src_size, src_size),
                      dtype=np.uint8)
    labels = rs.randint(0, classes, batch_size * 2).astype(np.int32)
    prng = np.random.RandomState(2)

    wire_mode = wire_mode_from_env()
    echo = max(1, int(os.environ.get("SPARKNET_ECHO", "1") or 1))
    staging = os.environ.get("SPARKNET_STAGING", "") == "on"
    codec = WireCodec(devt, rec_shape, mode=wire_mode,
                      bits=wire_bits_from_env(), sample=pool) \
        if wire_mode != "raw" else None
    if echo > 1 and codec is not None and codec.precrop:
        raise ValueError("SPARKNET_ECHO > 1 is incompatible with a "
                         "precrop wire mode (crops are baked into the "
                         "shipped bytes)")

    inner0 = devt.device_fn(precropped=codec.precrop if codec else False)

    def cast_fn(b):
        # match the synthetic row's activation dtype (bf16) so the two
        # rows isolate the input pipeline, not a compute-dtype change
        b = inner0(b)
        b["data"] = b["data"].astype(jnp.bfloat16)
        return b
    tf = codec.device_fn(inner=cast_fn) if codec else cast_fn
    over = codec.raw_overrides(batch_size) if codec \
        else devt.raw_overrides(batch_size, rec_shape)
    solver.set_input_transform(tf, raw_overrides=over)

    def host_batch():
        idx = prng.randint(0, len(pool) - batch_size + 1)
        b = {"data": pool[idx:idx + batch_size],
             "label": labels[idx:idx + batch_size],
             **devt.aux(batch_size, rec_shape)}
        return codec.encode(b) if codec else b

    # ACTUAL shipped bytes per image (pixel wire + labels + aux draws)
    kb_per_image = sum(v.nbytes for v in host_batch().values()) \
        / batch_size / 1024.0

    def _sync_d(d):
        return float(jnp.sum(d["data"].ravel()[:4].astype(jnp.float32)))

    # leg 1: pure H2D transfer (encoded batch + aux), synced per batch
    def put_once():
        return {k: jax.device_put(v) for k, v in host_batch().items()}
    _sync_d(put_once())
    t_dt, t_dts = _time_windows(put_once, _sync_d, iters=5, windows=3)
    transfer_img_s = batch_size * 5 / t_dt

    # leg 2: device step with a RESIDENT raw batch (no transfer in loop)
    resident = put_once()
    for _ in range(WARMUP):
        loss = solver.train_step(resident)
    float(loss)
    s_dt, _ = _time_windows(lambda: solver.train_step(resident), float,
                            windows=3)
    step_img_s = batch_size * ITERS / s_dt

    # end to end: the feed staged ahead of the step in a prefetch worker —
    # rotating-slot non-blocking staging when SPARKNET_STAGING=on, the
    # classic blocking device_put-in-worker otherwise
    stager = H2DStager(slots=2) if staging else None

    def produce():
        while True:
            if stager is not None:
                yield host_batch()
            else:
                yield {k: jax.device_put(v) for k, v in host_batch().items()}

    it = PrefetchIterator(produce(), depth=3, transform=stager)
    if echo > 1:
        it = EchoIterator(it, echo,
                          fresh_aux=lambda b: devt.aux(batch_size,
                                                       rec_shape))
    try:
        for _ in range(WARMUP):
            loss = solver.train_step(next(it))
        float(loss)
        dt, dts = _time_windows(lambda: solver.train_step(next(it)), float)
    finally:
        it.close()
    img_s = batch_size * ITERS / dt
    flops = model_train_flops_per_image(solver)
    row = {"model": name, "mode": "host_fed", "batch": batch_size,
           "images_per_sec": round(img_s, 2),
           "images_per_sec_spread": _rate_stats(batch_size * ITERS, dts),
           "h2d_kb_per_image": round(kb_per_image, 1),
           "wire": wire_mode, "echo": echo, "staging": int(staging),
           "transfer_only_images_per_sec": round(transfer_img_s, 2),
           "transfer_only_spread": _rate_stats(batch_size * 5, t_dts),
           "device_step_images_per_sec": round(step_img_s, 2),
           # sharded-ingest view: this process's feed leg, and what the
           # fleet aggregates to when every host feeds its own partition
           "per_host_feed_images_per_sec": round(transfer_img_s, 2),
           "feed_processes": jax.process_count(),
           "aggregate_feed_images_per_sec": round(
               transfer_img_s * jax.process_count(), 2)}
    if codec is not None and codec.packing:
        row["wire_bits"] = codec.bits
    if peak:
        row["mfu"] = round(img_s * flops / peak, 4)
    bound = min(transfer_img_s, step_img_s)
    if bound > 0:
        # >=1.0 means the feed overlap hides the cheaper leg entirely;
        # with echo, served img/s can exceed the transfer bound by up
        # to the echo factor — that excess IS the lever working
        row["overlap_efficiency"] = round(img_s / bound, 3)
    if transfer_img_s < 0.1 * step_img_s:
        # machine-readable guard: this row measures the link, not the
        # chip — downstream tooling must not read it as a perf number
        row["tunnel_bound"] = True
    if transfer_img_s < 0.5 * step_img_s:
        row["note"] = ("transfer-bound link (remote-tunnel TPU): end-to-end "
                       "tracks the H2D leg; on co-located hosts the step "
                       "leg is the bound")
    return row


def bench_transformer_lm(peak, seq_len=4096, batch=4, d_model=512,
                         num_layers=6, num_heads=8, vocab=8192):
    """Long-context row: causal transformer LM with the pallas flash
    kernel (zoo.transformer_lm) — the workload the reference never had."""
    import jax.numpy as jnp
    from sparknet_tpu.models import zoo
    # mixed precision: f32 master params, activations cast bf16 at the
    # embedding (compute_dtype) — tokens enter as int32, so unlike the
    # CNN rows the feed can't choose the compute dtype itself
    solver = _mk_solver(zoo.transformer_lm(
        vocab_size=vocab, seq_len=seq_len, batch_size=batch,
        d_model=d_model, num_layers=num_layers, num_heads=num_heads,
        flash=True), compute_dtype=jnp.bfloat16)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, vocab, (batch, seq_len))
    batch_d = {"data": jnp.asarray(toks, jnp.int32),
               "label": jnp.asarray((toks + 1) % vocab, jnp.int32)}
    for _ in range(WARMUP):
        loss = solver.train_step(batch_d)
    float(loss)
    dt, dts = _time_windows(lambda: solver.train_step(batch_d), float)
    tok_s = batch * seq_len * ITERS / dt
    # analytic train FLOPs/token: 12*d^2 dense MACs/layer + causal
    # attention S*d MACs/layer + d*vocab head MACs, x2 FLOP x3 train
    flops = 3 * 2 * (num_layers * (12 * d_model ** 2 + seq_len * d_model)
                     + d_model * vocab)
    row = {"model": "transformer_lm", "mode": "synthetic",
           "batch": batch, "seq_len": seq_len, "d_model": d_model,
           "num_layers": num_layers,
           "tokens_per_sec": round(tok_s, 1),
           "tokens_per_sec_spread": _rate_stats(batch * seq_len * ITERS,
                                                dts),
           "train_kflops_per_token": round(flops / 1e3, 1),
           "model_tflops_per_sec": round(tok_s * flops / 1e12, 2)}
    row.update(_mem_cols(solver, batch_d))
    if peak:
        row["mfu"] = round(tok_s * flops / peak, 4)
    return row


# --------------------------------------------------------------- ablations

# lever -> (env var, baseline arm value, lever arm value). Each lever's
# natural workload is the row it is supposed to move (ISSUE/PERF.md):
# epilogue -> googlenet b256 (the one 3-op conv+relu+lrn site lives in
# its conv2 tower), scan/remat -> the d512x6 LM row (per-layer dispatch
# overhead), overlap -> data-parallel caffenet (the grad allreduce).
# The input-pipeline levers (wire/staging/echo) A/B the HOST-FED feed
# path instead of a compute trace — run_feed_ablation.
# The sharding/precision levers (fsdp/tp/bf16) A/B the LM over the
# device mesh: fsdp swaps DataParallelSolver for FSDPSolver (throughput
# should hold, peak_hbm_mb is the payoff column), tp swaps in a
# GSPMDSolver over the (data, model) mesh, bf16 flips
# SPARKNET_PRECISION on a single-device LM.
ABLATE_ENVS = {
    "epilogue": ("SPARKNET_EPILOGUE", "off", "on"),
    "scan": ("SPARKNET_SCAN", "off", "on"),
    "remat": ("SPARKNET_REMAT", "none", "dots"),
    "overlap": ("SPARKNET_OVERLAP", "off", "on"),
    "wire": ("SPARKNET_WIRE", "raw", "precrop+pack"),
    "staging": ("SPARKNET_STAGING", "off", "on"),
    "echo": ("SPARKNET_ECHO", "1", "4"),
    "fsdp": ("SPARKNET_FSDP", "off", "on"),
    "tp": ("SPARKNET_TP", "1", "2"),
    "bf16": ("SPARKNET_PRECISION", "fp32", "bf16"),
}
FEED_LEVERS = ("wire", "staging", "echo")


def run_ablation(lever, peak, emit):
    """--ablate LEVER: paired baseline/lever rows from ONE process.

    Both arms trace under their own env value (the knobs are read at
    trace time), then the timed windows INTERLEAVE arms — the
    experiments/ab_s2d.py discipline — so chip-contention drift lands on
    both arms equally and the delta is the lever's, not the hour's. Rows
    carry {"ablation": lever, "arm": ...} for A/B provenance in
    bench_metrics.jsonl."""
    import os
    import jax.numpy as jnp
    from sparknet_tpu.models import zoo
    if lever in FEED_LEVERS:
        return run_feed_ablation(lever, peak, emit)
    env, off_v, on_v = ABLATE_ENVS[lever]
    rs = np.random.RandomState(0)
    # SPARKNET_BENCH_TINY=1: shrink every workload to smoke-test the
    # A/B plumbing off-TPU (CI, laptops). Rows still carry the device
    # kind from bench_config, so tiny CPU rows can't impersonate TPU
    # measurements.
    tiny = bool(os.environ.get("SPARKNET_BENCH_TINY"))

    if lever in ("scan", "remat"):
        seq, d, nl, vocab, batch = (128, 64, 3, 256, 2) if tiny \
            else (4096, 512, 6, 8192, 4)
        toks = rs.randint(0, vocab, (batch, seq))
        batch_d = {"data": jnp.asarray(toks, jnp.int32),
                   "label": jnp.asarray((toks + 1) % vocab, jnp.int32)}
        unit, unit_key = batch * seq * ITERS, "tokens_per_sec"
        fixed_flops = 3 * 2 * (nl * (12 * d ** 2 + seq * d) + d * vocab)
        base = {"model": "transformer_lm", "batch": batch, "seq_len": seq,
                "d_model": d, "num_layers": nl}

        def mk():
            return _mk_solver(zoo.transformer_lm(
                vocab_size=vocab, seq_len=seq, batch_size=batch,
                d_model=d, num_layers=nl, num_heads=8, flash=True),
                compute_dtype=jnp.bfloat16)
    elif lever in ("fsdp", "tp", "bf16"):
        # the "one big model" lever set: same LM both arms, the env var
        # picks the solver/precision. fsdp and tp need every device in
        # the timed program, so batch rows must divide the mesh.
        seq, d, nl, vocab, batch = (128, 64, 2, 256, 8) if tiny \
            else (1024, 1024, 8, 8192, 8)
        toks = rs.randint(0, vocab, (batch, seq))
        batch_d = {"data": jnp.asarray(toks, jnp.int32),
                   "label": jnp.asarray((toks + 1) % vocab, jnp.int32)}
        unit, unit_key = batch * seq * ITERS, "tokens_per_sec"
        fixed_flops = 3 * 2 * (nl * (12 * d ** 2 + seq * d) + d * vocab)
        base = {"model": "transformer_lm", "batch": batch, "seq_len": seq,
                "d_model": d, "num_layers": nl}

        def mk():
            from sparknet_tpu.proto import Message
            net = zoo.transformer_lm(
                vocab_size=vocab, seq_len=seq, batch_size=batch,
                d_model=d, num_layers=nl, num_heads=8, flash=not tiny)
            if lever == "bf16":
                # compute_dtype=None -> CompiledNet resolves the
                # SPARKNET_PRECISION env var: that resolution IS the arm
                return _mk_solver(net)
            sp = Message("SolverParameter", base_lr=0.01,
                         lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0, display=0, random_seed=0)
            if lever == "tp":
                from sparknet_tpu.parallel import (GSPMDSolver,
                                                   transformer_tp_rule)
                from sparknet_tpu.parallel.mesh import make_tp_mesh
                ways = int(os.environ.get("SPARKNET_TP", "1") or 1)
                return GSPMDSolver(sp, mesh=make_tp_mesh(ways),
                                   param_rule=transformer_tp_rule(ways),
                                   net_param=net)
            from sparknet_tpu.parallel import (DataParallelSolver,
                                               FSDPSolver, fsdp_enabled)
            cls = FSDPSolver if fsdp_enabled() else DataParallelSolver
            return cls(sp, net_param=net)
    elif lever == "epilogue":
        batch, side, classes = (8, 32, 10) if tiny else (256, 224, 1000)
        batch_d = {"data": jnp.asarray(rs.randn(batch, 3, side, side),
                                       jnp.bfloat16),
                   "label": jnp.asarray(rs.randint(0, classes, batch),
                                        jnp.int32)}
        unit, unit_key = batch * ITERS, "images_per_sec"
        fixed_flops = None          # per-arm, from the solver's graph
        base = {"model": "cifar10_full" if tiny else "googlenet",
                "batch": batch}

        def mk():
            if tiny:                # conv/relu fusion sites without the
                return _mk_solver(  # 27M-param googlenet build time
                    zoo.cifar10_full(batch_size=batch))
            return _mk_solver(zoo.googlenet(batch_size=batch,
                                            num_classes=1000))
    else:                           # overlap: DP caffenet, grads allreduce
        from sparknet_tpu.parallel import DataParallelSolver
        from sparknet_tpu.proto import Message
        batch, side, classes = (16, 28, 10) if tiny else (256, 227, 1000)
        batch_d = {"data": jnp.asarray(rs.randn(batch, 1 if tiny else 3,
                                                side, side), jnp.bfloat16),
                   "label": jnp.asarray(rs.randint(0, classes, batch),
                                        jnp.int32)}
        unit, unit_key = batch * ITERS, "images_per_sec"
        fixed_flops = None
        base = {"model": "lenet_dp" if tiny else "caffenet_dp",
                "batch": batch}

        def mk():
            sp = Message("SolverParameter", base_lr=0.01,
                         lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005, display=0, random_seed=0)
            net = zoo.lenet(batch_size=batch) if tiny \
                else zoo.caffenet(batch_size=batch, num_classes=1000)
            return DataParallelSolver(sp, net_param=net)

    arms = {}
    for arm, val in (("baseline", off_v), (lever, on_v)):
        old = os.environ.get(env)
        os.environ[env] = val
        try:
            s = mk()
            for _ in range(WARMUP):     # first step traces under `val`
                loss = s.train_step(batch_d)
            float(loss)
            # memory columns lower under the SAME env value the arm
            # traced with (the knobs are read at trace time)
            arms[arm] = (s, val, _mem_cols(s, batch_d))
        finally:
            os.environ.pop(env, None)
            if old is not None:
                os.environ[env] = old

    dts = {a: [] for a in arms}
    for _ in range(WINDOWS):
        for a, (s, _v, _m) in arms.items():
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = s.train_step(batch_d)
            float(out)
            dts[a].append(time.perf_counter() - t0)

    for a, (s, val, mem) in arms.items():
        flops = fixed_flops if fixed_flops is not None \
            else model_train_flops_per_image(s)
        rate = unit / min(dts[a])
        row = dict(base, mode="ablation", ablation=lever, arm=a, **mem)
        row[env] = val
        row[unit_key] = round(rate, 1)
        row[unit_key + "_spread"] = _rate_stats(unit, dts[a])
        row["model_tflops_per_sec"] = round(rate * flops / 1e12, 2)
        if peak:
            row["mfu"] = round(rate * flops / peak, 4)
        emit(row)
    return 0


def run_feed_ablation(lever, peak, emit):
    """--ablate {wire,staging,echo}: paired A/B over the HOST-FED feed
    path. Same interleaved-window discipline as run_ablation, but each
    arm builds the full pipeline — source pool, wire codec, prefetch,
    staging, echo — under its env value, because these levers live in
    the feed, not the compute trace.

    The wire arm feeds a LOW-ENTROPY pool (pixel values 0..3, 2-bit
    packable — the "optional lossless pack for low-entropy sources"
    case) so the pack stage is active and the row's pool_bits field
    says so; staging/echo arms feed full-range uint8."""
    import os
    import jax
    import jax.numpy as jnp
    from sparknet_tpu.data.prefetch import (PrefetchIterator, H2DStager,
                                            EchoIterator)
    from sparknet_tpu.data.device_transform import DeviceTransformer
    from sparknet_tpu.data.transforms import DataTransformer
    from sparknet_tpu.data.wire import (WireCodec, wire_mode_from_env,
                                        wire_bits_from_env)
    from sparknet_tpu.models import zoo
    from sparknet_tpu.proto import Message

    env, off_v, on_v = ABLATE_ENVS[lever]
    tiny = bool(os.environ.get("SPARKNET_BENCH_TINY"))
    low_entropy = lever == "wire"
    if tiny:
        # lenet keeps the crop geometry in play at smoke scale: 1x32x32
        # source records cropped to lenet's 1x28x28 input
        batch, ch, src, crop, classes = 16, 1, 32, 28, 10
        model, mean_vals = "lenet", [128.0]

        def mk_net():
            return zoo.lenet(batch_size=batch)
    else:
        batch, ch, src, crop, classes = 256, 3, 256, 227, 1000
        model, mean_vals = "caffenet", [104.0, 117.0, 123.0]

        def mk_net():
            return zoo.caffenet(batch_size=batch, num_classes=1000)
    base = {"model": model, "batch": batch}

    def build():
        """Full feed pipeline under the CURRENT env -> (solver, it,
        closers, info)."""
        solver = _mk_solver(mk_net())
        tp = Message("TransformationParameter", crop_size=crop, mirror=1)
        tp.mean_value.extend(mean_vals)
        devt = DeviceTransformer(
            DataTransformer(tp, phase=0, rng=np.random.RandomState(1)))
        rec_shape = (ch, src, src)
        rs = np.random.RandomState(0)
        pool = rs.randint(0, 4 if low_entropy else 256,
                          (batch * 2, ch, src, src)).astype(np.uint8)
        labels = rs.randint(0, classes, batch * 2).astype(np.int32)
        prng = np.random.RandomState(2)
        wire_mode = wire_mode_from_env()
        codec = WireCodec(devt, rec_shape, mode=wire_mode,
                          bits=wire_bits_from_env(), sample=pool) \
            if wire_mode != "raw" else None
        inner0 = devt.device_fn(precropped=codec.precrop if codec
                                else False)

        def cast_fn(b):
            b = inner0(b)
            b["data"] = b["data"].astype(jnp.bfloat16)
            return b
        tf = codec.device_fn(inner=cast_fn) if codec else cast_fn
        over = codec.raw_overrides(batch) if codec \
            else devt.raw_overrides(batch, rec_shape)
        solver.set_input_transform(tf, raw_overrides=over)

        def host_batch():
            i = prng.randint(0, len(pool) - batch + 1)
            b = {"data": pool[i:i + batch], "label": labels[i:i + batch],
                 **devt.aux(batch, rec_shape)}
            return codec.encode(b) if codec else b

        kb = sum(v.nbytes for v in host_batch().values()) / batch / 1024.0
        staging = os.environ.get("SPARKNET_STAGING", "") == "on"
        echo = max(1, int(os.environ.get("SPARKNET_ECHO", "1") or 1))
        stager = H2DStager(slots=2) if staging else None

        def produce():
            while True:
                if stager is not None:
                    yield host_batch()
                else:
                    yield {k: jax.device_put(v)
                           for k, v in host_batch().items()}

        it = PrefetchIterator(produce(), depth=3, transform=stager)
        if echo > 1:
            it = EchoIterator(it, echo,
                              fresh_aux=lambda b: devt.aux(batch,
                                                           rec_shape))
        info = {"h2d_kb_per_image": round(kb, 1), "wire": wire_mode,
                "echo": echo, "staging": int(staging)}
        if low_entropy:
            info["pool_bits"] = 2
        if codec is not None and codec.packing:
            info["wire_bits"] = codec.bits
        return solver, it, info

    arms = {}
    for arm, val in (("baseline", off_v), (lever, on_v)):
        old = os.environ.get(env)
        os.environ[env] = val
        try:
            s, it, info = build()
            for _ in range(WARMUP):
                loss = s.train_step(next(it))
            float(loss)
            arms[arm] = (s, it, info, val)
        finally:
            os.environ.pop(env, None)
            if old is not None:
                os.environ[env] = old

    try:
        dts = {a: [] for a in arms}
        for _ in range(WINDOWS):
            for a, (s, it, _info, _v) in arms.items():
                t0 = time.perf_counter()
                for _ in range(ITERS):
                    out = s.train_step(next(it))
                float(out)
                dts[a].append(time.perf_counter() - t0)

        unit = batch * ITERS
        for a, (s, it, info, val) in arms.items():
            flops = model_train_flops_per_image(s)
            rate = unit / min(dts[a])
            row = dict(base, mode="ablation", ablation=lever, arm=a,
                       **info)
            row[env] = val
            row["images_per_sec"] = round(rate, 1)
            row["images_per_sec_spread"] = _rate_stats(unit, dts[a])
            if peak:
                row["mfu"] = round(rate * flops / peak, 4)
            emit(row)
    finally:
        for _a, (_s, it, _info, _v) in arms.items():
            it.close()
    return 0


# --------------------------------------------------- multi-chip projection

# Ring-allreduce cost model: a pmean of B bytes over N peers moves
# 2*(N-1)/N * B past every chip (reduce-scatter + all-gather), so
#   t_comm = 2*(N-1)/N * B / bw_per_chip.
# Link-budget defaults (public TPU specs; override by flag):
#   v5e ICI: 2D torus, 4 links/chip x ~50 GB/s -> one bidirectional ring
#   axis sustains ~90 GB/s per chip. DCN (between slices/regions, the
#   SparkNet EC2 regime): ~12.5 GB/s per host.
ICI_GBPS = 90.0
DCN_GBPS = 12.5


def project_multichip(step_sec, batch, param_bytes, n_chips, tau=1,
                      bw_gbps=ICI_GBPS):
    """Projected img/s for N-chip data parallelism from the measured
    single-chip step. tau=1 is per-step DP (allreduce of GRADIENTS every
    step); tau>1 is local SGD (one allreduce of WEIGHTS per tau steps —
    the SparkNet algorithm, CifarApp.scala:92-135). Conservative: no
    comm/compute overlap is assumed, though XLA overlaps the ring with
    the tail of the backward pass in practice."""
    t_comm = 2 * (n_chips - 1) / n_chips * param_bytes / (bw_gbps * 1e9)
    t_round = tau * step_sec + t_comm
    return n_chips * batch * tau / t_round, t_comm


def run_projection(args):
    """bench.py --project: analytic scaling table, inputs shown.

    The compute leg comes from bench_details.json's measured synthetic
    rows (median window — the projection must not inherit best-window
    luck); the comm leg from the ring model above. The reference's own
    published scaling claim for this workload class is ~1.8x at 2 GPUs
    and ~3.5x weak-scaling at 4 (caffe/docs/multigpu.md); the BASELINE.md
    north star is >=4x wall-clock at v4-32."""
    with open(args.details) as f:
        details = json.load(f)
    rows = [r for r in details["rows"]
            if r.get("model") == "caffenet" and r.get("mode") == "synthetic"]
    if not rows:
        raise SystemExit("no caffenet synthetic rows in bench_details.json; "
                         "run `python bench.py` first")
    from sparknet_tpu.models import zoo
    from sparknet_tpu.graph.compiler import CompiledNet, TRAIN
    net = CompiledNet(zoo.caffenet(batch_size=8, num_classes=1000), TRAIN)
    param_bytes = 4 * sum(
        int(np.prod(shape))
        for layer in net.layers for shape, *_ in layer[1].param_shapes())
    out = {"model": "caffenet", "param_bytes": param_bytes,
           "comm_model": "ring allreduce 2(N-1)/N * B / bw, no overlap",
           "ici_gbps": args.ici_gbps, "dcn_gbps": args.dcn_gbps,
           "projections": []}
    for r in rows:
        batch = r["batch"]
        med = r.get("images_per_sec_spread", {}).get("median",
                                                     r["images_per_sec"])
        step = batch / med
        for n in args.chips:
            dp, c_dp = project_multichip(step, batch, param_bytes, n,
                                         bw_gbps=args.ici_gbps)
            ls, c_ls = project_multichip(step, batch, param_bytes, n,
                                         tau=50, bw_gbps=args.ici_gbps)
            ls_dcn, c_dcn = project_multichip(step, batch, param_bytes, n,
                                              tau=50, bw_gbps=args.dcn_gbps)
            out["projections"].append({
                "batch_per_chip": batch, "n_chips": n,
                "measured_step_ms": round(step * 1e3, 3),
                "dp_img_per_sec": round(dp, 1),
                "dp_comm_ms": round(c_dp * 1e3, 3),
                "dp_scaling_eff": round(dp / (n * med), 3),
                "local_sgd_tau50_img_per_sec": round(ls, 1),
                "local_sgd_scaling_eff": round(ls / (n * med), 3),
                "local_sgd_tau50_dcn_img_per_sec": round(ls_dcn, 1),
                "dcn_scaling_eff": round(ls_dcn / (n * med), 3),
            })
    print(json.dumps(out, indent=1))
    return 0


def _check_row_key(r):
    """Identity of a bench row across runs: workload coordinates only,
    never measured values."""
    return tuple(str(r.get(k)) for k in
                 ("model", "mode", "batch", "seq_len", "d_model",
                  "num_layers"))


def _check_row_median(r):
    """(median, metric_name, spread|None) for a row — the spread median
    when recorded (best-window headline values inherit contention luck;
    the median is the comparable number), else the headline value."""
    for k in ("images_per_sec", "tokens_per_sec"):
        sp = r.get(f"{k}_spread")
        if isinstance(sp, dict) and \
                isinstance(sp.get("median"), (int, float)):
            return float(sp["median"]), k, sp
        if isinstance(r.get(k), (int, float)):
            return float(r[k]), k, None
    return None, None, None


def run_check(args):
    """bench.py --check: the perf-regression gate (ISSUE 16).

    Compares the rows in --details (the current run's output) against
    the committed baseline medians (--check-baseline, default the
    committed bench_details.json; a BASELINE.json with published rows
    is accepted too). Per row the threshold is noise-tolerant: the
    current median must stay above

        baseline_median * (1 - max(--check-tolerance, baseline
                                   median-to-min spread ratio))

    so a workload whose committed windows already vary by 30% is not
    gated at 15%. Any breach (or a baseline row missing from the
    current file) fails with the offending row named; exit 1. Runs
    without jax or an accelerator — pure JSON compare — so CI gates on
    any machine."""
    try:
        with open(args.check_baseline) as f:
            base = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench --check: cannot read baseline "
              f"{args.check_baseline}: {e}", file=sys.stderr)
        return 2
    base_rows = base.get("rows") or base.get("published") or []
    if isinstance(base_rows, dict):
        base_rows = list(base_rows.values())
    if not base_rows:
        print(f"bench --check: baseline {args.check_baseline} has no "
              "rows to gate against", file=sys.stderr)
        return 2
    try:
        with open(args.details) as f:
            cur = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench --check: cannot read current rows "
              f"{args.details}: {e}", file=sys.stderr)
        return 2
    cur_by_key = {_check_row_key(r): r for r in (cur.get("rows") or [])}
    failures, checked = [], 0
    for br in base_rows:
        med_b, metric, sp = _check_row_median(br)
        if med_b is None or med_b <= 0:
            continue
        key = _check_row_key(br)
        name = " ".join(k for k in key if k != "None")
        cr = cur_by_key.get(key)
        if cr is None:
            failures.append(f"row MISSING from {args.details}: {name} "
                            f"(baseline {metric} median {med_b:,.1f})")
            continue
        med_c, _, _ = _check_row_median(cr)
        if med_c is None:
            failures.append(f"row has no {metric} in {args.details}: "
                            f"{name}")
            continue
        tol = args.check_tolerance
        if sp and isinstance(sp.get("min"), (int, float)) and med_b > 0:
            tol = max(tol, (med_b - float(sp["min"])) / med_b)
        floor = med_b * (1.0 - tol)
        checked += 1
        verdict = "ok" if med_c >= floor else "REGRESSED"
        line = (f"  {verdict:<9} {name}: {metric} median "
                f"{med_c:,.1f} vs baseline {med_b:,.1f} "
                f"(floor {floor:,.1f}, tol {tol:.0%})")
        print(line, file=sys.stderr)
        if med_c < floor:
            failures.append(
                f"{name}: {metric} median {med_c:,.1f} fell below "
                f"{floor:,.1f} ({med_b:,.1f} - {tol:.0%} noise "
                "tolerance)")
    if failures:
        print(f"bench --check: FAIL — {len(failures)} failing row(s), "
              f"{checked} compared:", file=sys.stderr)
        for fmsg in failures:
            print(f"  {fmsg}", file=sys.stderr)
        return 1
    print(f"bench --check: OK — {checked} row(s) within noise "
          f"tolerance of {args.check_baseline}", file=sys.stderr)
    return 0


def main():
    import argparse
    global WINDOWS

    ap = argparse.ArgumentParser()
    ap.add_argument("--windows", type=int, default=WINDOWS,
                    help="timing windows per row (spread is recorded)")
    ap.add_argument("--metrics", default="bench_metrics.jsonl",
                    help="JSONL metrics stream for every bench row "
                         "(BENCH_*.json provenance reproducible from the "
                         "JSONL alone; '' disables)")
    ap.add_argument("--project", action="store_true",
                    help="print the analytic multi-chip projection from "
                         "the measured single-chip rows and exit")
    ap.add_argument("--ablate", choices=sorted(ABLATE_ENVS),
                    help="run ONE paired baseline/lever A/B for a perf "
                         "lever (same process, interleaved windows) and "
                         "exit; rows land in --metrics and "
                         "bench_ablation.json with ablation provenance")
    ap.add_argument("--details", default="bench_details.json")
    ap.add_argument("--chips", type=int, nargs="+", default=[2, 4, 8, 32])
    ap.add_argument("--ici-gbps", type=float, default=ICI_GBPS)
    ap.add_argument("--dcn-gbps", type=float, default=DCN_GBPS)
    ap.add_argument("--check", action="store_true",
                    help="perf-regression gate: compare the rows in "
                         "--details against the committed baseline "
                         "medians and exit 1 naming any row below its "
                         "noise-tolerant floor (no jax needed)")
    ap.add_argument("--check-baseline", default="bench_details.json",
                    help="baseline rows for --check (committed "
                         "bench_details.json, or a BASELINE.json with "
                         "published rows)")
    ap.add_argument("--check-tolerance", type=float, default=0.15,
                    help="minimum allowed regression fraction before "
                         "--check fails a row; widened per-row to the "
                         "baseline's own median-to-min window spread")
    args = ap.parse_args()
    WINDOWS = max(1, args.windows)
    if args.check:
        raise SystemExit(run_check(args))
    if args.project:
        raise SystemExit(run_projection(args))

    import jax
    from sparknet_tpu.models import zoo

    # persistent compile cache: repeat bench runs skip the (minutes-long)
    # XLA compiles; keyed by HLO so code changes still recompile
    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/sparknet_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass

    dev = jax.devices()[0]
    peak = next((v for k, v in _PEAK.items()
                 if k.lower() in dev.device_kind.lower()), None)
    rows = []
    # every row also goes through the structured metrics stream
    # (sparknet_tpu.obs backend), so `sparknet report bench_metrics.jsonl`
    # reconstructs a BENCH_*.json's provenance from the JSONL alone
    from sparknet_tpu.utils.metrics import MetricsLogger
    mlog = MetricsLogger(args.metrics) if args.metrics else None
    if mlog:
        mlog.log("bench_config", device=dev.device_kind,
                 platform=dev.platform, peak_bf16_flops=peak,
                 windows=WINDOWS, warmup=WARMUP, iters_per_window=ITERS)

    # ablation A/Bs get their own details file: a lever smoke run must
    # never clobber the committed full-run bench_details.json artifact
    details_path = args.details
    if args.ablate and details_path == "bench_details.json":
        details_path = "bench_ablation.json"

    def emit(row):
        # stream rows as they finish: a killed/timed-out run still leaves
        # every completed measurement on stderr and in the details file
        # (written atomically so a mid-write kill can't truncate it)
        import os
        rows.append(row)
        print("#BENCH " + json.dumps(row), file=sys.stderr, flush=True)
        if mlog:
            mlog.log("bench", **row)
        with open(details_path + ".tmp", "w") as f:
            json.dump({"device": dev.device_kind, "platform": dev.platform,
                       "peak_bf16_flops": peak, "rows": rows}, f, indent=1)
        os.replace(details_path + ".tmp", details_path)

    if args.ablate:
        rc = run_ablation(args.ablate, peak, emit)
        if mlog:
            mlog.close()
        return rc

    # headline: CaffeNet batch 256, synthetic-fed (the reference workload).
    # The driver's ONE JSON line prints immediately — supplementary rows
    # below must not be able to take it down with them.
    head, solver = bench_synthetic(
        "caffenet", zoo.caffenet(batch_size=256, num_classes=1000),
        256, (3, 227, 227), 1000, peak)
    headline = {
        "metric": "caffenet_train_throughput",
        "value": head["images_per_sec"],
        "unit": "images/sec",
        "vs_baseline": round(head["images_per_sec"] / BASELINE_IMG_PER_SEC,
                             3),
    }
    print(json.dumps(headline), flush=True)
    if mlog:
        mlog.log("bench_headline", **headline)
    emit(head)

    del solver
    # honest row: same model+batch fed raw uint8 from the host, with the
    # crop/mirror/mean transform running inside the jitted step
    try:
        emit(bench_hostfed("caffenet",
                           zoo.caffenet(batch_size=256, num_classes=1000),
                           256, 256, 227, 1000, peak))
    except Exception as e:
        print(f"#BENCH-SKIP host_fed: {e}", file=sys.stderr, flush=True)

    # bigger batches: larger MXU tiles amortize the small spatial dims
    # (b1024 measured best: 38.2% MFU vs 30.8% at the reference's b256)
    for bsz in (512, 1024):
        try:
            rowb, sb = bench_synthetic(
                "caffenet", zoo.caffenet(batch_size=bsz, num_classes=1000),
                bsz, (3, 227, 227), 1000, peak)
            emit(rowb)
            del sb
        except Exception as e:
            print(f"#BENCH-SKIP caffenet_b{bsz}: {e}", file=sys.stderr,
                  flush=True)

    # GoogLeNet (the reference's third headline model family). Batch 256:
    # round-5 sweep measured medians b128 4,034 / b192 3,336 / b256 4,350
    # / b512 4,381 img/s — b256 is +8% over the old b128 row with
    # non-overlapping window spreads, b512 adds nothing, and b192's
    # non-power-of-two batch tiles the MXU badly.
    try:
        rowg, sg = bench_synthetic(
            "googlenet", zoo.googlenet(batch_size=256, num_classes=1000),
            256, (3, 224, 224), 1000, peak)
        emit(rowg)
        del sg
    except Exception as e:
        print(f"#BENCH-SKIP googlenet: {e}", file=sys.stderr, flush=True)

    # long-context: flash-attention transformer LM at S=4096 — the toy
    # scale (d=512, round-over-round continuity) and a real scale
    # (d=1024 x 12 layers, ~160M params) where MFU is meaningful
    try:
        emit(bench_transformer_lm(peak))
    except Exception as e:                  # keep the headline rows alive
        print(f"#BENCH-SKIP transformer_lm: {e}", file=sys.stderr,
              flush=True)
    try:
        # heads=8 -> head_dim 128 == the TPU lane width: head_dim 64 (16
        # heads) half-fills every (..., D)-minor tile and measured 24.8%
        # MFU vs 38.2% here (PERF.md round-3 notes)
        emit(bench_transformer_lm(peak, batch=4, d_model=1024,
                                  num_layers=12, num_heads=8))
    except Exception as e:
        print(f"#BENCH-SKIP transformer_lm_1024: {e}", file=sys.stderr,
              flush=True)
    if mlog:
        mlog.close()


if __name__ == "__main__":
    main()
