"""Headline benchmark: CaffeNet (AlexNet-class) training throughput.

Reference baseline (BASELINE.md): stock Caffe trains CaffeNet at 256-image
batches in 26.5 s / 20 iters on a K40 (~193 img/s), 19.2 s with cuDNN
(~267 img/s). We time the same workload — batch 256, 227x227, full
forward+backward+momentum-SGD update — as ONE jitted XLA step, mixed
precision (fp32 params, bf16 activations driving the MXU).

stdout: ONE JSON line {"metric", "value", "unit", "vs_baseline"} — the
synthetic-fed headline number (input pipeline excluded, like the reference's
in-memory LMDB page cache).
stderr: supplementary rows ("#BENCH {...}"): host-fed throughput (uint8
256x256 host batches through the native crop/mirror/mean transform +
double-buffered prefetch — the honest end-to-end number), a batch-512
variant, GoogLeNet, and MFU accounting. All rows also land in
bench_details.json.
"""

import json
import sys
import time

import numpy as np

BASELINE_IMG_PER_SEC = 267.0   # K40 + cuDNN, caffe/docs/performance_hardware.md:19-25
WARMUP = 3
ITERS = 20

# bf16 peak FLOP/s by device kind (public TPU specs; MFU denominators)
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v5": 459e12, "TPU v5p": 459e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def model_train_flops_per_image(solver):
    """Analytic MXU FLOPs: 2*MACs forward for conv/fc, x3 for training
    (grad wrt activations + grad wrt weights each re-run the matmuls).
    Elementwise/LRN/pool FLOPs are excluded — this is the standard MFU
    numerator, so the reported MFU slightly *understates* utilization."""
    net = solver.net
    fwd = 0
    batch = None
    for lp, impl, bottoms, tops in net.layers:
        if lp.type == "Convolution":
            out = net.blob_shapes[tops[0]]
            n, co, ho, wo = out
            batch = batch or n
            ci = net.blob_shapes[bottoms[0]][1]
            cp = lp.convolution_param
            ks = [int(x) for x in cp.kernel_size]
            if ks:
                kh = kw = ks[0]
            else:                        # DSL nets use kernel_h/kernel_w
                kh = int(cp.kernel_h)
                kw = int(cp.kernel_w)
            group = int(cp.group) if cp.has("group") else 1
            fwd += 2 * n * co * ho * wo * (ci // group) * kh * kw
        elif lp.type == "InnerProduct":
            out = net.blob_shapes[tops[0]]
            n = out[0]
            batch = batch or n
            cin = int(np.prod(net.blob_shapes[bottoms[0]][1:]))
            fwd += 2 * n * out[1] * cin
    return 3 * fwd // (batch or 1)


def _time_windows(step, sync, iters=ITERS, windows=3):
    # best of N windows: the tunneled chip is shared, single windows vary 2x
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step()
        sync(out)   # value fetch = true sync (block_until_ready returns
        # immediately under the axon TPU tunnel, inflating throughput ~200x)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _mk_solver(net_param, base_lr=0.01):
    from sparknet_tpu.proto import Message
    from sparknet_tpu.solver.solver import Solver
    sp = Message("SolverParameter", base_lr=base_lr, lr_policy="fixed",
                 momentum=0.9, weight_decay=0.0005, display=0, random_seed=0)
    return Solver(sp, net_param=net_param)


def bench_synthetic(name, net_param, batch_size, shape, classes, peak):
    import jax.numpy as jnp
    solver = _mk_solver(net_param)
    rs = np.random.RandomState(0)
    data = jnp.asarray(rs.randn(batch_size, *shape), jnp.bfloat16)
    label = jnp.asarray(rs.randint(0, classes, batch_size), jnp.int32)
    batch = {"data": data, "label": label}
    for _ in range(WARMUP):
        loss = solver.train_step(batch)
    float(loss)
    dt = _time_windows(lambda: solver.train_step(batch), float)
    img_s = batch_size * ITERS / dt
    flops = model_train_flops_per_image(solver)
    row = {"model": name, "mode": "synthetic", "batch": batch_size,
           "images_per_sec": round(img_s, 2),
           "train_gflops_per_image": round(flops / 1e9, 2),
           "model_tflops_per_sec": round(img_s * flops / 1e12, 2)}
    if peak:
        row["mfu"] = round(img_s * flops / peak, 4)
    return row, solver


def bench_hostfed(name, solver, batch_size, src_size, crop, classes, peak):
    """uint8 source batches -> native random-crop/mirror/mean transform in a
    prefetch worker -> device_put -> step. The input pipeline the synthetic
    row excludes; overlap should keep it within ~15% (VERDICT #3)."""
    import jax
    import jax.numpy as jnp
    from sparknet_tpu.data.prefetch import PrefetchIterator
    from sparknet_tpu import native

    rs = np.random.RandomState(0)
    pool = rs.randint(0, 256, (batch_size * 2, 3, src_size, src_size),
                      dtype=np.uint8)
    labels = rs.randint(0, classes, batch_size * 2).astype(np.int32)
    mean = np.full((3,), 120.0, np.float32)
    prng = np.random.RandomState(1)

    def produce_host():
        n = len(pool)
        while True:
            idx = prng.randint(0, n - batch_size + 1)
            imgs = pool[idx:idx + batch_size]
            ys = prng.randint(0, src_size - crop + 1, batch_size) \
                .astype(np.int32)
            xs = prng.randint(0, src_size - crop + 1, batch_size) \
                .astype(np.int32)
            flips = prng.randint(0, 2, batch_size).astype(np.uint8)
            f32 = native.transform_batch(imgs, crop, ys=ys, xs=xs,
                                         mirror=flips, mean=mean)
            yield f32, labels[idx:idx + batch_size]

    def produce():
        for f32, labs in produce_host():
            yield {"data": jax.device_put(jnp.asarray(f32, jnp.bfloat16)),
                   "label": jnp.asarray(labs)}

    # host transform alone (decode-side ceiling, no device in the loop)
    gen = produce_host()
    next(gen)
    t0 = time.perf_counter()
    for _ in range(5):
        next(gen)
    host_img_s = 5 * batch_size / (time.perf_counter() - t0)

    it = PrefetchIterator(produce(), depth=3)
    try:
        for _ in range(WARMUP):
            loss = solver.train_step(next(it))
        float(loss)
        dt = _time_windows(lambda: solver.train_step(next(it)), float)
    finally:
        it.close()
    img_s = batch_size * ITERS / dt
    flops = model_train_flops_per_image(solver)
    row = {"model": name, "mode": "host_fed", "batch": batch_size,
           "images_per_sec": round(img_s, 2),
           "host_transform_images_per_sec": round(host_img_s, 2)}
    if peak:
        row["mfu"] = round(img_s * flops / peak, 4)
    if img_s < 0.5 * host_img_s:
        # on this rig the chip is remote (axon tunnel): every step ships the
        # batch over the tunnel at ~MB/s, so end-to-end is transfer-bound,
        # not pipeline-bound. The two numbers above separate the stories.
        row["note"] = ("end-to-end limited by host->device transfer "
                       "(remote-tunnel TPU); host transform itself "
                       "sustains the rate above")
    return row


def bench_transformer_lm(peak, seq_len=4096, batch=4, d_model=512,
                         num_layers=6, num_heads=8, vocab=8192):
    """Long-context row: causal transformer LM with the pallas flash
    kernel (zoo.transformer_lm) — the workload the reference never had."""
    import jax.numpy as jnp
    from sparknet_tpu.models import zoo
    solver = _mk_solver(zoo.transformer_lm(
        vocab_size=vocab, seq_len=seq_len, batch_size=batch,
        d_model=d_model, num_layers=num_layers, num_heads=num_heads,
        flash=True))
    rs = np.random.RandomState(0)
    toks = rs.randint(0, vocab, (batch, seq_len))
    batch_d = {"data": jnp.asarray(toks, jnp.int32),
               "label": jnp.asarray((toks + 1) % vocab, jnp.int32)}
    for _ in range(WARMUP):
        loss = solver.train_step(batch_d)
    float(loss)
    dt = _time_windows(lambda: solver.train_step(batch_d), float)
    tok_s = batch * seq_len * ITERS / dt
    # analytic train FLOPs/token: 12*d^2 dense MACs/layer + causal
    # attention S*d MACs/layer + d*vocab head MACs, x2 FLOP x3 train
    flops = 3 * 2 * (num_layers * (12 * d_model ** 2 + seq_len * d_model)
                     + d_model * vocab)
    row = {"model": "transformer_lm", "mode": "synthetic",
           "batch": batch, "seq_len": seq_len,
           "tokens_per_sec": round(tok_s, 1),
           "train_kflops_per_token": round(flops / 1e3, 1),
           "model_tflops_per_sec": round(tok_s * flops / 1e12, 2)}
    if peak:
        row["mfu"] = round(tok_s * flops / peak, 4)
    return row


def main():
    import jax
    from sparknet_tpu.models import zoo

    # persistent compile cache: repeat bench runs skip the (minutes-long)
    # XLA compiles; keyed by HLO so code changes still recompile
    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/sparknet_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass

    dev = jax.devices()[0]
    peak = next((v for k, v in _PEAK.items()
                 if k.lower() in dev.device_kind.lower()), None)
    rows = []

    def emit(row):
        # stream rows as they finish: a killed/timed-out run still leaves
        # every completed measurement on stderr and in bench_details.json
        # (written atomically so a mid-write kill can't truncate it)
        import os
        rows.append(row)
        print("#BENCH " + json.dumps(row), file=sys.stderr, flush=True)
        with open("bench_details.json.tmp", "w") as f:
            json.dump({"device": dev.device_kind, "platform": dev.platform,
                       "peak_bf16_flops": peak, "rows": rows}, f, indent=1)
        os.replace("bench_details.json.tmp", "bench_details.json")

    # headline: CaffeNet batch 256, synthetic-fed (the reference workload).
    # The driver's ONE JSON line prints immediately — supplementary rows
    # below must not be able to take it down with them.
    head, solver = bench_synthetic(
        "caffenet", zoo.caffenet(batch_size=256, num_classes=1000),
        256, (3, 227, 227), 1000, peak)
    print(json.dumps({
        "metric": "caffenet_train_throughput",
        "value": head["images_per_sec"],
        "unit": "images/sec",
        "vs_baseline": round(head["images_per_sec"] / BASELINE_IMG_PER_SEC,
                             3),
    }), flush=True)
    emit(head)

    # honest row: same model+batch fed from uint8 host data via the
    # native transform + prefetch pipeline
    try:
        emit(bench_hostfed("caffenet", solver, 256, 256, 227, 1000, peak))
    except Exception as e:
        print(f"#BENCH-SKIP host_fed: {e}", file=sys.stderr, flush=True)
    del solver

    # batch-512 variant: bigger MXU tiles amortize the small spatial dims
    try:
        row512, s512 = bench_synthetic(
            "caffenet", zoo.caffenet(batch_size=512, num_classes=1000),
            512, (3, 227, 227), 1000, peak)
        emit(row512)
        del s512
    except Exception as e:
        print(f"#BENCH-SKIP caffenet_b512: {e}", file=sys.stderr, flush=True)

    # GoogLeNet (the reference's third headline model family)
    try:
        rowg, sg = bench_synthetic(
            "googlenet", zoo.googlenet(batch_size=128, num_classes=1000),
            128, (3, 224, 224), 1000, peak)
        emit(rowg)
        del sg
    except Exception as e:
        print(f"#BENCH-SKIP googlenet: {e}", file=sys.stderr, flush=True)

    # long-context: flash-attention transformer LM at S=4096
    try:
        emit(bench_transformer_lm(peak))
    except Exception as e:                  # keep the headline rows alive
        print(f"#BENCH-SKIP transformer_lm: {e}", file=sys.stderr,
              flush=True)


if __name__ == "__main__":
    main()
