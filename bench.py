"""Headline benchmark: CaffeNet (AlexNet-class) training throughput.

Reference baseline (BASELINE.md): stock Caffe trains CaffeNet at 256-image
batches in 26.5 s / 20 iters on a K40 (~193 img/s), 19.2 s with cuDNN
(~267 img/s). We time the same workload — batch 256, 227x227, full
forward+backward+momentum-SGD update — as ONE jitted XLA step on whatever
chip is present, mixed precision (fp32 params, bf16 activations: the ops
cast weights to the activation dtype, so feeding bf16 drives the MXU the
way cuDNN's fp32 path drove the K40's SMs).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

BASELINE_IMG_PER_SEC = 267.0   # K40 + cuDNN, caffe/docs/performance_hardware.md:19-25
BATCH = 256
WARMUP = 3
ITERS = 20


def main():
    import jax
    import jax.numpy as jnp
    from sparknet_tpu.models import zoo
    from sparknet_tpu.proto import Message
    from sparknet_tpu.solver.solver import Solver

    sp = Message("SolverParameter", base_lr=0.01, lr_policy="fixed",
                 momentum=0.9, weight_decay=0.0005, display=0, random_seed=0)
    solver = Solver(sp, net_param=zoo.caffenet(batch_size=BATCH,
                                               num_classes=1000))
    rs = np.random.RandomState(0)
    data = jnp.asarray(rs.randn(BATCH, 3, 227, 227), jnp.bfloat16)
    label = jnp.asarray(rs.randint(0, 1000, BATCH), jnp.int32)
    batch = {"data": data, "label": label}

    for _ in range(WARMUP):
        loss = solver.train_step(batch)
    float(loss)  # value fetch = true sync (block_until_ready returns
    # immediately under the axon TPU tunnel, inflating throughput ~200x)

    # best of 3 windows: the tunneled chip is shared, single windows vary 2x
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = solver.train_step(batch)
        float(loss)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    dt = best

    img_per_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "caffenet_train_throughput",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
    }))
    print(f"# {ITERS} iters x {BATCH} imgs in {dt:.2f}s on "
          f"{jax.devices()[0].platform}; loss={float(loss):.4f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
