"""Command-line interface — the reference native CLI (tools/caffe.cpp).

Verbs (mirroring the brew registry, caffe.cpp:55):
  train         train from a -solver prototxt (caffe.cpp:153)
  test          score a model (caffe.cpp:222)
  time          per-layer fwd/bwd timing (caffe.cpp:290)
  device_query  enumerate devices (caffe.cpp:110)
plus the app drivers:
  cifar         CifarApp (reference src/main/scala/apps/CifarApp.scala)
  imagenet      ImageNetApp (reference ImageNetApp.scala)

Signal semantics follow the reference flags -sigint_effect/-sighup_effect
(caffe.cpp:43-46): snapshot / stop / none.
"""

import argparse
import json
import os
import sys
import time

# Honor JAX_PLATFORMS for every verb: deployment sitecustomize modules may
# force-register an accelerator platform and override the env var's effect
# (see tests/conftest.py) — "JAX_PLATFORMS=cpu sparknet lm --ep 4" on a
# virtual CPU mesh must still work on such hosts.
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _mesh_arg(s):
    """"data=8,seq=2" -> {"data": 8, "seq": 2}; "8" -> {"data": 8}."""
    if s.isdigit():
        return {"data": int(s)}
    out = {}
    for part in s.split(","):
        k, _, v = part.partition("=")
        out[k.strip()] = int(v)
    return out


def cmd_device_query(args):
    import jax
    for d in jax.devices():
        print(f"id {d.id}: {d.device_kind} ({d.platform}) "
              f"process {d.process_index}")
    return 0


def _make_data_iter(net, seed=0):
    """Synthetic batch stream matching the net's feed shapes — the fallback
    when a prototxt's DB source doesn't exist on this machine."""
    import numpy as np
    rs = np.random.RandomState(seed)
    shapes = net.feed_shapes()

    def gen():
        while True:
            batch = {}
            for name, shape in shapes.items():
                if len(shape) <= 1 or "label" in name:
                    batch[name] = rs.randint(0, 10, shape).astype(np.int32)
                else:
                    batch[name] = rs.randn(*shape).astype(np.float32)
            yield batch
    return gen()


def _real_feeds(train_np, test_np, base_dir, seed=None,
                device_transform=False):
    """Open the LMDB sources the net's Data layers name, when they exist.
    Returns (train_shapes, train_src, test_shapes, test_src) with None
    entries where no real source is available."""
    from .graph.compiler import TRAIN, TEST
    from .data.db_source import build_db_feed
    train_shapes, train_src = build_db_feed(
        train_np, TRAIN, base_dir, seed=seed,
        device_transform=device_transform)
    test_shapes = test_src = None
    if test_np is not None:
        test_shapes, test_src = build_db_feed(
            test_np, TEST, base_dir, seed=seed,
            device_transform=device_transform)
    return train_shapes, train_src, test_shapes, test_src


def _net_base_dir(sp, solver_path):
    """Stock solver prototxts name their net relative to the caffe repo root
    (e.g. "examples/cifar10/..."); caffe resolves against CWD. Walk up from
    the solver file until the referenced net path exists."""
    import os
    rel = None
    for f in ("net", "train_net"):
        if sp.has(f):
            rel = getattr(sp, f)
            break
    if rel is None or os.path.isabs(rel) or os.path.exists(rel):
        return ""
    d = os.path.dirname(os.path.abspath(solver_path))
    while True:
        if os.path.exists(os.path.join(d, rel)):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return ""
        d = parent


def _feed_shapes_arg(specs):
    """["data=100,3,32,32", ...] -> {"data": (100,3,32,32)} (the shape LMDB
    records would supply in stock caffe)."""
    out = {}
    for s in specs or ():
        name, _, dims = s.partition("=")
        out[name.strip()] = tuple(int(d) for d in dims.replace("x", ",")
                                  .split(","))
    return out


def cmd_train(args):
    from .proto import text_format
    from .solver.solver import Solver, resolve_nets
    from .utils.signals import SignalPolicy
    from .utils.metrics import MetricsLogger
    from .data.prefetch import PrefetchIterator, H2DStager, EchoIterator
    from .obs import Tracer, JaxProfiler

    import os
    # one metrics stream + span tracer for the whole run: the solver's
    # step/comms accounting, the prefetch gauges, and the CLI's phase
    # spans all land in the same JSONL (see sparknet_tpu.obs)
    _apply_perf_flags(args)   # before any net is compiled
    _apply_feed_flags(args)   # before any data source is constructed
    echo = max(1, int(os.environ.get("SPARKNET_ECHO", "1") or 1))
    if echo > 1 and args.host_transform:
        raise SystemExit(
            "--echo > 1 needs the device-transform feed (drop "
            "--host-transform): echoes re-draw crop/mirror on-device")
    metrics = MetricsLogger(args.metrics) if args.metrics else None
    tracer = Tracer(metrics)
    if args.chaos:
        # arm BEFORE solver/data construction so sources and the solver
        # pick the injectors up through active_chaos()
        from .resilience.chaos import ChaosMonkey, install_chaos
        install_chaos(ChaosMonkey.parse(args.chaos, metrics=metrics))
    sp = text_format.load(args.solver, "SolverParameter")
    base_dir = _net_base_dir(sp, args.solver)
    if sp.has("snapshot_prefix") and base_dir \
            and not os.path.isabs(sp.snapshot_prefix):
        # stock prefixes ("examples/cifar10/...") are caffe-root-relative;
        # anchor them where the net/sources resolved, not the process CWD
        sp.snapshot_prefix = os.path.join(base_dir, sp.snapshot_prefix)
    train_np, test_np = resolve_nets(sp, base_dir)
    seed = int(sp.random_seed) if int(sp.random_seed) >= 0 else None
    train_shapes, train_src, test_shapes, test_src = _real_feeds(
        train_np, test_np, base_dir, seed=seed,
        device_transform=not args.host_transform)
    if not args.host_transform and args.strategy == "single":
        # datasets that fit the HBM budget become device-resident: one bulk
        # upload, then each step ships a ~few-hundred-byte control array
        # (data/device_cache.py — the RDD-in-cluster-memory model, HBM
        # edition). SPARKNET_DEVICE_CACHE_MB=0 disables.
        from .data.device_cache import maybe_device_cache
        budget = float(os.environ.get("SPARKNET_DEVICE_CACHE_MB", "2048"))
        if budget > 0:
            isz = int(sp.iter_size)
            train_src = maybe_device_cache(train_src, budget, iter_size=isz,
                                           metrics=metrics)
            if hasattr(train_src, "nbytes"):     # budget is SHARED
                budget -= train_src.nbytes / (1 << 20)
            test_src = maybe_device_cache(test_src, budget, iter_size=isz,
                                          metrics=metrics)
    feed = {**(train_shapes or {}), **_feed_shapes_arg(args.input_shape)}

    with tracer.span("setup", strategy=args.strategy):
        if args.strategy == "dp":
            from .parallel import DataParallelSolver, make_mesh
            solver = DataParallelSolver(
                sp, mesh=make_mesh(_mesh_arg(args.mesh))
                if args.mesh else None, base_dir=base_dir,
                feed_shapes=feed or None, test_feed_shapes=test_shapes,
                metrics=metrics, tracer=tracer)
        else:
            solver = Solver(sp, base_dir=base_dir, feed_shapes=feed or None,
                            test_feed_shapes=test_shapes, metrics=metrics,
                            tracer=tracer)
    # device-transform mode: the source yields raw uint8 records + offset
    # arrays; crop/mirror/mean run inside the jitted step (3-4x fewer H2D
    # bytes — data/device_transform.py). Must install before first compile.
    if train_src is not None and getattr(train_src, "device_mode", False):
        solver.set_input_transform(
            train_src.device_fn, train_src.raw_feed_overrides,
            test_fn=test_src.device_fn
            if test_src is not None
            and getattr(test_src, "device_mode", False) else None)
    elif test_src is not None and getattr(test_src, "device_mode", False):
        solver.set_input_transform(None, None, test_fn=test_src.device_fn)
    solver.snapshot_keep = args.keep or None
    prefix = args.snapshot_prefix or (
        sp.snapshot_prefix if sp.has("snapshot_prefix") else None)
    if args.stall_seconds:
        solver.arm_watchdog(stall_seconds=args.stall_seconds)
    if args.recover:
        solver.arm_recovery(max_rollbacks=args.recover,
                            lr_decay=args.recover_lr_decay,
                            explode_factor=args.recover_explode_factor)
    _apply_health_flags(solver, args)
    _apply_heartbeat_flags(solver, args)     # before elastic: the relay
    _apply_elastic_flags(solver, args)       # world sizes to processes
    hb = solver.heartbeat                    # close() drops the reference
    if args.weights:
        solver.load_weights(args.weights)
    reshard = getattr(args, "reshard", "strict")
    if args.snapshot:
        solver.restore(args.snapshot, reshard=reshard)
    if args.resume:
        from .resilience import checkpoint
        if args.resume == "auto":
            if not prefix:
                raise SystemExit("--resume auto needs a snapshot prefix "
                                 "(--snapshot-prefix or the solver's "
                                 "snapshot_prefix)")
            checkpoint.resume_auto(solver, prefix, log_fn=print,
                                   reshard=reshard)
        else:
            solver.restore(args.resume, reshard=reshard)
    total = args.iterations or int(sp.max_iter) or 1000
    # H2D in the prefetch WORKER thread, so batch k+1's host->HBM copy
    # overlaps step k on the device (the overlap the reference got from
    # cudaMemcpyAsync + prefetch threads). SPARKNET_STAGING=on (default)
    # uses the rotating-slot H2DStager — puts DISPATCH non-blocking and
    # only the transfer the consumer is about to need gets waited on —
    # off reverts to the blocking device_put. Only on the single-device,
    # iter_size==1 path: the dp strategy re-shards via np.asarray (a
    # blocking readback of anything already on device), and iter_size>1
    # stacks micro-batches on the host first.
    import jax
    from .resilience.chaos import active_chaos
    staging = os.environ.get("SPARKNET_STAGING", "on") != "off"
    stager = None
    if args.strategy == "single" and int(sp.iter_size) <= 1:
        if staging:
            stager = H2DStager(slots=2, metrics=metrics, name="train_feed",
                               chaos=active_chaos())
            put = stager
        else:
            put = jax.device_put
    else:
        put = None
    if train_src is not None:
        kind = "device-cached" if hasattr(train_src, "nbytes") else (
            "device-transform" if getattr(train_src, "device_mode", False)
            else "host-transform")
        print(f"Training from {train_src.source} "
              f"({train_src.num_records} records, {kind})")
        extra = {"echo": echo, "staging": int(put is stager
                                              and stager is not None)}
        codec = getattr(train_src, "wire", None)
        if codec is not None:
            extra.update(codec.describe())
        data_iter = PrefetchIterator(iter(train_src), depth=3,
                                     transform=put, metrics=metrics,
                                     name="train_feed", extra=extra)
        if echo > 1:
            if hasattr(train_src, "nbytes"):
                # device-cached feed: each "batch" is already a tiny
                # on-device control array — nothing worth echoing
                print("NOTE: --echo ignored for the device-cached feed")
            elif not hasattr(train_src, "fresh_aux"):
                raise SystemExit(
                    f"--echo > 1 needs a source with re-drawable "
                    f"device-side augmentation; "
                    f"{type(train_src).__name__} has none")
            else:
                data_iter = EchoIterator(
                    data_iter, echo,
                    fresh_aux=lambda b: train_src.fresh_aux())
    else:
        print("WARNING: no Data-layer LMDB source found; "
              "feeding synthetic noise (shapes only)")
        data_iter = _make_data_iter(solver.net)
    if test_src is not None:
        # fresh pass per test, UN-prefetched: a prefetch worker would draw
        # augmentation rng for batches past the test_iter consumed,
        # advancing the source's RandomState nondeterministically between
        # passes. Tests are rare; reproducibility wins.
        test_fn = lambda: iter(test_src)  # noqa: E731
    else:
        test_fn = (lambda: _make_data_iter(solver.test_net, seed=1)) \
            if solver.test_net is not None else None
    policy = SignalPolicy(sigint=args.sigint_effect,
                          sighup=args.sighup_effect,
                          sigterm=args.sigterm_effect)
    prof = JaxProfiler(args.profile)
    from .resilience.chaos import active_chaos
    from .resilience.recovery import RecoveryAbort
    from .resilience.elastic import QuorumLost, EXIT_QUORUM_LOST
    from .utils.exit_codes import EXIT_RECOVERY_ABORT
    blocks_done = 0
    rc = 0
    try:
        with policy:
            while solver.iter < total:
                prof.maybe_start(blocks_done, total - solver.iter)
                n = min(100, total - solver.iter)
                with tracer.span("train_block", iter0=solver.iter, iters=n):
                    try:
                        solver.step(n, data_iter, test_data_fn=test_fn)
                    except RecoveryAbort as e:
                        # clean abort: the run is over, but the last
                        # known-good snapshot (if any) is intact on disk
                        print(f"ABORT: {e}")
                        rc = EXIT_RECOVERY_ABORT
                        break
                    except QuorumLost as e:
                        # too few live workers for a trustworthy
                        # consensus — distinct exit for the supervisor
                        # (DEPLOY.md runbook). The masked consensus up
                        # to here is healthy: keep it for the relaunch,
                        # and in a multi-host world barrier every
                        # survivor on the same manifest before exiting.
                        print(f"QUORUM LOST: {e}")
                        if prefix:
                            solver.snapshot(prefix=prefix)
                            solver.coordinated_restart(prefix)
                        rc = EXIT_QUORUM_LOST
                        break
                blocks_done += 1
                prof.maybe_stop()
                ch = active_chaos()
                if ch is not None:
                    ch.maybe_sigterm(blocks_done)
                action = policy.pending()
                if action in ("snapshot", "snapshot_stop"):
                    solver.snapshot(prefix=prefix or "snap")
                if action in ("stop", "snapshot_stop"):
                    print("stopping early on signal")
                    break
    finally:
        prof.abort()
        if train_src is not None:
            data_iter.close()
            train_src.close()
        if test_src is not None:
            test_src.close()
        solver.close()          # watchdog thread + step/comms summaries
        if args.profile:
            # the host-side twin of the device trace: the run's spans in
            # Chrome trace_event format, next to jax.profiler's output
            tracer.export_chrome(os.path.join(args.profile,
                                              "spans.trace.json"))
    # final snapshot unless disabled or this iter was already snapshotted
    # by the in-loop cadence (reference solver.cpp Solve tail :300-306,
    # snapshot_after_train). The cadence path only fires when the
    # SolverParameter itself carries a prefix (Solver.step), so a
    # --snapshot-prefix-only run must still get its tail snapshot.
    cadence_fired = int(sp.snapshot) and sp.has("snapshot_prefix") \
        and solver.iter % int(sp.snapshot) == 0
    # on a recovery abort the in-memory params may be the diverged ones —
    # never overwrite good snapshots with them
    if prefix and sp.snapshot_after_train and not cadence_fired and rc == 0:
        solver.snapshot(prefix=prefix)
    print(f"Optimization done, iter={solver.iter}")
    if metrics:
        metrics.close()
    # a run that SURVIVED a peer-host death must report ITS exit code,
    # not die in the unreachable jax.distributed shutdown barrier
    from .parallel.multihost import exit_if_peers_died
    exit_if_peers_died(rc, hb)
    return rc


def cmd_test(args):
    import os
    import numpy as np
    from .proto import text_format
    from .solver.solver import Solver, resolve_nets
    from .proto import Message
    from .graph.compiler import TEST
    from .data.db_source import resolve_db_feed

    net_param = text_format.load(args.model, "NetParameter")
    sp = Message("SolverParameter", base_lr=0.0, lr_policy="fixed",
                 display=0)
    sp.net_param = net_param

    # resolve the TEST data layer's source relative to the model file,
    # walking up (stock prototxt sources are caffe-root-relative)
    test_shapes, test_src = resolve_db_feed(
        net_param, TEST, os.path.dirname(os.path.abspath(args.model)))
    # the (unused) TRAIN net compiles with the test shapes — param shapes
    # don't depend on batch size, and only the TEST net is stepped here
    solver = Solver(sp, feed_shapes=_feed_shapes_arg(args.input_shape)
                    or test_shapes, test_feed_shapes=test_shapes)
    if args.weights:
        solver.load_weights(args.weights)
    if test_src is not None:
        print(f"Scoring on {test_src.source} "
              f"({test_src.num_records} records)")
        it = iter(test_src)
    else:
        print("WARNING: no Data-layer LMDB source found; synthetic batches")
        it = _make_data_iter(solver.test_net or solver.net)
    scores = solver.test(it, num_iters=args.iterations)
    for k, v in scores.items():
        print(f"{k} = {np.asarray(v).mean():.6f}")
    if test_src is not None:
        test_src.close()
    return 0


def cmd_convert_cifar(args):
    from . import tools
    tools.convert_cifar_data(args.input, args.output)
    return 0


def cmd_make_synth_cifar(args):
    from . import tools
    tools.make_synth_cifar(args.output, n_train=args.train, n_test=args.test,
                           seed=args.seed, noise=args.noise,
                           label_noise=args.label_noise)
    return 0


def cmd_compute_mean(args):
    from . import tools
    # backend=None -> open_db sniffs the on-disk layout, so LevelDB dirs
    # from `convert_imageset --backend leveldb` work like the reference
    # tool's -backend flag (compute_image_mean.cpp:22)
    tools.compute_image_mean(args.db, args.output, backend=args.backend)
    return 0


def cmd_convert_imageset(args):
    from . import tools
    tools.convert_imageset(args.root, args.listfile, args.db,
                           resize_height=args.resize_height,
                           resize_width=args.resize_width, gray=args.gray,
                           shuffle=args.shuffle, encoded=args.encoded,
                           backend=args.backend)
    return 0


def cmd_upgrade_net_proto(args):
    from . import tools
    tools.upgrade_net_proto(args.input, args.output, binary=args.binary)
    return 0


def cmd_upgrade_solver_proto(args):
    from . import tools
    tools.upgrade_solver_proto(args.input, args.output)
    return 0


def cmd_extract_features(args):
    from . import tools
    blobs = args.blobs.split(",")
    dbs = args.dbs.split(",")
    if args.db_type not in ("lmdb", "leveldb"):
        raise SystemExit(f"unknown db_type {args.db_type!r}")
    weights = None if args.weights.lower() == "none" else args.weights
    tools.extract_features(args.model, blobs, dbs, args.num_batches,
                           weights_path=weights,
                           backend=args.db_type)
    return 0


def cmd_time(args):
    """Per-layer forward/backward timing — `caffe time` (caffe.cpp:290-376).
    Each layer is jitted in isolation on random inputs of its true shapes."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .proto import text_format
    from .graph.compiler import CompiledNet, TRAIN

    net_param = text_format.load(args.model, "NetParameter")
    net = CompiledNet(net_param, TRAIN,
                      feed_shapes=_feed_shapes_arg(args.input_shape))
    params, state = net.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    iters = args.iterations
    print(f"{'layer':<28}{'type':<18}{'fwd ms':>10}{'fwd+bwd ms':>12}")
    total_f = total_fb = 0.0
    for lp, impl, bottoms, tops in net.layers:
        if getattr(impl, "is_feed", False):
            continue
        bvals = [jnp.asarray(rs.randn(*net.blob_shapes[b]), jnp.float32)
                 for b in bottoms]
        lparams = net.resolve_params(params, lp.name)
        lstate = state.get(lp.name)
        rng = jax.random.PRNGKey(0)

        def fwd(lparams, bvals):
            if impl.has_state:
                tv, _ = impl.apply_stateful(lparams, lstate, bvals, True, rng)
            else:
                tv = impl.apply(lparams, bvals, True, rng)
            return sum(jnp.sum(t.astype(jnp.float32)) for t in tv)

        jf = jax.jit(fwd)
        jg = jax.jit(jax.grad(lambda bv: fwd(lparams, bv), argnums=0))
        try:
            float(jf(lparams, bvals))         # compile + sanity
            t0 = time.perf_counter()
            for _ in range(iters):
                r = jf(lparams, bvals)
            float(r)
            f_ms = (time.perf_counter() - t0) / iters * 1e3
            g = jg(bvals)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), g)
            t0 = time.perf_counter()
            for _ in range(iters):
                g = jg(bvals)
            float(jax.tree_util.tree_leaves(g)[0].ravel()[0])
            fb_ms = f_ms + (time.perf_counter() - t0) / iters * 1e3
        except Exception as e:                      # non-differentiable etc.
            print(f"{lp.name:<28}{lp.type:<18}{'—':>10}  ({e})")
            continue
        total_f += f_ms
        total_fb += fb_ms
        print(f"{lp.name:<28}{lp.type:<18}{f_ms:>10.3f}{fb_ms:>12.3f}")
    print(f"{'TOTAL':<28}{'':<18}{total_f:>10.3f}{total_fb:>12.3f}")
    print("note: per-layer jit; the fused full-step is faster "
          "(XLA cross-layer fusion)")
    return 0


def cmd_cifar(args):
    from .apps import CifarApp
    _apply_perf_flags(args)   # before app/solver construction
    _apply_feed_flags(args)   # echo/shard-ingest land as env for the app
    if args.chaos:
        # arm BEFORE app/solver construction so active_chaos() sees it
        from .resilience.chaos import ChaosMonkey, install_chaos
        install_chaos(ChaosMonkey.parse(args.chaos))
    app = CifarApp(num_workers=args.workers, data_dir=args.data,
                   prototxt_dir=args.prototxt_dir, strategy=args.strategy,
                   tau=args.tau, log_path=args.log,
                   metrics_path=args.metrics, hosts=args.hosts)
    from .resilience.chaos import active_chaos
    ch = active_chaos()
    if ch is not None and ch.metrics is None and app.metrics is not None:
        ch.metrics = app.metrics     # chaos events land in the run's JSONL
    _apply_health_flags(app.solver, args)
    _apply_heartbeat_flags(app.solver, args)
    _apply_elastic_flags(app.solver, args)
    hb = getattr(app.solver, "heartbeat", None)   # close() drops the ref
    from .resilience.elastic import QuorumLost, EXIT_QUORUM_LOST
    from .parallel.multihost import exit_if_peers_died
    rc = 0
    try:
        app.run(num_rounds=args.rounds, test_every=args.test_every,
                snapshot_prefix=args.snapshot_prefix,
                snapshot_every=args.snapshot_every,
                resume=args.resume, reshard=args.reshard)
    except QuorumLost as e:
        print(f"QUORUM LOST: {e}")
        # keep the healthy consensus for the supervisor relaunch, and
        # barrier every survivor on the same manifest (same contract as
        # `sparknet train`)
        if args.snapshot_prefix:
            try:
                app.solver.snapshot(prefix=args.snapshot_prefix)
                app.solver.coordinated_restart(args.snapshot_prefix)
            except Exception as snap_err:
                print(f"QUORUM LOST: best-effort snapshot failed "
                      f"({snap_err})")
        rc = EXIT_QUORUM_LOST
    # a run that SURVIVED a peer-host death must report ITS exit code,
    # not die in the unreachable jax.distributed shutdown barrier
    exit_if_peers_died(rc, hb)
    return rc


def cmd_lm(args):
    """Transformer-LM training driver on the synthetic bigram corpus —
    the zoo's long-context family end to end: plain single-device Solver,
    or the GPipe pipeline (--pipeline-stages N -> PipelineLMSolver over a
    "pipe" mesh axis). Emits a JSONL loss curve whose floor (the corpus
    bigram entropy) is logged up front, so convergence is checkable."""
    import time as _time
    import numpy as np
    import jax.numpy as jnp
    from .proto import Message
    from .data.synthetic import lm_batch_stream
    from .utils.metrics import MetricsLogger

    if args.snapshot_every and not args.snapshot_prefix:
        raise SystemExit("--snapshot-every needs --snapshot-prefix")
    _apply_perf_flags(args)   # before any solver traces the net
    sp = Message("SolverParameter", base_lr=args.lr, lr_policy="fixed",
                 display=args.display, type=args.solver_type,
                 random_seed=args.seed,
                 snapshot=args.snapshot_every or 0)
    if args.snapshot_prefix:
        sp.snapshot_prefix = args.snapshot_prefix
    metrics = MetricsLogger(args.metrics) if args.metrics else None
    lm_kw = dict(vocab_size=args.vocab, seq_len=args.seq_len,
                 batch_size=args.batch, d_model=args.d_model,
                 num_heads=args.heads, flash=not args.no_flash)
    # bf16 means MIXED precision: f32 master params (optimizer updates
    # would underflow in bf16 — a d=1024 Adam run measurably stalls at the
    # unigram plateau with bf16 masters), bf16 activations cast at the
    # embedding so every matmul drives the MXU at full rate. --precision
    # is the same policy through the SPARKNET_PRECISION env var (applied
    # above), which CompiledNet resolves when compute_dtype is None.
    import os as _os
    compute_dtype = jnp.bfloat16 if args.dtype == "bf16" else None
    dtype = jnp.float32
    from .parallel.fsdp import fsdp_enabled
    fsdp_on = fsdp_enabled()
    tp_ways = int(_os.environ.get("SPARKNET_TP", "0") or 0)
    if fsdp_on and tp_ways > 1:
        raise SystemExit(
            "--fsdp and --tp do not compose yet: FSDP shards over the "
            "data axis via shard_map, TP annotates a (data, model) mesh "
            "via GSPMD — pick one lever per run")
    if (fsdp_on or tp_ways > 1) and (args.pipeline_stages > 1
                                     or args.ep > 1 or args.sp > 1):
        raise SystemExit("--fsdp/--tp compose with --dp only (not "
                         "--ep/--sp/--pipeline-stages)")
    stream, floor = lm_batch_stream(args.vocab, args.batch, args.seq_len,
                                    seed=args.seed)
    if metrics:
        metrics.log("config", loss_floor_nats=round(floor, 4),
                    d_model=args.d_model, layers=args.layers,
                    seq_len=args.seq_len, batch=args.batch,
                    pipeline_stages=args.pipeline_stages,
                    dtype=args.dtype,
                    precision=_os.environ.get("SPARKNET_PRECISION",
                                              "fp32") or "fp32",
                    fsdp=int(fsdp_on), tp=max(tp_ways, 1))
    print(f"bigram corpus floor: {floor:.4f} nats/token "
          f"(untrained: {np.log(args.vocab):.4f})")

    if tp_ways > 1:
        # tensor parallelism: GSPMD annotations over a (data, model)
        # mesh — wqkv/ffn1/lm_head column-split, wo/ffn2 row-split
        # (parallel/gspmd.py transformer_tp_rule); the batch shards
        # over whatever devices remain on "data"
        from .parallel import GSPMDSolver, transformer_tp_rule
        from .parallel.mesh import make_tp_mesh
        from .models import zoo
        net = zoo.transformer_lm(num_layers=args.layers, **lm_kw)
        solver = GSPMDSolver(
            sp, mesh=make_tp_mesh(tp_ways),
            param_rule=transformer_tp_rule(tp_ways),
            net_param=net, metrics=metrics, dtype=dtype,
            compute_dtype=compute_dtype)
        if args.resume:
            solver.restore(args.resume)
        start_iter = solver.iter
        t0 = _time.time()
        solver.step(args.steps - solver.iter, iter(stream))
    elif args.ep > 1 or args.dp > 1 or args.sp > 1 or fsdp_on:
        # mesh-axis solvers: --ep (x --dp x --sp) -> ExpertParallelSolver
        # (expert weights + optimizer state sharded over "expert", batch
        # over data/expert, sequence over "seq" with ring attention);
        # --sp without MoE -> SeqParallelSolver (dp x sp); --dp alone ->
        # DataParallelSolver
        if args.pipeline_stages > 1:
            raise SystemExit("--ep/--dp/--sp cannot combine with "
                             "--pipeline-stages")
        if args.ep > 1 and not args.moe_experts:
            raise SystemExit("--ep needs --moe-experts")
        from .parallel import make_mesh
        from .models import zoo
        if args.sp > 1:
            lm_kw = dict(lm_kw, flash=False)   # ring attention path
        net = zoo.transformer_lm(num_layers=args.layers,
                                 moe_experts=args.moe_experts,
                                 moe_aux_weight=args.moe_aux_weight,
                                 moe_stats=bool(args.moe_experts),
                                 ring=args.sp > 1, **lm_kw)
        if args.moe_experts:
            from .parallel import ExpertParallelSolver
            axes = {"data": args.dp}
            if args.sp > 1:
                axes["seq"] = args.sp
            axes["expert"] = args.ep
            solver = ExpertParallelSolver(
                sp, mesh=make_mesh(axes),
                seq_axis="seq" if args.sp > 1 else None,
                net_param=net, metrics=metrics, dtype=dtype,
                compute_dtype=compute_dtype)
        elif args.sp > 1:
            from .parallel import SeqParallelSolver
            solver = SeqParallelSolver(
                sp, mesh=make_mesh({"data": args.dp, "seq": args.sp}),
                net_param=net, metrics=metrics, dtype=dtype,
                compute_dtype=compute_dtype)
        else:
            # --dp alone (or --fsdp, which implies the data axis): the
            # per-step allreduce family. FSDP swaps in the sharded-state
            # twin — params + optimizer state dim0-sharded over "data",
            # all-gather at use, reduce-scatter grads (parallel/fsdp.py)
            from .parallel import DataParallelSolver, FSDPSolver
            cls = FSDPSolver if fsdp_on else DataParallelSolver
            dp_axes = {"data": args.dp if args.dp > 1 else -1}
            solver = cls(
                sp, mesh=make_mesh(dp_axes), net_param=net,
                metrics=metrics, dtype=dtype,
                compute_dtype=compute_dtype)
            import jax as _jax
            if _jax.process_count() > 1:
                # DataParallelSolver's multi-host discipline is per-host
                # batch SLICES (unlike the global-feed EP/Seq branches);
                # every host draws the identical seeded stream, so each
                # takes its own slice of it
                from .parallel import local_batch_slice

                def _host_slice(it, B=args.batch):
                    for b in it:
                        s0, ln = local_batch_slice(B)
                        yield {k: v[s0:s0 + ln] for k, v in b.items()}
                stream = _host_slice(stream)
        if args.resume:
            solver.restore(args.resume)
        start_iter = solver.iter
        t0 = _time.time()
        chunk = args.display or 50
        while solver.iter < args.steps:
            solver.step(min(chunk, args.steps - solver.iter), stream)
            if not args.moe_experts:
                continue
            # routing diagnostics: one TEST-phase forward; the stats tops
            # (per-expert token fractions + overflow) pmean'd over the mesh
            scores = solver.test(iter([next(stream)]), num_iters=1)
            stats = {k: np.asarray(v) for k, v in scores.items()
                     if k.endswith("/moe_stats")}
            if stats:
                util = np.mean([s[:-1] for s in stats.values()], axis=0)
                overflow = float(np.mean([s[-1] for s in stats.values()]))
                print(f"    iter {solver.iter}: expert util "
                      f"[{', '.join(f'{u:.3f}' for u in util)}] "
                      f"overflow {overflow:.4f}")
                if metrics:
                    # eval_ce = the SoftmaxWithLoss top alone — the
                    # train "loss" series includes the weighted aux terms
                    ce = scores.get("loss")
                    metrics.log("moe", iter=solver.iter,
                                eval_ce=round(float(np.mean(ce)), 4)
                                if ce is not None else None,
                                expert_util=[round(float(u), 4)
                                             for u in util],
                                overflow_fraction=round(overflow, 5),
                                **{k.replace("/moe_stats", "_util"):
                                   [round(float(x), 4) for x in s[:-1]]
                                   for k, s in stats.items()})
    elif args.pipeline_stages > 1:
        from .parallel import PipelineLMSolver, make_mesh
        if args.moe_experts:
            raise SystemExit("--moe-experts is not supported under "
                             "--pipeline-stages (dense-FFN blocks only)")
        solver = PipelineLMSolver(
            sp, mesh=make_mesh({"pipe": args.pipeline_stages}),
            num_layers=args.layers,
            num_microbatches=args.microbatches or None,
            metrics=metrics, dtype=dtype, compute_dtype=compute_dtype,
            **lm_kw)
        solver.snapshot_prefix = args.snapshot_prefix
        if args.resume:
            solver.restore(args.resume)
        start_iter = solver.iter
        t0 = _time.time()
        solver.step(args.steps - solver.iter, stream)
    else:
        from .solver.solver import Solver
        from .models import zoo
        net = zoo.transformer_lm(num_layers=args.layers,
                                 moe_experts=args.moe_experts,
                                 moe_aux_weight=args.moe_aux_weight,
                                 **lm_kw)
        solver = Solver(sp, net_param=net, metrics=metrics, dtype=dtype,
                        compute_dtype=compute_dtype)
        if args.resume:
            solver.restore(args.resume)
        start_iter = solver.iter
        t0 = _time.time()
        solver.step(args.steps - solver.iter, iter(stream))
    dt = _time.time() - t0
    executed = solver.iter - start_iter
    toks = executed * args.batch * args.seq_len
    final = solver.smoothed_loss()
    if args.snapshot_prefix:
        solver.snapshot(args.snapshot_prefix)
    rate = toks / dt if dt > 0 else 0
    print(f"done: {executed} steps, {rate:,.0f} tokens/s wall, "
          f"final loss {final}")
    if metrics:
        metrics.log("summary", steps=executed,
                    tokens_per_sec=round(rate, 1),
                    final_loss=final, loss_floor_nats=round(floor, 4))
    if hasattr(solver, "close"):
        solver.close()          # flush step/comms summaries, stop threads
    if metrics:
        metrics.close()
    return 0


def cmd_report(args):
    """Aggregate a --metrics JSONL into a run report (sparknet_tpu.obs):
    per-phase time breakdown, step-time percentiles, comms volume,
    recompile count, training-health (divergence/stragglers/alarms),
    loss-curve summary — human-readable on stdout, machine-readable with
    --json, Chrome trace_event spans with --chrome."""
    from .obs import report as obs_report
    events = [s for s in (args.event.split(",") if args.event else [])
              if s.strip()]
    try:
        obs_report.report_file(args.jsonl, json_out=args.json,
                               chrome_out=args.chrome,
                               since=args.since,
                               event_types=events or None,
                               fmt=args.format)
    except obs_report.MetricsFileError as e:
        # missing/empty/unreadable metrics is an operator error, not a
        # crash: one line on stderr, distinct exit code
        print(f"sparknet report: error: {e}", file=sys.stderr)
        return 2
    except BrokenPipeError:
        # `sparknet report | head`: downstream closed the pipe mid-render
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    return 0


def cmd_monitor(args):
    """Tail a --metrics JSONL and render a live terminal summary
    (sparknet_tpu.obs.monitor): round/iter/loss, per-worker losses,
    divergence, stragglers, memory, last health alarm."""
    from .obs import monitor as obs_monitor
    from .obs.report import MetricsFileError
    try:
        state = obs_monitor.monitor_file(
            args.jsonl, interval=args.interval, once=args.once,
            wait=args.wait, duration=args.duration)
    except MetricsFileError as e:
        print(f"sparknet monitor: error: {e}", file=sys.stderr)
        return 2
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    return 0 if state.events else 2


def cmd_trace(args):
    """`sparknet trace`: merge N per-host metrics JSONLs into one
    clock-aligned fleet timeline (obs/fleettrace.py). --chrome writes a
    single Chrome trace_event file with one track group per host plus
    the solved per-host clock offsets; --critpath renders the per-round
    critical-path decomposition (obs/critpath.py) naming the blocking
    host and phase; --round N limits the critpath to one round. With
    neither flag, prints the alignment summary. Also consumes a single
    multiplexed `sparknet simfleet --metrics` stream unchanged."""
    import json as _json
    from .obs import critpath as obs_critpath
    from .obs import fleettrace as obs_fleettrace
    from .obs.report import MetricsFileError, load_events
    try:
        streams, bad = [], 0
        for path in args.metrics:
            evs, b = load_events(path)
            streams.append(evs)
            bad += b
        if not any(streams):
            raise MetricsFileError(
                "no parseable events in "
                + ", ".join(args.metrics)
                + (f" ({bad} malformed line(s) skipped)" if bad else ""))
        ft = obs_fleettrace.merge_streams(streams)
        if bad:
            print(f"sparknet trace: WARNING: {bad} malformed JSONL "
                  "line(s) skipped", file=sys.stderr)
        if args.chrome:
            obs_fleettrace.export_chrome(args.chrome, ft)
            n_hosts = len(ft.hosts)
            print(f"wrote {args.chrome} ({n_hosts} host track(s), "
                  f"{sum(len(v) for v in ft.events.values())} event(s))")
        if args.critpath:
            cp = obs_critpath.compute(ft, round_filter=args.round)
            if args.json:
                print(_json.dumps(cp, indent=1, sort_keys=True,
                                  default=str))
            else:
                obs_critpath.render(cp)
        if not args.chrome and not args.critpath:
            summ = obs_fleettrace.align_summary(ft)
            if args.json:
                print(_json.dumps(summ, indent=1, sort_keys=True))
            else:
                print(f"fleet: {len(summ['hosts'])} track(s), "
                      f"{summ['beacons']} clock beacon(s)")
                for h, o in sorted(summ["offsets"].items()):
                    if not o.get("aligned"):
                        print(f"  host {h}: unaligned (no beacon path)")
                        continue
                    err = o.get("err_s")
                    err_txt = "one-sided bound" if err is None \
                        else f"±{err * 1e3:.1f} ms"
                    print(f"  host {h}: offset "
                          f"{o.get('offset_s', 0.0) * 1e3:+.1f} ms "
                          f"({err_txt}, {o.get('samples', 0)} "
                          "beacon(s))")
    except MetricsFileError as e:
        print(f"sparknet trace: error: {e}", file=sys.stderr)
        return 2
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    return 0


def cmd_simfleet(args):
    """`sparknet simfleet`: the discrete-event fleet simulator
    (sparknet_tpu.sim) — thousands of virtual hosts driving the REAL
    heartbeat/consensus/elastic-policy code against a simulated clock
    and in-memory rendezvous dir. One run, a --sweep grid, or the
    replay-validation pair (--record_real / --replay). With --serve,
    the SERVING-fleet simulator instead (sim/servefleet.py): virtual
    replicas + the real router under open-loop arrival traces. Exit 0
    on success, 1 on a replay mismatch or a lost serving request
    (no-lost-request-without-429 invariant), 2 on a bad chaos/sweep
    spec, 4 (EXIT_QUORUM_LOST) when the simulated fleet loses quorum —
    the same exit a real run would take."""
    import json as _json
    import tempfile
    from .utils.exit_codes import EXIT_QUORUM_LOST
    from .utils.metrics import MetricsLogger
    from .sim import FleetSim, ServeFleetSim, replay, sweep

    metrics = MetricsLogger(args.metrics) if args.metrics else None
    log = print if args.verbose else None
    try:
        if args.serve:
            if args.sweep:
                cells = []
                for spec in args.sweep:
                    cells.extend(sweep.parse_serve_grid(spec))
                results = sweep.run_sweep(cells, metrics=metrics,
                                          log_fn=print,
                                          budget_s=args.budget_s,
                                          cell_fn=sweep.run_serve_cell)
                print(sweep.render_serve_table(results))
                if args.json:
                    with open(args.json, "w") as f:
                        _json.dump(results, f, indent=1)
                lost = sum(r["lost"] for r in results)
                if lost:
                    print(f"sparknet simfleet: {lost} request(s) LOST "
                          "without an explicit 429/5xx — the serving "
                          "invariant is broken", file=sys.stderr)
                    return 1
                return 0
            sim = ServeFleetSim(
                replicas=args.replicas, windows=args.windows,
                window_s=args.window_s, interval_s=args.interval,
                lease_s=args.lease, service_ms=args.service_ms,
                queue_limit=args.queue_limit, rate=args.rate,
                trace=args.trace, spike_x=args.spike_x,
                slo_p99_ms=args.slo_p99_ms, slo_depth=args.slo_depth,
                breach_windows=args.breach_windows,
                idle_windows=args.idle_windows,
                max_replicas=args.max_replicas, canary_w=args.canary_w,
                canary_pct=args.canary_pct, canary_err=args.canary_err,
                canary_min_requests=args.canary_min_requests,
                die_w=args.die_w, rejoin_w=args.rejoin_w,
                chaos=args.chaos, seed=args.seed,
                trace_sample=args.trace_sample,
                tail_ms=args.trace_tail_ms, slo_burn=args.slo_burn,
                burn_scale=args.burn_scale, metrics=metrics,
                log_fn=log)
            s = sim.run()
            print(f"servefleet: {s['replicas']} replicas x "
                  f"{s['windows']} windows (sim {s['sim_s']}s) "
                  f"trace={s['trace']} rate={s['rate']:g}/s "
                  f"lease={s['lease_s']:g} interval={s['interval_s']:g}")
            print(f"traffic: {s['arrivals']} arrivals -> {s['ok']} ok, "
                  f"{s['rejected']} rejected (429), {s['errors']} "
                  f"errors, {s['retries']} retried; lost {s['lost']}")
            print(f"availability {s['availability']}  "
                  f"p99 {s['p99_ms']}ms"
                  + (f"  top stage {s['top_stage']}"
                     if s.get("top_stage") else ""))
            if s.get("burn"):
                b = s["burn"]
                print(f"slo burn: fast x{b.get('fast')}"
                      f"/{b.get('fast_long')} slow x{b.get('slow')}"
                      f"/{b.get('slow_long')} budget left "
                      f"{b.get('budget_left')}"
                      + (f"  ALERT {b['alert']}" if b.get("alert")
                         else ""))
            print(f"membership: {s['evictions']} evictions, "
                  f"{s['readmissions']} readmissions, "
                  f"{s['admissions']} admissions; final live "
                  f"{s['replicas_final']}; grow {s['grow']} shrink "
                  f"{s['shrink']}; canary rollbacks "
                  f"{s['canary_rollbacks']}"
                  + ("  QUORUM LOST" if s["quorum_lost"] else ""))
            if args.json:
                with open(args.json, "w") as f:
                    _json.dump(s, f, indent=1)
            if s["quorum_lost"]:
                return EXIT_QUORUM_LOST
            return 1 if s["lost"] else 0
        if args.record_real:
            with tempfile.TemporaryDirectory() as d:
                rec = replay.record_real(
                    d, hosts=min(args.hosts, 4), rounds=args.rounds,
                    interval_s=args.interval, lease_s=args.lease,
                    round_s=args.round_s or 0.12,
                    evict_after=args.evict_after,
                    readmit_after=args.readmit_after,
                    quorum=args.quorum, log_fn=log)
            with open(args.record_real, "w") as f:
                _json.dump(rec, f, indent=1)
            print(f"simfleet: recorded real {rec['config']['hosts']}-"
                  f"coordinator run -> {args.record_real} "
                  f"({len(rec['sequence'])} membership events)")
            return 0
        if args.replay:
            with open(args.replay) as f:
                rec = _json.load(f)
            ok, real_seq, sim_seq = replay.replay_sim(
                rec, metrics=metrics, log_fn=log)
            if ok:
                print(f"simfleet: REPLAY MATCH — {len(sim_seq)} "
                      "membership events reproduced exactly")
                return 0
            print("simfleet: REPLAY MISMATCH", file=sys.stderr)
            print(f"  real: {real_seq}", file=sys.stderr)
            print(f"  sim:  {sim_seq}", file=sys.stderr)
            return 1
        if args.sweep:
            cells = []
            for spec in args.sweep:
                cells.extend(sweep.parse_grid(spec))
            results = sweep.run_sweep(cells, metrics=metrics,
                                      log_fn=print,
                                      budget_s=args.budget_s)
            print(sweep.render_table(results))
            if args.json:
                with open(args.json, "w") as f:
                    _json.dump(results, f, indent=1)
            return 0
        sim = FleetSim(hosts=args.hosts, rounds=args.rounds,
                       interval_s=args.interval, lease_s=args.lease,
                       round_s=args.round_s, jitter=args.jitter,
                       tau=args.tau, step_s=args.step_s,
                       quorum=args.quorum, evict_after=args.evict_after,
                       readmit_after=args.readmit_after,
                       staleness=args.staleness, s_decay=args.s_decay,
                       consensus=args.consensus,
                       recover_after=args.recover_after,
                       chaos=args.chaos, seed=args.seed,
                       metrics=metrics, log_fn=log)
        s = sim.run()
        w = s["gate_wait_s"]
        print(f"fleet: {s['hosts']} hosts x {s['rounds']} rounds "
              f"(sim {s['sim_s']}s) consensus={s['consensus']} "
              f"lease={s['lease_s']:g} interval={s['interval_s']:g} "
              f"round_s={s['round_s']:g}")
        print(f"membership: {s['evictions']} evictions, "
              f"{s['readmissions']} readmissions, "
              f"{s['admissions']} admissions; "
              f"final live {s['live_final']}/{s['hosts']}"
              + ("  QUORUM LOST" if s["quorum_lost"] else ""))
        print(f"gate wait: mean {w['mean']}s p50 {w['p50']}s "
              f"p95 {w['p95']}s max {w['max']}s")
        print(f"staleness: parks {s['parks']} unparks {s['unparks']}"
              + (f" max_lag {s['max_lag']}" if "max_lag" in s else "")
              + f"  rollbacks {s['rollbacks']}"
              + f"  retry_exhausted {s['retry_exhausted']}")
        if args.json:
            with open(args.json, "w") as f:
                _json.dump(s, f, indent=1)
        return EXIT_QUORUM_LOST if s["quorum_lost"] else 0
    except ValueError as e:
        # a typo'd chaos/sweep spec must fail loudly, not run vacuously
        print(f"sparknet simfleet: error: {e}", file=sys.stderr)
        return 2
    finally:
        if metrics is not None:
            metrics.close()


def cmd_serve(args):
    """`sparknet serve`: weights-only inference over a resilient
    checkpoint prefix — continuous batching, hot reload, graceful
    drain. Exit 0 after a clean SIGTERM/SIGINT drain; exit 3
    (EXIT_RECOVERY_ABORT) when the checkpoint has no servable model
    blob, before the socket ever opens; exit 2 on a bad --chaos spec.
    With --fleet_dir the replica leases into the fleet rendezvous
    (serve/fleet.py) for `sparknet route` to discover."""
    from .utils.signals import SignalPolicy
    from .utils.metrics import MetricsLogger
    from .utils.exit_codes import EXIT_RECOVERY_ABORT
    from .obs.tracing import TraceSampler
    from .serve import ServeEngine, Batcher, serve_http

    _apply_perf_flags(args)   # before any net is compiled
    net_param = None
    if args.model:
        from .proto import text_format
        net_param = text_format.load(args.model, "NetParameter")
    metrics = MetricsLogger(args.metrics) if args.metrics else None
    chaos = None
    if args.chaos:
        from .resilience.chaos import ChaosMonkey
        try:
            chaos = ChaosMonkey.parse(args.chaos, metrics=metrics)
        except ValueError as e:
            print(f"sparknet serve: error: {e}", file=sys.stderr)
            if metrics:
                metrics.close()
            return 2
    engine = ServeEngine(args.prefix, net_param=net_param,
                         max_batch=args.max_batch, metrics=metrics)
    try:
        engine.load()
    except ValueError as e:
        print(f"sparknet serve: error: {e}", file=sys.stderr)
        if metrics:
            metrics.close()
        return EXIT_RECOVERY_ABORT
    if not args.no_warmup:
        engine.warmup()           # trace every bucket before traffic
    batcher = Batcher(max_batch=args.max_batch,
                      max_wait_s=args.max_wait_ms / 1e3,
                      queue_limit=args.queue_limit, metrics=metrics)
    member = None
    if args.fleet_dir:
        from .serve import ReplicaMember
        member = ReplicaMember(args.fleet_dir, args.replica,
                               replicas=args.replicas, engine=engine,
                               batcher=batcher,
                               interval_s=args.heartbeat_interval,
                               lease_s=args.lease, metrics=metrics)
    tracer = TraceSampler(sample=args.trace_sample,
                          tail_ms=args.trace_tail_ms)
    # SIGTERM = the scheduler's preemption notice -> drain, exit 0
    policy = SignalPolicy(sigint="stop", sighup="none", sigterm="stop")
    with policy:
        rc = serve_http(engine, batcher, host=args.host, port=args.port,
                        metrics=metrics, policy=policy,
                        reload_poll_s=args.reload_poll,
                        request_timeout_s=args.request_timeout,
                        member=member, chaos=chaos,
                        replica=args.replica, tracer=tracer)
    if metrics:
        metrics.close()
    return rc


def cmd_route(args):
    """`sparknet route`: the serving-fleet router (serve/fleet.py) —
    lease-based membership over --fleet_dir, least-queue-depth dispatch
    with retry-once failover, SLO autoscaling decisions, canary
    auto-rollback. Exit 0 after a clean SIGTERM/SIGINT drain."""
    from .utils.signals import SignalPolicy
    from .utils.metrics import MetricsLogger
    from .obs.tracing import BurnRateLedger, TraceSampler
    from .serve import (Router, SLOAutoscaler, CanaryController,
                        route_http)

    metrics = MetricsLogger(args.metrics) if args.metrics else None
    canary = CanaryController(
        pct=args.canary_pct, min_requests=args.canary_min_requests,
        max_err_delta=args.canary_err_delta,
        max_p99_delta_ms=args.canary_p99_delta_ms, metrics=metrics)
    tracer = TraceSampler(sample=args.trace_sample,
                          tail_ms=args.trace_tail_ms)
    slo = None
    if not args.no_slo_burn:
        slo = BurnRateLedger(
            slo_ms=(args.slo_ms if args.slo_ms is not None
                    else args.slo_p99_ms),
            objective=args.slo_objective, scale=args.burn_scale,
            metrics=metrics)
    router = Router(args.fleet_dir, replicas=args.replicas,
                    lease_s=args.lease, canary=canary, metrics=metrics,
                    tracer=tracer, slo=slo)
    autoscaler = None
    if not args.no_autoscale:
        autoscaler = SLOAutoscaler(
            p99_ms=args.slo_p99_ms, depth=args.slo_depth,
            windows=args.breach_windows, idle_windows=args.idle_windows,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas, metrics=metrics)
    policy = SignalPolicy(sigint="stop", sighup="none", sigterm="stop")
    with policy:
        rc = route_http(router, autoscaler=autoscaler, host=args.host,
                        port=args.port, window_s=args.window_s,
                        policy=policy,
                        request_timeout_s=args.request_timeout)
    if metrics:
        metrics.close()
    return rc


def cmd_serve_bench(args):
    """`sparknet serve-bench`: load-generate against a running
    `sparknet serve` endpoint (closed and/or open loop)."""
    from .utils.metrics import MetricsLogger
    from .serve import run_loadgen

    metrics = MetricsLogger(args.metrics) if args.metrics else None
    modes = ("closed", "open") if args.mode == "both" else (args.mode,)
    results = []
    for mode in modes:
        results.append(run_loadgen(
            args.url, mode=mode, concurrency=args.concurrency,
            rate=args.rate, duration_s=args.duration, rows=args.rows,
            timeout=args.request_timeout, metrics=metrics))
    if metrics:
        metrics.close()
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    bad = sum(r["errors"] for r in results)
    return 0 if bad == 0 else 1


def _add_perf_flags(p, scan=False):
    """--remat (and for the LM driver --scan): the trace-time perf knobs
    of graph/compiler.py. The flags write the SPARKNET_* env vars before
    any solver is constructed, so the env vars stay the back-compat
    fallback (SPARKNET_REMAT=0/1 still means none/full) and every code
    path — including nets built by apps — sees one consistent policy."""
    p.add_argument("--remat", choices=("none", "dots", "full"),
                   default=None,
                   help="rematerialization policy for the train trace: "
                        "none (store everything), dots (checkpoint_dots "
                        "— keep matmul outputs, recompute elementwise), "
                        "full (recompute whole segments). Default: "
                        "SPARKNET_REMAT env var, else none")
    if scan:
        p.add_argument("--scan", choices=("auto", "on", "off"),
                       default=None,
                       help="scan-over-layers for isomorphic block "
                            "stacks: one traced body + lax.scan instead "
                            "of N unrolled copies (auto: TPU only). "
                            "Default: SPARKNET_SCAN env var, else auto")
    p.add_argument("--precision", choices=("bf16", "fp32"), default=None,
                   help="mixed-precision policy: bf16 activations with "
                        "fp32 master weights + fp32 grad accumulation, "
                        "or the untouched fp32 path. Default: "
                        "SPARKNET_PRECISION env var, else fp32")


def _add_sharding_flags(p):
    """--fsdp / --tp: the one-big-model levers (parallel/fsdp.py,
    parallel/gspmd.py). Same discipline as the perf flags: each writes
    its SPARKNET_* env var before any solver is constructed."""
    p.add_argument("--fsdp", choices=("on", "off"), default=None,
                   help="ZeRO/FSDP sharding: params + optimizer state "
                        "live dim0-sharded over the data axis "
                        "(all-gather at use, reduce-scatter grads, "
                        "per-shard update — bit-for-bit the replicated "
                        "DP path at fp32). Default: SPARKNET_FSDP env "
                        "var, else off")
    p.add_argument("--tp", type=int, default=None, metavar="N",
                   help="N>1: Megatron-style tensor parallelism for the "
                        "LM's matmuls over an N-way \"model\" mesh axis "
                        "(GSPMD annotations; remaining devices form the "
                        "data axis). Default: SPARKNET_TP env var, "
                        "else 1")


def _apply_perf_flags(args):
    import os
    if getattr(args, "remat", None) is not None:
        os.environ["SPARKNET_REMAT"] = args.remat
    if getattr(args, "scan", None) is not None:
        os.environ["SPARKNET_SCAN"] = args.scan
    if getattr(args, "precision", None) is not None:
        os.environ["SPARKNET_PRECISION"] = args.precision
    if getattr(args, "fsdp", None) is not None:
        os.environ["SPARKNET_FSDP"] = args.fsdp
    if getattr(args, "tp", None) is not None:
        os.environ["SPARKNET_TP"] = str(args.tp)


def _add_feed_flags(p):
    """Input-pipeline levers (PERF.md "Input pipeline"). Like the perf
    flags, each writes its SPARKNET_* env var before any source/solver is
    constructed — env-only use keeps working, and an A/B run differs by
    exactly one variable."""
    p.add_argument("--wire", default=None,
                   choices=("raw", "precrop", "pack", "precrop+pack"),
                   help="wire format for the device-transform feed: raw "
                        "uint8 records (default), host-side pre-crop to "
                        "the net's input geometry (crop/mirror still "
                        "applied on-device, bit-exact), lossless bit-pack "
                        "for low-entropy sources, or both. Default: "
                        "SPARKNET_WIRE env var, else raw")
    p.add_argument("--wire-bits", type=int, choices=(1, 2, 4, 8),
                   default=None,
                   help="pack width for --wire pack modes (8 = no pack); "
                        "default: SPARKNET_WIRE_BITS env var, else "
                        "inferred from the first record and enforced "
                        "losslessly (out-of-range batches raise)")
    p.add_argument("--staging", choices=("on", "off"), default=None,
                   help="true double-buffered H2D staging: dispatch batch "
                        "N+1's transfer non-blocking into a rotating slot "
                        "while step N runs (data/prefetch.py H2DStager). "
                        "off = the blocking device_put in the prefetch "
                        "worker. Default: SPARKNET_STAGING env var, "
                        "else on")
    p.add_argument("--echo", type=int, default=None, metavar="E",
                   help="data echoing: serve each transferred batch E "
                        "times, with fresh on-device crop/mirror draws "
                        "per echo (Choi et al.) — for transfer-bound "
                        "links. Default: SPARKNET_ECHO env var, else 1")
    p.add_argument("--shard-ingest", choices=("on", "off"), default=None,
                   help="per-host sharded ingest in multi-process runs: "
                        "each host reads only its owned record partition "
                        "(data/ingest.py; ownership re-spreads with "
                        "elastic membership). Default: "
                        "SPARKNET_SHARD_INGEST env var, else on")


def _apply_feed_flags(args):
    import os
    if getattr(args, "wire", None) is not None:
        os.environ["SPARKNET_WIRE"] = args.wire
    if getattr(args, "wire_bits", None) is not None:
        os.environ["SPARKNET_WIRE_BITS"] = str(args.wire_bits)
    if getattr(args, "staging", None) is not None:
        os.environ["SPARKNET_STAGING"] = args.staging
    if getattr(args, "echo", None) is not None:
        os.environ["SPARKNET_ECHO"] = str(args.echo)
    if getattr(args, "shard_ingest", None) is not None:
        os.environ["SPARKNET_SHARD_INGEST"] = args.shard_ingest
    echo = int(os.environ.get("SPARKNET_ECHO", "1") or 1)
    wire = os.environ.get("SPARKNET_WIRE", "raw") or "raw"
    if echo > 1 and "precrop" in wire:
        raise SystemExit(
            "--echo > 1 is incompatible with a precrop wire mode: "
            "pre-cropping bakes the crop window into the shipped bytes, "
            "so echoes could not get fresh crop draws (use --wire raw "
            "or --wire pack with echo)")


def _add_heartbeat_flags(p):
    """--heartbeat-dir / --lease-s / --heartbeat-interval: host-level
    fault domains (resilience/heartbeat.py). Passing --heartbeat-dir
    arms leased liveness + the pre-round rendezvous gate; in a
    multi-process world it also selects the snapshot writer and the
    coordinated-restart barrier."""
    p.add_argument("--heartbeat-dir", metavar="DIR",
                   help="shared rendezvous directory (every host must "
                        "reach it): arms leased heartbeats, host-level "
                        "eviction on lease expiry, the no-hang round "
                        "gate, and coordinated restart on quorum loss")
    p.add_argument("--lease-s", type=float, default=3.0,
                   help="heartbeat lease: a host silent this long is "
                        "dead (evicted at the next round gate)")
    p.add_argument("--heartbeat-interval", type=float, default=0.5,
                   help="seconds between heartbeat re-leases (must be "
                        "well under --lease-s)")
    p.add_argument("--grow", action="store_true",
                   help="late-join an already-RUNNING world through "
                        "--heartbeat-dir: this standalone process scans "
                        "the fresh leases, takes the next host id, and "
                        "is admitted at the incumbents' next round gate "
                        "(zero recompiles); pair with --resume auto "
                        "--reshard auto to bootstrap weights from the "
                        "running world's checkpoint")


def _apply_heartbeat_flags(solver, args):
    if not getattr(args, "heartbeat_dir", None) or \
            not hasattr(solver, "arm_heartbeat"):
        return
    solver.arm_heartbeat(args.heartbeat_dir,
                         interval_s=args.heartbeat_interval,
                         lease_s=args.lease_s,
                         grow=getattr(args, "grow", False))


def _add_elastic_flags(p):
    """--quorum / --evict-after / --readmit-after: the elastic
    membership layer (resilience/elastic.py). Passing any of them arms
    an ElasticPolicy on the sharded solver."""
    p.add_argument("--quorum", type=int, default=0, metavar="N",
                   help="arm elastic membership: sync rounds become "
                        "validity-masked quorum averages that survive "
                        "worker loss; abort with exit 4 when fewer than "
                        "N workers are live (0 = elasticity off unless "
                        "--evict-after/--readmit-after is given, then "
                        "quorum defaults to 1)")
    p.add_argument("--evict-after", type=int, default=None, metavar="R",
                   help="evict a worker after R consecutive rounds with "
                        "an invalid (non-finite) contribution "
                        "(default 2); its data shard re-spreads over "
                        "the survivors")
    p.add_argument("--readmit-after", type=int, default=None, metavar="R",
                   help="readmit an evicted worker after an R-round "
                        "cooldown, restarting it from the consensus "
                        "weights (default 5; 0 = never readmit)")
    p.add_argument("--staleness", type=int, default=None, metavar="S",
                   help="arm the ASYNC bounded-staleness update mode "
                        "(the knob next to --tau): rounds are barrier-"
                        "free — a worker up to S rounds behind the "
                        "fastest live peer still contributes "
                        "(staleness-discounted), beyond S it is parked "
                        "and resynced from the consensus; the round "
                        "never waits for a straggler. S=0 is bit-for-"
                        "bit the synchronous masked round")
    p.add_argument("--s-decay", type=float, default=0.5,
                   help="geometric per-round-of-lag discount applied "
                        "to stale contributions in async mode "
                        "(1.0 = no discount inside the bound)")
    p.add_argument("--unpark-after", type=int, default=1, metavar="R",
                   help="rounds a parked (over-stale) worker spends "
                        "resyncing before it rejoins at the front "
                        "(async mode; default 1)")
    p.add_argument("--evict-stale-after", type=int, default=0,
                   metavar="K",
                   help="evict a worker after K chronic parks without "
                        "a sustained in-bound stretch (async mode; "
                        "0 = park/resync forever, never evict)")


def _apply_elastic_flags(solver, args):
    if not hasattr(solver, "arm_elastic"):
        return
    on = args.quorum > 0 or args.evict_after is not None \
        or args.readmit_after is not None
    if on:
        solver.arm_elastic(
            quorum=max(1, args.quorum),
            evict_after=args.evict_after
            if args.evict_after is not None else 2,
            readmit_after=args.readmit_after
            if args.readmit_after is not None else 5)
    if getattr(args, "staleness", None) is not None and \
            hasattr(solver, "arm_staleness"):
        # after arm_elastic: the policy the flags armed gains the
        # staleness fields (arm_staleness updates it in place)
        solver.arm_staleness(args.staleness, decay=args.s_decay,
                             unpark_after=args.unpark_after,
                             evict_parked_after=args.evict_stale_after)


def _add_health_flags(p):
    """--health-* threshold flags shared by the training verbs; applied
    via _apply_health_flags after the solver is built."""
    p.add_argument("--no-health", action="store_true",
                   help="disable the training-dynamics health detectors")
    p.add_argument("--health-straggler-factor", type=float, default=1.5,
                   help="flag a worker whose round latency exceeds this "
                        "factor x the median of its peers")
    p.add_argument("--health-loss-skew-factor", type=float, default=3.0,
                   help="flag when the per-worker loss spread jumps past "
                        "this factor x its rolling EMA")
    p.add_argument("--health-div-abs", type=float, default=0.0,
                   help=">0: critical alarm when mean worker divergence "
                        "crosses this absolute L2 threshold")
    p.add_argument("--health-trend-rounds", type=int, default=5,
                   help="divergence-trend alarm window (consecutive "
                        "growing observations)")
    p.add_argument("--health-trend-factor", type=float, default=2.0,
                   help="total growth over the trend window that "
                        "triggers the divergence-trend alarm")
    p.add_argument("--health-cooldown", type=int, default=5,
                   help="min observations between same-kind alarms")
    p.add_argument("--health-arm-recovery", action="store_true",
                   help="critical health alarms arm the divergence "
                        "RecoveryPolicy if none is armed yet")


def _apply_health_flags(solver, args):
    if getattr(solver, "metrics", None) is None or \
            not hasattr(solver, "arm_health"):
        return
    if getattr(args, "no_health", False):
        solver.arm_health(enabled=False)
        return
    solver.arm_health(
        straggler_factor=args.health_straggler_factor,
        loss_skew_factor=args.health_loss_skew_factor,
        div_abs=args.health_div_abs,
        trend_rounds=args.health_trend_rounds,
        trend_factor=args.health_trend_factor,
        cooldown=args.health_cooldown,
        arm_recovery=args.health_arm_recovery)


def cmd_lint(args):
    """JAX-aware static analysis (sparknet_tpu.analysis): host-sync /
    recompile / PRNG-reuse / collective-axis hazards in compiled code
    plus the guarded-by lock-discipline race checker for the threaded
    host side. No jax import — runs on any checkout."""
    from .analysis.cli import run_lint
    return run_lint(args)


def cmd_imagenet(args):
    from .apps import ImageNetApp
    app = ImageNetApp(num_workers=args.workers, strategy=args.strategy,
                      tau=args.tau, batch=args.batch, log_path=args.log,
                      num_classes=args.classes, metrics_path=args.metrics)
    app.run(num_rounds=args.rounds)
    return 0


# deprecated tool shims (reference tools/{train,test,finetune}_net.cpp,
# net_speed_benchmark.cpp: LOG(FATAL) pointing at the real verb). Handled
# before argparse so legacy flag syntax still reaches the redirect message.
_DEPRECATED_VERBS = {
    "train_net": "train --solver=... [--snapshot=...]",
    "test_net": "test --model=... --weights=... [--iterations=50]",
    "finetune_net": "train --solver=... --weights=...",
    "net_speed_benchmark": "time --model=... [--iterations=50]",
}


def main(argv=None):
    args0 = sys.argv[1:] if argv is None else argv
    if args0 and args0[0] in _DEPRECATED_VERBS:
        print(f"Deprecated. Use sparknet {_DEPRECATED_VERBS[args0[0]]} "
              "instead.", file=sys.stderr)
        return 1
    p = argparse.ArgumentParser(
        prog="sparknet",
        description="TPU-native SparkNet: train/test/time/apps")
    sub = p.add_subparsers(dest="verb", required=True)

    t = sub.add_parser("train", help="train from a solver prototxt")
    t.add_argument("--solver", required=True)
    t.add_argument("--weights", help=".caffemodel to finetune from")
    t.add_argument("--snapshot", help=".solverstate to resume from")
    t.add_argument("--iterations", type=int, default=None)
    t.add_argument("--strategy", choices=("single", "dp"), default="single")
    t.add_argument("--mesh", help='e.g. "data=8"')
    t.add_argument("--snapshot-prefix",
                   help="override the solver's snapshot_prefix")
    t.add_argument("--input-shape", action="append", default=[],
                   help='feed blob shape hint, e.g. "data=100,3,32,32" '
                        "(stands in for the LMDB record shape)")
    t.add_argument("--metrics", help="JSONL metrics output path")
    t.add_argument("--profile",
                   help="write a jax.profiler trace of one steady-state "
                        "100-iter block to this directory (`caffe time`'s "
                        "deeper sibling; view with tensorboard/xprof)")
    t.add_argument("--stall-seconds", type=float, default=0,
                   help="arm a stall/NaN watchdog with this timeout")
    t.add_argument("--host-transform", action="store_true",
                   help="apply crop/mirror/mean on the HOST (native kernel) "
                        "and ship float32 crops, instead of the default "
                        "on-device transform fed raw uint8 records")
    t.add_argument("--sigint_effect", default="stop",
                   choices=("snapshot", "stop", "snapshot_stop", "none"))
    t.add_argument("--sighup_effect", default="snapshot",
                   choices=("snapshot", "stop", "snapshot_stop", "none"))
    t.add_argument("--sigterm_effect", default="snapshot_stop",
                   choices=("snapshot", "stop", "snapshot_stop", "none"),
                   help="preemption-notice handling; the default snapshots "
                        "then stops, so `--resume auto` can continue")
    t.add_argument("--resume", metavar="auto|STATE",
                   help="'auto': continue from the newest valid snapshot "
                        "under the snapshot prefix (partial/corrupt ones "
                        "are skipped with a reason); or an explicit "
                        ".solverstate[.h5] path")
    t.add_argument("--reshard", choices=("strict", "auto"),
                   default="strict",
                   help="cross-world restore policy: 'strict' refuses a "
                        "snapshot stamped by a different world "
                        "(WorldMismatch names both worlds); 'auto' "
                        "re-partitions it for THIS world — an 8-way "
                        "run's checkpoint resumes on 4 or 16 "
                        "(resilience/checkpoint.reshard_for_world)")
    t.add_argument("--keep", type=int, default=5,
                   help="snapshot retention: keep the newest N manifested "
                        "snapshots, delete older ones (0 = keep all)")
    t.add_argument("--recover", type=int, default=0, metavar="N",
                   help="arm divergence recovery: roll back to the last "
                        "known-good state on NaN/exploding loss, up to N "
                        "consecutive times before a clean abort (exit 3)")
    t.add_argument("--recover-lr-decay", type=float, default=1.0,
                   help="multiply the lr schedule by this on every "
                        "rollback (e.g. 0.5)")
    t.add_argument("--recover-explode-factor", type=float, default=0.0,
                   help=">0: also roll back when the loss exceeds this "
                        "factor times its recent healthy EMA")
    _add_perf_flags(t)
    _add_feed_flags(t)
    t.add_argument("--chaos", metavar="SPEC",
                   help="deterministic fault injection, e.g. "
                        "'nan_step=30,io_p=0.02,sigterm_round=3,seed=1' "
                        "(also via SPARKNET_CHAOS; see "
                        "sparknet_tpu/resilience/chaos.py)")
    _add_health_flags(t)
    _add_elastic_flags(t)
    _add_heartbeat_flags(t)
    t.set_defaults(fn=cmd_train)

    te = sub.add_parser("test", help="score a model")
    te.add_argument("--model", required=True)
    te.add_argument("--weights")
    te.add_argument("--iterations", type=int, default=50)
    te.add_argument("--input-shape", action="append", default=[])
    te.set_defaults(fn=cmd_test)

    ti = sub.add_parser("time", help="per-layer timing")
    ti.add_argument("--model", required=True)
    ti.add_argument("--iterations", type=int, default=10)
    ti.add_argument("--input-shape", action="append", default=[])
    ti.set_defaults(fn=cmd_time)

    d = sub.add_parser("device_query", help="list devices")
    d.set_defaults(fn=cmd_device_query)

    cc = sub.add_parser("convert_cifar_data",
                        help="CIFAR-10 .bin batches -> train/test LMDBs")
    cc.add_argument("input", help="dir with data_batch_*.bin + test_batch.bin")
    cc.add_argument("output", help="dir to create cifar10_{train,test}_lmdb")
    cc.set_defaults(fn=cmd_convert_cifar)

    ms = sub.add_parser("make_synth_cifar",
                        help="synthetic CIFAR-format dataset (zero-egress "
                             "stand-in for get_cifar10.sh)")
    ms.add_argument("output", help="dir for data_batch_*.bin/test_batch.bin")
    ms.add_argument("--train", type=int, default=50000)
    ms.add_argument("--test", type=int, default=10000)
    ms.add_argument("--seed", type=int, default=0)
    ms.add_argument("--noise", type=float, default=28.0)
    ms.add_argument("--label-noise", type=float, default=0.0,
                    help="fraction of labels resampled uniformly (hard "
                         "mode: caps accuracy at (1-p)+p/10)")
    ms.set_defaults(fn=cmd_make_synth_cifar)

    cm = sub.add_parser("compute_image_mean",
                        help="Datum DB -> mean image .binaryproto")
    cm.add_argument("db")
    cm.add_argument("output")
    cm.add_argument("--backend", choices=("lmdb", "leveldb"), default=None,
                    help="DB backend (default: sniff the directory layout)")
    cm.set_defaults(fn=cmd_compute_mean)

    ci = sub.add_parser("convert_imageset",
                        help='images + "path label" listfile -> Datum DB')
    ci.add_argument("root", help="root folder of image paths")
    ci.add_argument("listfile")
    ci.add_argument("db")
    ci.add_argument("--resize_height", type=int, default=0)
    ci.add_argument("--resize_width", type=int, default=0)
    ci.add_argument("--gray", action="store_true")
    ci.add_argument("--shuffle", action="store_true")
    ci.add_argument("--encoded", action="store_true")
    ci.add_argument("--backend", choices=["lmdb", "leveldb"],
                    default="lmdb")
    ci.set_defaults(fn=cmd_convert_imageset)

    for verb, bin_ in (("upgrade_net_proto_text", False),
                       ("upgrade_net_proto_binary", True)):
        u = sub.add_parser(verb,
                           help="V0/V1 NetParameter file -> latest format")
        u.add_argument("input")
        u.add_argument("output")
        u.set_defaults(fn=cmd_upgrade_net_proto, binary=bin_)

    us = sub.add_parser("upgrade_solver_proto_text",
                        help="solver_type enum -> type string")
    us.add_argument("input")
    us.add_argument("output")
    us.set_defaults(fn=cmd_upgrade_solver_proto)

    ef = sub.add_parser("extract_features",
                        help="forward a net, write named blobs as "
                             "float-Datum DBs — positional order matches "
                             "the reference binary "
                             "(tools/extract_features.cpp): "
                             "weights model blobs dbs n [db_type]")
    ef.add_argument("weights",
                    help=".caffemodel (the reference's pretrained_net_param "
                         "first positional); pass `none` for random init")
    ef.add_argument("model", help="feature-extraction prototxt with a "
                                  "TEST data layer")
    ef.add_argument("blobs", help="blob_name1[,name2,...]")
    ef.add_argument("dbs", help="db_path1[,path2,...]")
    ef.add_argument("num_batches", type=int)
    ef.add_argument("db_type", nargs="?", default="lmdb")
    ef.set_defaults(fn=cmd_extract_features)

    c = sub.add_parser("cifar", help="CifarApp driver")
    c.add_argument("--workers", type=int, default=None)
    c.add_argument("--data", help="dir with CIFAR-10 .bin batches")
    c.add_argument("--prototxt-dir", help="dir with stock cifar10 prototxts")
    c.add_argument("--strategy", choices=("local_sgd", "dp"),
                   default="local_sgd")
    c.add_argument("--hosts", type=int, default=0,
                   help="N>0: hierarchical local SGD over N host fault "
                        "domains (two-tier: per-step grad pmean inside "
                        "a host, tau-interval masked averaging across "
                        "hosts; membership/eviction at host "
                        "granularity). Single-process: N virtual "
                        "domains partition the local devices; "
                        "multi-process: one domain per process")
    c.add_argument("--tau", type=int, default=10)
    c.add_argument("--rounds", type=int, default=20)
    c.add_argument("--test-every", type=int, default=10,
                   help="test every N rounds (CifarApp.scala:98)")
    c.add_argument("--log")
    c.add_argument("--metrics", help="JSONL metrics output path")
    c.add_argument("--snapshot-prefix",
                   help="write periodic snapshots under this prefix "
                        "(enables --resume auto and the QuorumLost "
                        "best-effort snapshot)")
    c.add_argument("--snapshot-every", type=int, default=0,
                   help="snapshot every N rounds (0 disables)")
    c.add_argument("--resume", metavar="auto|STATE",
                   help="'auto': continue from the newest valid snapshot "
                        "under --snapshot-prefix; or an explicit "
                        ".solverstate[.h5] path")
    c.add_argument("--reshard", choices=("strict", "auto"),
                   default="strict",
                   help="cross-world restore policy: 'auto' re-partitions "
                        "a snapshot stamped by a different world for THIS "
                        "world (8-way checkpoint resumes on 4 or 16); "
                        "'strict' refuses with WorldMismatch")
    c.add_argument("--chaos", metavar="SPEC",
                   help="deterministic fault injection (e.g. "
                        "'stall_step=10,stall_s=2,stall_worker=1' to "
                        "simulate a straggler, or "
                        "'kill_worker=1,kill_round=3' to crash a worker "
                        "mid-run; also via SPARKNET_CHAOS)")
    _add_perf_flags(c)
    _add_feed_flags(c)
    _add_health_flags(c)
    _add_elastic_flags(c)
    _add_heartbeat_flags(c)
    c.set_defaults(fn=cmd_cifar)

    lm = sub.add_parser("lm", help="transformer-LM driver (synthetic "
                                   "bigram corpus; optional GPipe pipeline)")
    lm.add_argument("--vocab", type=int, default=512)
    lm.add_argument("--seq-len", type=int, default=256)
    lm.add_argument("--batch", type=int, default=8)
    lm.add_argument("--d-model", type=int, default=256)
    lm.add_argument("--layers", type=int, default=4)
    lm.add_argument("--heads", type=int, default=8)
    lm.add_argument("--steps", type=int, default=500)
    lm.add_argument("--lr", type=float, default=3e-4)
    lm.add_argument("--solver-type", default="Adam")
    lm.add_argument("--seed", type=int, default=0)
    lm.add_argument("--display", type=int, default=50)
    lm.add_argument("--dtype", choices=("f32", "bf16"), default="f32")
    lm.add_argument("--no-flash", action="store_true",
                    help="dense attention instead of the pallas kernel")
    lm.add_argument("--moe-experts", type=int, default=0)
    lm.add_argument("--moe-aux-weight", type=float, default=0.01,
                    help="Switch load-balancing aux loss weight")
    lm.add_argument("--ep", type=int, default=1,
                    help="N>1: ExpertParallelSolver over an N-way "
                         "\"expert\" mesh axis (needs --moe-experts)")
    lm.add_argument("--dp", type=int, default=1,
                    help="data-parallel ways composed with --ep "
                         "(mesh {data: dp, expert: ep})")
    lm.add_argument("--sp", type=int, default=1,
                    help="sequence-parallel ways composed with --ep: "
                         "dp x sp x ep long-context MoE (ring attention "
                         "over \"seq\")")
    lm.add_argument("--pipeline-stages", type=int, default=1,
                    help="N>1: run the trunk as an N-stage GPipe pipeline "
                         "over a pipe mesh axis (PipelineLMSolver)")
    lm.add_argument("--microbatches", type=int, default=0)
    _add_perf_flags(lm, scan=True)
    _add_sharding_flags(lm)
    lm.add_argument("--metrics", help="JSONL loss-curve output path")
    lm.add_argument("--snapshot-every", type=int, default=0)
    lm.add_argument("--snapshot-prefix")
    lm.add_argument("--resume", help=".lm.npz (pipeline) or "
                                     ".solverstate.h5 to resume from")
    lm.set_defaults(fn=cmd_lm)

    rp = sub.add_parser("report",
                        help="aggregate a --metrics JSONL into a run "
                             "report (phases, step percentiles, comms, "
                             "recompiles, loss curve)")
    rp.add_argument("jsonl", help="metrics JSONL written by --metrics")
    rp.add_argument("--json", help="also write machine-readable report "
                                   "JSON here (BENCH_*.json-comparable)")
    rp.add_argument("--chrome", help="also export the run's spans as a "
                                     "Chrome trace_event file")
    rp.add_argument("--since", type=float, default=None, metavar="T",
                    help="only aggregate events from T seconds into the "
                         "run on (the JSONL 't' field); selecting zero "
                         "events is an error (exit 2), never an empty "
                         "report that reads as healthy")
    rp.add_argument("--event", metavar="KINDS",
                    help="comma-separated event kinds to aggregate "
                         "(e.g. 'health,divergence'); selecting zero "
                         "events is an error (exit 2)")
    rp.add_argument("--format", choices=("text", "json"), default="text",
                    help="json: print the report dict itself on stdout "
                         "(stable keys mirroring the rendered sections) "
                         "for CI / perf-gate assertions")
    rp.set_defaults(fn=cmd_report)

    mo = sub.add_parser("monitor",
                        help="tail a --metrics JSONL and render a live "
                             "terminal summary (round/loss per worker, "
                             "divergence, stragglers, memory, alarms)")
    mo.add_argument("jsonl", help="metrics JSONL a run is writing "
                                  "via --metrics")
    mo.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in seconds")
    mo.add_argument("--once", action="store_true",
                    help="render the current state once and exit")
    mo.add_argument("--wait", action="store_true",
                    help="wait for the file to appear instead of erroring "
                         "(a run that hasn't started writing yet)")
    mo.add_argument("--duration", type=float, default=None,
                    help="stop after this many seconds (default: forever)")
    mo.set_defaults(fn=cmd_monitor)

    tr = sub.add_parser(
        "trace",
        help="merge per-host metrics JSONLs into one clock-aligned "
             "fleet timeline: Chrome trace export (one track per host "
             "+ clock-offset metadata) and per-round critical-path "
             "attribution naming the blocking host and phase")
    tr.add_argument("metrics", nargs="+",
                    help="metrics JSONL file(s) — one per host, or one "
                         "multiplexed simfleet stream")
    tr.add_argument("--chrome", metavar="OUT",
                    help="write the merged Chrome trace_event file here")
    tr.add_argument("--critpath", action="store_true",
                    help="render the per-round critical-path "
                         "decomposition (blocking host, phases, top "
                         "blockers, comms exposure)")
    tr.add_argument("--round", type=int, default=None, metavar="N",
                    help="limit --critpath to round N")
    tr.add_argument("--json", action="store_true",
                    help="emit the critpath/alignment result as JSON "
                         "on stdout instead of text")
    tr.set_defaults(fn=cmd_trace)

    sf = sub.add_parser(
        "simfleet",
        help="discrete-event fleet simulator: thousands of virtual "
             "hosts drive the real heartbeat/consensus/elastic-policy "
             "code (simulated clock, in-memory rendezvous) — single "
             "runs, --sweep grids, and replay validation against a "
             "recorded real multi-coordinator run")
    sf.add_argument("--hosts", type=int, default=64,
                    help="virtual fleet size")
    sf.add_argument("--rounds", type=int, default=50,
                    help="simulated training rounds")
    sf.add_argument("--interval", type=float, default=0.5,
                    help="heartbeat interval_s, simulated seconds")
    sf.add_argument("--lease", type=float, default=3.0,
                    help="heartbeat lease_s, simulated seconds")
    sf.add_argument("--round_s", type=float, default=None,
                    help="simulated round duration (default: "
                         "tau * step_s)")
    sf.add_argument("--tau", type=int, default=4,
                    help="local steps per consensus round (round_s = "
                         "tau * step_s — sweeping tau changes how much "
                         "compute amortizes each gate)")
    sf.add_argument("--step_s", type=float, default=0.25,
                    help="simulated seconds per local step")
    sf.add_argument("--jitter", type=float, default=0.15,
                    help="per-host round-duration jitter (std dev "
                         "fraction, seeded)")
    sf.add_argument("--quorum", type=int, default=1,
                    help="ElasticPolicy quorum (exit 4 below it)")
    sf.add_argument("--evict_after", type=int, default=1,
                    help="ElasticPolicy evict_after")
    sf.add_argument("--readmit_after", type=int, default=0,
                    help="ElasticPolicy readmit cooldown (0 = never)")
    sf.add_argument("--staleness", type=int, default=None,
                    help="bounded-staleness s (parking past it)")
    sf.add_argument("--s_decay", type=float, default=0.5,
                    help="staleness consensus weight decay per lag")
    sf.add_argument("--consensus",
                    choices=("auto", "sync", "async", "none"),
                    default="auto",
                    help="cross-host transport: the real File/"
                         "AsyncFileConsensus at small fleets, policy-"
                         "level version clocks at scale (auto)")
    sf.add_argument("--recover_after", type=int, default=0,
                    help="revive chaos-killed hosts after this many "
                         "rounds (0 = never) — the MTBF repair half")
    sf.add_argument("--chaos",
                    help="chaos spec, e.g. 'fail_rate=0.001,"
                         "fail_seed=7,fail_corr=8' or 'kill_host=2,"
                         "kill_host_round=5' (resilience/chaos.py)")
    sf.add_argument("--seed", type=int, default=0,
                    help="master seed: same spec + seed = same "
                         "timeline, to the event")
    sf.add_argument("--metrics",
                    help="JSONL metrics output — the standard stream; "
                         "renders through `sparknet report`/`monitor` "
                         "unchanged")
    sf.add_argument("--json", help="write the summary (or sweep "
                                   "results) JSON here")
    sf.add_argument("--sweep", action="append", metavar="GRID",
                    help="axis grid 'hosts=200:1000,fail_rate="
                         "0.0005:0.005' (Cartesian; repeatable — "
                         "cells accumulate)")
    sf.add_argument("--budget_s", type=float, default=None,
                    help="real wall-clock budget for a sweep; unfired "
                         "cells are reported, never silently dropped")
    sf.add_argument("--record_real", metavar="OUT",
                    help="run a REAL multi-coordinator SIGKILL-shaped "
                         "scenario (threads + wall clock + on-disk "
                         "rendezvous) and record its membership "
                         "sequence to OUT for --replay")
    sf.add_argument("--replay", metavar="REC",
                    help="re-run a recording in the simulator; exit 1 "
                         "unless the membership sequence matches "
                         "exactly")
    sf.add_argument("-v", "--verbose", action="store_true",
                    help="log the simulated fleet's membership story")
    # -- the SERVING-fleet simulator (sim/servefleet.py) --
    sf.add_argument("--serve", action="store_true",
                    help="simulate the serving fleet instead: virtual "
                         "replicas + the REAL router/autoscaler/canary "
                         "under open-loop arrival traces; exit 1 when "
                         "any request is lost without an explicit "
                         "429/5xx")
    sf.add_argument("--replicas", type=int, default=3,
                    help="(--serve) initial replica count")
    sf.add_argument("--windows", type=int, default=30,
                    help="(--serve) router windows to simulate")
    sf.add_argument("--window_s", type=float, default=1.0,
                    help="(--serve) router window, simulated seconds")
    sf.add_argument("--service_ms", type=float, default=20.0,
                    help="(--serve) per-request service time")
    sf.add_argument("--queue_limit", type=int, default=64,
                    help="(--serve) per-replica queue bound (429 past "
                         "it)")
    sf.add_argument("--rate", type=float, default=40.0,
                    help="(--serve) base arrival rate, req/s")
    sf.add_argument("--trace",
                    choices=("flat", "diurnal", "spike", "flash"),
                    default="flat",
                    help="(--serve) open-loop arrival shape")
    sf.add_argument("--spike_x", type=float, default=4.0,
                    help="(--serve) spike/flash rate multiplier")
    sf.add_argument("--slo_p99_ms", type=float, default=500.0,
                    help="(--serve) autoscaler p99 target")
    sf.add_argument("--slo_depth", type=int, default=32,
                    help="(--serve) autoscaler queue-depth target")
    sf.add_argument("--breach_windows", type=int, default=3,
                    help="(--serve) consecutive breach windows before "
                         "grow")
    sf.add_argument("--idle_windows", type=int, default=10,
                    help="(--serve) consecutive idle windows before "
                         "shrink")
    sf.add_argument("--max_replicas", type=int, default=8,
                    help="(--serve) autoscaler growth ceiling")
    sf.add_argument("--canary_w", type=int, default=0,
                    help="(--serve) window at which one replica "
                         "hot-reloads to a faulty sha (0 = never)")
    sf.add_argument("--canary_pct", type=float, default=20.0,
                    help="(--serve) canary traffic percentage")
    sf.add_argument("--canary_err", type=float, default=1.0,
                    help="(--serve) canary per-request fault "
                         "probability")
    sf.add_argument("--canary_min_requests", type=int, default=10,
                    help="(--serve) canary verdict sample floor")
    sf.add_argument("--die_w", type=int, default=None,
                    help="(--serve) window at which the lowest live "
                         "replica dies (deterministic kill)")
    sf.add_argument("--rejoin_w", type=int, default=None,
                    help="(--serve) window at which a dead replica "
                         "rejoins")
    sf.add_argument("--trace_sample", type=float, default=1.0,
                    help="(--serve) serve_trace head-sampling rate "
                         "(1.0 = every request)")
    sf.add_argument("--trace_tail_ms", type=float, default=None,
                    help="(--serve) always keep serve_trace exemplars "
                         "at/above this latency, regardless of "
                         "sampling")
    sf.add_argument("--slo_burn", action="store_true",
                    help="(--serve) track the SLO error budget and "
                         "multi-window burn-rate alerts")
    sf.add_argument("--burn_scale", type=float, default=1.0,
                    help="(--serve) burn-rate window scale (0.01 "
                         "shrinks the 5m/1h/6h windows 100x for "
                         "short sims)")
    sf.set_defaults(fn=cmd_simfleet)

    sv = sub.add_parser(
        "serve",
        help="serve a resilient checkpoint over HTTP: weights-only "
             "load, continuous batching into power-of-two buckets, "
             "hot reload on new snapshots, graceful SIGTERM drain")
    sv.add_argument("--prefix", required=True,
                    help="snapshot prefix (the training run's "
                         "--snapshot_prefix; reads <prefix>.latest.json)")
    sv.add_argument("--model",
                    help="deploy/net prototxt (optional for binaryproto "
                         "checkpoints — the model blob is "
                         "self-describing; required for .h5)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=0,
                    help="0 = pick a free port (announced on stdout)")
    sv.add_argument("--max_batch", type=int, default=8,
                    help="largest padding bucket; buckets are powers "
                         "of two up to this, one jit each")
    sv.add_argument("--max_wait_ms", type=float, default=5.0,
                    help="deadline: a batch closes once its oldest "
                         "request waited this long, even unfilled")
    sv.add_argument("--queue_limit", type=int, default=64,
                    help="queued-row bound; submissions beyond it get "
                         "429 (backpressure, not a latency tail)")
    sv.add_argument("--reload_poll", type=float, default=2.0,
                    help="seconds between manifest polls for hot "
                         "reload (0 disables)")
    sv.add_argument("--request_timeout", type=float, default=30.0,
                    help="per-request inference timeout (504 past it)")
    sv.add_argument("--no_warmup", action="store_true",
                    help="skip tracing every bucket before traffic")
    sv.add_argument("--metrics", help="JSONL metrics output path")
    sv.add_argument("--fleet_dir",
                    help="fleet rendezvous directory: lease this "
                         "replica into the serving fleet "
                         "(serve/fleet.py) for `sparknet route`")
    sv.add_argument("--replica", type=int, default=0,
                    help="this replica's id in the fleet (also tags "
                         "the chaos injectors)")
    sv.add_argument("--replicas", type=int, default=0,
                    help="initial fleet size hint (a higher --replica "
                         "grows the world, the PR 12 admission path)")
    sv.add_argument("--lease", type=float, default=3.0,
                    help="fleet lease_s: the router evicts this "
                         "replica when its beat goes stale past this")
    sv.add_argument("--heartbeat_interval", type=float, default=0.5,
                    help="fleet beat cadence (also bounds how stale "
                         "the router's queue-depth view can be)")
    sv.add_argument("--chaos",
                    help="chaos spec, e.g. 'kill_replica=0,kill_req=20'"
                         " (SIGKILL self after the 20th request) or "
                         "'slow_replica=0,slow_ms=50' "
                         "(resilience/chaos.py)")
    sv.add_argument("--trace_sample", type=float, default=1.0,
                    help="serve_trace head-sampling rate (1.0 = every "
                         "request emits a trace event)")
    sv.add_argument("--trace_tail_ms", type=float, default=250.0,
                    help="always keep serve_trace exemplars at/above "
                         "this latency, regardless of sampling")
    _add_perf_flags(sv, scan=True)
    sv.set_defaults(fn=cmd_serve)

    rt = sub.add_parser(
        "route",
        help="serving-fleet router: discovers `sparknet serve "
             "--fleet_dir` replicas through their leases, spreads "
             "POST /predict by least queue depth with retry-once "
             "failover, makes SLO autoscaling decisions, auto-rolls-"
             "back a bad canary checkpoint")
    rt.add_argument("--fleet_dir", required=True,
                    help="the fleet rendezvous directory replicas "
                         "lease into")
    rt.add_argument("--host", default="127.0.0.1")
    rt.add_argument("--port", type=int, default=0,
                    help="0 = pick a free port (announced on stdout)")
    rt.add_argument("--replicas", type=int, default=1,
                    help="expected initial fleet size (late replicas "
                         "grow the world on admission)")
    rt.add_argument("--lease", type=float, default=3.0,
                    help="lease_s: a replica whose beat is staler "
                         "than this is evicted (failover window)")
    rt.add_argument("--window_s", type=float, default=1.0,
                    help="membership/SLO evaluation cadence")
    rt.add_argument("--request_timeout", type=float, default=30.0,
                    help="per-dispatch timeout toward a replica")
    rt.add_argument("--no_autoscale", action="store_true",
                    help="disable SLO autoscaling decisions")
    rt.add_argument("--slo_p99_ms", type=float, default=500.0,
                    help="autoscaler p99 target")
    rt.add_argument("--slo_depth", type=int, default=32,
                    help="autoscaler queue-depth target")
    rt.add_argument("--breach_windows", type=int, default=3,
                    help="consecutive breach windows before a grow "
                         "decision (scale events; an orchestrator "
                         "launches the replica)")
    rt.add_argument("--idle_windows", type=int, default=30,
                    help="consecutive idle windows before a shrink "
                         "(drain order to the highest replica)")
    rt.add_argument("--min_replicas", type=int, default=1)
    rt.add_argument("--max_replicas", type=int, default=8)
    rt.add_argument("--canary_pct", type=float, default=20.0,
                    help="traffic share for a second checkpoint sha "
                         "while a canary is in flight")
    rt.add_argument("--canary_min_requests", type=int, default=20,
                    help="canary responses required before a verdict")
    rt.add_argument("--canary_err_delta", type=float, default=0.05,
                    help="rollback when canary error rate exceeds "
                         "baseline by this")
    rt.add_argument("--canary_p99_delta_ms", type=float, default=500.0,
                    help="rollback when canary p99 exceeds baseline "
                         "by this")
    rt.add_argument("--metrics", help="JSONL metrics output path "
                                      "(route/scale/canary + "
                                      "membership events)")
    rt.add_argument("--trace_sample", type=float, default=1.0,
                    help="serve_trace head-sampling rate at the "
                         "router (1.0 = every request)")
    rt.add_argument("--trace_tail_ms", type=float, default=250.0,
                    help="always keep serve_trace exemplars at/above "
                         "this latency, regardless of sampling")
    rt.add_argument("--slo_ms", type=float, default=None,
                    help="error-budget SLO latency bound (default: "
                         "--slo_p99_ms)")
    rt.add_argument("--slo_objective", type=float, default=0.999,
                    help="error-budget availability objective "
                         "(fraction of requests that must be good)")
    rt.add_argument("--burn_scale", type=float, default=1.0,
                    help="burn-rate window scale (0.01 shrinks the "
                         "5m/1h/6h windows 100x for short runs)")
    rt.add_argument("--no_slo_burn", action="store_true",
                    help="disable the SLO error-budget ledger")
    rt.set_defaults(fn=cmd_route)

    sb = sub.add_parser(
        "serve-bench",
        help="load-generate against a running `sparknet serve` "
             "(closed loop = capacity, open loop = honest tail "
             "latency at a fixed arrival rate)")
    sb.add_argument("--url", required=True,
                    help="server base URL, e.g. http://127.0.0.1:8080")
    sb.add_argument("--mode", choices=("closed", "open", "both"),
                    default="closed")
    sb.add_argument("--concurrency", type=int, default=4,
                    help="closed loop: workers with one request in "
                         "flight each (also bounds open-loop dispatch)")
    sb.add_argument("--rate", type=float, default=50.0,
                    help="open loop: offered requests/second")
    sb.add_argument("--duration", type=float, default=5.0,
                    help="seconds per mode")
    sb.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    sb.add_argument("--request_timeout", type=float, default=10.0)
    sb.add_argument("--metrics", help="JSONL metrics output path "
                                      "(bench rows)")
    sb.add_argument("--json", help="write per-mode summaries here")
    sb.set_defaults(fn=cmd_serve_bench)

    li = sub.add_parser(
        "lint",
        help="static analysis: JAX hazard rules (host syncs/recompiles/"
             "PRNG reuse/axis mismatches in jitted code), the "
             "guarded-by lock-discipline race checker, deadlock rules "
             "(lock-order cycles, blocking/callbacks under locks), "
             "distributed file-protocol rules (atomic rendezvous "
             "writes, bounded gates, canonical exit codes), and the "
             "metrics event-schema rules")
    li.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "sparknet_tpu package source)")
    li.add_argument("--strict", action="store_true",
                    help="exit 1 on ANY non-baselined finding (warnings "
                         "included), stale baseline entries, or "
                         "baseline entries without a justification — "
                         "the CI mode (scripts/lint.sh)")
    li.add_argument("--baseline",
                    help="baseline file (default: "
                         ".sparknet-lint-baseline.json next to the lint "
                         "root, then CWD)")
    li.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings into the baseline "
                         "(new entries need --justification; stale "
                         "entries expire)")
    li.add_argument("--justification",
                    help="justification text recorded on entries newly "
                         "added by --write-baseline")
    li.add_argument("--select", metavar="CODES",
                    help="comma-separated rule codes to run "
                         "(e.g. SPK101,SPK201), or a profile: @tests "
                         "(parse/file-protocol/exit-code rules for the "
                         "test tree), @tools (those plus the JAX "
                         "host-sync hazards, for scripts/ and "
                         "experiments/)")
    li.add_argument("--exclude", action="append", default=[],
                    metavar="PATTERN",
                    help="skip files whose path matches (substring, "
                         "glob, or path-component glob); repeatable — "
                         "e.g. --exclude fixtures")
    li.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="lint files across N forked workers (the "
                         "parsed project index is shared "
                         "copy-on-write)")
    li.add_argument("--cache", action="store_true",
                    help="reuse per-file results keyed on content + "
                         "rule sources + cross-module summaries "
                         "(.sparknet-lint-cache.json next to the "
                         "root; safe to delete any time)")
    li.add_argument("--write-event-schema", action="store_true",
                    help="regenerate sparknet_tpu/obs/event_schema.py "
                         "from the repo's metrics emit sites and exit "
                         "(rules SPK401/402 and "
                         "tests/test_event_schema.py check against "
                         "it)")
    li.add_argument("--root", help="directory finding paths are "
                                   "reported relative to (default: "
                                   "CWD, or the package parent when "
                                   "linting the default target)")
    li.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    li.add_argument("-v", "--verbose", action="store_true",
                    help="also print baselined findings with their "
                         "justifications")
    li.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    li.set_defaults(fn=cmd_lint)

    i = sub.add_parser("imagenet", help="ImageNetApp driver")
    i.add_argument("--workers", type=int, default=None)
    i.add_argument("--strategy", choices=("local_sgd", "dp"),
                   default="local_sgd")
    i.add_argument("--tau", type=int, default=50)
    i.add_argument("--batch", type=int, default=256)
    i.add_argument("--classes", type=int, default=1000)
    i.add_argument("--rounds", type=int, default=2)
    i.add_argument("--log")
    i.add_argument("--metrics", help="JSONL metrics output path")
    i.set_defaults(fn=cmd_imagenet)

    args = p.parse_args(argv)
    if args.verb in ("train", "test", "time", "device_query", "cifar",
                     "imagenet", "lm", "serve"):
        # multi-host bootstrap (no-op single-process; SPARKNET_COORDINATOR
        # et al. select the jax.distributed rendezvous — see DEPLOY.md)
        from .parallel import distributed_init
        distributed_init()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
