"""Command-line interface — the reference native CLI (tools/caffe.cpp).

Verbs (mirroring the brew registry, caffe.cpp:55):
  train         train from a -solver prototxt (caffe.cpp:153)
  test          score a model (caffe.cpp:222)
  time          per-layer fwd/bwd timing (caffe.cpp:290)
  device_query  enumerate devices (caffe.cpp:110)
plus the app drivers:
  cifar         CifarApp (reference src/main/scala/apps/CifarApp.scala)
  imagenet      ImageNetApp (reference ImageNetApp.scala)

Signal semantics follow the reference flags -sigint_effect/-sighup_effect
(caffe.cpp:43-46): snapshot / stop / none.
"""

import argparse
import json
import sys
import time


def _mesh_arg(s):
    """"data=8,seq=2" -> {"data": 8, "seq": 2}; "8" -> {"data": 8}."""
    if s.isdigit():
        return {"data": int(s)}
    out = {}
    for part in s.split(","):
        k, _, v = part.partition("=")
        out[k.strip()] = int(v)
    return out


def cmd_device_query(args):
    import jax
    for d in jax.devices():
        print(f"id {d.id}: {d.device_kind} ({d.platform}) "
              f"process {d.process_index}")
    return 0


def _make_data_iter(net, seed=0):
    """Synthetic batch stream matching the net's feed shapes (stands in for
    LMDB: the stock prototxt data sources are host-side concerns)."""
    import numpy as np
    rs = np.random.RandomState(seed)
    shapes = net.feed_shapes()

    def gen():
        while True:
            batch = {}
            for name, shape in shapes.items():
                if len(shape) <= 1 or "label" in name:
                    batch[name] = rs.randint(0, 10, shape).astype(np.int32)
                else:
                    batch[name] = rs.randn(*shape).astype(np.float32)
            yield batch
    return gen()


def _net_base_dir(sp, solver_path):
    """Stock solver prototxts name their net relative to the caffe repo root
    (e.g. "examples/cifar10/..."); caffe resolves against CWD. Walk up from
    the solver file until the referenced net path exists."""
    import os
    rel = None
    for f in ("net", "train_net"):
        if sp.has(f):
            rel = getattr(sp, f)
            break
    if rel is None or os.path.isabs(rel) or os.path.exists(rel):
        return ""
    d = os.path.dirname(os.path.abspath(solver_path))
    while True:
        if os.path.exists(os.path.join(d, rel)):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return ""
        d = parent


def _feed_shapes_arg(specs):
    """["data=100,3,32,32", ...] -> {"data": (100,3,32,32)} (the shape LMDB
    records would supply in stock caffe)."""
    out = {}
    for s in specs or ():
        name, _, dims = s.partition("=")
        out[name.strip()] = tuple(int(d) for d in dims.replace("x", ",")
                                  .split(","))
    return out


def cmd_train(args):
    from .proto import text_format
    from .solver.solver import Solver
    from .utils.signals import SignalPolicy

    sp = text_format.load(args.solver, "SolverParameter")
    base_dir = _net_base_dir(sp, args.solver)
    feed = _feed_shapes_arg(args.input_shape)
    if args.strategy == "dp":
        from .parallel import DataParallelSolver, make_mesh
        solver = DataParallelSolver(sp, mesh=make_mesh(_mesh_arg(args.mesh))
                                    if args.mesh else None, base_dir=base_dir,
                                    feed_shapes=feed)
    else:
        solver = Solver(sp, base_dir=base_dir, feed_shapes=feed)
    if args.weights:
        solver.load_weights(args.weights)
    if args.snapshot:
        solver.restore(args.snapshot)
    total = args.iterations or int(sp.max_iter) or 1000
    data_iter = _make_data_iter(solver.net)
    test_fn = (lambda: _make_data_iter(solver.test_net, seed=1)) \
        if solver.test_net is not None else None
    prefix = args.snapshot_prefix or (
        sp.snapshot_prefix if sp.has("snapshot_prefix") else None)
    policy = SignalPolicy(sigint=args.sigint_effect,
                          sighup=args.sighup_effect)
    with policy:
        while solver.iter < total:
            n = min(100, total - solver.iter)
            solver.step(n, data_iter, test_data_fn=test_fn)
            action = policy.pending()
            if action == "snapshot":
                solver.snapshot(prefix=prefix or "snap")
            elif action == "stop":
                print("stopping early on signal")
                break
    if prefix and sp.snapshot:
        solver.snapshot(prefix=prefix)
    print(f"Optimization done, iter={solver.iter}")
    return 0


def cmd_test(args):
    import numpy as np
    from .proto import text_format
    from .graph.compiler import CompiledNet, TEST
    from .solver.solver import Solver
    from .proto import Message

    net_param = text_format.load(args.model, "NetParameter")
    sp = Message("SolverParameter", base_lr=0.0, lr_policy="fixed",
                 display=0)
    sp.net_param = net_param
    solver = Solver(sp, feed_shapes=_feed_shapes_arg(args.input_shape))
    if args.weights:
        solver.load_weights(args.weights)
    it = _make_data_iter(solver.test_net or solver.net)
    scores = solver.test(it, num_iters=args.iterations)
    for k, v in scores.items():
        print(f"{k} = {np.asarray(v).mean():.6f}")
    return 0


def cmd_time(args):
    """Per-layer forward/backward timing — `caffe time` (caffe.cpp:290-376).
    Each layer is jitted in isolation on random inputs of its true shapes."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .proto import text_format
    from .graph.compiler import CompiledNet, TRAIN

    net_param = text_format.load(args.model, "NetParameter")
    net = CompiledNet(net_param, TRAIN,
                      feed_shapes=_feed_shapes_arg(args.input_shape))
    params, state = net.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    iters = args.iterations
    print(f"{'layer':<28}{'type':<18}{'fwd ms':>10}{'fwd+bwd ms':>12}")
    total_f = total_fb = 0.0
    for lp, impl, bottoms, tops in net.layers:
        if getattr(impl, "is_feed", False):
            continue
        bvals = [jnp.asarray(rs.randn(*net.blob_shapes[b]), jnp.float32)
                 for b in bottoms]
        lparams = net.resolve_params(params, lp.name)
        lstate = state.get(lp.name)
        rng = jax.random.PRNGKey(0)

        def fwd(lparams, bvals):
            if impl.has_state:
                tv, _ = impl.apply_stateful(lparams, lstate, bvals, True, rng)
            else:
                tv = impl.apply(lparams, bvals, True, rng)
            return sum(jnp.sum(t.astype(jnp.float32)) for t in tv)

        jf = jax.jit(fwd)
        jg = jax.jit(jax.grad(lambda bv: fwd(lparams, bv), argnums=0))
        try:
            float(jf(lparams, bvals))         # compile + sanity
            t0 = time.perf_counter()
            for _ in range(iters):
                r = jf(lparams, bvals)
            float(r)
            f_ms = (time.perf_counter() - t0) / iters * 1e3
            g = jg(bvals)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), g)
            t0 = time.perf_counter()
            for _ in range(iters):
                g = jg(bvals)
            float(jax.tree_util.tree_leaves(g)[0].ravel()[0])
            fb_ms = f_ms + (time.perf_counter() - t0) / iters * 1e3
        except Exception as e:                      # non-differentiable etc.
            print(f"{lp.name:<28}{lp.type:<18}{'—':>10}  ({e})")
            continue
        total_f += f_ms
        total_fb += fb_ms
        print(f"{lp.name:<28}{lp.type:<18}{f_ms:>10.3f}{fb_ms:>12.3f}")
    print(f"{'TOTAL':<28}{'':<18}{total_f:>10.3f}{total_fb:>12.3f}")
    print("note: per-layer jit; the fused full-step is faster "
          "(XLA cross-layer fusion)")
    return 0


def cmd_cifar(args):
    from .apps import CifarApp
    app = CifarApp(num_workers=args.workers, data_dir=args.data,
                   prototxt_dir=args.prototxt_dir, strategy=args.strategy,
                   tau=args.tau, log_path=args.log)
    app.run(num_rounds=args.rounds)
    return 0


def cmd_imagenet(args):
    from .apps import ImageNetApp
    app = ImageNetApp(num_workers=args.workers, strategy=args.strategy,
                      tau=args.tau, batch=args.batch, log_path=args.log,
                      num_classes=args.classes)
    app.run(num_rounds=args.rounds)
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="sparknet",
        description="TPU-native SparkNet: train/test/time/apps")
    sub = p.add_subparsers(dest="verb", required=True)

    t = sub.add_parser("train", help="train from a solver prototxt")
    t.add_argument("--solver", required=True)
    t.add_argument("--weights", help=".caffemodel to finetune from")
    t.add_argument("--snapshot", help=".solverstate to resume from")
    t.add_argument("--iterations", type=int, default=None)
    t.add_argument("--strategy", choices=("single", "dp"), default="single")
    t.add_argument("--mesh", help='e.g. "data=8"')
    t.add_argument("--snapshot-prefix",
                   help="override the solver's snapshot_prefix")
    t.add_argument("--input-shape", action="append", default=[],
                   help='feed blob shape hint, e.g. "data=100,3,32,32" '
                        "(stands in for the LMDB record shape)")
    t.add_argument("--sigint_effect", default="stop",
                   choices=("snapshot", "stop", "none"))
    t.add_argument("--sighup_effect", default="snapshot",
                   choices=("snapshot", "stop", "none"))
    t.set_defaults(fn=cmd_train)

    te = sub.add_parser("test", help="score a model")
    te.add_argument("--model", required=True)
    te.add_argument("--weights")
    te.add_argument("--iterations", type=int, default=50)
    te.add_argument("--input-shape", action="append", default=[])
    te.set_defaults(fn=cmd_test)

    ti = sub.add_parser("time", help="per-layer timing")
    ti.add_argument("--model", required=True)
    ti.add_argument("--iterations", type=int, default=10)
    ti.add_argument("--input-shape", action="append", default=[])
    ti.set_defaults(fn=cmd_time)

    d = sub.add_parser("device_query", help="list devices")
    d.set_defaults(fn=cmd_device_query)

    c = sub.add_parser("cifar", help="CifarApp driver")
    c.add_argument("--workers", type=int, default=None)
    c.add_argument("--data", help="dir with CIFAR-10 .bin batches")
    c.add_argument("--prototxt-dir", help="dir with stock cifar10 prototxts")
    c.add_argument("--strategy", choices=("local_sgd", "dp"),
                   default="local_sgd")
    c.add_argument("--tau", type=int, default=10)
    c.add_argument("--rounds", type=int, default=20)
    c.add_argument("--log")
    c.set_defaults(fn=cmd_cifar)

    i = sub.add_parser("imagenet", help="ImageNetApp driver")
    i.add_argument("--workers", type=int, default=None)
    i.add_argument("--strategy", choices=("local_sgd", "dp"),
                   default="local_sgd")
    i.add_argument("--tau", type=int, default=50)
    i.add_argument("--batch", type=int, default=256)
    i.add_argument("--classes", type=int, default=1000)
    i.add_argument("--rounds", type=int, default=2)
    i.add_argument("--log")
    i.set_defaults(fn=cmd_imagenet)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
