"""Elastic membership: quorum-based sync rounds that survive worker loss.

The sync round inherited from the paper is all-or-nothing — the params
(or gradient) average includes every mesh slot on the data axis, so one
dead or NaN'd worker poisons the consensus and stalls the run. PR 3's
sensors can *name* a sick worker (straggler, loss skew,
worker_nonfinite); this module is the layer that *acts* on it, in the
spirit of sync-SGD-with-backup-workers (Chen et al. 2016,
arXiv:1604.00981) and elastic runtimes (TorchElastic, Elastic Horovod).

Two halves, like obs/divergence.py:

device half (pure jnp, called inside shard_map by the sharded solvers):

  masked_consensus        validity-masked weighted average across the
                          axis: each worker contributes iff its host-
                          declared alive bit AND its on-device finite
                          check hold; weights renormalize over the live
                          count. BIT-FOR-BIT equal to ``lax.pmean`` when
                          every worker is valid (`jnp.where` keeps dead
                          workers' NaNs out of the psum entirely —
                          ``NaN * 0`` would still be NaN).
  masked_consensus_stats  the same average plus the divergence aux of
                          obs/divergence.consensus_stats, with dead
                          workers excluded from the drift statistics and
                          a ``valid``/``n_live`` membership report.
  tree_finite             scalar "all leaves finite" — the device-side
                          validity bit, so a worker whose replica went
                          non-finite mid-round can never poison the
                          consensus even before the host reacts.

host half:

  ElasticPolicy   per-round membership controller: consumes the fetched
                  membership aux (per-worker validity, losses) plus the
                  chaos ``kill_worker``/``dead_p`` injectors, evicts a
                  worker after ``evict_after`` consecutive invalid
                  rounds (per-worker ``eviction`` records in the
                  metrics stream), readmits it after a
                  ``readmit_after``-round cooldown (the replicated
                  consensus weights ARE the re-broadcast — every slot,
                  dead or alive, leaves the round holding them), and
                  raises QuorumLost when the live count would drop
                  below ``quorum`` — the CLI maps that to exit code
                  EXIT_QUORUM_LOST (4), documented in DEPLOY.md.
  expand_to_slots re-partition helper: lay batches drawn for the LIVE
                  workers back onto the full slot grid (dead slots get
                  a survivor's copy, which the device mask discards) —
                  the sampler/shard_batch path only pays for data that
                  will actually be consumed.

Eviction is an input (the (n,) alive mask) to the already-compiled
round, so membership changes cost zero recompiles; when an eviction is
persistent, ``LocalSGDSolver.shrink_to_survivors()`` optionally rebuilds
the mesh over the live devices (one recompile) so dead slots stop
burning compute.

Bounded staleness (the async local-SGD mode, ISSUE 7) generalizes the
0/1 validity bit to a [0, 1] per-worker WEIGHT: a worker ``lag`` rounds
behind the fastest live peer contributes with weight
``staleness_discount(lag, s, decay)`` — exactly 1.0 at lag 0 (the
bit-for-bit anchor: an s=0 async round IS the synchronous masked
round), geometrically discounted while 0 < lag <= s, and excluded by
the same where-mask as a dead worker once the bound is hit. The host
half of the mode also lives here: ElasticPolicy tracks per-worker round
versions on virtual clocks (a chaos ``slow_worker`` accrues its injected
seconds instead of blocking the consensus), PARKS a worker whose lag
crosses the bound (weight 0, still a member), readmits it after
``unpark_after`` rounds by resyncing it onto the replicated consensus
(the same free re-broadcast as eviction readmission), and optionally
evicts a chronically-parked worker — stale and dead workers degrade
through identical machinery, and progress never blocks on the slowest
fault domain.
"""

import numpy as np

from ..utils.exit_codes import EXIT_QUORUM_LOST  # noqa: F401  (re-export)


class QuorumLost(RuntimeError):
    """Live worker count fell below the quorum — the run cannot make a
    trustworthy consensus anymore. The CLI exits EXIT_QUORUM_LOST (4);
    see the DEPLOY.md supervisor runbook."""


# -- device half (inside shard_map) ----------------------------------------

def tree_finite(tree):
    """Replicated-per-worker bool scalar: every leaf of ``tree`` is
    finite everywhere. One elementwise pass, no collectives."""
    import jax
    import jax.numpy as jnp
    ok = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(
            jnp.asarray(leaf, jnp.float32))))
    return ok


def _live_scale(valid, axis):
    """(n_live, scale) for a masked average: scale = n/max(n_live, 1),
    EXACTLY 1.0f when every worker is valid (n/n with small ints exact
    in f32), so `pmean(masked) * scale` is bit-for-bit `pmean(x)` in the
    all-valid case no matter how the backend lowers pmean's division."""
    import jax
    import jax.numpy as jnp
    from ..parallel.compat import axis_size
    n = axis_size(axis)
    n_live = jax.lax.psum(jnp.asarray(valid, jnp.float32), axis)
    scale = jnp.float32(n) / jnp.maximum(n_live, jnp.float32(1))
    return n_live, scale


def masked_consensus(tree, valid, axis):
    """Validity-masked average of ``tree`` across ``axis`` (inside
    shard_map). ``valid``: this worker's f32 0/1 scalar. Returns
    (consensus, n_live); the consensus is replicated (same on every
    worker, dead ones included — that replication is the readmission
    re-broadcast for free).

    All-valid bit-for-bit contract: ``where(True, x, 0) == x`` exactly,
    and the renormalization scale n/n_live is exactly 1.0, so the value
    is the plain ``pmean`` bit-for-bit — the same pmean the collective
    always was, not a reimplementation that could round differently.
    Dead workers are excluded with ``jnp.where`` — a multiplicative
    mask would leak their NaNs (NaN*0 == NaN)."""
    import jax
    import jax.numpy as jnp
    n_live, scale = _live_scale(valid, axis)
    keep = valid > 0

    def one(x):
        x = jnp.asarray(x)
        m = jax.lax.pmean(jnp.where(keep, x, jnp.zeros_like(x)), axis)
        return m * scale.astype(m.dtype)

    return jax.tree_util.tree_map(one, tree), n_live


def masked_scalar_mean(x, valid, axis):
    """Masked mean of one replicated-output scalar (e.g. the round
    loss): dead workers' NaNs stay out of the displayed value. Same
    all-valid bit-for-bit contract as masked_consensus."""
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(x, jnp.float32)
    _, scale = _live_scale(valid, axis)
    return jax.lax.pmean(jnp.where(valid > 0, x, jnp.float32(0)),
                         axis) * scale


def masked_consensus_stats(tree, valid, axis, consensus=None):
    """masked_consensus + the divergence aux of
    obs/divergence.consensus_stats, dead workers excluded from the
    drift statistics (their distance to consensus is garbage). The aux
    additionally carries the membership report:

      valid    (N,) all_gather of each worker's effective validity
      n_live   live count the average renormalized over

    ``consensus``: optional precomputed (consensus, n_live) pair — the
    DP solver passes the bucketed collective's result (parallel/
    overlap.py, bit-for-bit the direct call) so overlap and divergence
    metering compose instead of excluding each other.
    """
    import jax
    import jax.numpy as jnp
    from ..obs.divergence import tree_sq_dist
    consensus, n_live = masked_consensus(tree, valid, axis) \
        if consensus is None else consensus
    per_layer, local_sq = tree_sq_dist(tree, consensus)
    keep = valid > 0
    local_sq = jnp.where(keep, local_sq, jnp.float32(0))
    aux = {
        "div_mean_sq": masked_scalar_mean(local_sq, valid, axis),
        "div_max_sq": jax.lax.pmax(local_sq, axis),
        "div_worker_sq": jax.lax.all_gather(local_sq, axis),
        "layer_div_sq": {k: masked_scalar_mean(v, valid, axis)
                         for k, v in per_layer.items()},
        "valid": jax.lax.all_gather(jnp.asarray(valid, jnp.float32), axis),
        "n_live": n_live,
    }
    return consensus, aux


# -- bounded staleness (device half) ----------------------------------------

def staleness_discount(lag, s, decay=0.5):
    """Per-worker staleness weight in [0, 1] for the async bounded-
    staleness consensus. ``lag``: rounds behind the fastest live peer
    (f32 scalar or vector); ``s``: the staleness bound; ``decay``: the
    geometric discount per round of lag.

      lag == 0      -> EXACTLY 1.0f (the bit-for-bit anchor: with every
                       lag zero the weighted average degenerates to the
                       synchronous masked round, bit for bit)
      0 < lag <= s  -> decay ** lag (strictly monotone in lag for
                       decay < 1; decay=1 keeps all in-bound workers at
                       full weight — pure bounded staleness, no discount)
      lag > s       -> 0.0 (over-stale == dead to the consensus; the
                       same where-mask excludes both)

    Pure jnp, usable inside shard_map; lag arrives as a traced input so
    staleness changes cost zero recompiles (like the alive mask)."""
    import jax.numpy as jnp
    lag = jnp.asarray(lag, jnp.float32)
    w = jnp.where(lag <= 0, jnp.float32(1),
                  jnp.float32(decay) ** lag)
    return jnp.where(lag > jnp.float32(s), jnp.float32(0), w)


def weighted_consensus(tree, weight, axis):
    """masked_consensus generalized from a 0/1 validity bit to a [0, 1]
    per-worker weight: consensus = sum_w weight_w * x_w / sum_w weight_w
    across ``axis``, zero-weight workers excluded via ``jnp.where`` (so
    their NaNs never reach the psum — identical discipline to the dead-
    worker mask). Returns (consensus, weight_sum).

    Bit-for-bit contract: with every weight EXACTLY 1.0 this is the
    masked_consensus all-valid path bit for bit — ``x * 1.0f`` is
    bitwise ``x`` for every IEEE value, the weight psum equals the live
    count exactly (small ints exact in f32), and the renormalization
    scale is exactly 1.0 — so an s=0 async round is THE synchronous
    round, not a reimplementation that could round differently."""
    import jax
    import jax.numpy as jnp
    from ..parallel.compat import axis_size
    n = axis_size(axis)
    weight = jnp.asarray(weight, jnp.float32)
    wsum = jax.lax.psum(weight, axis)
    # the 1e-6 floor only matters when EVERY weight is zero (the
    # all-excluded round returns zeros either way); for any wsum >= one
    # worker's weight the scale is exact
    scale = jnp.float32(n) / jnp.maximum(wsum, jnp.float32(1e-6))
    keep = weight > 0

    def one(x):
        x = jnp.asarray(x)
        xw = jnp.where(keep, x * weight.astype(x.dtype),
                       jnp.zeros_like(x))
        m = jax.lax.pmean(xw, axis)
        return m * scale.astype(m.dtype)

    return jax.tree_util.tree_map(one, tree), wsum


def weighted_consensus_stats(tree, valid, weight, axis, consensus=None):
    """weighted_consensus + the divergence aux of masked_consensus_stats.
    ``valid`` is the membership bit (alive AND device-finite — what the
    ElasticPolicy consumes for eviction streaks; a parked-but-healthy
    worker stays valid), ``weight`` the staleness-discounted consensus
    weight (valid * staleness_discount(lag)). Drift statistics cover the
    INCLUDED workers (weight > 0); the aux additionally gathers the
    weight vector so the host can attribute drift to staleness.
    ``consensus``: optional precomputed (consensus, weight_sum) pair —
    same contract as masked_consensus_stats."""
    import jax
    import jax.numpy as jnp
    from ..obs.divergence import tree_sq_dist
    consensus, wsum = weighted_consensus(tree, weight, axis) \
        if consensus is None else consensus
    included = (jnp.asarray(weight, jnp.float32) > 0)
    inc_f32 = included.astype(jnp.float32)
    per_layer, local_sq = tree_sq_dist(tree, consensus)
    local_sq = jnp.where(included, local_sq, jnp.float32(0))
    aux = {
        "div_mean_sq": masked_scalar_mean(local_sq, inc_f32, axis),
        "div_max_sq": jax.lax.pmax(local_sq, axis),
        "div_worker_sq": jax.lax.all_gather(local_sq, axis),
        "layer_div_sq": {k: masked_scalar_mean(v, inc_f32, axis)
                         for k, v in per_layer.items()},
        "valid": jax.lax.all_gather(jnp.asarray(valid, jnp.float32), axis),
        "weight": jax.lax.all_gather(jnp.asarray(weight, jnp.float32),
                                     axis),
        "n_live": jax.lax.psum(inc_f32, axis),
    }
    return consensus, aux


# -- host half -------------------------------------------------------------

def expand_to_slots(shards, owners):
    """Re-partition helper: ``shards`` is a list/array of per-LIVE-worker
    batch shards (worker-major); ``owners[slot]`` indexes into it for
    every mesh slot (identity-ish for live slots, a survivor for dead
    ones — see ElasticPolicy.shard_owners). Returns the full-slot-grid
    array the compiled round expects; dead slots' copies are discarded
    by the device mask, so only live shards carry fresh data."""
    shards = [np.asarray(s) for s in shards]
    return np.stack([shards[o] for o in owners])


class ElasticPolicy:
    """Membership controller for one sharded solver.

    observe_round(round_idx, valid=..., worker_loss=...) once per
    materialized sync round:

      * chaos ``kill_worker``/``dead_p`` injections evict immediately
        (the simulated crash — reason "chaos_kill")
      * an alive worker whose device validity bit was 0 (non-finite
        replica) for ``evict_after`` consecutive observed rounds is
        evicted (reason "nonfinite")
      * an evicted worker is readmitted after ``readmit_after`` rounds
        (0 disables readmission); the consensus weights every slot
        already holds are its restart state
      * if the live count would drop below ``quorum``, QuorumLost is
        raised (after logging a ``membership`` quorum_lost event)

    Every eviction/readmission logs a per-worker ``eviction`` /
    ``readmission`` metrics event, so `sparknet report` and
    `sparknet monitor` can render the membership history.
    """

    def __init__(self, n_workers, quorum=1, evict_after=2, readmit_after=5,
                 shrink_after=0, metrics=None, log_fn=print, chaos=None,
                 unit="worker", staleness=None, s_decay=0.5,
                 unpark_after=1, evict_parked_after=0):
        self.n = int(n_workers)
        # membership granularity: "worker" (a mesh slot on the data
        # axis — PR 4) or "host" (a whole fault domain on the host axis
        # of the hierarchical runtime). Only labeling and which chaos
        # injector feeds evictions differ; the masked-consensus math is
        # identical at either granularity.
        self.unit = str(unit)
        if self.n < 1:
            raise ValueError(f"elastic membership needs >= 1 {self.unit}")
        self.quorum = max(1, int(quorum))
        if self.quorum > self.n:
            raise ValueError(f"quorum {self.quorum} exceeds world size "
                             f"{self.n}")
        self.evict_after = max(1, int(evict_after))
        self.readmit_after = max(0, int(readmit_after))
        # >0: after this many consecutive rounds with ANY eviction in
        # force, suggest shrinking the mesh (the solver acts on it)
        self.shrink_after = max(0, int(shrink_after))
        # bounded staleness (the async local-SGD mode): None = the
        # synchronous policy; an int s >= 0 arms per-worker round-version
        # tracking on virtual clocks — a worker more than s rounds
        # behind the fastest live peer is PARKED (consensus weight 0,
        # still a member), resynced onto the replicated consensus after
        # ``unpark_after`` rounds, and (optionally) evicted after
        # ``evict_parked_after`` parks without a sustained in-bound
        # stretch in between (reason "staleness").
        self.staleness = None if staleness is None else max(0, int(staleness))
        self.s_decay = float(s_decay)
        if not (0.0 < self.s_decay <= 1.0):
            raise ValueError(f"s_decay {self.s_decay} must be in (0, 1]")
        self.unpark_after = max(1, int(unpark_after))
        self.evict_parked_after = max(0, int(evict_parked_after))
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self.chaos = chaos
        self.alive = np.ones(self.n, bool)
        self.evictions = []             # [{worker, round, reason}, ...]
        self.readmissions = []          # [{worker, round}, ...]
        self.admissions = []            # [{worker, round, via}, ...]
        self._bad_streak = np.zeros(self.n, np.int64)
        self._evicted_at = {}           # worker -> eviction round
        self._admitted_at = {}          # worker -> admission round
        self._degraded_rounds = 0       # consecutive rounds not at full n
        self.quorum_lost = False
        # async version accounting (all no-ops while staleness is None)
        self.parked = np.zeros(self.n, bool)
        self.version = np.zeros(self.n, np.int64)
        self.park_rounds = np.zeros(self.n, np.int64)  # total parked time
        self.parks = []                 # [{worker, round, lag}, ...]
        self.unparks = []               # [{worker, round, parked_rounds}]
        self._parked_at = {}            # worker -> park round
        self._park_streak = np.zeros(self.n, np.int64)
        self._inbound_streak = np.zeros(self.n, np.int64)
        self._done_at = np.zeros(self.n, np.float64)   # virtual clocks
        self._wall = 0.0

    # -- views -------------------------------------------------------------
    def live(self):
        """Sorted indices of live workers."""
        return [int(w) for w in np.nonzero(self.alive)[0]]

    def live_count(self):
        return int(self.alive.sum())

    def alive_f32(self):
        """The (n,) host alive mask the compiled round consumes."""
        return self.alive.astype(np.float32)

    def shard_owners(self):
        """For every mesh slot, the index (into the LIVE-ordered shard
        list) of the shard that fills it: live slots own their shard in
        live order; dead slots borrow a survivor's round-robin — see
        data/sampler.partition_owners and expand_to_slots."""
        from ..data.sampler import partition_owners
        owner_worker = partition_owners(self.n, self.alive)
        live = self.live()
        rank = {w: i for i, w in enumerate(live)}
        return [rank[int(w)] for w in owner_worker]

    def lag(self):
        """(n,) rounds each worker trails the fastest LIVE peer (0 with
        the synchronous policy). Parked and evicted workers' versions
        stop advancing, so their lag keeps growing until resync."""
        if self.staleness is None:
            return np.zeros(self.n, np.float64)
        fastest = self.version[self.alive].max() if self.alive.any() else 0
        return np.maximum(0, fastest - self.version).astype(np.float64)

    def consensus_weights(self):
        """(n,) f32 staleness-discounted consensus weight per worker —
        the host-side twin of the device staleness_discount path, for
        transports that average on the host (the async file relay).
        All ones with the synchronous policy."""
        w = self.alive_f32()
        if self.staleness is None:
            return w
        lag = self.lag()
        disc = np.where(lag <= 0, np.float32(1),
                        np.float32(self.s_decay) ** lag.astype(np.float32))
        disc = np.where(lag > self.staleness, np.float32(0), disc)
        return (w * disc).astype(np.float32)

    def summary(self):
        out = {"world": self.n, "live": self.live_count(),
               "quorum": self.quorum, "unit": self.unit,
               "evictions": list(self.evictions),
               "readmissions": list(self.readmissions),
               "admissions": list(self.admissions),
               "quorum_lost": self.quorum_lost}
        if self.staleness is not None:
            out.update(staleness=self.staleness,
                       parks=len(self.parks), unparks=len(self.unparks),
                       parked=[int(w) for w in np.nonzero(self.parked)[0]],
                       park_rounds_by_worker={
                           str(w): int(r) for w, r in
                           enumerate(self.park_rounds) if r},
                       max_lag=int(self.lag().max()))
        return out

    # -- membership transitions --------------------------------------------
    def evict(self, worker, round_idx, reason):
        w = int(worker)
        if not (0 <= w < self.n) or not self.alive[w]:
            return False
        if self.live_count() - 1 < self.quorum:
            self._quorum_lost(round_idx, would_evict=w, reason=reason)
        self.alive[w] = False
        self._bad_streak[w] = 0
        self._evicted_at[w] = round_idx
        if self.parked[w]:              # an evicted worker is no longer
            self.parked[w] = False      # "parked" — dead outranks stale
            r0 = self._parked_at.pop(w, round_idx)
            self.park_rounds[w] += max(0, round_idx - r0)
        rec = {"worker": w, "round": round_idx, "reason": reason,
               "live": self.live_count(), "unit": self.unit}
        self.evictions.append(rec)
        self.log(f"elastic: EVICTED {self.unit} {w} at round {round_idx} "
                 f"({reason}); {self.live_count()}/{self.n} live, "
                 f"shard re-spread over survivors")
        if self.metrics is not None:
            self.metrics.log("eviction", **rec)
            if self.unit == "host":
                # the per-host liveness stream (resilience/heartbeat.py
                # satellite): monitor/report render host evictions
                # without reparsing the generic eviction records
                self.metrics.log("host_evicted", host=w, round=round_idx,
                                 reason=reason, live=self.live_count())
        return True

    def readmit(self, worker, round_idx):
        w = int(worker)
        if not (0 <= w < self.n) or self.alive[w]:
            return False
        self.alive[w] = True
        self._bad_streak[w] = 0
        self._evicted_at.pop(w, None)
        if self.staleness is not None:
            # the replicated consensus IS the readmission re-broadcast:
            # the worker rejoins at the front, lag 0
            self.version[w] = self.version[self.alive].max()
            self._done_at[w] = self._wall
            self._park_streak[w] = 0
        rec = {"worker": w, "round": round_idx, "live": self.live_count(),
               "unit": self.unit}
        self.readmissions.append(rec)
        self.log(f"elastic: readmitted {self.unit} {w} at round {round_idx} "
                 f"from the consensus weights; "
                 f"{self.live_count()}/{self.n} live")
        if self.metrics is not None:
            self.metrics.log("readmission", **rec)
        return True

    def admit(self, worker, round_idx, via="grow"):
        """Admit ``worker`` into the world mid-run — the grow twin of
        evict/readmit (ROADMAP item 4: cluster size as a runtime knob).
        A known evicted slot is a readmission (a preempted host
        rejoining through the rendezvous); a slot index at or beyond
        the current world GROWS every per-worker array by append — the
        same masked-collective trick that makes eviction free makes
        admission free, because membership is host-side state and the
        compiled round never sees the world size change (zero
        recompiles). Either way the newcomer bootstraps from the
        replicated consensus weights, exactly like a readmission.
        Emits a ``membership`` admission record plus ``host_joined``
        (host unit) so report/monitor render joins beside evictions."""
        w = int(worker)
        if w < 0:
            return False
        if w < self.n:
            if self.alive[w] or not self.readmit(w, round_idx):
                return False
            self._record_admission(w, round_idx, via)
            return True
        front = int(self.version[self.alive].max()) if self.alive.any() \
            else 0
        grow = w + 1 - self.n
        self.alive = np.append(self.alive, np.ones(grow, bool))
        self._bad_streak = np.append(self._bad_streak,
                                     np.zeros(grow, np.int64))
        self.parked = np.append(self.parked, np.zeros(grow, bool))
        # the newcomer joins at the front of the version clocks: the
        # consensus it bootstraps from IS the freshest state
        self.version = np.append(self.version,
                                 np.full(grow, front, np.int64))
        self.park_rounds = np.append(self.park_rounds,
                                     np.zeros(grow, np.int64))
        self._park_streak = np.append(self._park_streak,
                                      np.zeros(grow, np.int64))
        self._inbound_streak = np.append(self._inbound_streak,
                                         np.zeros(grow, np.int64))
        self._done_at = np.append(self._done_at,
                                  np.full(grow, self._wall, np.float64))
        self.n = w + 1
        self._record_admission(w, round_idx, via)
        return True

    def _record_admission(self, w, round_idx, via):
        # the round that just materialized ran with this slot masked
        # out, so its validity bit is stale for the newcomer — exempt
        # it from this round's bad-streak accounting or evict_after=1
        # would re-evict every admission as "nonfinite" on arrival
        self._admitted_at[w] = round_idx
        rec = {"worker": w, "round": round_idx, "live": self.live_count(),
               "unit": self.unit, "via": via, "world": self.n}
        self.admissions.append(rec)
        self.log(f"elastic: ADMITTED {self.unit} {w} at round {round_idx} "
                 f"({via}); {self.live_count()}/{self.n} live, newcomer "
                 "bootstraps from the consensus weights")
        if self.metrics is not None:
            self.metrics.log("membership", kind="admission", **rec)
            if self.unit == "host":
                self.metrics.log("host_joined", host=w, round=round_idx,
                                 live=self.live_count(), via=via,
                                 world=self.n)

    # -- bounded staleness: park / unpark / version clocks -------------------
    def park(self, worker, round_idx, lag=None):
        """Park a worker whose staleness bound was hit: consensus weight
        0 (the same exclusion machinery as a dead worker) but it stays a
        MEMBER — no quorum impact, and the unpark below is its
        readmission. A ``parked`` metrics event records the transition;
        ``evict_parked_after`` consecutive parks without a sustained
        in-bound stretch escalate to a real eviction (reason
        "staleness"), which CAN raise QuorumLost."""
        w = int(worker)
        if not (0 <= w < self.n) or not self.alive[w] or self.parked[w]:
            return False
        self.parked[w] = True
        self._parked_at[w] = round_idx
        self._park_streak[w] += 1
        self._inbound_streak[w] = 0
        rec = {"worker": w, "round": round_idx, "unit": self.unit,
               "lag": None if lag is None else int(lag),
               "streak": int(self._park_streak[w])}
        self.parks.append(rec)
        self.log(f"elastic: PARKED {self.unit} {w} at round {round_idx} "
                 f"(lag {lag} > staleness bound {self.staleness}); "
                 f"excluded from the consensus until resync")
        if self.metrics is not None:
            self.metrics.log("parked", **rec)
        if self.evict_parked_after and \
                self._park_streak[w] >= self.evict_parked_after:
            return self.evict(w, round_idx, "staleness")
        return True

    def unpark(self, worker, round_idx):
        """Readmit a parked worker: it adopts the replicated consensus
        (every slot already holds it — the free re-broadcast) and
        rejoins at the front with lag 0. Emits an ``unparked`` event
        carrying the park duration (the park-time metric)."""
        w = int(worker)
        if not (0 <= w < self.n) or not self.parked[w]:
            return False
        self.parked[w] = False
        r0 = self._parked_at.pop(w, round_idx)
        dur = max(0, round_idx - r0)
        self.park_rounds[w] += dur
        self.version[w] = self.version[self.alive].max() \
            if self.alive.any() else self.version[w]
        self._done_at[w] = self._wall
        rec = {"worker": w, "round": round_idx, "unit": self.unit,
               "parked_rounds": int(dur),
               "park_rounds_total": int(self.park_rounds[w])}
        self.unparks.append(rec)
        self.log(f"elastic: unparked {self.unit} {w} at round {round_idx} "
                 f"after {dur} round(s), resynced from the consensus")
        if self.metrics is not None:
            self.metrics.log("unparked", **rec)
        return True

    def advance_versions(self, round_idx, round_s, slow=None):
        """Advance the per-worker virtual clocks by one wall round of
        ``round_s`` seconds. A healthy worker completes exactly one
        local round per wall round; a straggler (``slow``: the chaos
        ``slow_worker`` spec ``(worker, extra_s)``) pays ``extra_s``
        more per local round, so it completes them at rate
        round_s / (round_s + extra_s) and its version lag grows — the
        consensus does NOT wait for it (that is the whole point), it
        just discounts or excludes its contributions."""
        if self.staleness is None:
            return
        dt = max(float(round_s), 1e-6)
        self._wall += dt
        extra = np.zeros(self.n, np.float64)
        if slow is not None and slow[0] is not None \
                and 0 <= int(slow[0]) < self.n:
            extra[int(slow[0])] = max(0.0, float(slow[1]))
        for w in range(self.n):
            if not self.alive[w] or self.parked[w]:
                # parked/dead workers aren't racing; they rejoin fresh
                self._done_at[w] = self._wall
                continue
            cost = dt + extra[w]
            while self._done_at[w] + cost <= self._wall + 1e-9:
                self._done_at[w] += cost
                self.version[w] += 1

    def observe_staleness(self, round_idx):
        """The per-round staleness controller (async mode only): unpark
        workers whose cooldown elapsed (resync = readmission), then park
        any live worker whose lag crossed the bound. Returns True when
        park state changed (the next round's weights differ)."""
        if self.staleness is None:
            return False
        changed = False
        for w, r0 in sorted(self._parked_at.items()):
            if round_idx - r0 >= self.unpark_after:
                changed |= self.unpark(w, round_idx)
        lag = self.lag()
        for w in range(self.n):
            if not self.alive[w] or self.parked[w]:
                continue
            if lag[w] > self.staleness:
                changed |= self.park(w, round_idx, lag=lag[w])
            else:
                # a sustained in-bound stretch clears the park streak
                # (the worker genuinely recovered, it isn't cycling)
                self._inbound_streak[w] += 1
                if self._inbound_streak[w] > self.unpark_after + 1:
                    self._park_streak[w] = 0
        return changed

    def _quorum_lost(self, round_idx, **fields):
        self.quorum_lost = True
        if self.metrics is not None:
            self.metrics.log("membership", kind="quorum_lost",
                             round=round_idx, live=self.live_count(),
                             quorum=self.quorum, **fields)
        self.log(f"elastic: QUORUM LOST at round {round_idx}: "
                 f"{self.live_count()} live, need {self.quorum}")
        raise QuorumLost(
            f"live {self.unit}s would drop below quorum {self.quorum} "
            f"at round {round_idx} (exit {EXIT_QUORUM_LOST})")

    # -- the per-round controller ------------------------------------------
    def observe_round(self, round_idx, valid=None, worker_loss=None):
        """Feed one materialized round's membership signals. ``valid``:
        the (n,) effective validity vector fetched from the compiled
        round (host mask AND device finite bit). Raises QuorumLost when
        an eviction (or a chaos kill) would break the quorum. Returns
        True when membership changed (the caller may want to re-spread
        data or shrink)."""
        changed = False
        injector = "dead_hosts" if self.unit == "host" else "dead_workers"
        if self.chaos is not None and hasattr(self.chaos, injector):
            for w in getattr(self.chaos, injector)(round_idx, self.n):
                changed |= self.evict(w, round_idx, "chaos_kill")
        if self.chaos is not None and \
                hasattr(self.chaos, "rejoining_hosts") and \
                self.unit == "host":
            # preempt_host=H,rejoin_after=R (virtual hosts): the
            # preempted host comes back through the rendezvous R rounds
            # after its lease-drop, as an admission rather than the
            # readmit cooldown below
            for w in self.chaos.rejoining_hosts(round_idx):
                changed |= self.admit(w, round_idx, via="rejoin")
        if valid is not None:
            v = np.asarray(valid, np.float64).ravel()[:self.n]
            for w in range(len(v)):
                if not self.alive[w]:
                    continue
                if self._admitted_at.get(w) == round_idx:
                    continue    # admitted after this round ran: the
                                # validity bit predates its membership
                if v[w] > 0:
                    self._bad_streak[w] = 0
                    continue
                self._bad_streak[w] += 1
                if self._bad_streak[w] >= self.evict_after:
                    reason = "nonfinite"
                    if worker_loss is not None:
                        wl = np.asarray(worker_loss, np.float64).ravel()
                        if w < len(wl) and not np.isfinite(wl[w]):
                            reason = f"nonfinite loss ({wl[w]})"
                    changed |= self.evict(w, round_idx, reason)
        if self.readmit_after:
            for w, r0 in sorted(self._evicted_at.items()):
                if round_idx - r0 >= self.readmit_after:
                    changed |= self.readmit(w, round_idx)
        self._degraded_rounds = self._degraded_rounds + 1 \
            if self.live_count() < self.n else 0
        return changed

    def should_shrink(self):
        """True when evictions have been in force long enough that the
        solver should rebuild its mesh over the survivors (shrink_after
        rounds; 0 disables)."""
        return bool(self.shrink_after) and \
            self._degraded_rounds >= self.shrink_after and \
            self.live_count() < self.n

    def reset_world(self, n_workers):
        """After a mesh shrink: the survivors ARE the new world."""
        self.n = int(n_workers)
        self.quorum = min(self.quorum, self.n)
        self.alive = np.ones(self.n, bool)
        self._bad_streak = np.zeros(self.n, np.int64)
        self._evicted_at = {}
        self._admitted_at = {}
        self._degraded_rounds = 0
        self.parked = np.zeros(self.n, bool)
        self.version = np.zeros(self.n, np.int64)
        self.park_rounds = np.zeros(self.n, np.int64)
        self._parked_at = {}
        self._park_streak = np.zeros(self.n, np.int64)
        self._inbound_streak = np.zeros(self.n, np.int64)
        self._done_at = np.zeros(self.n, np.float64)
        self._wall = 0.0
        if self.metrics is not None:
            self.metrics.log("membership", kind="world_reset",
                             live=self.n)
