"""Elastic membership: quorum-based sync rounds that survive worker loss.

The sync round inherited from the paper is all-or-nothing — the params
(or gradient) average includes every mesh slot on the data axis, so one
dead or NaN'd worker poisons the consensus and stalls the run. PR 3's
sensors can *name* a sick worker (straggler, loss skew,
worker_nonfinite); this module is the layer that *acts* on it, in the
spirit of sync-SGD-with-backup-workers (Chen et al. 2016,
arXiv:1604.00981) and elastic runtimes (TorchElastic, Elastic Horovod).

Two halves, like obs/divergence.py:

device half (pure jnp, called inside shard_map by the sharded solvers):

  masked_consensus        validity-masked weighted average across the
                          axis: each worker contributes iff its host-
                          declared alive bit AND its on-device finite
                          check hold; weights renormalize over the live
                          count. BIT-FOR-BIT equal to ``lax.pmean`` when
                          every worker is valid (`jnp.where` keeps dead
                          workers' NaNs out of the psum entirely —
                          ``NaN * 0`` would still be NaN).
  masked_consensus_stats  the same average plus the divergence aux of
                          obs/divergence.consensus_stats, with dead
                          workers excluded from the drift statistics and
                          a ``valid``/``n_live`` membership report.
  tree_finite             scalar "all leaves finite" — the device-side
                          validity bit, so a worker whose replica went
                          non-finite mid-round can never poison the
                          consensus even before the host reacts.

host half:

  ElasticPolicy   per-round membership controller: consumes the fetched
                  membership aux (per-worker validity, losses) plus the
                  chaos ``kill_worker``/``dead_p`` injectors, evicts a
                  worker after ``evict_after`` consecutive invalid
                  rounds (per-worker ``eviction`` records in the
                  metrics stream), readmits it after a
                  ``readmit_after``-round cooldown (the replicated
                  consensus weights ARE the re-broadcast — every slot,
                  dead or alive, leaves the round holding them), and
                  raises QuorumLost when the live count would drop
                  below ``quorum`` — the CLI maps that to exit code
                  EXIT_QUORUM_LOST (4), documented in DEPLOY.md.
  expand_to_slots re-partition helper: lay batches drawn for the LIVE
                  workers back onto the full slot grid (dead slots get
                  a survivor's copy, which the device mask discards) —
                  the sampler/shard_batch path only pays for data that
                  will actually be consumed.

Eviction is an input (the (n,) alive mask) to the already-compiled
round, so membership changes cost zero recompiles; when an eviction is
persistent, ``LocalSGDSolver.shrink_to_survivors()`` optionally rebuilds
the mesh over the live devices (one recompile) so dead slots stop
burning compute.
"""

import numpy as np


EXIT_QUORUM_LOST = 4


class QuorumLost(RuntimeError):
    """Live worker count fell below the quorum — the run cannot make a
    trustworthy consensus anymore. The CLI exits EXIT_QUORUM_LOST (4);
    see the DEPLOY.md supervisor runbook."""


# -- device half (inside shard_map) ----------------------------------------

def tree_finite(tree):
    """Replicated-per-worker bool scalar: every leaf of ``tree`` is
    finite everywhere. One elementwise pass, no collectives."""
    import jax
    import jax.numpy as jnp
    ok = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(
            jnp.asarray(leaf, jnp.float32))))
    return ok


def _live_scale(valid, axis):
    """(n_live, scale) for a masked average: scale = n/max(n_live, 1),
    EXACTLY 1.0f when every worker is valid (n/n with small ints exact
    in f32), so `pmean(masked) * scale` is bit-for-bit `pmean(x)` in the
    all-valid case no matter how the backend lowers pmean's division."""
    import jax
    import jax.numpy as jnp
    from ..parallel.compat import axis_size
    n = axis_size(axis)
    n_live = jax.lax.psum(jnp.asarray(valid, jnp.float32), axis)
    scale = jnp.float32(n) / jnp.maximum(n_live, jnp.float32(1))
    return n_live, scale


def masked_consensus(tree, valid, axis):
    """Validity-masked average of ``tree`` across ``axis`` (inside
    shard_map). ``valid``: this worker's f32 0/1 scalar. Returns
    (consensus, n_live); the consensus is replicated (same on every
    worker, dead ones included — that replication is the readmission
    re-broadcast for free).

    All-valid bit-for-bit contract: ``where(True, x, 0) == x`` exactly,
    and the renormalization scale n/n_live is exactly 1.0, so the value
    is the plain ``pmean`` bit-for-bit — the same pmean the collective
    always was, not a reimplementation that could round differently.
    Dead workers are excluded with ``jnp.where`` — a multiplicative
    mask would leak their NaNs (NaN*0 == NaN)."""
    import jax
    import jax.numpy as jnp
    n_live, scale = _live_scale(valid, axis)
    keep = valid > 0

    def one(x):
        x = jnp.asarray(x)
        m = jax.lax.pmean(jnp.where(keep, x, jnp.zeros_like(x)), axis)
        return m * scale.astype(m.dtype)

    return jax.tree_util.tree_map(one, tree), n_live


def masked_scalar_mean(x, valid, axis):
    """Masked mean of one replicated-output scalar (e.g. the round
    loss): dead workers' NaNs stay out of the displayed value. Same
    all-valid bit-for-bit contract as masked_consensus."""
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(x, jnp.float32)
    _, scale = _live_scale(valid, axis)
    return jax.lax.pmean(jnp.where(valid > 0, x, jnp.float32(0)),
                         axis) * scale


def masked_consensus_stats(tree, valid, axis):
    """masked_consensus + the divergence aux of
    obs/divergence.consensus_stats, dead workers excluded from the
    drift statistics (their distance to consensus is garbage). The aux
    additionally carries the membership report:

      valid    (N,) all_gather of each worker's effective validity
      n_live   live count the average renormalized over
    """
    import jax
    import jax.numpy as jnp
    from ..obs.divergence import tree_sq_dist
    consensus, n_live = masked_consensus(tree, valid, axis)
    per_layer, local_sq = tree_sq_dist(tree, consensus)
    keep = valid > 0
    local_sq = jnp.where(keep, local_sq, jnp.float32(0))
    aux = {
        "div_mean_sq": masked_scalar_mean(local_sq, valid, axis),
        "div_max_sq": jax.lax.pmax(local_sq, axis),
        "div_worker_sq": jax.lax.all_gather(local_sq, axis),
        "layer_div_sq": {k: masked_scalar_mean(v, valid, axis)
                         for k, v in per_layer.items()},
        "valid": jax.lax.all_gather(jnp.asarray(valid, jnp.float32), axis),
        "n_live": n_live,
    }
    return consensus, aux


# -- host half -------------------------------------------------------------

def expand_to_slots(shards, owners):
    """Re-partition helper: ``shards`` is a list/array of per-LIVE-worker
    batch shards (worker-major); ``owners[slot]`` indexes into it for
    every mesh slot (identity-ish for live slots, a survivor for dead
    ones — see ElasticPolicy.shard_owners). Returns the full-slot-grid
    array the compiled round expects; dead slots' copies are discarded
    by the device mask, so only live shards carry fresh data."""
    shards = [np.asarray(s) for s in shards]
    return np.stack([shards[o] for o in owners])


class ElasticPolicy:
    """Membership controller for one sharded solver.

    observe_round(round_idx, valid=..., worker_loss=...) once per
    materialized sync round:

      * chaos ``kill_worker``/``dead_p`` injections evict immediately
        (the simulated crash — reason "chaos_kill")
      * an alive worker whose device validity bit was 0 (non-finite
        replica) for ``evict_after`` consecutive observed rounds is
        evicted (reason "nonfinite")
      * an evicted worker is readmitted after ``readmit_after`` rounds
        (0 disables readmission); the consensus weights every slot
        already holds are its restart state
      * if the live count would drop below ``quorum``, QuorumLost is
        raised (after logging a ``membership`` quorum_lost event)

    Every eviction/readmission logs a per-worker ``eviction`` /
    ``readmission`` metrics event, so `sparknet report` and
    `sparknet monitor` can render the membership history.
    """

    def __init__(self, n_workers, quorum=1, evict_after=2, readmit_after=5,
                 shrink_after=0, metrics=None, log_fn=print, chaos=None,
                 unit="worker"):
        self.n = int(n_workers)
        # membership granularity: "worker" (a mesh slot on the data
        # axis — PR 4) or "host" (a whole fault domain on the host axis
        # of the hierarchical runtime). Only labeling and which chaos
        # injector feeds evictions differ; the masked-consensus math is
        # identical at either granularity.
        self.unit = str(unit)
        if self.n < 1:
            raise ValueError(f"elastic membership needs >= 1 {self.unit}")
        self.quorum = max(1, int(quorum))
        if self.quorum > self.n:
            raise ValueError(f"quorum {self.quorum} exceeds world size "
                             f"{self.n}")
        self.evict_after = max(1, int(evict_after))
        self.readmit_after = max(0, int(readmit_after))
        # >0: after this many consecutive rounds with ANY eviction in
        # force, suggest shrinking the mesh (the solver acts on it)
        self.shrink_after = max(0, int(shrink_after))
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self.chaos = chaos
        self.alive = np.ones(self.n, bool)
        self.evictions = []             # [{worker, round, reason}, ...]
        self.readmissions = []          # [{worker, round}, ...]
        self._bad_streak = np.zeros(self.n, np.int64)
        self._evicted_at = {}           # worker -> eviction round
        self._degraded_rounds = 0       # consecutive rounds not at full n
        self.quorum_lost = False

    # -- views -------------------------------------------------------------
    def live(self):
        """Sorted indices of live workers."""
        return [int(w) for w in np.nonzero(self.alive)[0]]

    def live_count(self):
        return int(self.alive.sum())

    def alive_f32(self):
        """The (n,) host alive mask the compiled round consumes."""
        return self.alive.astype(np.float32)

    def shard_owners(self):
        """For every mesh slot, the index (into the LIVE-ordered shard
        list) of the shard that fills it: live slots own their shard in
        live order; dead slots borrow a survivor's round-robin — see
        data/sampler.partition_owners and expand_to_slots."""
        from ..data.sampler import partition_owners
        owner_worker = partition_owners(self.n, self.alive)
        live = self.live()
        rank = {w: i for i, w in enumerate(live)}
        return [rank[int(w)] for w in owner_worker]

    def summary(self):
        return {"world": self.n, "live": self.live_count(),
                "quorum": self.quorum, "unit": self.unit,
                "evictions": list(self.evictions),
                "readmissions": list(self.readmissions),
                "quorum_lost": self.quorum_lost}

    # -- membership transitions --------------------------------------------
    def evict(self, worker, round_idx, reason):
        w = int(worker)
        if not (0 <= w < self.n) or not self.alive[w]:
            return False
        if self.live_count() - 1 < self.quorum:
            self._quorum_lost(round_idx, would_evict=w, reason=reason)
        self.alive[w] = False
        self._bad_streak[w] = 0
        self._evicted_at[w] = round_idx
        rec = {"worker": w, "round": round_idx, "reason": reason,
               "live": self.live_count(), "unit": self.unit}
        self.evictions.append(rec)
        self.log(f"elastic: EVICTED {self.unit} {w} at round {round_idx} "
                 f"({reason}); {self.live_count()}/{self.n} live, "
                 f"shard re-spread over survivors")
        if self.metrics is not None:
            self.metrics.log("eviction", **rec)
            if self.unit == "host":
                # the per-host liveness stream (resilience/heartbeat.py
                # satellite): monitor/report render host evictions
                # without reparsing the generic eviction records
                self.metrics.log("host_evicted", host=w, round=round_idx,
                                 reason=reason, live=self.live_count())
        return True

    def readmit(self, worker, round_idx):
        w = int(worker)
        if not (0 <= w < self.n) or self.alive[w]:
            return False
        self.alive[w] = True
        self._bad_streak[w] = 0
        self._evicted_at.pop(w, None)
        rec = {"worker": w, "round": round_idx, "live": self.live_count(),
               "unit": self.unit}
        self.readmissions.append(rec)
        self.log(f"elastic: readmitted {self.unit} {w} at round {round_idx} "
                 f"from the consensus weights; "
                 f"{self.live_count()}/{self.n} live")
        if self.metrics is not None:
            self.metrics.log("readmission", **rec)
        return True

    def _quorum_lost(self, round_idx, **fields):
        self.quorum_lost = True
        if self.metrics is not None:
            self.metrics.log("membership", kind="quorum_lost",
                             round=round_idx, live=self.live_count(),
                             quorum=self.quorum, **fields)
        self.log(f"elastic: QUORUM LOST at round {round_idx}: "
                 f"{self.live_count()} live, need {self.quorum}")
        raise QuorumLost(
            f"live {self.unit}s would drop below quorum {self.quorum} "
            f"at round {round_idx} (exit {EXIT_QUORUM_LOST})")

    # -- the per-round controller ------------------------------------------
    def observe_round(self, round_idx, valid=None, worker_loss=None):
        """Feed one materialized round's membership signals. ``valid``:
        the (n,) effective validity vector fetched from the compiled
        round (host mask AND device finite bit). Raises QuorumLost when
        an eviction (or a chaos kill) would break the quorum. Returns
        True when membership changed (the caller may want to re-spread
        data or shrink)."""
        changed = False
        injector = "dead_hosts" if self.unit == "host" else "dead_workers"
        if self.chaos is not None and hasattr(self.chaos, injector):
            for w in getattr(self.chaos, injector)(round_idx, self.n):
                changed |= self.evict(w, round_idx, "chaos_kill")
        if valid is not None:
            v = np.asarray(valid, np.float64).ravel()[:self.n]
            for w in range(len(v)):
                if not self.alive[w]:
                    continue
                if v[w] > 0:
                    self._bad_streak[w] = 0
                    continue
                self._bad_streak[w] += 1
                if self._bad_streak[w] >= self.evict_after:
                    reason = "nonfinite"
                    if worker_loss is not None:
                        wl = np.asarray(worker_loss, np.float64).ravel()
                        if w < len(wl) and not np.isfinite(wl[w]):
                            reason = f"nonfinite loss ({wl[w]})"
                    changed |= self.evict(w, round_idx, reason)
        if self.readmit_after:
            for w, r0 in sorted(self._evicted_at.items()):
                if round_idx - r0 >= self.readmit_after:
                    changed |= self.readmit(w, round_idx)
        self._degraded_rounds = self._degraded_rounds + 1 \
            if self.live_count() < self.n else 0
        return changed

    def should_shrink(self):
        """True when evictions have been in force long enough that the
        solver should rebuild its mesh over the survivors (shrink_after
        rounds; 0 disables)."""
        return bool(self.shrink_after) and \
            self._degraded_rounds >= self.shrink_after and \
            self.live_count() < self.n

    def reset_world(self, n_workers):
        """After a mesh shrink: the survivors ARE the new world."""
        self.n = int(n_workers)
        self.quorum = min(self.quorum, self.n)
        self.alive = np.ones(self.n, bool)
        self._bad_streak = np.zeros(self.n, np.int64)
        self._evicted_at = {}
        self._degraded_rounds = 0
        if self.metrics is not None:
            self.metrics.log("membership", kind="world_reset",
                             live=self.n)
