"""Jittered exponential backoff with a retry budget for flaky IO.

The reference's contract was one IO error = one dead job
(spark.task.maxFailures=1); here a transient read error on a data source
costs a short sleep. Backoff is exponential with seeded jitter (so two
workers hammered by the same outage don't retry in lockstep, and tests
are deterministic), attempts are bounded per call, and an optional
``budget`` bounds total retries across the policy's lifetime — a
permanently sick disk exhausts the budget and surfaces as a real error
instead of an infinite crawl.
"""

import os
import time

import numpy as np


class RetryExhausted(OSError):
    """Retries exhausted; ``last`` holds the final underlying error."""

    def __init__(self, msg, last=None):
        super().__init__(msg)
        self.last = last


class RetryPolicy:
    """call(fn, ...) runs fn, retrying ``retry_on`` errors up to
    ``attempts`` times per call with jittered exponential backoff
    (base_s * 2^attempt, capped at max_s, +/- jitter fraction)."""

    def __init__(self, attempts=4, base_s=0.05, max_s=2.0, jitter=0.5,
                 budget=None, retry_on=(OSError,), seed=0,
                 sleep=time.sleep, metrics=None, log_fn=None):
        self.attempts = int(attempts)
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self.budget = None if budget is None else int(budget)
        self.retry_on = tuple(retry_on)
        self.sleep = sleep
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self._rng = np.random.RandomState(seed)
        self.retries_used = 0

    def delay(self, attempt):
        d = min(self.max_s, self.base_s * (2.0 ** (attempt - 1)))
        if self.jitter:
            d *= 1.0 + self.jitter * float(self._rng.uniform(-1.0, 1.0))
        return max(0.0, d)

    def record_failure(self, e, attempt, where=""):
        """Book one failed try: raise RetryExhausted when ``attempt``
        exceeds the per-call attempts or the lifetime budget is spent,
        else sleep the backoff delay and return. For retry loops that
        can't be expressed as re-invoking a function (e.g. restarting a
        DB cursor mid-generator) — ``attempt`` is the caller's count,
        reset on progress."""
        self.retries_used += 1
        exhausted = attempt > self.attempts or (
            self.budget is not None and self.retries_used > self.budget)
        if self.metrics is not None:
            self.metrics.log("retry", where=where, attempt=attempt,
                             error=repr(e), exhausted=exhausted)
        if exhausted:
            why = f"{self.attempts} attempts" if attempt > self.attempts \
                else f"retry budget ({self.budget})"
            raise RetryExhausted(f"{where or 'io'}: {why} exhausted: {e}",
                                 last=e) from e
        d = self.delay(attempt)
        self.log(f"retry {attempt}/{self.attempts} "
                 f"{where or 'io'} in {d * 1e3:.0f} ms: {e!r}")
        self.sleep(d)

    def call(self, fn, *args, where="", **kw):
        attempt = 0
        while True:
            try:
                return fn(*args, **kw)
            except self.retry_on as e:
                attempt += 1
                self.record_failure(e, attempt, where=where)


def retry_from_env(metrics=None, log_fn=None):
    """Default policy for data sources: SPARKNET_IO_RETRIES attempts
    (default 4; 0 disables -> None)."""
    try:
        attempts = int(os.environ.get("SPARKNET_IO_RETRIES", "4"))
    except ValueError:
        attempts = 4
    if attempts <= 0:
        return None
    return RetryPolicy(attempts=attempts, metrics=metrics, log_fn=log_fn)
