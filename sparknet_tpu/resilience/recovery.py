"""Divergence recovery: roll back to last-known-good instead of dying.

The watchdog (PR 1) made NaN/inf losses *visible*; this makes them
survivable. A RecoveryPolicy keeps an in-memory last-known-good copy of
the solver's params/state/history (host-resident numpy, so buffer
donation can't invalidate it) and, when a loss comes back non-finite or
exploded, rewinds the solver to it — optionally decaying the lr and
reshuffling the data stream — so one bad round degrades into a short
replay instead of poisoning the averaged weights. Retries are bounded:
after ``max_rollbacks`` rollbacks without reaching a new known-good
point past the failure, it raises RecoveryAbort for a clean exit the
supervisor can tell apart from a crash.

Wired into Solver.step (loss sync/display points — losses are observed
with up to the async-dispatch lag, which only delays the rollback by
that many steps) and LocalSGDSolver.run (per-round).
"""

import math

import numpy as np


class RecoveryAbort(RuntimeError):
    """Divergence persisted through the rollback budget; stop cleanly."""


class RecoveryPolicy:
    """observe(solver, loss) after each materialized loss:

    healthy  -> refresh the last-known-good copy (at most every
                ``good_interval`` iters) and return False
    bad      -> roll the solver back and return True (caller should
                redo the work), or raise RecoveryAbort once
                ``max_rollbacks`` consecutive rollbacks have not reached
                a new healthy point past the failure iter

    A loss is bad when it is non-finite, or — with ``explode_factor`` > 0
    — larger than explode_factor x the EMA of recent healthy losses.
    ``lr_decay`` < 1 multiplies the lr schedule on every rollback (the
    compiled step is rebuilt; a recompile per rare rollback is cheap
    next to a dead run). ``reshuffle`` is an optional zero-arg hook to
    re-seed/skip the data stream so the replay doesn't hit the exact
    batch sequence that diverged.
    """

    def __init__(self, max_rollbacks=3, lr_decay=1.0, explode_factor=0.0,
                 good_interval=1, ema_decay=0.9, reshuffle=None,
                 metrics=None, log_fn=print):
        self.max_rollbacks = int(max_rollbacks)
        self.lr_decay = float(lr_decay)
        self.explode_factor = float(explode_factor)
        self.good_interval = max(1, int(good_interval))
        self.ema_decay = float(ema_decay)
        self.reshuffle = reshuffle
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self.rollbacks = 0          # lifetime count (for reporting)
        self._consecutive = 0
        self._ema = None
        self._good = None           # (iter, params, state, history, rng)
        self._good_iter = -1

    # -- last-known-good capture -------------------------------------------
    def note_good(self, solver):
        """Snapshot the solver's training state to host memory."""
        import jax
        if self._good is not None and \
                solver.iter - self._good_iter < self.good_interval:
            return
        get = jax.device_get
        self._good = (solver.iter, get(solver.params), get(solver.state),
                      get(solver.history), np.asarray(solver.rng))
        self._good_iter = solver.iter

    def is_bad(self, loss):
        v = float(loss)
        if not math.isfinite(v):
            return "non-finite loss"
        if self.explode_factor > 0 and self._ema is not None and \
                abs(v) > self.explode_factor * max(abs(self._ema), 1e-8):
            return (f"loss {v:.6g} exploded past "
                    f"{self.explode_factor:g}x EMA {self._ema:.6g}")
        return None

    def observe(self, solver, loss):
        """-> True if the solver was rolled back (redo the work)."""
        if loss is None:
            return False
        v = float(loss)
        reason = self.is_bad(v)
        if reason is None:
            self._ema = v if self._ema is None else \
                self.ema_decay * self._ema + (1 - self.ema_decay) * v
            if self._consecutive and solver.iter > self._good_iter:
                self._consecutive = 0       # healthy past the failure point
            self.note_good(solver)
            return False
        return self._rollback(solver, v, reason)

    # -- the rollback itself -----------------------------------------------
    def _rollback(self, solver, v, reason):
        import jax
        import jax.numpy as jnp
        if self._good is None:
            self._abort(solver, v, reason
                        + " before any known-good state was captured")
        self.rollbacks += 1
        self._consecutive += 1
        if self._consecutive > self.max_rollbacks:
            self._abort(solver, v, f"{reason}; {self._consecutive - 1} "
                        "rollbacks exhausted without progress")
        it, params, state, history, rng = self._good
        asarray = jnp.asarray
        solver.params = jax.tree_util.tree_map(asarray, params)
        solver.state = jax.tree_util.tree_map(asarray, state)
        solver.history = jax.tree_util.tree_map(asarray, history)
        solver.rng = jnp.asarray(rng)
        solver.iter = it
        solver._it_dev = None               # re-seed the device counter
        solver._smoothed.clear()            # the window is poisoned
        if self.lr_decay != 1.0:
            solver.scale_lr(self.lr_decay)
        if self.reshuffle is not None:
            try:
                self.reshuffle()
            except Exception as e:          # a hook must not kill recovery
                self.log(f"recovery: reshuffle hook raised: {e!r}")
        self.log(f"recovery: {reason}; rolled back to iter {it} "
                 f"(rollback {self._consecutive}/{self.max_rollbacks}"
                 + (f", lr x{self.lr_decay:g}" if self.lr_decay != 1.0
                    else "") + ")")
        if self.metrics is not None:
            self.metrics.log("recovery", kind="rollback", reason=reason,
                             loss=v, to_iter=it,
                             attempt=self._consecutive,
                             lr_decay=self.lr_decay)
        return True

    def _abort(self, solver, v, reason):
        if self.metrics is not None:
            self.metrics.log("recovery", kind="abort", reason=reason,
                             loss=v, iter=solver.iter,
                             rollbacks=self.rollbacks)
        self.log(f"recovery: ABORT at iter {solver.iter}: {reason}")
        raise RecoveryAbort(f"training diverged at iter {solver.iter}: "
                            f"{reason}")
