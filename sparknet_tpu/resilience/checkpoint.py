"""Crash-safe checkpointing over the Solver's snapshot formats.

The base Solver writes model then state as two independent files; a crash
between the two (or mid-write) leaves a snapshot that pairs new weights
with stale history — silently wrong to resume from. The commit protocol
here makes a snapshot either fully visible or invisible:

  1. both files are written under temp names IN the final directory
     (same filesystem, so the later rename is atomic)
  2. each temp file is fsync'd and sha256'd
  3. both are atomic-renamed to their final names; the directory is fsync'd
  4. <prefix>.latest.json is committed last (temp + fsync + rename): the
     manifest entry names BOTH files with their checksums, so the pair is
     one atomic unit — a crash at any earlier point leaves the previous
     manifest pointing at the previous complete snapshot
  5. retention: manifest history beyond keep-N is dropped and only files
     the manifest itself recorded are deleted

find_resumable() walks the manifest newest-first, verifying existence and
checksums, and falls back to un-manifested legacy snapshot pairs; every
snapshot it refuses is reported with the reason. resume_auto() is the
`--resume auto` entry point: restore the newest valid state, or start
fresh when there is none.
"""

import glob
import hashlib
import itertools
import json
import os
import re
import threading
import time


MANIFEST_SUFFIX = ".latest.json"
_TMP_TAG = ".tmp."
_TMP_COUNTER = itertools.count()


class WorldMismatch(RuntimeError):
    """A snapshot written by a different world (process count / mesh
    shape) than the one trying to restore it. Deliberately NOT a
    ValueError: resume_auto treats ValueError as "this snapshot is
    damaged, try the next one", but a world mismatch damns every
    snapshot under the prefix equally — falling back (or silently
    starting fresh) would throw the run's history away. The operator
    must either relaunch with the matching topology, opt into
    cross-world resharding with ``--reshard auto``, or choose a new
    snapshot prefix; the message says exactly that."""


def manifest_path(prefix):
    return prefix + MANIFEST_SUFFIX


def world_signature(solver):
    """The world a snapshot is only resumable in: the process count and
    the training mesh's named axis sizes. Stamped into every manifest
    entry so a relaunch on the wrong topology fails with a sentence,
    not a cryptic reshape error deep inside restore()."""
    try:
        import jax
        procs = jax.process_count()
    except Exception:
        procs = 1
    sig = {"processes": int(procs)}
    mesh = getattr(solver, "mesh", None)
    if mesh is not None and hasattr(mesh, "shape"):
        try:
            sig["mesh"] = {str(k): int(v) for k, v in mesh.shape.items()}
        except Exception:
            pass
    return sig


def check_world(entry, world, state_path):
    """Raise WorldMismatch when manifest ``entry`` carries a world
    stamp that disagrees with ``world`` (the restoring run's
    world_signature). Entries without a stamp (pre-stamp snapshots)
    pass through."""
    want = entry.get("world") if isinstance(entry, dict) else None
    if not want or not world:
        return
    mismatches = []
    if want.get("processes") is not None and \
            world.get("processes") is not None and \
            int(want["processes"]) != int(world["processes"]):
        mismatches.append(f"process count {want['processes']} vs "
                          f"{world['processes']}")
    if want.get("mesh") and world.get("mesh") and \
            dict(want["mesh"]) != dict(world["mesh"]):
        mismatches.append(f"mesh {want['mesh']} vs {world['mesh']}")
    if mismatches:
        raise WorldMismatch(
            f"snapshot {state_path} was written by a different world: "
            f"snapshot world {want} vs this run's world {world} "
            f"({'; '.join(mismatches)} — snapshot first). Relaunch with "
            "the topology the snapshot names, pass `--reshard auto` "
            "(restore(reshard=\"auto\")) to re-partition the snapshot "
            "for this world, or start a new run under a different "
            "snapshot prefix; refusing to guess.")


def world_slots(sig):
    """Worker-slot count a world signature describes: process count x
    the product of the mesh's named axis sizes. This is the partition
    count data ownership is spread over, so it is the unit
    reshard_for_world() plans in."""
    if not isinstance(sig, dict):
        return None
    n = int(sig.get("processes") or 1)
    for size in (sig.get("mesh") or {}).values():
        n *= int(size)
    return n


def reshard_for_world(from_world, to_world):
    """Plan the re-partitioning that carries a snapshot stamped for
    ``from_world`` (W1) onto the restoring run's ``to_world`` (W2), or
    None when the worlds already agree (bit-for-bit restore, no plan).

    The plan leans on the LocalSGD replication invariant: params and
    optimizer history are REPLICATED across the consensus axis (every
    worker holds the full tree after a consensus round), so the model
    and state blobs themselves are world-shape independent — restoring
    them under W2 needs no tensor surgery. What DOES change across
    worlds is data ownership: W1's partitions must be re-spread over
    W2's slots, and that mapping reuses the same round-robin
    partition_owners rule eviction already uses (see
    data/sampler.reshard_owners for the two directions). The snapshot
    is re-stamped with W2's signature at the next save_snapshot; the
    reshard itself is read-only, so a crash mid-restore leaves the
    original snapshot untouched."""
    a, b = world_slots(from_world), world_slots(to_world)
    if a is None or b is None:
        return None
    if from_world == to_world:
        return None
    from ..data.sampler import reshard_owners
    direction = "shrink" if b < a else ("grow" if b > a else "remap")
    return {
        "from_world": dict(from_world),
        "to_world": dict(to_world),
        "n_from": a,
        "n_to": b,
        "direction": direction,
        "owners": [int(o) for o in reshard_owners(a, b)],
    }


def _sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(dirname):
    """Durability of the rename itself. Best-effort: some filesystems
    refuse O_RDONLY on directories."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _tmp_name(path):
    """A temp sibling of ``path`` unique to this (process, thread,
    call): concurrent writers — the heartbeat writer thread racing a
    round arrival, two hosts on one machine — can never collide on a
    temp name, so interleaved atomic-rename sequences cannot eat each
    other's os.replace. The _TMP_TAG marker keeps every half-written
    file recognizable to the snapshot verifiers and the ghost reaper."""
    return (f"{path}{_TMP_TAG}{os.getpid()}."
            f"{threading.get_ident()}.{next(_TMP_COUNTER)}")


def atomic_write_bytes(path, write_fn, fsync_dir=False):
    """The repo's ONE tmp+fsync+os.replace writer (`sparknet lint`
    SPK301 enforces that rendezvous/checkpoint paths go through this
    shape). ``write_fn(f)`` receives the binary temp file; after it
    returns the file is flushed, fsync'd, and atomically renamed to
    ``path`` — a crash at any point leaves either the old file or a
    recognizable ``.tmp.`` orphan, never a torn ``path``."""
    tmp = _tmp_name(path)
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):         # write_fn raised: no partials
            try:
                os.remove(tmp)
            except OSError:
                pass
    if fsync_dir:
        _fsync_dir(os.path.dirname(path))


def atomic_write_json(path, obj, indent=None, sort_keys=False,
                      fsync_dir=False):
    """atomic_write_bytes for one JSON document (the lease / mask /
    manifest / restart-barrier records)."""
    data = json.dumps(obj, indent=indent, sort_keys=sort_keys)
    atomic_write_bytes(path, lambda f: f.write(data.encode("utf-8")),
                       fsync_dir=fsync_dir)


def _atomic_write_json(path, obj):
    atomic_write_json(path, obj, indent=1, sort_keys=True,
                      fsync_dir=True)


def load_manifest(prefix):
    """The manifest dict, or None when missing/corrupt (a torn manifest
    write must read as "no manifest", not an error)."""
    try:
        with open(manifest_path(prefix)) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    return man if isinstance(man, dict) else None


def save_snapshot(solver, prefix, format=None, keep=None, metrics=None):
    """Atomically write one (model, state) snapshot pair for ``solver``
    and commit it to the manifest; returns the final paths.

    ``keep``: retention — manifest entries beyond the newest N are
    dropped and their files deleted. None/0 keeps everything.
    """
    model_path, state_path, format = solver._snapshot_paths(prefix, format)
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    tag = f"{_TMP_TAG}{os.getpid()}"
    tmp_model, tmp_state = model_path + tag, state_path + tag
    try:
        # the state file embeds the model path (SolverState.learned_net) —
        # it must name the FINAL path, not the temp name
        solver._write_snapshot_files(tmp_model, tmp_state, format,
                                     learned_net=model_path)
        for p in (tmp_model, tmp_state):
            _fsync_file(p)
        entry = {
            "iter": int(solver.iter),
            "format": format,
            "model": os.path.basename(model_path),
            "state": os.path.basename(state_path),
            "sha256": {"model": _sha256(tmp_model),
                       "state": _sha256(tmp_state)},
            "bytes": {"model": os.path.getsize(tmp_model),
                      "state": os.path.getsize(tmp_state)},
            "time": round(time.time(), 3),
            "world": world_signature(solver),
        }
        os.replace(tmp_model, model_path)
        os.replace(tmp_state, state_path)
        _fsync_dir(d)
    finally:
        for p in (tmp_model, tmp_state):        # never leave partials
            if os.path.exists(p):
                try:
                    os.remove(p)
                except OSError:
                    pass

    man = load_manifest(prefix) or {}
    snaps = [e for e in man.get("snapshots", ())
             if isinstance(e, dict) and
             not (e.get("iter") == entry["iter"] and
                  e.get("format") == format)]
    snaps.append(entry)
    snaps.sort(key=lambda e: (e.get("iter", -1), e.get("time", 0)))
    dropped = []
    if keep and int(keep) > 0 and len(snaps) > int(keep):
        dropped, snaps = snaps[:-int(keep)], snaps[-int(keep):]
    _atomic_write_json(manifest_path(prefix),
                       {"version": 1, "latest": entry, "snapshots": snaps})
    for e in dropped:
        for k in ("model", "state"):
            name = e.get(k)
            if not name:
                continue
            try:
                os.remove(os.path.join(d, name))
            except OSError:
                pass
    if metrics is not None:
        metrics.log("checkpoint", iter=entry["iter"], format=format,
                    model=model_path, state=state_path,
                    bytes=entry["bytes"]["model"] + entry["bytes"]["state"],
                    kept=len(snaps), dropped=len(dropped))
    return model_path, state_path


def _verify_entry(d, entry):
    """Reason string this manifest entry is not restorable, or None."""
    for k in ("model", "state"):
        name = entry.get(k)
        if not name:
            return f"manifest entry has no {k} file recorded"
        path = os.path.join(d, name)
        if not os.path.exists(path):
            return f"{k} file {name} is missing"
        if os.path.getsize(path) == 0:
            return f"{k} file {name} is empty"
        want = (entry.get("sha256") or {}).get(k)
        if want and _sha256(path) != want:
            return f"{k} file {name} fails its sha256 check " \
                   "(truncated or corrupt)"
    return None


def _verify_model_entry(d, entry):
    """Reason string this entry's MODEL blob is not servable, or None.

    The weights-only half of _verify_entry: the serving path never
    reads the optimizer-state file, so a missing or corrupt state blob
    must not disqualify a snapshot whose model blob verifies."""
    name = entry.get("model")
    if not name:
        return "manifest entry has no model file recorded"
    path = os.path.join(d, name)
    if not os.path.exists(path):
        return f"model file {name} is missing"
    if os.path.getsize(path) == 0:
        return f"model file {name} is empty"
    want = (entry.get("sha256") or {}).get("model")
    if want and _sha256(path) != want:
        return f"model file {name} fails its sha256 check " \
               "(truncated or corrupt)"
    return None


def load_model_only(prefix, log_fn=None):
    """Weights-only restore target: the newest manifest entry whose
    MODEL blob verifies -> (model_path, entry). The optimizer-state
    file is neither required nor read — a snapshot whose .solverstate
    was pruned, torn, or never written still serves fine.

    Raises ValueError naming the manifest when there is no manifest at
    all or no entry's model blob verifies; every refused entry's reason
    is in the message (and logged via ``log_fn``). Unlike the resume
    path there is no legacy-pair fallback: serving trusts only
    sha256-stamped manifests."""
    log = log_fn or (lambda *a: None)
    man_path = manifest_path(prefix)
    man = load_manifest(prefix)
    if man is None:
        raise ValueError(
            f"no checkpoint manifest at {man_path} (missing, torn, or "
            "corrupt) — run `sparknet train` with snapshotting enabled, "
            "or point --prefix at an existing manifest")
    d = os.path.dirname(prefix)
    refused = []
    entries = [man.get("latest")] + list(reversed(man.get("snapshots", [])))
    seen = set()
    for entry in entries:
        if not isinstance(entry, dict):
            continue
        key = (entry.get("iter"), entry.get("model"))
        if key in seen:
            continue
        seen.add(key)
        reason = _verify_model_entry(d, entry)
        if reason is None:
            for name, r in refused:
                log(f"refusing model blob {name}: {r}")
            return os.path.join(d, entry.get("model")), entry
        refused.append((entry.get("model") or "?", reason))
    detail = "; ".join(f"{name}: {r}" for name, r in refused) \
        or "manifest records no snapshots"
    raise ValueError(
        f"manifest {man_path} has no servable model blob ({detail})")


_ITER_RE = re.compile(r"_iter_(\d+)\.solverstate(\.h5)?$")


def _legacy_pairs(prefix):
    """Un-manifested (iter, model, state) snapshot pairs, newest first."""
    pairs = []
    for state in glob.glob(glob.escape(prefix) + "_iter_*.solverstate*"):
        if _TMP_TAG in state:
            continue
        m = _ITER_RE.search(state)
        if not m:
            continue
        model = state[:m.start()] + f"_iter_{m.group(1)}.caffemodel" \
            + (m.group(2) or "")
        pairs.append((int(m.group(1)), model, state))
    return sorted(pairs, reverse=True)


def find_resumable(prefix, log_fn=None, exclude=()):
    """Newest valid snapshot for ``prefix`` -> (state_path, skipped).

    skipped is [(state_path, reason), ...] for every newer snapshot that
    was refused (partial write, checksum mismatch, missing pair file).
    Returns (None, skipped) when nothing valid exists. Manifested
    snapshots are checksum-verified; legacy un-manifested pairs are only
    checked for existence and non-emptiness. ``exclude``: state paths to
    pass over even if they verify (resume_auto's fallback loop — a
    snapshot that verified but then failed to restore, e.g. deleted by a
    concurrent keep-N pruner between the check and the read).
    """
    log = log_fn or (lambda *a: None)
    skipped = []
    seen_states = set()
    exclude = {os.path.basename(p) for p in exclude}
    d = os.path.dirname(prefix)
    man = load_manifest(prefix)
    for entry in reversed((man or {}).get("snapshots", [])):
        if not isinstance(entry, dict):
            continue
        state = os.path.join(d, entry.get("state") or "?")
        seen_states.add(os.path.basename(state))
        if os.path.basename(state) in exclude:
            continue
        reason = _verify_entry(d, entry)
        if reason is None:
            for s, r in skipped:
                log(f"refusing snapshot {s}: {r}")
            return state, skipped
        skipped.append((state, reason))
    for it, model, state in _legacy_pairs(prefix):
        if os.path.basename(state) in seen_states or \
                os.path.basename(state) in exclude:
            continue            # manifest already ruled on this one
        if not os.path.exists(model):
            skipped.append((state, f"model file {model} is missing"))
            continue
        if os.path.getsize(model) == 0 or os.path.getsize(state) == 0:
            skipped.append((state, "snapshot pair has an empty file "
                            "(partial write)"))
            continue
        for s, r in skipped:
            log(f"refusing snapshot {s}: {r}")
        return state, skipped
    for s, r in skipped:
        log(f"refusing snapshot {s}: {r}")
    return None, skipped


def check_restorable(state_path, world=None, reshard="strict"):
    """Guard an explicit restore(): if a manifest in the snapshot's
    directory covers this state file, verify the whole pair and raise
    ValueError naming the snapshot and the reason when it fails. Temp
    files from torn writes are always refused. With ``world`` (the
    restoring run's world_signature), a stamped snapshot from a
    different world raises WorldMismatch under ``reshard="strict"`` —
    the actionable error instead of the cryptic reshape failure a
    silent restore would produce — while ``reshard="auto"`` accepts
    the entry so the caller can reshard_for_world() it. Returns the
    matched manifest entry, or None for un-manifested snapshots
    (legacy callers pass through)."""
    if reshard not in ("strict", "auto"):
        raise ValueError(f"reshard must be 'strict' or 'auto', "
                         f"got {reshard!r}")
    if _TMP_TAG in os.path.basename(state_path):
        raise ValueError(f"refusing snapshot {state_path}: temp file from "
                         "an interrupted snapshot write")
    d = os.path.dirname(state_path)
    base = os.path.basename(state_path)
    for man_file in glob.glob(os.path.join(glob.escape(d) if d else ".",
                                           "*" + MANIFEST_SUFFIX)):
        prefix = man_file[:-len(MANIFEST_SUFFIX)]
        man = load_manifest(prefix)
        for entry in (man or {}).get("snapshots", []):
            if isinstance(entry, dict) and entry.get("state") == base:
                reason = _verify_entry(d, entry)
                if reason is not None:
                    raise ValueError(
                        f"refusing snapshot {state_path}: {reason}")
                if reshard == "strict":
                    check_world(entry, world, state_path)
                return entry
    return None


def resume_auto(solver, prefix, log_fn=None, reshard="strict"):
    """`--resume auto`: restore ``solver`` from the newest valid snapshot
    under ``prefix``; returns the state path used, or None (fresh start).
    Every refused snapshot is logged with its reason.

    find_resumable's verification and the actual restore are two reads —
    a retention race (keep-N pruning in a concurrent writer, an external
    cleaner) can delete the manifested files in between, and a manifest
    can outlive files a crashed pruner already removed. A snapshot that
    verified but fails to RESTORE is therefore logged with the reason
    and excluded, and the search falls back to the next valid one
    instead of killing the relaunch. WorldMismatch is deliberately NOT
    in the fallback set: a wrong-world stamp damns every snapshot under
    the prefix equally, so it propagates instead of silently degrading
    into a fresh start. ``reshard`` is passed through to
    solver.restore() — "auto" re-partitions a cross-world snapshot for
    this run's world instead of refusing it."""
    log = log_fn or (lambda *a: None)
    tried = []
    while True:
        state, skipped = find_resumable(prefix, log_fn=log, exclude=tried)
        if state is None:
            refused = len(skipped) + len(tried)
            log(f"resume auto: no resumable snapshot under {prefix!r}"
                + (f" ({refused} refused)" if refused else "")
                + "; starting fresh")
            return None
        try:
            if reshard == "strict":
                solver.restore(state)
            else:
                solver.restore(state, reshard=reshard)
        except (OSError, ValueError, KeyError) as e:
            log(f"refusing snapshot {state}: restore failed ({e}); "
                "falling back to the next valid snapshot")
            tried.append(state)
            continue
        log(f"resume auto: restored iter {solver.iter} from {state}")
        if getattr(solver, "metrics", None) is not None:
            solver.metrics.log("checkpoint", kind="resume",
                               iter=solver.iter, state=state,
                               refused=len(skipped) + len(tried))
        return state


def wait_for_manifest(prefix, min_iter=None, timeout=120.0, poll=0.2):
    """Block until the manifest under ``prefix`` records a snapshot at
    iter >= ``min_iter`` (any snapshot when None); returns the matching
    entry dict or None on timeout.

    This is the non-writing half of the multi-process snapshot
    discipline: params/state/history are replicated, so N processes
    writing the same files would race each other's atomic renames and
    the manifest commit. Only the designated writer (process 0, or the
    lowest live host after failures) runs save_snapshot; everyone else
    barriers here on the manifest the writer committed — the same
    manifest a coordinated restart later agrees on."""
    deadline = time.time() + float(timeout)
    while True:
        man = load_manifest(prefix)
        latest = (man or {}).get("latest")
        if isinstance(latest, dict) and (
                min_iter is None or
                int(latest.get("iter", -1)) >= int(min_iter)):
            return latest
        if time.time() >= deadline:
            return None
        time.sleep(poll)
