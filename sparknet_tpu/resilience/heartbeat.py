"""Host-level fault domains: leased heartbeats, round rendezvous, and
coordinated restart over one shared directory.

PR 4's elastic membership generalizes device-worker loss, but the real
production failure unit is a HOST: preemption, OOM-kill, and network
partitions take out whole processes. A dead host cannot be detected
from inside a compiled collective — the collective just hangs — so the
liveness channel must live entirely on the host side. This module is
that channel, jax-free so it runs identically on any checkout:

  HeartbeatCoordinator  each process leases its liveness into a shared
                        rendezvous directory (atomic JSON writes, a
                        background writer thread), a monitor view marks
                        peers dead on lease expiry, and the pre-round
                        ``gate()`` is the no-hang contract: a cross-host
                        round is dispatched only after every live peer
                        arrived at the same round index — a dead peer
                        costs an eviction (via ElasticPolicy, at host
                        granularity, zero recompiles), never a hang.
  FileConsensus         the tau-interval cross-host weight average
                        executed THROUGH the rendezvous directory — the
                        transport used when the backend has no
                        cross-host collectives (multi-process CPU), and
                        a faithful rendering of the paper's own
                        architecture: SparkNet's driver collected and
                        re-broadcast weights every tau steps; here the
                        shared filesystem is the driver, the masked
                        average is the consensus, and tau amortizes the
                        slow transport exactly as the paper argues.
  restart_barrier       coordinated restart: on quorum loss every
                        surviving process converges on the SAME
                        checkpoint manifest (barrier on the manifest
                        file's sha256) before exiting
                        EXIT_QUORUM_LOST (4), so a supervisor relaunch
                        resumes one consistent world.

Time and storage go through the injectable seam (resilience/seam.py):
``clock`` (wall stamps, MONOTONIC durations, sleep) and ``dirops``
(atomic name-based file ops). The defaults are the process wall clock
and the real directory — bit-identical production behavior — while the
fleet simulator (sparknet_tpu/sim) injects a discrete-event clock and
an in-memory directory and runs this exact code at 1,000 virtual
hosts. Two time disciplines, deliberately split:

  * durations and deadlines (lease ages, gate/consensus timeouts, the
    startup grace) are computed on ``clock.monotonic()`` — an NTP step
    or suspend/resume must never mass-expire every peer's lease;
  * the stamps WRITTEN to disk stay wall-clock (human-readable, and
    the only time base two processes on different machines share).
    Cross-process stamp comparisons happen only where they must:
    startup ghost reaping and late-joiner discovery, where the other
    process may be long dead.

Lease freshness bridges the two: a peer's age is measured monotonically
from the moment THIS process last observed a new lease record (its
seq/stamp advanced); the on-disk wall stamp only seeds the age the
first time a pre-existing record is seen (a ghost's stale lease must
still read as old).

Rendezvous directory layout (one per run, on storage every host
reaches — NFS/GCS-fuse on fleets, tmp dirs in tests):

  hb-<host>.json        the lease: {host, seq, round, stamp} rewritten
                        atomically by the writer thread every
                        ``interval_s`` and at every round arrival
  part-<host>-<r>.npz   FileConsensus: host's post-round contribution
  mask-<r>.json         FileConsensus: the round's membership decided
                        by the lowest-indexed live host
  restart-<host>.json   restart_barrier: the manifest sha this host
                        will resume from
"""

import glob
import hashlib
import json
import os
import threading
import time

import numpy as np

from .checkpoint import atomic_write_bytes, atomic_write_json
from .seam import WALL_CLOCK, RealDir

# back-compat: this module's private writer predates the shared helper
_atomic_write_json = atomic_write_json


def _read_json(path):
    """Parse a JSON file, or None — a torn write must read as absent,
    not an error (the writer re-writes within interval_s)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


class _HeartbeatDir(RealDir):
    """The default Dir seam, reading through this module's late-bound
    ``_read_json`` so tests can inject torn/racy reads exactly as they
    always have."""

    def read_json(self, name):
        return _read_json(self.path(name))


def fresh_leases(directory, lease_s, now=None, dirops=None):
    """{host: lease record} for every UNEXPIRED hb-*.json lease in a
    rendezvous dir — the running world a late joiner (`--grow`)
    discovers before it has a coordinator of its own (it picks host id
    max(existing)+1 and leases itself into the same directory). Wall
    stamps compared against wall ``now``: the prober has no receipt
    history yet, and the leaseholders are other processes."""
    now = time.time() if now is None else now
    if dirops is None:
        recs = (_read_json(p) for p in glob.glob(os.path.join(
            glob.escape(str(directory)), "hb-*.json")))
    else:
        recs = (dirops.read_json(n) for n in dirops.glob("hb-*.json"))
    out = {}
    for rec in recs:
        if rec is None or not isinstance(rec.get("host"), int):
            continue
        if now - float(rec.get("stamp", 0.0)) <= float(lease_s):
            out[rec["host"]] = rec
    return out


class HostDead(RuntimeError):
    """A peer host's lease expired (reported by gate/exchange)."""


class GateResult:
    """What the pre-round rendezvous saw: which hosts arrived at the
    round, which leases expired while waiting, and the wait itself —
    the cross-host round-latency signal the obs layer renders."""

    def __init__(self, arrived, dead, wait_s):
        self.arrived = sorted(arrived)
        self.dead = sorted(dead)
        self.wait_s = float(wait_s)


class HeartbeatCoordinator:
    """One process's end of the liveness protocol.

    Thread contract: a background writer/monitor thread re-leases this
    host's heartbeat and refreshes the peer view while the training
    loop reads it; the mutable shared state (seq/round counters, the
    published liveness view, the lease-receipt table, the stop flag) is
    guarded by ``_lock`` (enforced by `sparknet lint` SPK201/202).
    Configuration fields (dir/host/lease_s/...) are immutable after
    __init__; the world size ``n`` is the one exception —
    admit_host() GROWS it (with the view arrays, under ``_lock``) when
    a late-started `--grow` process leases itself into the rendezvous
    dir mid-run.

    ``clock``/``dirops``: the time + storage seam (resilience/seam.py).
    Leave at None for production (wall clock, real directory); the
    fleet simulator injects SimClock/MemDir and this class runs
    unchanged against virtual time."""

    def __init__(self, directory, host=None, n_hosts=None, interval_s=0.5,
                 lease_s=3.0, metrics=None, log_fn=print, chaos=None,
                 clock=None, dirops=None, payload_fn=None):
        if host is None or n_hosts is None:
            raise ValueError("heartbeat needs host= (this process's id) "
                             "and n_hosts= (the world size)")
        self.dir = str(directory)
        self.clock = WALL_CLOCK if clock is None else clock
        # the default Dir seam creates the rendezvous dir on disk; an
        # injected one (the simulator's MemDir) owns its own storage
        self.dirops = _HeartbeatDir(self.dir) if dirops is None else dirops
        self.host = int(host)
        self.n = int(n_hosts)
        if not (0 <= self.host < self.n):
            raise ValueError(f"host {self.host} outside world {self.n}")
        self.interval_s = float(interval_s)
        self.lease_s = float(lease_s)
        if self.lease_s <= self.interval_s:
            raise ValueError(f"lease_s {self.lease_s} must exceed the "
                             f"heartbeat interval_s {self.interval_s}")
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self.chaos = chaos
        # optional beat payload: a callable returning extra JSON-safe
        # fields merged into every lease record (a serve replica's
        # queue depth / in-flight / checkpoint sha / drain state —
        # serve/fleet.py). Core protocol keys always win on collision,
        # and readers use .get(), so beats from payload-free builds
        # stay interchangeable with enriched ones.
        self.payload_fn = payload_fn
        self._lock = threading.Lock()
        self._seq = 0                                # spk: guarded-by=_lock
        self._round = -1                             # spk: guarded-by=_lock
        self._alive_view = np.ones(self.n, bool)     # spk: guarded-by=_lock
        self._age_view = np.zeros(self.n, np.float64)  # spk: guarded-by=_lock
        self._ever_dead = set()                      # spk: guarded-by=_lock
        self._stopped = False                        # spk: guarded-by=_lock
        # lease receipts: host -> ((seq, stamp), monotonic-at-receipt,
        # initial age). Freshness is monotonic from the receipt, so a
        # wall-clock step can never mass-expire peers (ISSUE 15).
        self._lease_seen = {}                        # spk: guarded-by=_lock
        # trace_align throttle: host -> observer mono of the last beacon
        # emitted for that peer (at most one per lease_s per peer keeps
        # the metrics volume O(hosts / lease_s) even at sim scale)
        self._align_last = {}                        # spk: guarded-by=_lock
        self._t0_mono = self.clock.monotonic()
        self._stop = threading.Event()
        self._thread = None
        if self.chaos is not None and self.n > 1:
            # real multi-process world: kill_host is rendered by the
            # TARGET process SIGKILLing itself at the gate
            # (maybe_kill_self); the virtual dead_hosts injector must
            # not double-fire on the survivors
            self.chaos.kill_host_self_mode = True

    # -- the lease ---------------------------------------------------------
    def _hb_name(self, host):
        return f"hb-{int(host)}.json"

    def _hb_path(self, host):
        return os.path.join(self.dir, self._hb_name(host))

    def beat(self):                          # spk: thread-entry
        """Re-lease this host's liveness (writer thread + round
        arrivals both call this). The lease record is snapshotted UNDER
        the lock (seq/round/stopped are shared with the training
        thread) but the file write happens OUTSIDE it:
        atomic_write_json gives every call a unique temp name, so
        concurrent beats cannot race each other's os.replace — and a
        slow fsync (NFS can stall for hundreds of ms) no longer blocks
        view()/gate() readers on the state lock (`sparknet lint`
        SPK206). Two interleaved beats may land out of order; the loser
        differs by one seq and a stamp milliseconds older — noise far
        below lease_s, and the writer re-leases every interval_s.

        The record carries BOTH time bases: ``stamp`` (wall, the only
        cross-process base a shared directory offers) and ``mono``
        (this host's monotonic clock) — the send half of a sync beacon.
        A peer that observes the new record pairs ``mono`` with its own
        monotonic receipt time (a ``trace_align`` event), which is what
        obs/fleettrace.py solves per-host clock offsets from. Readers
        use .get(): beats from older builds without ``mono`` stay
        readable, they just contribute no beacon.

        ``payload_fn`` extras are gathered OUTSIDE the lock (the
        callable typically reads other locked state — a batcher's
        queue depth — and calling into foreign locks under ``_lock``
        would invert lock order); core protocol keys always win."""
        extra = None
        if self.payload_fn is not None:
            try:
                extra = self.payload_fn()
            except Exception as e:   # a payload bug must not stop leasing
                self.log(f"heartbeat: payload_fn error: {e!r}")
        with self._lock:
            if self._stopped:
                return
            self._seq += 1
            rec = dict(extra) if extra else {}
            rec.update({"host": self.host, "seq": self._seq,
                        "round": self._round, "stamp": self.clock.time(),
                        "mono": self.clock.monotonic()})
        self.dirops.write_json(self._hb_name(self.host), rec)

    def announce_round(self, round_idx):
        """Post this host's arrival at ``round_idx`` (the rendezvous
        half of gate())."""
        with self._lock:
            self._round = int(round_idx)
        self.beat()

    def _reap_ghosts(self):
        """Startup GC: a previous run that crashed in the SAME rendezvous
        directory leaves hb-*.json leases (and orphaned round files —
        part/delta/mask/consensus/restart) behind. A ghost's stale lease
        would count toward the pre-round gate and the quorum until its
        (already expired) stamp is re-examined — worse, a ghost with a
        FUTURE round number could satisfy gates it never attended. Reap
        every lease whose stamp is already older than lease_s at startup
        and every orphaned round file with an mtime that old, and emit
        one ``ghost_reaped`` metrics event naming them. Fresh files from
        live peers of THIS run are untouched (they re-lease every
        interval_s, so their stamps are never near the lease). Stamp
        comparisons here are wall-vs-wall across PROCESSES — the one
        place that has to be, because the ghost's clock is all it left
        behind."""
        now = self.clock.time()
        ghost_hosts, orphans = [], 0
        for name in self.dirops.glob("hb-*.json"):
            rec = self.dirops.read_json(name)
            stamp = float(rec.get("stamp", 0.0)) \
                if rec is not None else 0.0
            if now - stamp <= self.lease_s:
                continue
            # re-read immediately before removing: a REJOINING host
            # (chaos preempt/rejoin, a `--grow` relaunch) may have
            # re-leased this exact path between our glob read and now —
            # reaping its fresh lease would make the rejoin look like a
            # second crash. Fresh-on-second-read means live: skip it.
            rec2 = self.dirops.read_json(name)
            if rec2 is not None and \
                    self.clock.time() - float(rec2.get("stamp", 0.0)) \
                    <= self.lease_s:
                continue
            rec = rec2 or rec
            if not self.dirops.remove(name):
                continue        # a concurrent peer reaped it first
            ghost_hosts.append(rec.get("host") if rec is not None
                               else name)
        for pat in ("part-*.npz", "mask-*.json", "delta-*.npz",
                    "delta-*.json", "consensus-*.npz", "consensus-*.json",
                    "restart-*.json", "*.tmp.*"):
            for name in self.dirops.glob(pat):
                mt = self.dirops.mtime(name)
                if mt is None or now - mt <= self.lease_s:
                    continue
                if self.dirops.remove(name):
                    orphans += 1
        if ghost_hosts or orphans:
            self.log(f"heartbeat: reaped {len(ghost_hosts)} ghost "
                     f"lease(s) {sorted(map(str, ghost_hosts))} and "
                     f"{orphans} orphaned round file(s) left by a "
                     "previous run in this rendezvous dir")
            if self.metrics is not None:
                self.metrics.log("ghost_reaped",
                                 hosts=sorted(map(str, ghost_hosts)),
                                 orphaned_files=orphans,
                                 observer=self.host)

    def start(self):
        """First beat + the background re-leaser. Idempotent. Reaps
        ghost leases/round files from a previous run in the same
        rendezvous dir BEFORE the first beat, so ghosts never count
        toward the gate or the quorum."""
        if self._thread is not None:
            return self
        self._reap_ghosts()
        self.beat()
        self._refresh_view()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"sparknet-hb-{self.host}")
        self._thread.start()
        return self

    def _run(self):                          # spk: thread-entry
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
                self._refresh_view()
            except Exception as e:   # liveness must never kill the run
                self.log(f"heartbeat: writer error: {e!r}")

    def stop(self):
        """Stop leasing (the host will be seen dead after lease_s).
        Idempotent; used by tests to simulate a silent host."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
        with self._lock:
            self._stopped = True

    def close(self):
        self.stop()

    # -- the peer view -----------------------------------------------------
    def _peer_visible(self, peer, round_idx):
        """chaos partition_host: a partitioned pair can't see each
        other's heartbeats (each side independently concludes the other
        is gone — the classic split-brain the quorum resolves)."""
        if self.chaos is None or \
                not hasattr(self.chaos, "host_partitioned"):
            return True
        return not self.chaos.host_partitioned(self.host, peer, round_idx)

    def peers(self):
        """{host: lease record} for every heartbeat file present."""
        out = {}
        for name in self.dirops.glob("hb-*.json"):
            rec = self.dirops.read_json(name)
            if rec is not None and isinstance(rec.get("host"), int):
                out[rec["host"]] = rec
        return out

    def view(self, now=None):
        """-> (alive bool (n,), lease_age_s (n,)). A host is alive while
        its lease is fresh; a host with NO heartbeat yet is granted one
        lease of startup grace (it may still be initializing), then
        dead. This host is always alive to itself.

        Freshness is MONOTONIC: a peer's age counts from the moment
        this process last saw a NEW lease record for it (seq/stamp
        advanced), not as ``wall_now - stamp`` — so an NTP step or a
        suspend/resume can shift the wall clock arbitrarily without
        expiring (or resurrecting) anyone. The on-disk wall stamp seeds
        the age only the FIRST time a pre-existing record is seen: a
        ghost's stale lease still reads as old on first sight. ``now``:
        optional wall time for that first-sight seeding (tests)."""
        mono = self.clock.monotonic()
        wall = self.clock.time() if now is None else float(now)
        with self._lock:
            round_idx = self._round
        n = self.n
        peers = self.peers()
        recs = {}
        for h in range(n):
            if h == self.host:
                continue
            recs[h] = peers.get(h) \
                if self._peer_visible(h, round_idx) else None
        alive = np.zeros(n, bool)
        age = np.full(n, np.inf, np.float64)
        beacons = []
        with self._lock:
            for h in range(n):
                if h == self.host:
                    alive[h] = True
                    age[h] = 0.0
                    continue
                rec = recs.get(h)
                if rec is None:
                    # no heartbeat ever seen: one lease of startup
                    # grace (the peer may still be initializing), then
                    # dead
                    if mono - self._t0_mono <= self.lease_s:
                        alive[h] = True
                        age[h] = 0.0
                    continue
                key = (rec.get("seq"), rec.get("stamp"))
                seen = self._lease_seen.get(h)
                if seen is None or seen[0] != key:
                    # a new record: the receipt resets the age. First-
                    # ever sight seeds from the wall stamp so a record
                    # that predates this process (a ghost) reads old.
                    init = max(0.0, wall - float(rec.get("stamp", 0.0))) \
                        if seen is None else 0.0
                    seen = (key, mono, init)
                    self._lease_seen[h] = seen
                    # a fresh receipt is a clock-sync beacon: the
                    # sender's (stamp, mono) paired with OUR monotonic
                    # receipt time bounds the pairwise clock offset
                    # (obs/fleettrace.py). Old-format beats carry no
                    # mono and contribute nothing. Throttled per peer;
                    # emitted after the lock drops (SPK206).
                    if self.metrics is not None and \
                            isinstance(rec.get("mono"), (int, float)):
                        last = self._align_last.get(h)
                        if last is None or mono - last >= self.lease_s:
                            self._align_last[h] = mono
                            beacons.append(
                                {"observer": self.host, "peer": h,
                                 "seq": int(rec.get("seq") or 0),
                                 "peer_mono": float(rec["mono"]),
                                 "peer_stamp":
                                     float(rec.get("stamp", 0.0)),
                                 "obs_mono": mono})
                age[h] = seen[2] + (mono - seen[1])
                alive[h] = age[h] <= self.lease_s
        for b in beacons:
            self.metrics.log(
                "trace_align", observer=b["observer"], peer=b["peer"],
                seq=b["seq"], peer_mono=b["peer_mono"],
                peer_stamp=b["peer_stamp"], obs_mono=b["obs_mono"])
        return alive, age

    def _refresh_view(self):                 # spk: thread-entry
        """Fold the current view into the published one, emitting a
        ``host_alive`` metrics event per liveness transition (the
        per-host liveness stream `sparknet monitor`/`report` render)."""
        alive, age = self.view()
        n = len(alive)
        with self._lock:
            prev = self._alive_view
            self._alive_view = alive
            self._age_view = age
            self._ever_dead |= {h for h in range(n) if not alive[h]}
            flips = [h for h in range(min(n, len(prev)))
                     if alive[h] != prev[h]]
        for h in flips:
            self.log(f"heartbeat: host {h} is now "
                     f"{'ALIVE' if alive[h] else 'DEAD'} "
                     f"(lease age {min(age[h], 1e9):.2f}s / "
                     f"{self.lease_s}s)")
            if self.metrics is not None:
                self.metrics.log("host_alive", host=h, alive=bool(alive[h]),
                                 lease_age_s=round(float(min(
                                     age[h], 1e9)), 3),
                                 observer=self.host)

    def alive_hosts(self):
        """Host ids currently holding a fresh lease (this host's view)."""
        alive, _ = self.view()
        return [h for h in range(len(alive)) if alive[h]]

    def live_processes(self):
        return self.alive_hosts()

    def lease_ages(self):
        _, age = self.view()
        return [round(float(min(a, 1e9)), 3) for a in age]

    def ever_dead(self):
        """Hosts whose lease EVER expired this run — after any real
        peer-process death, the jax.distributed shutdown barrier can
        never complete, so the CLI must exit without it
        (parallel.multihost.exit_if_peers_died)."""
        with self._lock:
            return set(self._ever_dead)

    # -- grow-mid-run: late joiners through the rendezvous dir -------------
    def poll_joiners(self):
        """Host ids with a FRESH lease at or beyond this coordinator's
        world size — late-started `--grow` processes leasing themselves
        into the rendezvous dir, waiting to be admitted at the next
        gate. Expired out-of-world leases (ghosts of a larger previous
        run) are ignored; _reap_ghosts removed them at startup anyway.
        Wall-vs-wall stamp comparison: the joiner is another process
        this coordinator has no receipt history for."""
        now = self.clock.time()
        return sorted(
            h for h, rec in self.peers().items()
            if h >= self.n and
            now - float(rec.get("stamp", 0.0)) <= self.lease_s)

    def admit_host(self, joiner):
        """Grow this coordinator's world to include host ``joiner``:
        the view arrays extend under ``_lock`` (the joiner starts
        alive — its fresh lease is what got it here) and every later
        view()/gate() spans the larger world. Returns True when the
        world actually grew (idempotent across repeated polls)."""
        j = int(joiner)
        if j < self.n:
            return False
        with self._lock:
            grow = j + 1 - self.n
            self._alive_view = np.append(self._alive_view,
                                         np.ones(grow, bool))
            self._age_view = np.append(self._age_view,
                                       np.zeros(grow, np.float64))
            self.n = j + 1
        self.log(f"heartbeat: host {j} joined the rendezvous; world "
                 f"grown to {self.n} host(s)")
        return True

    def peer_round_max(self):
        """The most advanced round any fresh peer lease announces, or
        -1 — how a joiner fast-forwards its round counter to the front
        of the running world before its first gate (incumbents' gates
        accept any arrival at round >= theirs)."""
        now = self.clock.time()
        front = -1
        for h, rec in self.peers().items():
            if h == self.host or \
                    now - float(rec.get("stamp", 0.0)) > self.lease_s:
                continue
            front = max(front, int(rec.get("round", -1)))
        return front

    # -- the pre-round rendezvous gate -------------------------------------
    def gate(self, round_idx, expect=None, timeout=None):
        """Arrive at ``round_idx`` and wait until every expected peer
        either arrived (its heartbeat shows round >= round_idx) or its
        lease expired. Never dispatch a cross-host collective before
        this returns: a dead peer must cost an eviction, not a hang.

        expect: host ids to wait for (default: everyone else). Returns
        a GateResult; hosts in ``.dead`` should be evicted by the
        caller's ElasticPolicy (reason "lease_expired"). The deadline
        (and the reported wait) live on the monotonic clock."""
        if self.chaos is not None:
            # deterministic host-level injections anchored at the gate:
            # a killed host dies BEFORE announcing arrival (so peers see
            # lease expiry, the real crash shape), a slow host arrives
            # late (the straggler shape)
            if hasattr(self.chaos, "maybe_kill_self"):
                self.chaos.maybe_kill_self(self.host, round_idx,
                                           on_kill=self.stop)
            if self.n > 1 and hasattr(self.chaos, "maybe_preempt_self"):
                # preempt_host in a REAL multi-process world: same
                # SIGKILL-at-the-gate crash shape as kill_host; the
                # orchestration layer relaunches the corpse with --grow
                self.chaos.maybe_preempt_self(self.host, round_idx,
                                              on_kill=self.stop)
            if hasattr(self.chaos, "maybe_slow_host"):
                self.chaos.maybe_slow_host(self.host, round_idx)
        self.announce_round(round_idx)
        expect = set(range(self.n)) - {self.host} if expect is None \
            else {int(h) for h in expect} - {self.host}
        t0 = self.clock.monotonic()
        deadline = None if timeout is None else t0 + timeout
        arrived, dead = set(), set()
        while True:
            alive, age = self.view()
            peers = self.peers()
            for h in sorted(expect - arrived - dead):
                rec = peers.get(h) \
                    if self._peer_visible(h, round_idx) else None
                if rec is not None and \
                        int(rec.get("round", -1)) >= round_idx:
                    arrived.add(h)
                elif h < len(alive) and not alive[h]:
                    dead.add(h)
            if expect <= arrived | dead:
                break
            if deadline is not None and \
                    self.clock.monotonic() >= deadline:
                # an unresponsive-but-leasing host: report as neither
                # arrived nor dead; the caller decides (straggler alarm)
                break
            self.clock.sleep(min(self.interval_s / 4, 0.05))
        res = GateResult(arrived, dead, self.clock.monotonic() - t0)
        if dead:
            with self._lock:
                self._ever_dead |= dead
        if self.metrics is not None:
            # mono: gate-exit time on this host's monotonic clock —
            # lets the fleet merger place the wait exactly on the
            # aligned timeline instead of via the wall-t fallback
            self.metrics.log("host_round", round=round_idx,
                             observer=self.host,
                             wait_s=round(res.wait_s, 4),
                             mono=self.clock.monotonic(),
                             arrived=res.arrived, dead=res.dead,
                             lease_age_s=self.lease_ages())
        for h in res.dead:
            self.log(f"heartbeat: host {h} missed round {round_idx} "
                     f"(lease expired after {self.lease_s}s)")
        return res


# -- tau-interval consensus over the rendezvous dir -------------------------

class FileConsensus:
    """Masked cross-host weight averaging through the shared directory.

    The device half of the hierarchy (per-step pmean inside a host)
    stays a compiled collective; this is the cross-host tier for
    backends without multi-process collectives. Protocol per round r:

      1. every live host atomically posts part-<host>-<r>.npz: its
         post-round leaves + {valid, loss} meta
      2. the LOWEST-indexed live host waits for the others (lease-
         bounded), then posts mask-<r>.json naming exactly which
         contributions count — ONE authority per round, so every host
         computes the identical consensus (the paper's driver, made
         crash-tolerant: if the authority dies, the next-lowest live
         host takes over on lease expiry)
      3. every host averages the masked-in contributions with weight
         1/n_live and adopts the result — evicted or readmitted hosts
         included, which makes readmission the same free re-broadcast
         as the replicated collective path

    All file I/O is atomic-rename through the coordinator's Dir seam;
    round r's part files are deleted at round r+2 so the directory
    stays O(hosts) files."""

    def __init__(self, coord, keep_rounds=2):
        self.coord = coord
        self.dir = coord.dir
        self.dirops = coord.dirops
        self.clock = coord.clock
        self.keep_rounds = max(1, int(keep_rounds))

    def _part_name(self, host, round_idx):
        return f"part-{int(host)}-{int(round_idx)}.npz"

    def _mask_name(self, round_idx):
        return f"mask-{int(round_idx)}.json"

    def _post(self, round_idx, leaves, valid, loss):
        meta = json.dumps({"host": self.coord.host, "round": int(round_idx),
                           "valid": int(bool(valid)),
                           "loss": float(loss)})
        arrays = {"meta": np.frombuffer(meta.encode(), np.uint8)}
        for i, a in enumerate(leaves):
            arrays[f"leaf{i}"] = np.asarray(a)
        self.dirops.write_npz(self._part_name(self.coord.host, round_idx),
                              arrays)

    def _load(self, host, round_idx, n_leaves):
        z = self.dirops.load_npz(self._part_name(host, round_idx))
        if z is None:
            return None, None
        try:
            meta = json.loads(bytes(z["meta"]).decode())
            leaves = [z[f"leaf{i}"] for i in range(n_leaves)]
        except (KeyError, ValueError):
            return None, None
        return leaves, meta

    def _wait_parts(self, round_idx, hosts, deadline):
        """Hosts whose contribution for ``round_idx`` landed before
        monotonic ``deadline`` (polling; arrival is the atomic
        rename)."""
        got = set()
        hosts = set(hosts)
        while True:
            for h in hosts - got:
                if self.dirops.exists(self._part_name(h, round_idx)):
                    got.add(h)
            if got >= hosts or self.clock.monotonic() >= deadline:
                return got
            self.clock.sleep(min(self.coord.interval_s / 4, 0.05))

    def _decide_mask(self, round_idx, alive, deadline):
        """The round's membership: written once by the lowest live
        host, awaited by the rest. If the authority dies before
        posting, its lease expires, the next-lowest live host becomes
        the authority and posts instead — one mask per round either
        way, so every host computes the identical consensus."""
        me = self.coord.host
        while True:
            rec = self.dirops.read_json(self._mask_name(round_idx))
            if rec is not None and rec.get("round") == round_idx:
                return [int(h) for h in rec.get("included", [])]
            live = set(self.coord.alive_hosts())
            if min(live | {me}) == me:
                got = self._wait_parts(round_idx, set(alive) | {me},
                                       deadline)
                mask = sorted(got)
                self.dirops.write_json(self._mask_name(round_idx),
                                       {"round": int(round_idx),
                                        "included": mask, "authority": me})
                return mask
            self.clock.sleep(min(self.coord.interval_s / 4, 0.05))

    def _gc(self, round_idx):
        for name in self.dirops.glob("part-*.npz"):
            try:
                r = int(name.rsplit("-", 1)[1].split(".")[0])
            except ValueError:
                continue
            if r <= round_idx - self.keep_rounds:
                self.dirops.remove(name)

    def exchange(self, round_idx, leaves, valid, loss, alive_hosts,
                 timeout=None):
        """One cross-host averaging round. ``leaves``: this host's flat
        list of numpy arrays (params+state in tree order); ``valid``:
        this host's finite bit; ``alive_hosts``: the membership in
        force (ElasticPolicy.live()). Returns (consensus_leaves, aux)
        where aux mirrors the compiled masked_consensus_stats membership
        report: valid (n,), n_live, worker_loss (n,), div_worker_sq
        (n,) — so the divergence/health/monitor pipeline runs unchanged
        over the relay transport."""
        n = self.coord.n
        timeout = self.coord.lease_s if timeout is None else timeout
        self._post(round_idx, leaves, valid, loss)
        deadline = self.clock.monotonic() + timeout
        included = self._decide_mask(round_idx, set(alive_hosts), deadline)
        parts, metas = {}, {}
        for h in included:
            lv, meta = self._load(h, round_idx, len(leaves))
            if lv is not None and meta.get("valid"):
                parts[h], metas[h] = lv, meta
        if not parts:
            # no valid contribution anywhere (every live host NaN'd):
            # keep our own leaves; the policy will see the all-invalid
            # vector and act (evict/quorum)
            parts = {self.coord.host: leaves}
            metas = {self.coord.host: {"loss": float(loss),
                                       "valid": int(bool(valid))}}
        w = 1.0 / len(parts)
        consensus = []
        for i in range(len(leaves)):
            acc = None
            for h in parts:
                x = np.asarray(parts[h][i], np.float64)
                acc = x * w if acc is None else acc + x * w
            consensus.append(acc.astype(np.asarray(leaves[i]).dtype))
        # admission skew (grow-mid-run): a peer that admitted a joiner
        # this round can publish a mask including a host id >= our
        # coord.n — size the aux vectors to the mask, not our (one
        # round stale) world, so the report indexes without blowing up
        n = max(n, max(parts) + 1)
        valid_vec = np.zeros(n, np.float32)
        loss_vec = np.full(n, np.nan, np.float32)
        div_sq = np.zeros(n, np.float32)
        for h in parts:
            valid_vec[h] = 1.0
            loss_vec[h] = metas[h].get("loss", float("nan"))
            div_sq[h] = sum(
                float(((np.asarray(parts[h][i], np.float64)
                        - np.asarray(consensus[i], np.float64)) ** 2).sum())
                for i in range(len(leaves)))
        live_div = div_sq[valid_vec > 0]
        aux = {"valid": valid_vec, "n_live": np.float32(len(parts)),
               "worker_loss": loss_vec, "div_worker_sq": div_sq,
               "div_mean_sq": np.float32(live_div.mean()),
               "div_max_sq": np.float32(live_div.max()),
               "transport": "relay"}
        self._gc(round_idx)
        return consensus, aux


# -- bounded-staleness async consensus over the rendezvous dir ---------------

class AsyncFileConsensus(FileConsensus):
    """Versioned, BARRIER-FREE cross-host delta exchange — the async
    bounded-staleness rendering of FileConsensus (ISSUE 7). Where the
    synchronous relay's authority WAITS for every live host's part file
    before publishing the round mask, this one never waits for anyone:

      1. after each local round a host atomically posts
         ``delta-<host>-<v>.npz`` (payload) + ``delta-<host>-<v>.json``
         (meta: host, version, valid, loss, stamp) at ITS OWN version
         counter v — a slow host simply posts lower versions
      2. the LOWEST-live-host merge authority publishes
         ``consensus-<v*>`` at the fastest version it can see, averaging
         each live host's LATEST delta with weight decay**(v* - v_h);
         deltas more than ``s`` versions behind (and lease-expired
         hosts) are excluded — the same degradation as death
      3. every host adopts the newest published consensus it hasn't
         adopted yet, or keeps its own weights when none is visible yet
         (early rounds, a dead authority mid-failover) — it NEVER blocks
      4. a host that finds itself more than ``s`` versions behind the
         fastest live peer PARKS: it abandons its stale line, adopts the
         latest consensus, and jumps its version to the front (the
         relay twin of ElasticPolicy.park/unpark)

    GC is lease-driven: a host whose lease expired has ALL its delta
    files removed (its stale pushes must stop haunting merges), and
    superseded delta/consensus versions are trimmed to a keep window.
    s=0 with every host in step degenerates to one full-weight merge
    per version — the synchronous consensus, reached without a barrier.
    """

    def __init__(self, coord, s=0, decay=0.5, keep_versions=3):
        super().__init__(coord)
        self.s = max(0, int(s))
        self.decay = float(decay)
        self.keep_versions = max(2, int(keep_versions))
        self.version = 0            # this host's completed-round counter
        self.parks = 0
        self._adopted = -1          # newest consensus version adopted

    # -- files ---------------------------------------------------------------
    def _delta_npz(self, host, v):
        return f"delta-{int(host)}-{int(v)}.npz"

    def _delta_meta(self, host, v):
        return f"delta-{int(host)}-{int(v)}.json"

    def _consensus_npz(self, v):
        return f"consensus-{int(v)}.npz"

    def _consensus_meta(self, v):
        return f"consensus-{int(v)}.json"

    def _push(self, v, leaves, valid, loss):
        """Payload first, meta last — the meta's atomic rename commits
        the delta, so a reader that sees the meta can read the npz."""
        self.dirops.write_npz(self._delta_npz(self.coord.host, v),
                              {f"leaf{i}": np.asarray(a)
                               for i, a in enumerate(leaves)})
        self.dirops.write_json(self._delta_meta(self.coord.host, v),
                               {"host": self.coord.host, "version": int(v),
                                "valid": int(bool(valid)),
                                "loss": float(loss),
                                "stamp": self.clock.time()})

    def _peer_versions(self):
        """{host: newest committed delta version} from the meta files."""
        vers = {}
        for name in self.dirops.glob("delta-*.json"):
            rec = self.dirops.read_json(name)
            if rec is None or not isinstance(rec.get("host"), int):
                continue
            h, v = rec["host"], int(rec.get("version", -1))
            if v > vers.get(h, -1):
                vers[h] = v
        return vers

    def _load_delta(self, host, v, n_leaves):
        meta = self.dirops.read_json(self._delta_meta(host, v))
        if meta is None:
            return None, None
        z = self.dirops.load_npz(self._delta_npz(host, v))
        if z is None:
            return None, None
        try:
            leaves = [z[f"leaf{i}"] for i in range(n_leaves)]
        except KeyError:
            return None, None
        return leaves, meta

    # -- the merge authority -------------------------------------------------
    def _merge(self, v_ref, live, vers, n_leaves):
        """Publish consensus-<v_ref> from each live host's latest delta
        within the staleness bound, discounted by decay**lag. Runs on
        the lowest live host; failover is automatic (the next-lowest
        live host sees itself lowest once the lease expires). Idempotent
        per v_ref — an existing consensus file is left alone."""
        if self.dirops.read_json(self._consensus_meta(v_ref)) is not None:
            return
        included, wsum = [], 0.0
        parts = {}
        for h in sorted(live):
            vh = vers.get(h, -1)
            if vh < 0 or v_ref - vh > self.s:
                continue                    # over-stale == excluded
            leaves, meta = self._load_delta(h, vh, n_leaves)
            if leaves is None or not meta.get("valid"):
                continue                    # torn or non-finite: out
            lagh = max(0, v_ref - vh)
            w = 1.0 if lagh == 0 else self.decay ** lagh
            parts[h] = (leaves, meta, lagh, w)
            wsum += w
        if not parts:
            return                          # nothing mergeable yet
        consensus = []
        for i in range(n_leaves):
            a = None
            for h, (leaves, _, _, w) in parts.items():
                x = np.asarray(leaves[i], np.float64) * (w / wsum)
                a = x if a is None else a + x
            consensus.append(a)
        for h, (leaves, meta, lagh, w) in sorted(parts.items()):
            div = sum(float(((np.asarray(leaves[i], np.float64)
                              - consensus[i]) ** 2).sum())
                      for i in range(n_leaves))
            included.append({"host": h, "version": int(vers[h]),
                             "lag": int(lagh), "weight": round(w, 6),
                             "loss": float(meta.get("loss",
                                                    float("nan"))),
                             "div_sq": div})
        self.dirops.write_npz(self._consensus_npz(v_ref),
                              {f"leaf{i}": c.astype(np.float64)
                               for i, c in enumerate(consensus)})
        self.dirops.write_json(self._consensus_meta(v_ref),
                               {"version": int(v_ref),
                                "authority": self.coord.host,
                                "included": included,
                                "stamp": self.clock.time()})

    def _latest_consensus(self, n_leaves):
        """(version, leaves, meta) of the newest committed consensus,
        or (None,)*3 — purely a read, never a wait."""
        best = None
        for name in self.dirops.glob("consensus-*.json"):
            rec = self.dirops.read_json(name)
            if rec is not None and isinstance(rec.get("version"), int):
                if best is None or rec["version"] > best["version"]:
                    best = rec
        if best is None:
            return None, None, None
        z = self.dirops.load_npz(self._consensus_npz(best["version"]))
        if z is None:
            return None, None, None
        try:
            leaves = [z[f"leaf{i}"] for i in range(n_leaves)]
        except KeyError:
            return None, None, None
        return best["version"], leaves, best

    def _gc_async(self, vers, live):
        """Lease-expiry GC: every delta of a host whose lease expired is
        removed (its stale pushes must stop haunting merges), and
        committed versions older than the keep window are trimmed."""
        floor = max(vers.values(), default=0) - self.s - self.keep_versions
        for name in self.dirops.glob("delta-*.json"):
            rec = self.dirops.read_json(name)
            if rec is None:
                continue
            h, v = rec.get("host"), int(rec.get("version", -1))
            dead = isinstance(h, int) and h not in live
            if dead or v < floor:
                self.dirops.remove(name)
                self.dirops.remove(self._delta_npz(h, v))
        keep = self.keep_versions
        cons = sorted(int(name.rsplit("-", 1)[1].split(".")[0])
                      for name in self.dirops.glob("consensus-*.json")
                      if name.rsplit("-", 1)[1].split(".")[0].isdigit())
        for v in cons[:-keep] if len(cons) > keep else []:
            self.dirops.remove(self._consensus_npz(v))
            self.dirops.remove(self._consensus_meta(v))

    # -- the exchange --------------------------------------------------------
    def exchange(self, round_idx, leaves, valid, loss, alive_hosts,
                 timeout=None):
        """One barrier-free exchange (same signature as the synchronous
        FileConsensus so LocalSGDSolver._train_round_relay is transport-
        agnostic; ``round_idx``/``timeout`` are accepted but versioning
        is internal and nothing ever waits). Returns (consensus_leaves,
        aux) with the same aux fields plus ``lag`` (per-host version
        lag), ``parked_self`` and ``version``."""
        me = self.coord.host
        n = self.coord.n
        v = self.version
        self._push(v, leaves, valid, loss)
        vers = self._peer_versions()
        vers[me] = max(vers.get(me, -1), v)
        live = set(int(h) for h in alive_hosts) | {me}
        live &= set(self.coord.alive_hosts()) | {me}
        fastest = max((vers.get(h, -1) for h in live), default=v)
        my_lag = max(0, fastest - v)
        if me == min(live):
            self._merge(fastest, live, vers, len(leaves))
        cv, cleaves, cmeta = self._latest_consensus(len(leaves))
        parked = my_lag > self.s
        if parked:
            # the bound is hit: abandon the stale line, adopt the
            # consensus, rejoin at the front (the relay park/unpark)
            self.parks += 1
            self.coord.log(
                f"async relay: host {me} PARKED at version {v} "
                f"(lag {my_lag} > s={self.s}); resyncing to the front")
            if self.coord.metrics is not None:
                self.coord.metrics.log("parked", worker=me, unit="host",
                                       round=int(v), lag=int(my_lag))
            self.version = fastest          # resynced
        else:
            self.version = v + 1
        if cleaves is not None and cv > self._adopted:
            self._adopted = cv
            out = [c.astype(np.asarray(leaves[i]).dtype)
                   for i, c in enumerate(cleaves)]
            meta_inc = {e["host"]: e for e in cmeta.get("included", [])}
        else:
            # no (new) consensus visible — keep our own post-round
            # weights and keep moving; the next exchange will adopt
            out = [np.asarray(x) for x in leaves]
            meta_inc = {me: {"host": me, "version": v, "lag": 0,
                             "weight": 1.0, "loss": float(loss),
                             "div_sq": 0.0}}
        valid_vec = np.zeros(n, np.float32)
        weight_vec = np.zeros(n, np.float32)
        loss_vec = np.full(n, np.nan, np.float32)
        div_sq = np.zeros(n, np.float32)
        lag_vec = np.zeros(n, np.float32)
        for h in range(n):
            lag_vec[h] = max(0, fastest - vers.get(h, fastest))
            e = meta_inc.get(h)
            if e is not None:
                valid_vec[h] = 1.0
                weight_vec[h] = float(e.get("weight", 1.0))
                loss_vec[h] = e.get("loss", float("nan"))
                div_sq[h] = e.get("div_sq", 0.0)
        live_div = div_sq[valid_vec > 0] if (valid_vec > 0).any() \
            else np.zeros(1, np.float32)
        aux = {"valid": valid_vec, "weight": weight_vec,
               "n_live": np.float32((valid_vec > 0).sum()),
               "worker_loss": loss_vec, "div_worker_sq": div_sq,
               "div_mean_sq": np.float32(live_div.mean()),
               "div_max_sq": np.float32(live_div.max()),
               "lag": [int(x) for x in lag_vec],
               "parked": [me] if parked else [],
               "parked_self": parked, "version": int(self.version),
               "transport": "async-relay"}
        self._gc_async(vers, live)
        return out, aux


# -- coordinated restart -----------------------------------------------------

def manifest_sha(prefix):
    """sha256 of the checkpoint manifest file itself — the value every
    survivor must agree on before a coordinated exit."""
    from .checkpoint import manifest_path
    try:
        with open(manifest_path(prefix), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def restart_barrier(coord, sha, timeout=30.0):
    """Post this host's resume manifest sha and wait for every LIVE
    peer to post theirs. Returns (agreed, shas_by_host). Used on quorum
    loss so all survivors exit 4 holding the SAME resumable manifest —
    the supervisor relaunch then resumes one consistent world."""
    coord.dirops.write_json(f"restart-{coord.host}.json",
                            {"host": coord.host, "sha": sha,
                             "stamp": coord.clock.time()})
    deadline = coord.clock.monotonic() + timeout
    while True:
        live = coord.alive_hosts()
        shas = {}
        for h in live:
            rec = coord.dirops.read_json(f"restart-{h}.json")
            if rec is not None:
                shas[h] = rec.get("sha")
        if set(live) <= set(shas) or \
                coord.clock.monotonic() >= deadline:
            agreed = len(set(shas.values())) == 1 and \
                set(live) <= set(shas)
            if coord.metrics is not None:
                coord.metrics.log("membership", kind="coordinated_restart",
                                  observer=coord.host, agreed=agreed,
                                  sha=sha, hosts=sorted(shas))
            if not agreed:
                coord.log(f"coordinated restart: survivors did NOT "
                          f"converge on one manifest: {shas}")
            else:
                coord.log("coordinated restart: all "
                          f"{len(shas)} survivor(s) agree on manifest "
                          f"{str(sha)[:12]}… — exiting for supervisor "
                          "relaunch")
            return agreed, shas
        coord.clock.sleep(min(coord.interval_s / 2, 0.1))
