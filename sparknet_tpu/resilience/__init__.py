"""Fault-tolerant training runtime.

The reference ran with spark.task.maxFailures=1 (CifarApp.scala:38): any
worker failure killed the whole job, because native solver state could not
survive Spark's lineage replay (SURVEY.md section 5). This package makes the
opposite contract hold — a preemption, a wedged device, a corrupt read, or a
diverging loss costs at most one sync round, never the run:

  checkpoint.py  crash-safe snapshots: write-temp -> fsync -> atomic-rename
                 with per-file sha256 and a <prefix>.latest.json manifest
                 that commits BOTH snapshot files (model + solver state) as
                 one unit, keep-N retention, and find_resumable() /
                 resume_auto() that skip partial or corrupt snapshots with
                 a stated reason
  recovery.py    RecoveryPolicy: in-memory last-known-good state; on a
                 non-finite or exploding loss, roll params/state/history
                 back, optionally decay the lr and reshuffle the stream,
                 with bounded retries before a clean RecoveryAbort
  retry.py       jittered exponential backoff with a retry budget, wrapped
                 around the data sources so transient IO errors don't kill
                 a round
  chaos.py       deterministic, seed-driven fault injectors (NaN at step k,
                 IO error with probability p, stall of s seconds, SIGTERM
                 at round r, worker crash at round r / with probability p)
                 so every recovery path is exercised in CPU tests — armed
                 via --chaos / SPARKNET_CHAOS
  elastic.py     quorum-based sync rounds: a validity-masked consensus
                 average inside the compiled round (a dead or NaN'd
                 worker can't poison it) plus an ElasticPolicy that
                 evicts sick workers, re-spreads their data shard over
                 the survivors, readmits them after a cooldown, and
                 aborts with QuorumLost / exit EXIT_QUORUM_LOST (4) when
                 the live count drops below --quorum — at device-worker
                 OR host granularity (unit="host")
  heartbeat.py   host-level fault domains: every process leases its
                 liveness into a shared rendezvous directory, a monitor
                 marks peer hosts dead on lease expiry, the pre-round
                 gate guarantees a dead peer costs an eviction instead
                 of a hang inside a collective, FileConsensus relays
                 the tau-interval cross-host average through the
                 directory when the backend has no multi-process
                 collectives, and restart_barrier makes every survivor
                 exit 4 agreeing on the SAME resumable manifest

Everything reports through the run's MetricsLogger (events: checkpoint,
recovery, retry, chaos, eviction, readmission, membership), so
`sparknet report` shows failures and the recoveries next to the loss
curve they interrupted.
"""

from .checkpoint import (save_snapshot, find_resumable, resume_auto,
                         load_manifest, manifest_path, check_restorable,
                         wait_for_manifest, world_signature, WorldMismatch)
from .recovery import RecoveryPolicy, RecoveryAbort
from .retry import RetryPolicy, RetryExhausted, retry_from_env
from .chaos import ChaosMonkey, ChaosIOError, install_chaos, active_chaos
from .elastic import (ElasticPolicy, QuorumLost, EXIT_QUORUM_LOST,
                      masked_consensus, masked_consensus_stats,
                      masked_scalar_mean, tree_finite, expand_to_slots,
                      staleness_discount, weighted_consensus,
                      weighted_consensus_stats)
from .heartbeat import (HeartbeatCoordinator, FileConsensus,
                        AsyncFileConsensus, GateResult,
                        manifest_sha, restart_barrier)

__all__ = [
    "save_snapshot", "find_resumable", "resume_auto", "load_manifest",
    "manifest_path", "check_restorable",
    "wait_for_manifest", "world_signature", "WorldMismatch",
    "RecoveryPolicy", "RecoveryAbort",
    "RetryPolicy", "RetryExhausted", "retry_from_env",
    "ChaosMonkey", "ChaosIOError", "install_chaos", "active_chaos",
    "ElasticPolicy", "QuorumLost", "EXIT_QUORUM_LOST",
    "masked_consensus", "masked_consensus_stats", "masked_scalar_mean",
    "tree_finite", "expand_to_slots",
    "staleness_discount", "weighted_consensus", "weighted_consensus_stats",
    "HeartbeatCoordinator", "FileConsensus", "AsyncFileConsensus",
    "GateResult", "manifest_sha", "restart_barrier",
]
