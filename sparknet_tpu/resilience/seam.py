"""The injectable time + rendezvous-directory seam (ISSUE 15).

Every control-plane module in resilience/ used to reach straight for
``time.time()`` / ``time.sleep()`` and the filesystem. That hard wiring
made two things impossible:

  * simulating the control plane — the fleet simulator
    (sparknet_tpu/sim) drives the REAL HeartbeatCoordinator /
    FileConsensus / ElasticPolicy code against a discrete-event clock
    and an in-memory rendezvous directory, so a 1,000-host fleet runs
    200 rounds in seconds on one CPU;
  * surviving a wall-clock step — lease freshness computed as
    ``time.time() - stamp`` mass-expires every peer the instant NTP
    steps the clock backward past lease_s (or a laptop resumes from
    suspend). Duration/deadline arithmetic belongs on the MONOTONIC
    clock; only the human-readable stamps written to disk stay wall.

This module is the seam's REAL half — the defaults that keep production
behavior bit-identical:

  Clock    wall time (``time``), ``monotonic``, and ``sleep`` — the
           three time primitives the protocol code is allowed to use.
  RealDir  name-based file ops over one rendezvous directory, writes
           routed through the checkpoint layer's atomic helpers
           (tmp + fsync + os.replace — `sparknet lint` SPK301), reads
           tolerant of torn/absent files.

The simulated half (sim/clock.SimClock, sim/memdir.MemDir) implements
the same two duck types; heartbeat.py never knows which it got.
"""

import glob as _glob
import json
import os
import time

import numpy as np

from .checkpoint import atomic_write_bytes, atomic_write_json


class Clock:
    """Wall-clock default for the time seam.

    time()       wall seconds (for on-disk stamps other PROCESSES
                 compare against their own wall clock — human-readable,
                 and the only cross-process time base a shared
                 directory offers)
    monotonic()  this process's monotonic seconds — ALL duration and
                 deadline arithmetic (lease ages, gate deadlines,
                 consensus timeouts) happens here, so an NTP step or a
                 suspend/resume can never mass-expire leases
    sleep(s)     blocks this thread (the simulator's clock instead
                 advances virtual time and drains due events)
    """

    def time(self):
        return time.time()

    def monotonic(self):
        return time.monotonic()

    def sleep(self, seconds):
        time.sleep(seconds)


#: shared default instance — coordinators without an injected clock use
#: the process wall/monotonic clock (bit-identical to the pre-seam code)
WALL_CLOCK = Clock()


def read_json_file(path):
    """Parse a JSON object file, or None — a torn write must read as
    absent, not an error (rendezvous writers re-write within one
    heartbeat interval)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


class RealDir:
    """Name-based atomic file ops over one rendezvous directory — the
    on-disk default for the Dir seam. All names are basenames inside
    ``root``; globbing returns sorted basenames so every consumer
    iterates deterministically. Writes are atomic renames (a reader
    sees the old file or the new one, never a torn middle); reads
    return None for absent/torn files."""

    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def path(self, name):
        return os.path.join(self.root, name)

    def glob(self, pattern):
        root = _glob.escape(self.root)
        return sorted(os.path.basename(p)
                      for p in _glob.glob(os.path.join(root, pattern)))

    def read_json(self, name):
        return read_json_file(self.path(name))

    def write_json(self, name, obj):
        atomic_write_json(self.path(name), obj)

    def write_npz(self, name, arrays):
        """``arrays``: {key: ndarray}. Atomic like write_json."""
        atomic_write_bytes(self.path(name),
                           lambda f: np.savez(f, **arrays))

    def load_npz(self, name):
        """{key: ndarray} fully materialized, or None (absent/torn)."""
        try:
            with np.load(self.path(name)) as z:
                return {k: z[k] for k in z.files}
        except (OSError, ValueError, KeyError):
            return None

    def exists(self, name):
        return os.path.exists(self.path(name))

    def remove(self, name):
        """True when this call removed the file (False: already gone —
        a concurrent peer won the race, which is never an error in the
        rendezvous protocol)."""
        try:
            os.remove(self.path(name))
        except OSError:
            return False
        return True

    def mtime(self, name):
        try:
            return os.path.getmtime(self.path(name))
        except OSError:
            return None
