"""Deterministic, seed-driven fault injection.

Recovery paths that only run during real outages are recovery paths that
don't work. The ChaosMonkey injects the failure classes the resilience
subsystem claims to survive — on a schedule tests can replay exactly:

  nan_step=K       the loss observed at step K becomes NaN (once) —
                   exercises RecoveryPolicy rollback
  nan_repeat=1     ...at EVERY step >= K (persistent divergence) —
                   exercises the bounded-retry abort
  io_p=P           each data-source record read raises ChaosIOError with
                   probability P (seeded rng) — exercises retry backoff
  stall_step=K, stall_s=S   step K blocks the host for S seconds (once) —
                   exercises the watchdog stall path
  stall_worker=W   attribute the stall to mesh worker W: the injected
                   seconds land on W's per-round latency while its peers
                   finish early — a simulated straggler the health
                   detector (obs/health.py) must name
  stall_repeat=1   stall at EVERY step >= K (a persistent straggler)
  sigterm_round=R  the process SIGTERMs itself after round/block R (once)
                   — exercises snapshot-then-stop + `--resume auto`
  kill_worker=W, kill_round=R   mesh worker W "crashes" at sync round R
                   (once; R defaults to 0) — exercises the elastic
                   membership layer (resilience/elastic.py): eviction,
                   shard re-spreading, quorum accounting, readmission
  dead_p=P         each live worker independently crashes with
                   probability P at every round (seeded rng; a crashed
                   worker stays crashed until the policy readmits it)
  kill_host=H, kill_host_round=R   host (fault domain) H dies at round R
                   (once; R defaults to 0). In a real multi-process run
                   the targeted process SIGKILLs ITSELF at the round
                   gate, before announcing arrival — survivors see a
                   lease expiry, the true crash shape; in virtual
                   single-process host meshes the host is marked dead
                   like kill_worker. Exercises host eviction, the
                   no-hang gate, and coordinated restart.
  preempt_host=H, preempt_round=R, rejoin_after=K   host H is
                   PREEMPTED at round R (default 0) and rejoins K
                   rounds later through the rendezvous — the spot-fleet
                   cycle. In a real multi-process run the targeted
                   process SIGKILLs itself at the round gate (lease
                   drop; the orchestration layer relaunches it with
                   `--grow`, a real rejoin); in virtual single-process
                   host meshes the host is evicted like kill_host and
                   then ADMITTED back K rounds later
                   (ElasticPolicy.admit — a host_joined event).
  partition_host=H, partition_round=R   from round R, host H and the
                   rest of the fleet stop seeing each other's
                   heartbeats (both sides of the split independently
                   conclude the other is gone — the quorum breaks the
                   symmetry: the majority side keeps training, the
                   minority side exits 4)
  slow_host=H, slow_host_s=S, slow_host_round=R, slow_repeat=1
                   host H arrives S seconds late at the round gate
                   (once at round R, or every round with slow_repeat) —
                   the host-granularity straggler the health detectors
                   must name
  slow_worker=W, slow_s=S, slow_round=R
                   worker W is a PERSISTENT straggler from round R
                   (default 0): every local round costs it S extra
                   seconds. Synchronous solvers render it as a real
                   host stall per round (the barrier waits — round
                   latency tracks the straggler, the paper's failure
                   mode); the async bounded-staleness mode instead
                   feeds S to the virtual version clocks
                   (ElasticPolicy.advance_versions) and NEVER sleeps —
                   the round proceeds at the median worker's pace and
                   W's lag grows until it parks. The sync-vs-async
                   wall-clock gap under this injector IS the mode's
                   acceptance test (scripts/smoke.sh async stage).
  slow_h2d=S       every host->device batch transfer costs S extra
                   seconds (persistent; hooked by the feed path's
                   H2DStager / round feed) — the artificially slow wire
                   under which data echoing must win wall clock
                   (scripts/smoke.sh ingest stage)
  fail_rate=P, fail_seed=S   every round, every live host independently
                   crashes with probability P — the fleet-scale failure
                   process (MTBF model) the simulator sweeps. The draw
                   is a PER-ROUND derived rng (seeded from fail_seed and
                   the round index), so the schedule is a pure function
                   of (S, round): identical across replays and immune to
                   how many other injectors consumed randomness. Victims
                   stay down until explicitly revived (revive_host — the
                   simulator's recovery process, or a policy
                   readmission).
  fail_corr=K      correlate the failures: hosts are grouped into
                   failure domains of K consecutive ids (a rack, a
                   zone), the per-round Bernoulli is drawn PER DOMAIN,
                   and a failing domain takes all its hosts down
                   together — the correlated-outage shape quorum
                   settings must survive. K<=1 means independent hosts.
  kill_replica=R, kill_req=N   serve replica R dies after serving its
                   N-th request (N defaults to 0 — die on first). In a
                   real fleet the targeted `sparknet serve --replica R`
                   process SIGKILLs ITSELF mid-load — the router sees
                   in-flight dispatches fail and the lease lapse, the
                   true crash shape; in `sparknet simfleet --serve` the
                   virtual replica goes silent. Exercises router
                   retry-once + ElasticPolicy replica eviction.
  slow_replica=R, slow_ms=S   serve replica R pays S extra
                   milliseconds per request (persistent) — the serving
                   twin of slow_host: drives its queue depth up so the
                   router's least-depth spread and the SLO autoscaler
                   have a measurable straggler to route around.

Armed via `--chaos "nan_step=30,io_p=0.02,seed=1"` or the SPARKNET_CHAOS
env var (same spec), which data sources and solvers pick up through
active_chaos() without any plumbing. Unknown or malformed tokens raise a
ValueError naming the offending token and listing the valid injectors —
a typo'd spec must never let a resilience test pass vacuously. Every
injection logs a ``chaos`` metrics event so a report never confuses
injected faults with real ones.
"""

import os
import signal
import time

import numpy as np


class ChaosIOError(OSError):
    """An injected (not real) IO failure."""


_UNSET = object()
_active = _UNSET


def install_chaos(monkey):
    """Explicitly arm (or, with None, disarm) the process-wide monkey."""
    global _active
    _active = monkey
    return monkey


def active_chaos():
    """The process-wide ChaosMonkey, arming from SPARKNET_CHAOS on first
    use; None when chaos is off."""
    global _active
    if _active is _UNSET:
        spec = os.environ.get("SPARKNET_CHAOS", "").strip()
        _active = ChaosMonkey.parse(spec) if spec else None
    return _active


class ChaosMonkey:
    def __init__(self, nan_step=None, nan_repeat=False, io_p=0.0,
                 stall_step=None, stall_s=0.0, stall_worker=None,
                 stall_repeat=False, sigterm_round=None,
                 kill_worker=None, kill_round=0, dead_p=0.0,
                 kill_host=None, kill_host_round=0,
                 preempt_host=None, preempt_round=0, rejoin_after=1,
                 partition_host=None, partition_round=0,
                 slow_host=None, slow_host_s=0.0, slow_host_round=0,
                 slow_repeat=False,
                 slow_worker=None, slow_s=0.0, slow_round=0,
                 slow_h2d=0.0,
                 fail_rate=0.0, fail_seed=0, fail_corr=0,
                 kill_replica=None, kill_req=0,
                 slow_replica=None, slow_ms=0.0,
                 seed=0, metrics=None, log_fn=print):
        self.nan_step = None if nan_step is None else int(nan_step)
        self.nan_repeat = bool(nan_repeat)
        self.io_p = float(io_p)
        self.stall_step = None if stall_step is None else int(stall_step)
        self.stall_s = float(stall_s)
        self.stall_worker = None if stall_worker is None else int(stall_worker)
        self.stall_repeat = bool(stall_repeat)
        self._last_stall = None
        self.sigterm_round = None if sigterm_round is None \
            else int(sigterm_round)
        self.kill_worker = None if kill_worker is None else int(kill_worker)
        self.kill_round = int(kill_round)
        self.dead_p = float(dead_p)
        self._kill_fired = False
        self._dead = set()      # workers dead_p has already crashed
        # host-granularity injectors (fault domains; resilience/heartbeat)
        self.kill_host = None if kill_host is None else int(kill_host)
        self.kill_host_round = int(kill_host_round)
        self._host_kill_fired = False
        # set by a multi-process HeartbeatCoordinator: the target
        # process SIGKILLs itself (maybe_kill_self), so the virtual
        # dead_hosts rendering must not double-fire on survivors
        self.kill_host_self_mode = False
        # the preempt/rejoin cycle (spot fleets): preempt_host dies
        # like kill_host at preempt_round, then comes back through the
        # rendezvous rejoin_after rounds later (virtual hosts:
        # ElasticPolicy.admit; real runs: a relaunched --grow process)
        self.preempt_host = None if preempt_host is None \
            else int(preempt_host)
        self.preempt_round = int(preempt_round)
        self.rejoin_after = max(1, int(rejoin_after))
        self._preempt_fired = False
        self._preempted_at = None
        self._rejoin_fired = False
        self.partition_host = None if partition_host is None \
            else int(partition_host)
        self.partition_round = int(partition_round)
        self._partition_logged = False
        self.slow_host = None if slow_host is None else int(slow_host)
        self.slow_host_s = float(slow_host_s)
        self.slow_host_round = int(slow_host_round)
        self.slow_repeat = bool(slow_repeat)
        self._slow_fired = False
        self._last_slow = None
        # the worker-granularity persistent straggler (async local SGD)
        self.slow_worker = None if slow_worker is None else int(slow_worker)
        self.slow_s = float(slow_s)
        self.slow_round = int(slow_round)
        self._slow_worker_logged = False
        self._last_slow_worker = None
        # the persistent slow H2D wire (feed-path staging / echo tests)
        self.slow_h2d = float(slow_h2d)
        self._slow_h2d_logged = False
        # the fleet-scale failure process (per-round iid or
        # domain-correlated host crashes; resilience/README, sim/)
        self.fail_rate = float(fail_rate)
        if not 0.0 <= self.fail_rate <= 1.0:
            raise ValueError(f"fail_rate {self.fail_rate} must be a "
                             "probability in [0, 1]")
        self.fail_seed = int(fail_seed)
        self.fail_corr = max(0, int(fail_corr))
        self._fail_dead = set()   # hosts fail_rate already took down
        # serving-tier injectors (serve/fleet.py, sim/servefleet.py)
        self.kill_replica = None if kill_replica is None \
            else int(kill_replica)
        self.kill_req = int(kill_req)
        self._replica_kill_fired = False
        self.slow_replica = None if slow_replica is None \
            else int(slow_replica)
        self.slow_ms = float(slow_ms)
        self._slow_replica_logged = False
        self._rng = np.random.RandomState(seed)
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self._nan_fired = False
        self._stall_fired = False
        self._term_fired = False
        self.injected = 0

    @classmethod
    def parse(cls, spec, **kw):
        """"nan_step=30,io_p=0.05,stall_step=10,stall_s=2,sigterm_round=3,
        seed=1" -> ChaosMonkey. Unknown keys AND malformed values are an
        error naming the offending token and listing the valid injectors
        — a typo'd chaos spec silently injecting nothing would fake a
        green resilience test."""
        def truthy(v):
            return v not in ("0", "false", "False", "")
        known = {"nan_step": int, "nan_repeat": truthy, "io_p": float,
                 "stall_step": int, "stall_s": float,
                 "stall_worker": int, "stall_repeat": truthy,
                 "sigterm_round": int, "kill_worker": int,
                 "kill_round": int, "dead_p": float,
                 "kill_host": int, "kill_host_round": int,
                 "preempt_host": int, "preempt_round": int,
                 "rejoin_after": int,
                 "partition_host": int, "partition_round": int,
                 "slow_host": int, "slow_host_s": float,
                 "slow_host_round": int, "slow_repeat": truthy,
                 "slow_worker": int, "slow_s": float, "slow_round": int,
                 "slow_h2d": float,
                 "fail_rate": float, "fail_seed": int, "fail_corr": int,
                 "kill_replica": int, "kill_req": int,
                 "slow_replica": int, "slow_ms": float,
                 "seed": int}
        valid = f"valid injectors: {', '.join(sorted(known))}"
        fields = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            k, eq, v = part.partition("=")
            k, v = k.strip(), v.strip()
            if not eq:
                raise ValueError(f"chaos spec token {part!r}: expected "
                                 f"key=value; {valid}")
            if k not in known:
                raise ValueError(f"chaos spec token {part!r}: unknown "
                                 f"injector {k!r}; {valid}")
            try:
                fields[k] = known[k](v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"chaos spec token {part!r}: bad value {v!r} for "
                    f"{k} (expects {known[k].__name__}); {valid}") from None
        return cls(**fields, **kw)

    def _event(self, kind, **fields):
        self.injected += 1
        self.log(f"[chaos] injecting {kind} "
                 + " ".join(f"{k}={v}" for k, v in fields.items()))
        if self.metrics is not None:
            self.metrics.log("chaos", kind=kind, **fields)

    # -- injectors ---------------------------------------------------------
    def poison_loss(self, it):
        """True when the loss at step ``it`` should be replaced by NaN."""
        if self.nan_step is None or it < self.nan_step:
            return False
        if self._nan_fired and not self.nan_repeat:
            return False
        if not self._nan_fired:
            self._event("nan", iter=it)
        self._nan_fired = True
        return True

    def maybe_io_error(self, where=""):
        if self.io_p > 0 and self._rng.random_sample() < self.io_p:
            self._event("io_error", where=where)
            raise ChaosIOError(f"injected IO error reading {where or '?'}")

    def maybe_stall(self, it):
        """Block the host for stall_s at/after stall_step (every step
        with stall_repeat). Returns the seconds injected (0.0 if none)
        and records the attribution for pop_stall()."""
        if self.stall_step is None or it < self.stall_step \
                or self.stall_s <= 0:
            return 0.0
        if self._stall_fired and not self.stall_repeat:
            return 0.0
        self._stall_fired = True
        ev = {"iter": it, "seconds": self.stall_s}
        if self.stall_worker is not None:
            ev["worker"] = self.stall_worker
        self._event("stall", **ev)
        self._last_stall = (self.stall_worker, self.stall_s)
        time.sleep(self.stall_s)
        return self.stall_s

    def pop_stall(self):
        """(worker, seconds) of the stall injected since the last call,
        or None — how the sync-round latency probe attributes the
        injected straggler to a worker."""
        rep, self._last_stall = self._last_stall, None
        return rep

    def dead_workers(self, round_, n_workers):
        """Worker indices newly "crashed" at sync round ``round_`` —
        the elastic membership layer evicts them (reason chaos_kill).
        kill_worker fires once at kill_round; dead_p is a per-round,
        per-worker seeded Bernoulli whose victims stay down (until the
        policy readmits them — a replacement arriving)."""
        out = []
        if self.kill_worker is not None and not self._kill_fired \
                and round_ >= self.kill_round:
            self._kill_fired = True
            if 0 <= self.kill_worker < n_workers:
                self._event("kill_worker", worker=self.kill_worker,
                            round=round_)
                out.append(self.kill_worker)
        if self.dead_p > 0:
            for w in range(int(n_workers)):
                if w in self._dead or w in out:
                    continue
                if self._rng.random_sample() < self.dead_p:
                    self._dead.add(w)
                    self._event("kill_worker", worker=w, round=round_,
                                via="dead_p")
                    out.append(w)
        return out

    def maybe_sigterm(self, round_):
        if self.sigterm_round is not None and not self._term_fired \
                and round_ >= self.sigterm_round:
            self._term_fired = True
            self._event("sigterm", round=round_)
            os.kill(os.getpid(), signal.SIGTERM)

    # -- host-granularity injectors (fault domains) ------------------------
    def fail_rate_victims(self, round_, n_hosts):
        """Host ids the fail_rate process newly takes down at round
        ``round_``. The Bernoulli draws come from a rng derived from
        (fail_seed, round_) alone — a pure function of the schedule, so
        replays and sweeps see identical failures no matter what other
        injectors drew from the shared rng or how often this round was
        polled. With fail_corr=K > 1 the draw is per failure DOMAIN of K
        consecutive host ids and a failing domain dies as one."""
        if self.fail_rate <= 0 or n_hosts <= 0:
            return []
        rng = np.random.RandomState(
            (self.fail_seed * 1000003 + int(round_)) % (2 ** 32))
        n_hosts = int(n_hosts)
        corr = self.fail_corr if self.fail_corr > 1 else 1
        n_domains = -(-n_hosts // corr)         # ceil
        draws = rng.random_sample(n_domains)
        out = []
        for d in range(n_domains):
            if draws[d] >= self.fail_rate:
                continue
            for h in range(d * corr, min((d + 1) * corr, n_hosts)):
                if h not in self._fail_dead:
                    self._fail_dead.add(h)
                    out.append(h)
        if out:
            self._event("fail_rate", hosts=out, round=int(round_),
                        corr=self.fail_corr)
        return out

    def revive_host(self, host):
        """Forget a fail_rate/dead_p crash for ``host`` so the failure
        process can take it down again — the simulator's (or an
        autoscaler's) recovery half of the MTBF cycle."""
        self._fail_dead.discard(int(host))
        self._dead.discard(int(host))

    def dead_hosts(self, round_, n_hosts):
        """Host ids newly "crashed" at round ``round_`` — the virtual
        (single-process host mesh) rendering of kill_host, consumed by
        an ElasticPolicy(unit="host") exactly like dead_workers."""
        out = []
        if self.kill_host is not None and not self._host_kill_fired \
                and not self.kill_host_self_mode \
                and round_ >= self.kill_host_round:
            self._host_kill_fired = True
            if 0 <= self.kill_host < n_hosts:
                self._event("kill_host", host=self.kill_host, round=round_)
                out.append(self.kill_host)
        if self.preempt_host is not None and not self._preempt_fired \
                and not self.kill_host_self_mode \
                and round_ >= self.preempt_round:
            self._preempt_fired = True
            if 0 <= self.preempt_host < n_hosts:
                self._event("preempt_host", host=self.preempt_host,
                            round=round_)
                self._preempted_at = round_
                out.append(self.preempt_host)
        if not self.kill_host_self_mode:
            out.extend(h for h in self.fail_rate_victims(round_, n_hosts)
                       if h not in out)
        return out

    def rejoining_hosts(self, round_):
        """Host ids rejoining through the rendezvous at ``round_`` —
        the second half of preempt_host: rejoin_after rounds after the
        virtual preemption the host is back and ElasticPolicy ADMITS
        it (a host_joined event). Empty until the preempt fired, and
        always empty in real multi-process runs (kill_host_self_mode),
        where the rejoin is a real relaunched `--grow` process."""
        if self._rejoin_fired or self._preempted_at is None:
            return []
        if round_ - self._preempted_at < self.rejoin_after:
            return []
        self._rejoin_fired = True
        self._event("rejoin_host", host=self.preempt_host, round=round_)
        return [self.preempt_host]

    def maybe_kill_self(self, host, round_, on_kill=None):
        """The REAL multi-process rendering of kill_host: the targeted
        process dies by SIGKILL at the round gate, before announcing
        arrival — no cleanup, no snapshot, exactly what a preemption or
        OOM kill looks like to the survivors (lease expiry). ``on_kill``
        runs first (stop heartbeating so the last lease predates the
        corpse)."""
        if self.kill_host is None or host != self.kill_host \
                or round_ < self.kill_host_round or self._host_kill_fired:
            return False
        self._host_kill_fired = True
        self._event("kill_host", host=host, round=round_, via="SIGKILL")
        if on_kill is not None:
            try:
                on_kill()
            except Exception:
                pass
        os.kill(os.getpid(), signal.SIGKILL)
        return True                           # not reached

    def maybe_preempt_self(self, host, round_, on_kill=None):
        """The REAL multi-process rendering of preempt_host: identical
        crash shape to maybe_kill_self (SIGKILL at the gate, lease
        expiry on the survivors), but the orchestration layer —
        scripts/smoke.sh's resize stage, an autoscaler — relaunches
        the corpse with `--grow`, turning the cycle into a real rejoin
        through the rendezvous."""
        if self.preempt_host is None or host != self.preempt_host \
                or round_ < self.preempt_round or self._preempt_fired:
            return False
        self._preempt_fired = True
        self._event("preempt_host", host=host, round=round_, via="SIGKILL")
        if on_kill is not None:
            try:
                on_kill()
            except Exception:
                pass
        os.kill(os.getpid(), signal.SIGKILL)
        return True                           # not reached

    def host_partitioned(self, a, b, round_):
        """True when hosts ``a`` and ``b`` can't see each other's
        heartbeats at ``round_`` — partition_host cuts the target off
        from the whole fleet (both directions)."""
        if self.partition_host is None or round_ < self.partition_round \
                or round_ < 0:
            return False
        cut = a != b and self.partition_host in (a, b)
        if cut and not self._partition_logged:
            self._partition_logged = True
            self._event("partition_host", host=self.partition_host,
                        round=round_)
        return cut

    def maybe_slow_host(self, host, round_):
        """Delay host ``host`` by slow_host_s at the round gate (once at
        slow_host_round, every round with slow_repeat). Returns the
        injected seconds; pop_slow_host() reports the attribution."""
        if self.slow_host is None or host != self.slow_host \
                or round_ < self.slow_host_round or self.slow_host_s <= 0:
            return 0.0
        if self._slow_fired and not self.slow_repeat:
            return 0.0
        self._slow_fired = True
        self._event("slow_host", host=host, round=round_,
                    seconds=self.slow_host_s)
        self._last_slow = (host, self.slow_host_s)
        time.sleep(self.slow_host_s)
        return self.slow_host_s

    def pop_slow_host(self):
        """(host, seconds) of the slow-host injection since the last
        call, or None — how the round-latency probe attributes the
        host-granularity straggler."""
        rep, self._last_slow = self._last_slow, None
        return rep

    # -- the persistent worker straggler (async bounded staleness) ---------
    def slow_worker_spec(self, round_):
        """(worker, extra_seconds) when the slow_worker injector is
        active at ``round_``, else None — the NON-BLOCKING query the
        async scheduler feeds to its virtual version clocks (the
        straggler pays its seconds on its own clock, never on the
        consensus's). Logs one ``slow_worker`` chaos event on first
        activation."""
        if self.slow_worker is None or round_ < self.slow_round \
                or self.slow_s <= 0:
            return None
        if not self._slow_worker_logged:
            self._slow_worker_logged = True
            self._event("slow_worker", worker=self.slow_worker,
                        round=round_, seconds=self.slow_s)
        return (self.slow_worker, self.slow_s)

    def maybe_slow_worker(self, round_):
        """The SYNCHRONOUS rendering of slow_worker: the barrier waits,
        so the whole round blocks for the straggler's extra seconds
        (every round from slow_round on — a persistent straggler).
        Returns the injected seconds; pop_slow_worker() reports the
        attribution for the round-latency probe."""
        spec = self.slow_worker_spec(round_)
        if spec is None:
            return 0.0
        self._last_slow_worker = spec
        time.sleep(spec[1])
        return spec[1]

    def pop_slow_worker(self):
        """(worker, seconds) of the sync slow-worker stall since the
        last call, or None."""
        rep, self._last_slow_worker = self._last_slow_worker, None
        return rep

    # -- the slow H2D wire (input-pipeline staging/echo) --------------------
    def maybe_slow_h2d(self, nbytes=0):
        """Delay the current host->device batch transfer by slow_h2d
        seconds (persistent — every FRESH transfer pays; echoed batches
        don't transfer, which is exactly the wall-clock edge the echo
        smoke test asserts). Logs one chaos event on first activation."""
        if self.slow_h2d <= 0:
            return 0.0
        if not self._slow_h2d_logged:
            self._slow_h2d_logged = True
            self._event("slow_h2d", seconds=self.slow_h2d,
                        nbytes=int(nbytes))
        time.sleep(self.slow_h2d)
        return self.slow_h2d

    # -- serving-tier injectors (replica fleets) ----------------------------
    def replica_kill_due(self, replica, served):
        """True once replica ``replica`` has served ``served`` >=
        kill_req requests — the non-firing query both renderings share
        (the simulator silences the virtual replica; the real process
        calls maybe_kill_replica_self). One-shot."""
        if self.kill_replica is None or replica != self.kill_replica \
                or self._replica_kill_fired or served < self.kill_req:
            return False
        self._replica_kill_fired = True
        self._event("kill_replica", replica=replica, served=int(served))
        return True

    def maybe_kill_replica_self(self, replica, served, on_kill=None):
        """The REAL fleet rendering of kill_replica: the targeted
        `sparknet serve --replica R` process dies by SIGKILL after its
        kill_req-th request — in-flight dispatches fail at the router
        and the lease lapses, exactly what an OOM kill mid-load looks
        like. ``on_kill`` runs first (stop heartbeating so the last
        lease predates the corpse)."""
        if not self.replica_kill_due(replica, served):
            return False
        if on_kill is not None:
            try:
                on_kill()
            except Exception:
                pass
        os.kill(os.getpid(), signal.SIGKILL)
        return True                           # not reached

    def replica_slow_spec(self, replica):
        """(replica, extra_seconds_per_request) when the slow_replica
        injector targets ``replica``, else None. Non-blocking — the
        simulator adds the seconds to virtual service time; the real
        serve loop sleeps them (maybe_slow_replica). Logs one chaos
        event on first activation."""
        if self.slow_replica is None or replica != self.slow_replica \
                or self.slow_ms <= 0:
            return None
        if not self._slow_replica_logged:
            self._slow_replica_logged = True
            self._event("slow_replica", replica=replica,
                        ms=self.slow_ms)
        return (self.slow_replica, self.slow_ms / 1e3)

    def maybe_slow_replica(self, replica):
        """The REAL rendering of slow_replica: the serve loop sleeps
        slow_ms before answering each request (persistent straggler).
        Returns the injected seconds."""
        spec = self.replica_slow_spec(replica)
        if spec is None:
            return 0.0
        time.sleep(spec[1])
        return spec[1]
