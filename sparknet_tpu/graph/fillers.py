"""Weight fillers with Caffe semantics (reference include/caffe/filler.hpp).

Fan computation follows Caffe's blob convention: for a blob of shape
(num, ...) — fan_in = count/num, fan_out = count/shape[1]
(filler.hpp:150-151) — which for an OIHW conv weight gives
fan_in = I*kh*kw, fan_out = O*kh*kw (under group conv, I is already C/g).
"""

import numpy as np
import jax
import jax.numpy as jnp


def _fans(shape):
    count = int(np.prod(shape))
    fan_in = count // shape[0] if len(shape) > 0 else count
    fan_out = count // shape[1] if len(shape) > 1 else count
    return fan_in, fan_out


def _n_for(variance_norm, shape):
    fan_in, fan_out = _fans(shape)
    if variance_norm == 1:  # FAN_OUT
        return fan_out
    if variance_norm == 2:  # AVERAGE
        return (fan_in + fan_out) / 2.0
    return fan_in


def fill(rng, shape, filler, dtype=jnp.float32):
    """Materialize one blob from a FillerParameter (None -> constant 0)."""
    if filler is None:
        return jnp.zeros(shape, dtype)
    ftype = filler.type
    if ftype == "constant":
        return jnp.full(shape, filler.value, dtype)
    if ftype == "uniform":
        return jax.random.uniform(rng, shape, dtype, filler.min, filler.max)
    if ftype == "gaussian":
        # sparse gaussian (filler.hpp GaussianFiller) not needed for parity
        return filler.mean + filler.std * jax.random.normal(rng, shape, dtype)
    if ftype == "xavier":
        scale = float(np.sqrt(3.0 / _n_for(filler.variance_norm, shape)))
        return jax.random.uniform(rng, shape, dtype, -scale, scale)
    if ftype == "msra":
        std = float(np.sqrt(2.0 / _n_for(filler.variance_norm, shape)))
        return std * jax.random.normal(rng, shape, dtype)
    if ftype == "positive_unitball":
        x = jax.random.uniform(rng, shape, dtype)
        flat = x.reshape(shape[0], -1)
        flat = flat / jnp.sum(flat, axis=1, keepdims=True)
        return flat.reshape(shape)
    if ftype == "bilinear":
        # upsampling kernel for deconv (filler.hpp BilinearFiller)
        if len(shape) != 4 or shape[2] != shape[3]:
            raise ValueError("bilinear filler needs square 4D blob")
        k = shape[3]
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = np.ogrid[:k, :k]
        kernel = (1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c))
        return jnp.broadcast_to(jnp.asarray(kernel, dtype), shape)
    raise ValueError(f"unknown filler type {ftype!r}")
