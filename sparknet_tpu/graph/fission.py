"""Virtual channel-concat ("inception fission") — a TPU-first graph pass.

Profiling GoogLeNet on a TPU chip shows the training step dominated not by
convolutions but by data movement the Concat layers induce: the gradient
of every inception concatenate is a set of big channel `slice`s (~1 GB/step
at batch 128 across the 9 modules), pure HBM traffic with zero FLOPs. The
reference pays the same cost structure on GPU (concat_layer.cu copies in
both directions) and simply eats it; on TPU, where HBM bandwidth is the
binding resource, it is worth removing structurally.

The pass makes channel-concats *virtual*: a Concat over dim 1 yields a
`Branches` value (the list of branch tensors) instead of one fused array.
Consumers that can consume branches directly do so:

  * Convolution (group=1) fissions over input channels:
        conv(concat(x_1..x_k), W) == sum_i conv(x_i, W[:, o_i:o_i+c_i])
    — same single weight blob (checkpoint format unchanged), the slices
    now taken from the *small* weights instead of the huge activations,
    and the concat gradient disappears entirely: each branch gets its
    input gradient straight from its own conv's backward.
  * Pooling (MAX/AVE) is per-channel, so it maps over branches and stays
    virtual (the branch then reaches the pool-proj conv, which fissions).

Any other consumer (LRN, InnerProduct, Dropout, Slice, losses, ...)
materializes the real concatenate lazily; XLA CSE dedups repeated
materializations and DCE removes unused ones. Numerics: fission reorders
the input-channel summation (k partial convs instead of one), so outputs
match the fused form to accumulation rounding, not bit-exactly.

Enabled by default; set SPARKNET_FISSION=0 to compile the literal graph.
"""

import os

import jax.numpy as jnp

MAX_POOL, AVE_POOL = 0, 1


def enabled():
    return os.environ.get("SPARKNET_FISSION", "1") != "0"


class Branches:
    """A channel-concat that was never materialized: an ordered list of
    4D arrays agreeing on every dim but the channel axis (1)."""

    __slots__ = ("parts",)
    axis = 1

    def __init__(self, parts):
        flat = []
        for p in parts:
            if isinstance(p, Branches):
                flat.extend(p.parts)
            else:
                flat.append(p)
        self.parts = flat

    @property
    def channels(self):
        return [p.shape[self.axis] for p in self.parts]

    def concat(self):
        return jnp.concatenate(self.parts, axis=self.axis)


def materialize(v):
    return v.concat() if isinstance(v, Branches) else v


def try_apply(lp, impl, lparams, bvals, train, rng):
    """Fission-aware dispatch for one layer. Returns the layer's top values
    (which may contain Branches), or None to mean "run the normal path"
    (the caller materializes any Branches bottoms first)."""
    if lp.type == "Concat" and getattr(impl, "axis", None) == 1 \
            and len(bvals) > 1 \
            and all(getattr(v, "ndim", 4) == 4 or isinstance(v, Branches)
                    for v in bvals):
        return [Branches(bvals)]
    if not any(isinstance(v, Branches) for v in bvals):
        return None
    if lp.type == "Convolution" and impl.group == 1 \
            and isinstance(bvals[0], Branches):
        return [impl.apply_fissioned(lparams, bvals[0], train, rng)]
    if lp.type == "Pooling" and impl.method in (MAX_POOL, AVE_POOL) \
            and isinstance(bvals[0], Branches):
        return [Branches([impl.apply(lparams, [p], train, rng)[0]
                          for p in bvals[0].parts])]
    return None
