"""Legacy NetParameter upgrades — V0 "layer connections" and deprecated
data-layer transform fields.

Re-derives reference util/upgrade_proto.cpp:
  UpgradeV0PaddingLayers (:120)  fold standalone "padding" layers into the
                                 conv/pool consumer's pad field
  UpgradeV0LayerParameter (:179) per-field mapping of the flat
                                 V0LayerParameter into typed V1 params
  UpgradeV0LayerType (:531)      lowercase type strings -> V1 enum
  NetNeedsDataUpgrade (:586)     deprecated DataParameter-level
                                 scale/mean_file/crop_size/mirror ->
                                 TransformationParameter

The V1 -> V2 step lives in compiler.upgrade_v1; `upgrade_net` chains all
three so any vintage of prototxt/caffemodel loads.
"""

from ..proto.message import Message

# UpgradeV0LayerType (upgrade_proto.cpp:531-584)
V0_TYPE_MAP = {
    "accuracy": "ACCURACY", "bnll": "BNLL", "concat": "CONCAT",
    "conv": "CONVOLUTION", "data": "DATA", "dropout": "DROPOUT",
    "euclidean_loss": "EUCLIDEAN_LOSS", "flatten": "FLATTEN",
    "hdf5_data": "HDF5_DATA", "hdf5_output": "HDF5_OUTPUT",
    "im2col": "IM2COL", "images": "IMAGE_DATA",
    "infogain_loss": "INFOGAIN_LOSS", "innerproduct": "INNER_PRODUCT",
    "lrn": "LRN", "multinomial_logistic_loss": "MULTINOMIAL_LOGISTIC_LOSS",
    "pool": "POOLING", "relu": "RELU", "sigmoid": "SIGMOID",
    "softmax": "SOFTMAX", "softmax_loss": "SOFTMAX_LOSS", "split": "SPLIT",
    "tanh": "TANH", "window_data": "WINDOW_DATA",
}

# V0 field -> (allowed type -> (v1 sub-message, v1 field)). "add" marks
# repeated targets (conv pad/kernel_size/stride became repeated in V2, but
# in V1 they are scalar; we upgrade straight to the V1 scalar fields).
_POOL_ENUM = {0: "MAX", 1: "AVE", 2: "STOCHASTIC"}


def needs_v0_upgrade(net_param):
    """True when any legacy `layers` entry carries a V0 payload
    (upgrade_proto.cpp NetNeedsV0ToV1Upgrade)."""
    return any(v1.has("layer") for v1 in net_param.layers)


def upgrade_v0(net_param):
    """V0 net -> V1 net (upgrade_proto.cpp UpgradeV0Net :93). Returns a new
    NetParameter whose `layers` entries use typed V1 params; raises on
    fields the reference flagged as not-fully-compatible."""
    fused = _fuse_padding_layers(net_param)
    out = net_param.copy()
    out.clear("layers")
    for conn in fused.layers:
        out.layers.append(_upgrade_v0_layer(conn))
    return out


def _fuse_padding_layers(net_param):
    """UpgradeV0PaddingLayers (:120): drop "padding" layers, push their pad
    into the following conv/pool layer and rewire its bottom."""
    out = net_param.copy()
    out.clear("layers")
    last_top = {name: -1 for name in net_param.input}
    layers = list(net_param.layers)
    for i, conn in enumerate(layers):
        v0 = conn.layer
        if v0.type != "padding":
            out.layers.append(conn.copy())
        for j, bname in enumerate(conn.bottom):
            if bname not in last_top:
                raise ValueError(f"unknown blob input {bname} to layer {i}")
            src_idx = last_top[bname]
            if src_idx < 0:
                continue
            src = layers[src_idx]
            if src.layer.type == "padding":
                if v0.type not in ("conv", "pool"):
                    raise ValueError(
                        f"padding layer feeds non-conv/pool layer "
                        f"{v0.type!r} (undefined in Caffe)")
                if len(conn.bottom) != 1 or len(src.bottom) != 1 \
                        or len(src.top) != 1:
                    raise ValueError("padding fusion requires single-"
                                     "input/single-output layers")
                tgt = out.layers[-1]
                tgt.layer.pad = src.layer.pad
                tgt.bottom[j] = src.bottom[0]
        for bname in conn.top:
            last_top[bname] = i
    return out


def _upgrade_v0_layer(conn):
    """UpgradeV0LayerParameter (:179): one V0 layer connection -> V1."""
    v0 = conn.layer
    t = v0.type if v0.has("type") else None
    v1 = Message("V1LayerParameter")
    v1.bottom.extend(conn.bottom)
    v1.top.extend(conn.top)
    if v0.has("name"):
        v1.name = v0.name
    if t is not None:
        if t not in V0_TYPE_MAP:
            raise ValueError(f"unknown V0 layer type {t!r}")
        v1.type = V0_TYPE_MAP[t]
    for b in v0.blobs:
        v1.blobs.append(b.copy())
    v1.blobs_lr.extend(v0.blobs_lr)
    v1.weight_decay.extend(v0.weight_decay)

    def sub(name):
        if not v1.has(name):
            setattr(v1, name, Message({
                "convolution_param": "ConvolutionParameter",
                "inner_product_param": "InnerProductParameter",
                "pooling_param": "PoolingParameter",
                "dropout_param": "DropoutParameter",
                "lrn_param": "LRNParameter",
                "data_param": "DataParameter",
                "hdf5_data_param": "HDF5DataParameter",
                "image_data_param": "ImageDataParameter",
                "window_data_param": "WindowDataParameter",
                "infogain_loss_param": "InfogainLossParameter",
                "concat_param": "ConcatParameter",
                "transform_param": "TransformationParameter",
            }[name]))
        return getattr(v1, name)

    def route(field, table, setter=None):
        if not v0.has(field):
            return
        if t not in table:
            raise ValueError(
                f"unknown parameter {field} for layer type {t!r}")
        pname, attr = table[t]
        target = sub(pname)
        value = getattr(v0, field)
        if setter:
            value = setter(value)
        spec = target.spec(attr)
        if spec[2] != "opt":       # repeated target (conv pad/kernel/stride
            getattr(target, attr).append(value)  # became repeated in V2;
        else:                      # the reference add_pad()s them)
            setattr(target, attr, value)

    route("num_output", {"conv": ("convolution_param", "num_output"),
                         "innerproduct": ("inner_product_param",
                                          "num_output")})
    route("biasterm", {"conv": ("convolution_param", "bias_term"),
                       "innerproduct": ("inner_product_param", "bias_term")})
    if v0.has("weight_filler"):
        if t == "conv":
            sub("convolution_param").weight_filler = v0.weight_filler.copy()
        elif t == "innerproduct":
            sub("inner_product_param").weight_filler = v0.weight_filler.copy()
        else:
            raise ValueError(f"unknown parameter weight_filler for {t!r}")
    if v0.has("bias_filler"):
        if t == "conv":
            sub("convolution_param").bias_filler = v0.bias_filler.copy()
        elif t == "innerproduct":
            sub("inner_product_param").bias_filler = v0.bias_filler.copy()
        else:
            raise ValueError(f"unknown parameter bias_filler for {t!r}")
    route("pad", {"conv": ("convolution_param", "pad"),
                  "pool": ("pooling_param", "pad")})
    route("kernelsize", {"conv": ("convolution_param", "kernel_size"),
                         "pool": ("pooling_param", "kernel_size")})
    route("group", {"conv": ("convolution_param", "group")})
    route("stride", {"conv": ("convolution_param", "stride"),
                     "pool": ("pooling_param", "stride")})
    route("pool", {"pool": ("pooling_param", "pool")},
          setter=lambda v: _POOL_ENUM[int(v)])
    route("dropout_ratio", {"dropout": ("dropout_param", "dropout_ratio")})
    route("local_size", {"lrn": ("lrn_param", "local_size")})
    route("alpha", {"lrn": ("lrn_param", "alpha")})
    route("beta", {"lrn": ("lrn_param", "beta")})
    route("k", {"lrn": ("lrn_param", "k")})
    route("source", {"data": ("data_param", "source"),
                     "hdf5_data": ("hdf5_data_param", "source"),
                     "images": ("image_data_param", "source"),
                     "window_data": ("window_data_param", "source"),
                     "infogain_loss": ("infogain_loss_param", "source")})
    if v0.has("scale"):
        sub("transform_param").scale = v0.scale
    if v0.has("meanfile"):
        sub("transform_param").mean_file = v0.meanfile
    route("batchsize", {"data": ("data_param", "batch_size"),
                        "hdf5_data": ("hdf5_data_param", "batch_size"),
                        "images": ("image_data_param", "batch_size"),
                        "window_data": ("window_data_param", "batch_size")})
    if v0.has("cropsize"):
        sub("transform_param").crop_size = v0.cropsize
    if v0.has("mirror"):
        sub("transform_param").mirror = v0.mirror
    route("rand_skip", {"data": ("data_param", "rand_skip"),
                        "images": ("image_data_param", "rand_skip")})
    route("shuffle_images", {"images": ("image_data_param", "shuffle")})
    route("new_height", {"images": ("image_data_param", "new_height")})
    route("new_width", {"images": ("image_data_param", "new_width")})
    route("concat_dim", {"concat": ("concat_param", "concat_dim")})
    route("det_fg_threshold",
          {"window_data": ("window_data_param", "fg_threshold")})
    route("det_bg_threshold",
          {"window_data": ("window_data_param", "bg_threshold")})
    route("det_fg_fraction",
          {"window_data": ("window_data_param", "fg_fraction")})
    route("det_context_pad",
          {"window_data": ("window_data_param", "context_pad")})
    route("det_crop_mode",
          {"window_data": ("window_data_param", "crop_mode")})
    if v0.has("hdf5_output_param"):
        if t != "hdf5_output":
            raise ValueError("unknown parameter hdf5_output_param for "
                             f"layer type {t!r}")
        v1.hdf5_output_param = v0.hdf5_output_param.copy()
    return v1


_DATA_PARAM_FIELDS = ("data_param", "image_data_param", "window_data_param")
_DEPRECATED_TRANSFORM_FIELDS = ("scale", "mean_file", "crop_size", "mirror")


def net_needs_data_upgrade(net_param):
    """True when any V2 layer still carries deprecated transform fields in
    its data param (upgrade_proto.cpp NetNeedsDataUpgrade :586)."""
    return any(
        lp.has(pf) and any(getattr(lp, pf).has(f)
                           for f in _DEPRECATED_TRANSFORM_FIELDS)
        for lp in net_param.layer for pf in _DATA_PARAM_FIELDS)


def upgrade_data_transform(net_param):
    """Move deprecated DataParameter/ImageDataParameter/WindowDataParameter
    scale/mean_file/crop_size/mirror into the layer's transform_param
    (NetNeedsDataUpgrade :586 + UpgradeNetDataTransformation). Operates on
    V2 `layer` entries, after the V1 upgrade."""
    out = net_param.copy()
    for lp in out.layer:
        for pf in _DATA_PARAM_FIELDS:
            if not lp.has(pf):
                continue
            dp = getattr(lp, pf)
            for f in _DEPRECATED_TRANSFORM_FIELDS:
                if dp.has(f):
                    if not lp.has("transform_param"):
                        lp.transform_param = \
                            Message("TransformationParameter")
                    setattr(lp.transform_param, f, getattr(dp, f))
                    dp.clear(f)
    return out


def solver_needs_type_upgrade(solver_param):
    """True when the deprecated SolverType enum field is set
    (upgrade_proto.cpp SolverNeedsTypeUpgrade :940-946)."""
    return solver_param.has("solver_type")


def upgrade_solver(solver_param):
    """Deprecated `solver_type` enum -> `type` string
    (upgrade_proto.cpp UpgradeSolverType :948-990). Returns a new
    SolverParameter; raises if both old and new fields are set."""
    from ..solver.updates import SOLVER_TYPES
    out = solver_param.copy()
    if out.has("solver_type"):
        if out.has("type"):
            raise ValueError(
                "old solver_type field (enum) and new type field (string) "
                "cannot both be set")
        out.type = SOLVER_TYPES[int(out.solver_type)]
        out.clear("solver_type")
    return out


def upgrade_net(net_param):
    """Chain every upgrade so any prototxt vintage loads:
    V0 layer connections -> V1 typed layers -> deprecated data-transform
    fields -> V2 `layer` list (compiler.upgrade_v1)."""
    from .compiler import upgrade_v1
    if needs_v0_upgrade(net_param):
        net_param = upgrade_v0(net_param)
    net_param = upgrade_v1(net_param)
    return upgrade_data_transform(net_param)
