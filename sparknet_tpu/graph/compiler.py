"""NetParameter -> pure init/apply functions: the graph compiler.

The TPU-native replacement for Caffe's Net runtime (reference net.cpp:
FilterNet :287, split insertion :54, AppendTop/Bottom :385/:444, param
ownership & sharing, ForwardFromTo :565). Differences born of the platform:

  * No Split insertion — autodiff accumulates fan-out gradients natively.
  * No Backward graph — ``jax.grad`` of the compiled loss is the backward.
  * In-place ops (ReLU with top==bottom) are SSA rebinds of the blob name.
  * Data layers are feeds (see ops.feed): the compiled step takes a
    ``batch`` dict; nothing inside the graph performs IO.
  * BatchNorm-style mutable blobs are explicit functional state threaded
    through ``apply`` (Caffe mutates blobs_ in place).

The whole forward (and the grad through it) traces into ONE XLA program:
layer fusion, scheduling and memory planning are XLA's job, per the
compilation model in /opt/skills/guides (trace once, static shapes).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..proto.message import Message
from . import fillers as F
from .registry import get as get_layer, V1_TYPE_MAP

# import for registration side effects
from .. import ops as _ops  # noqa: F401

TRAIN, TEST = 0, 1

REMAT_POLICIES = ("none", "dots", "full")


def _env_remat():
    """SPARKNET_REMAT -> policy name. Back-compat: "0"/"1" mean
    none/full (the original boolean env var)."""
    import os
    v = os.environ.get("SPARKNET_REMAT", "").lower()
    pol = {"": "none", "0": "none", "none": "none",
           "1": "full", "full": "full", "dots": "dots"}.get(v)
    if pol is None:
        raise ValueError(
            f"SPARKNET_REMAT={v!r}: want none|dots|full (or 0/1)")
    return pol


def _env_precision():
    """SPARKNET_PRECISION -> compute dtype (the --precision CLI knob):
    "bf16" runs activations in bfloat16 with fp32 master weights
    (Micikevicius et al., 2018); "fp32"/unset is None — the untouched
    full-precision path, bit for bit."""
    import os
    v = os.environ.get("SPARKNET_PRECISION", "").strip().lower()
    if v in ("bf16", "bfloat16"):
        return jnp.bfloat16
    if v in ("", "fp32", "float32", "off"):
        return None
    raise ValueError(f"SPARKNET_PRECISION={v!r}: want bf16|fp32")


def _checkpointed(fn, pol):
    """Wrap fn in jax.checkpoint under the named remat policy: "full"
    recomputes everything in the backward, "dots" saves matmul/conv
    outputs and recomputes the cheap elementwise tails (the standard
    memory/FLOPs middle ground for transformer blocks)."""
    if pol == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.checkpoint_dots)
    return jax.checkpoint(fn)


def upgrade_v1(net_param):
    """Upgrade legacy V1 'layers' to V2 'layer' entries (the capability of
    reference util/upgrade_proto.cpp, re-derived from the schema mapping)."""
    if not net_param.layers:
        return net_param
    out = net_param.copy()
    out.clear("layers")
    for v1 in net_param.layers:
        lp = out.add("layer")
        if v1.has("name"):
            lp.name = v1.name
        if v1.has("type"):
            lp.type = V1_TYPE_MAP[v1.enum_name("type")]
        lp.bottom.extend(v1.bottom)
        lp.top.extend(v1.top)
        lp.loss_weight.extend(v1.loss_weight)
        for r in v1.include:
            lp.include.append(r.copy())
        for r in v1.exclude:
            lp.exclude.append(r.copy())
        for b in v1.blobs:
            lp.blobs.append(b.copy())
        # blobs_lr / weight_decay pairs -> ParamSpecs
        n = max(len(v1.blobs_lr), len(v1.weight_decay))
        for i in range(n):
            ps = lp.add("param")
            if i < len(v1.blobs_lr):
                ps.lr_mult = v1.blobs_lr[i]
            if i < len(v1.weight_decay):
                ps.decay_mult = v1.weight_decay[i]
        for f in ("accuracy_param", "argmax_param", "concat_param",
                  "contrastive_loss_param", "convolution_param", "data_param",
                  "dropout_param", "dummy_data_param", "eltwise_param",
                  "exp_param", "hdf5_data_param", "hdf5_output_param",
                  "hinge_loss_param", "image_data_param",
                  "infogain_loss_param", "inner_product_param", "lrn_param",
                  "memory_data_param", "mvn_param", "pooling_param",
                  "power_param", "relu_param", "sigmoid_param",
                  "softmax_param", "slice_param", "tanh_param",
                  "threshold_param", "window_data_param", "transform_param",
                  "loss_param"):
            if v1.has(f):
                setattr(lp, f, getattr(v1, f).copy())
    return out


def _rule_matches(rule, state):
    """NetStateRule vs NetState (reference net.cpp StateMeetsRule)."""
    if rule.has("phase") and rule.phase != state.phase:
        return False
    if rule.has("min_level") and state.level < rule.min_level:
        return False
    if rule.has("max_level") and state.level > rule.max_level:
        return False
    stages = set(state.stage)
    for s in rule.stage:
        if s not in stages:
            return False
    for s in rule.not_stage:
        if s in stages:
            return False
    return True


def filter_net(net_param, phase, level=0, stages=()):
    """Phase/level/stage filtering (reference net.cpp FilterNet :287)."""
    state = Message("NetState", phase=phase, level=level, stage=list(stages))
    out = net_param.copy()
    out.clear("layer")
    for lp in net_param.layer:
        inc = lp.include
        exc = lp.exclude
        if inc and exc:
            raise ValueError(f"layer {lp.name}: both include and exclude rules")
        keep = True
        if inc:
            keep = any(_rule_matches(r, state) for r in inc)
        elif exc:
            keep = not any(_rule_matches(r, state) for r in exc)
        if keep and lp.has("phase") and lp.phase != phase:
            keep = False
        if keep:
            out.layer.append(lp.copy())
    return out


class CompiledNet:
    """A phase-specific executable net.

    build: shape-infers every blob, instantiates layer impls, and indexes
    params (with cross-layer sharing via ParamSpec.name, reference net.cpp
    AppendParam).

      init(rng)                      -> (params, state)
      apply(params, state, batch, train=..., rng=...) -> (blobs, new_state)
      loss_fn(params, state, batch, rng)  -> loss, (blobs, new_state)

    params:  {layer_name: [jnp arrays]}   (owning layers only)
    state:   {layer_name: [jnp arrays]}   (e.g. BatchNorm running stats)
    blobs:   {blob_name: array} after the full forward
    """

    def __init__(self, net_param, phase=TRAIN, feed_shapes=None,
                 dtype=jnp.float32, level=0, stages=(), compute_dtype=None):
        from .upgrade import upgrade_net
        net_param = upgrade_net(net_param)
        self.phase = phase
        self.dtype = dtype
        # mixed precision: params stay `dtype` (f32 masters for the
        # optimizer), activations run `compute_dtype` (bf16 drives the
        # MXU at full rate). Layers cast weights to their input's dtype,
        # so the cast only needs to happen where activations are BORN
        # from params alone — the embedding lookups (ops/dense.py Embed).
        # Float feeds choose their own dtype at the batch boundary.
        # None defers to the SPARKNET_PRECISION env var (the --precision
        # knob), resolved HERE so per-shard twin nets built from
        # net.compute_dtype inherit the resolved policy.
        self.compute_dtype = compute_dtype if compute_dtype is not None \
            else _env_precision()
        self.net_param = filter_net(net_param, phase, level, stages)
        self.name = net_param.name
        feed_shapes = dict(feed_shapes or {})

        self.layers = []          # [(lp, impl, bottoms, tops)]
        self.param_refs = {}      # layer_name -> [(owner_name, idx)]
        self.param_meta = {}      # (owner, idx) -> (shape, filler, lr, decay)
        self.loss_weights = {}    # layer_name -> [w per top]
        shared = {}               # ParamSpec.name -> (owner, idx)
        blob_shapes = {}
        available = {}            # blob name -> producing layer (output tracking)

        # net-level inputs (deploy nets: net.input + input_shape/input_dim)
        self.net_inputs = list(self.net_param.input)
        if self.net_inputs:
            if self.net_param.input_shape:
                in_shapes = [tuple(int(d) for d in s.dim)
                             for s in self.net_param.input_shape]
            else:
                dims = [int(d) for d in self.net_param.input_dim]
                in_shapes = [tuple(dims[i:i + 4])
                             for i in range(0, len(dims), 4)]
            for nm, s in zip(self.net_inputs, in_shapes):
                blob_shapes[nm] = s
                available[nm] = "__input__"

        for li, lp in enumerate(self.net_param.layer):
            cls = get_layer(lp.type)
            bottoms = list(lp.bottom)
            tops = list(lp.top)
            for b in bottoms:
                if b not in blob_shapes:
                    raise ValueError(
                        f"layer {lp.name!r}: bottom {b!r} is undefined")
            bshapes = [blob_shapes[b] for b in bottoms]
            if getattr(cls, "is_feed", False):
                impl = cls(lp, bshapes, phase, feed_shapes=feed_shapes)
            else:
                impl = cls(lp, bshapes, phase)
            impl.compute_dtype = compute_dtype
            tshapes = impl.out_shapes()
            if len(tops) < len(tshapes) and impl.loss_like:
                # Caffe auto-top (net.cpp AppendTop, gated on
                # AutoTopBlobs() == loss layers only): a LOSS layer may
                # declare fewer tops than it produces — commonly none
                # (pascal_finetune's SoftmaxWithLoss) — and the missing
                # blobs get automatic names derived from the layer. For
                # any other layer type an under-declaration stays a hard
                # error (it is almost certainly a typo'd prototxt).
                auto = [lp.name if len(tshapes) - len(tops) == 1
                        else f"{lp.name}_top{i}"
                        for i in range(len(tops), len(tshapes))]
                tops = tops + auto
                lp.top.extend(auto)
            if len(tshapes) != len(tops):
                raise ValueError(
                    f"layer {lp.name!r} ({lp.type}): {len(tops)} tops declared "
                    f"but impl produces {len(tshapes)}")
            for b in bottoms:
                available.pop(b, None)
            for t, s in zip(tops, tshapes):
                blob_shapes[t] = tuple(s)
                available[t] = lp.name
            self.layers.append((lp, impl, bottoms, tops))

            # params (with sharing)
            refs = []
            pshapes = impl.param_shapes()
            for i, (shape, filler, lr_mult, decay_mult) in enumerate(pshapes):
                pname = lp.param[i].name if i < len(lp.param) and \
                    lp.param[i].has("name") else ""
                if pname and pname in shared:
                    owner = shared[pname]
                    oshape = self.param_meta[owner][0]
                    if int(np.prod(oshape)) != int(np.prod(shape)):
                        raise ValueError(
                            f"shared param {pname!r}: count mismatch")
                    refs.append(owner)
                else:
                    key = (lp.name, i)
                    self.param_meta[key] = (tuple(shape), filler,
                                            float(lr_mult), float(decay_mult))
                    if pname:
                        shared[pname] = key
                    refs.append(key)
            self.param_refs[lp.name] = refs

            # loss weights (reference layer.hpp SetLossWeights: *Loss layers
            # default top[0] weight to 1)
            ws = list(lp.loss_weight)
            if not ws:
                ws = [1.0] + [0.0] * (len(tops) - 1) if impl.loss_like \
                    else [0.0] * len(tops)
            elif len(ws) != len(tops):
                raise ValueError(f"layer {lp.name}: loss_weight count mismatch")
            self.loss_weights[lp.name] = ws

        self.blob_shapes = blob_shapes
        # net outputs: produced and never consumed (net.cpp:270-284)
        self.output_blobs = [b for b, l in available.items()
                             if l != "__input__"]
        # perf knobs, settable per-net (Solver.set_remat / CLI --remat);
        # None defers to the SPARKNET_REMAT / SPARKNET_SCAN env vars at
        # trace time
        self.remat = None
        self.scan = None
        self._scan_cache = None
        self._epilogue_cache = None

    # -- feeds -------------------------------------------------------------
    def feed_blobs(self):
        """Blob names the batch dict must provide."""
        names = list(self.net_inputs)
        for lp, impl, bottoms, tops in self.layers:
            if getattr(impl, "is_feed", False):
                names.extend(tops)
        return names

    def feed_shapes(self):
        return {n: self.blob_shapes[n] for n in self.feed_blobs()}

    # -- init --------------------------------------------------------------
    def init(self, rng):
        params, state = {}, {}
        keys_needed = sorted(self.param_meta.keys())
        keys = jax.random.split(rng, max(1, len(keys_needed)))
        key_of = dict(zip(keys_needed, keys))
        for lp, impl, bottoms, tops in self.layers:
            owned = [k for k in self.param_refs[lp.name] if k[0] == lp.name]
            if owned:
                blobs = []
                for key in owned:
                    shape, filler, lr, decay = self.param_meta[key]
                    blobs.append(F.fill(key_of[key], shape, filler,
                                        self.dtype))
                params[lp.name] = blobs
            ss = impl.state_shapes()
            if ss:
                state[lp.name] = [jnp.full(shape, val, self.dtype)
                                  for shape, val in ss]
        # pretrained blobs embedded in the prototxt (LayerParameter.blobs)
        self._load_embedded_blobs(params)
        return params, state

    def _load_embedded_blobs(self, params):
        for lp, impl, bottoms, tops in self.layers:
            if lp.blobs and lp.name in params:
                for i, bp in enumerate(lp.blobs):
                    if i < len(params[lp.name]):
                        arr = blob_to_array(bp)
                        params[lp.name][i] = jnp.asarray(
                            arr.reshape(params[lp.name][i].shape), self.dtype)

    def resolve_params(self, params, layer_name):
        out = []
        for owner, idx in self.param_refs[layer_name]:
            out.append(params[owner][idx])
        return out

    # -- forward -----------------------------------------------------------
    def _remat_groups(self):
        """Rematerialization segments: maximal runs of >= 2 consecutive
        layers sharing a name prefix before "/" (the zoo's "block{i}/..."
        convention). Cached; used only when SPARKNET_REMAT is on."""
        if getattr(self, "_remat_cache", None) is not None:
            return self._remat_cache
        groups = {}
        start = None
        prefix = None
        for li, (lp, impl, bottoms, tops) in enumerate(self.layers):
            p = lp.name.split("/")[0] if "/" in lp.name else None
            if p != prefix:
                if prefix is not None and li - start >= 2:
                    groups[start] = li
                start, prefix = li, p
        if prefix is not None and len(self.layers) - start >= 2:
            groups[start] = len(self.layers)
        self._remat_cache = groups
        return groups

    def _epilogue_plan(self):
        """Fusable conv-epilogue sites, cached: {conv_idx: (relu_idx,
        lrn_idx | None)}.

        A site is Convolution (bias_term, single top) immediately
        followed by a zero-slope in-place ReLU on the same blob — the
        zoo/prototxt idiom — optionally followed by an adjacent 4D
        ACROSS_CHANNELS LRN reading that blob. The LRN extension only
        qualifies when nothing ELSE reads the relu'd blob (no later
        consumer, no loss weight, not a net output): the fused kernel
        never materializes it, and the remat discipline applies — absent,
        never stale."""
        if self._epilogue_cache is not None:
            return self._epilogue_cache
        plan = {}
        nl = len(self.layers)
        for ci in range(nl - 1):
            lp, impl, bottoms, tops = self.layers[ci]
            if getattr(impl, "type_name", None) != "Convolution" \
                    or not impl.bias_term or len(tops) != 1 \
                    or any(self.loss_weights[lp.name]):
                continue
            top = tops[0]
            rlp, rimpl, rbot, rtop = self.layers[ci + 1]
            if getattr(rimpl, "type_name", None) != "ReLU" \
                    or rbot != [top] or rtop != [top] \
                    or any(self.loss_weights[rlp.name]):
                continue
            if rlp.has("relu_param") and rlp.relu_param.negative_slope:
                continue
            plan[ci] = (ci + 1, None)
            if ci + 2 >= nl or len(self.blob_shapes[top]) != 4:
                continue
            llp, limpl, lbot, ltop = self.layers[ci + 2]
            if getattr(limpl, "type_name", None) != "LRN" or limpl.within \
                    or lbot != [top]:
                continue
            later = sum(b == top for lj in range(ci + 3, nl)
                        for b in self.layers[lj][2])
            if later == 0 and top not in self.output_blobs:
                plan[ci] = (ci + 1, ci + 2)
        self._epilogue_cache = plan
        return plan

    def _active_epilogue(self):
        """The epilogue sites the SPARKNET_EPILOGUE policy enables for
        this trace: off — none; auto — only the 3-op bias+ReLU+LRN
        fusion, on TPU (plain bias+ReLU is already XLA's conv epilogue,
        and a pallas boundary there costs an extra HBM pass — the
        pallas-LRN lesson from PERF.md round-3); on — every site, any
        backend (CPU runs the kernels in interpret mode: tests)."""
        import os
        mode = os.environ.get("SPARKNET_EPILOGUE", "auto").lower()
        if mode == "off":
            return {}
        plan = self._epilogue_plan()
        if mode == "on":
            return plan
        if jax.default_backend() != "tpu":
            return {}
        return {ci: v for ci, v in plan.items() if v[1] is not None}

    def _apply_range(self, params, state, new_state, blobs, lo, hi, batch,
                     train, rng, fiss, ep=None):
        """Run layers [lo, hi) over the mutable blob dict (the body the
        remat segments replay). ``ep``: active epilogue-fusion sites;
        a site engages only when its whole conv/ReLU(/LRN) window lies
        inside [lo, hi), else the layers run unfused (correct either
        way)."""
        from . import fission
        skip = set()
        for li in range(lo, hi):
            if li in skip:
                continue
            lp, impl, bottoms, tops = self.layers[li]
            if getattr(impl, "is_feed", False):
                for t in tops:
                    blobs[t] = jnp.asarray(batch[t])
                continue
            lparams = self.resolve_params(params, lp.name)
            bvals = [blobs[b] for b in bottoms]
            lrng = jax.random.fold_in(rng, li) if impl.needs_rng else None
            fuse = ep.get(li) if ep else None
            if fuse is not None and max(x for x in fuse
                                        if x is not None) < hi:
                from ..ops import pallas_epilogue as pe
                ri, lrni = fuse
                bvals = [fission.materialize(v) for v in bvals]
                y = impl.apply_raw(lparams, bvals, train, lrng)
                b = lparams[1]
                skip.add(ri)
                if lrni is None:
                    # ReLU is the in-place rebind: the fused output IS
                    # the conv/relu blob, bit-for-bit
                    blobs[tops[0]] = pe.bias_relu(y, b)
                else:
                    lm = self.layers[lrni][1]
                    blobs[self.layers[lrni][3][0]] = pe.bias_relu_lrn(
                        y, b, lm.size, lm.alpha, lm.beta, lm.k)
                    skip.add(lrni)
                    # the relu'd pre-LRN blob is never materialized;
                    # absent, never stale (plan proved no consumer)
                    blobs.pop(tops[0], None)
                continue
            tvals = fission.try_apply(lp, impl, lparams, bvals,
                                      train, lrng) if fiss else None
            if tvals is None:
                # normal path; any virtual concat bottom materializes here
                bvals = [fission.materialize(v) for v in bvals]
                if impl.has_state:
                    tvals, st = impl.apply_stateful(
                        lparams, state[lp.name], bvals, train, lrng)
                    new_state[lp.name] = st
                else:
                    tvals = impl.apply(lparams, bvals, train, lrng)
            for t, v in zip(tops, tvals):
                blobs[t] = v

    def _scan_runs(self):
        """Scan-over-layers sites, cached: maximal runs of >= 2
        consecutive structurally identical "prefix/" layer groups (the
        zoo's "block{i}/..." transformer convention), each chained
        through a single boundary blob.

        Two groups are identical when every corresponding layer matches
        on type, name suffix, prefix-stripped bottoms/tops, top blob
        shapes, and owned param shapes/dtypes — and is stateless,
        rng-free, loss-free, feed-free, with no cross-layer param
        sharing. Chaining requires group i's one external input to be
        group i-1's one externally consumed top, read by nothing else.
        Under those conditions the whole run executes as ONE traced
        block body under lax.scan over stacked per-group params,
        collapsing per-layer trace/dispatch/compile cost from O(depth)
        to O(1) — the d512 LM row's dominant overhead (PERF.md).

        Returns [{lo, hi, glen, n, entry, body_out, out}]: layer range,
        group length/count, group-0's external input blob, group-0's
        boundary top (the scan carry), and the LAST group's boundary
        blob name (where the carry lands). Config fields that don't
        change shapes (e.g. LayerNorm eps) are not compared; the zoo
        emits blocks from one generator, so they cannot differ there."""
        if self._scan_cache is not None:
            return self._scan_cache
        pgroups = []                       # (prefix, lo, hi)
        prefix, start = None, 0
        for li, (lp, _, _, _) in enumerate(self.layers):
            p = lp.name.split("/")[0] if "/" in lp.name else None
            if p != prefix:
                if prefix is not None:
                    pgroups.append((prefix, start, li))
                prefix, start = p, li
        if prefix is not None:
            pgroups.append((prefix, start, len(self.layers)))
        nl = len(self.layers)

        def group_info(gi):
            """(signature, entry, boundary) or None if ineligible."""
            pfx, lo, hi = pgroups[gi]
            produced, sig, externals = set(), [], set()
            strip = len(pfx) + 1
            for li in range(lo, hi):
                lp, impl, bottoms, tops = self.layers[li]
                if getattr(impl, "is_feed", False) or impl.has_state \
                        or impl.needs_rng \
                        or any(self.loss_weights[lp.name]):
                    return None
                if any(owner != lp.name
                       for owner, _ in self.param_refs[lp.name]):
                    return None
                bsig = []
                for b in bottoms:
                    if b in produced:
                        bsig.append(b[strip:] if b.startswith(pfx + "/")
                                    else b)
                    else:
                        externals.add(b)
                        bsig.append("\x00ENTRY")
                pshapes = tuple(
                    (self.param_meta[k][0],)
                    for k in self.param_refs[lp.name])
                sig.append((lp.type, lp.name[strip:], tuple(bsig),
                            tuple(t[strip:] if t.startswith(pfx + "/")
                                  else "\x00T:" + t for t in tops),
                            tuple(tuple(self.blob_shapes[t]) for t in tops),
                            pshapes))
                produced.update(tops)
            if len(externals) != 1:
                return None
            out = {t for li in range(lo, hi) for t in self.layers[li][3]
                   if t in self.output_blobs
                   or any(t in self.layers[lj][2] for lj in range(hi, nl))}
            if len(out) != 1:
                return None
            return tuple(sig), next(iter(externals)), next(iter(out))

        infos = [group_info(gi) for gi in range(len(pgroups))]

        def chains(a, b):
            """Group b continues group a: same structure, b's input is
            a's boundary, and that blob is read by b ALONE."""
            ia, ib = infos[a], infos[b]
            if ia is None or ib is None or ia[0] != ib[0]:
                return False
            if pgroups[a][2] != pgroups[b][1]:     # must be adjacent
                return False
            if ib[1] != ia[2] or ia[2] in self.output_blobs:
                return False
            bhi = pgroups[b][2]
            return not any(ia[2] in self.layers[lj][2]
                           for lj in range(bhi, nl))

        runs, gi = [], 0
        while gi < len(pgroups):
            gj = gi
            while gj + 1 < len(pgroups) and chains(gj, gj + 1):
                gj += 1
            if gj > gi:
                lo, hi = pgroups[gi][1], pgroups[gj][2]
                runs.append({"lo": lo, "hi": hi,
                             "glen": pgroups[gi][2] - pgroups[gi][1],
                             "n": gj - gi + 1,
                             "entry": infos[gi][1],
                             "body_out": infos[gi][2],
                             "out": infos[gj][2]})
            gi = gj + 1
        self._scan_cache = runs
        return runs

    def _scan_enabled(self):
        """SPARKNET_SCAN / self.scan policy: off — unrolled (every blob
        materialized, the extract_features-friendly default off-TPU);
        auto — scan on TPU only (XLA:CPU pessimizes loop bodies, the
        LocalSGD unroll precedent); on — scan everywhere (tests)."""
        import os
        mode = self.scan if self.scan is not None \
            else os.environ.get("SPARKNET_SCAN", "auto").lower()
        if mode == "on":
            return True
        if mode == "auto":
            return jax.default_backend() == "tpu"
        return False

    def _apply_scan(self, run, params, blobs, train, pol):
        """Execute one scan run: stack each group's params on a leading
        scan axis and run group 0's traced body once under lax.scan.
        Group-internal blobs are never materialized (absent, never
        stale); only the final boundary blob lands in ``blobs``. The
        remat policy composes by checkpointing the body — one block of
        activations live at a time in the backward."""
        from . import fission
        lo, glen, n = run["lo"], run["glen"], run["n"]
        g0 = self.layers[lo:lo + glen]
        stacked = []
        for j in range(glen):
            names = [self.layers[lo + g * glen + j][0].name
                     for g in range(n)]
            stacked.append([jnp.stack([params[nm][i] for nm in names])
                            for i in range(len(params.get(names[0], [])))])
        entry, body_out = run["entry"], run["body_out"]

        def body(x, ps):
            sblobs = {entry: x}
            for j, (lp, impl, bottoms, tops) in enumerate(g0):
                tvals = impl.apply(ps[j], [sblobs[b] for b in bottoms],
                                   train, None)
                for t, v in zip(tops, tvals):
                    sblobs[t] = v
            return sblobs[body_out], None

        if pol != "none":
            body = _checkpointed(body, pol)
        x0 = fission.materialize(blobs[entry])
        xN, _ = jax.lax.scan(body, x0, stacked)
        blobs[run["out"]] = xN

    def _segment_externals(self, lo, hi):
        """Blob names a [lo, hi) segment must surface: consumed by later
        layers, carrying loss weight, or net outputs."""
        produced = set()
        for li in range(lo, hi):
            produced.update(self.layers[li][3])
        needed = set()
        for li in range(hi, len(self.layers)):
            needed.update(self.layers[li][2])
        for li in range(lo, hi):
            lp = self.layers[li][0]
            for t, w in zip(self.layers[li][3], self.loss_weights[lp.name]):
                if w:
                    needed.add(t)
        needed.update(self.output_blobs)
        return sorted(produced & needed)

    def apply(self, params, state, batch, train=None, rng=None):
        """Run the forward pass. Pure; jit/grad-safe.

        Three trace-time policies compose here (each read once per
        trace, so a long-lived jit never sees them change — toggles go
        through Solver.set_remat/set_scan, which rebuild the jit):

        * remat (--remat / SPARKNET_REMAT: none|dots|full) — with
          train=True, runs of layers sharing a "prefix/" name (the
          zoo's per-block convention) execute under jax.checkpoint with
          the named policy: the backward recomputes their internals
          instead of saving every intermediate activation. Segment-
          INTERNAL blobs are then absent from the returned dict (only
          segment boundaries, loss tops and net outputs survive).
        * scan (SPARKNET_SCAN: auto|on|off) — structurally identical
          block chains (_scan_runs) execute as one lax.scan over
          stacked params: one traced body instead of depth copies.
          Block-internal blobs are absent; remat checkpoints the body.
        * epilogue (SPARKNET_EPILOGUE: auto|on|off) — conv bias+ReLU
          (+LRN) tails run as one fused pallas pass (_active_epilogue).

        Keep all three off for extract_features-style blob inspection."""
        if train is None:
            train = (self.phase == TRAIN)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        from . import fission
        fiss = fission.enabled()
        pol = (self.remat if self.remat is not None else _env_remat()) \
            if train else "none"
        if pol not in REMAT_POLICIES:
            raise ValueError(f"remat={pol!r}: want none|dots|full")
        groups = self._remat_groups() if pol != "none" else {}
        scans = {r["lo"]: r for r in self._scan_runs()} \
            if self._scan_enabled() else {}
        ep = self._active_epilogue()
        blobs = {}
        for n in self.net_inputs:
            blobs[n] = jnp.asarray(batch[n])
        new_state = dict(state)
        li = 0
        while li < len(self.layers):
            run = scans.get(li)
            if run is not None:
                self._apply_scan(run, params, blobs, train, pol)
                li = run["hi"]
                continue
            hi = groups.get(li)
            if hi is None:
                # a fusion site outside any remat segment dispatches its
                # whole conv/ReLU(/LRN) window in one range so the fused
                # branch engages; a window straddling a segment or scan
                # run falls back to unfused (correct either way)
                fuse = ep.get(li) if ep else None
                if fuse is not None:
                    end = max(x for x in fuse if x is not None) + 1
                    if all(j not in groups and j not in scans
                           for j in range(li + 1, end)):
                        self._apply_range(params, state, new_state, blobs,
                                          li, end, batch, train, rng,
                                          fiss, ep=ep)
                        li = end
                        continue
                self._apply_range(params, state, new_state, blobs,
                                  li, li + 1, batch, train, rng, fiss,
                                  ep=ep)
                li += 1
                continue
            # remat segment [li, hi): close over statics, checkpoint the
            # array-valued computation
            lo = li
            in_names = sorted({b for j in range(lo, hi)
                               for b in self.layers[j][2] if b in blobs})
            out_names = self._segment_externals(lo, hi)
            seg_states = sorted({self.layers[j][0].name
                                 for j in range(lo, hi)
                                 if self.layers[j][1].has_state})

            def seg_fn(params, state, in_vals, rng, lo=lo, hi=hi,
                       in_names=in_names, out_names=out_names,
                       seg_states=seg_states):
                sblobs = {n: fission.materialize(v)
                          for n, v in zip(in_names, in_vals)}
                sstate = dict(state)
                self._apply_range(params, state, sstate, sblobs,
                                  lo, hi, batch, train, rng, fiss, ep=ep)
                return ([fission.materialize(sblobs[n])
                         for n in out_names],
                        [sstate[n] for n in seg_states])

            out_vals, out_states = _checkpointed(seg_fn, pol)(
                params, state,
                [fission.materialize(blobs[n]) for n in in_names], rng)
            # a blob produced before the segment and overwritten in-place
            # inside it (top==bottom across the boundary) must not survive
            # with its stale pre-segment value — internal blobs are ABSENT,
            # never wrong
            produced = {t for j in range(lo, hi) for t in self.layers[j][3]}
            for n in produced.difference(out_names):
                blobs.pop(n, None)
            for n, v in zip(out_names, out_vals):
                blobs[n] = v
            for n, st in zip(seg_states, out_states):
                new_state[n] = st
            li = hi
        # callers see arrays only; unconsumed materializations are DCE'd
        return {k: fission.materialize(v) for k, v in blobs.items()}, \
            new_state

    def total_loss(self, blobs):
        """Weighted sum of loss tops (reference net.cpp ForwardFromTo loss
        accumulation via loss_weight)."""
        total = jnp.zeros((), jnp.float32)
        for lp, impl, bottoms, tops in self.layers:
            for t, w in zip(tops, self.loss_weights[lp.name]):
                if w:
                    total = total + w * jnp.sum(blobs[t]).astype(jnp.float32)
        return total

    def loss_fn(self, params, state, batch, rng=None):
        blobs, new_state = self.apply(params, state, batch, rng=rng)
        return self.total_loss(blobs), (blobs, new_state)

    # -- weight io ---------------------------------------------------------
    def params_to_netproto(self, params, state=None):
        """Emit a NetParameter with blobs filled — .caffemodel-compatible
        (reference net.cpp ToProto :911)."""
        out = Message("NetParameter", name=self.name or "net")
        for lp, impl, bottoms, tops in self.layers:
            olp = lp.copy()
            olp.clear("blobs")
            merged = []
            if lp.name in params:
                merged += list(params[lp.name])
            if state and lp.name in state:
                merged += list(state[lp.name])
            for arr in merged:
                olp.blobs.append(array_to_blob(np.asarray(arr)))
            out.layer.append(olp)
        return out

    def load_netproto(self, net_proto, params, state=None, strict=False):
        """Copy weights from a NetParameter by layer name (reference
        net.cpp CopyTrainedLayersFrom :805): shapes must match; layers
        absent from either side are skipped unless strict."""
        from .upgrade import upgrade_net
        net_proto = upgrade_net(net_proto)
        by_name = {l.name: l for l in net_proto.layer}
        params = {k: list(v) for k, v in params.items()}
        state = {k: list(v) for k, v in (state or {}).items()}
        for lp, impl, bottoms, tops in self.layers:
            src = by_name.get(lp.name)
            if src is None or not src.blobs:
                if strict and lp.name in params:
                    raise ValueError(f"no weights for layer {lp.name!r}")
                continue
            tgt = list(params.get(lp.name, []))
            n_p = len(tgt)
            sblobs = list(src.blobs)
            for i, bp in enumerate(sblobs):
                arr = blob_to_array(bp)
                if i < n_p:
                    if arr.size != tgt[i].size:
                        raise ValueError(
                            f"layer {lp.name!r} blob {i}: size mismatch "
                            f"{arr.shape} vs {tgt[i].shape}")
                    tgt[i] = jnp.asarray(arr.reshape(tgt[i].shape),
                                         self.dtype)
                elif lp.name in state and i - n_p < len(state[lp.name]):
                    j = i - n_p
                    state[lp.name][j] = jnp.asarray(
                        arr.reshape(state[lp.name][j].shape), self.dtype)
            if tgt:
                params[lp.name] = tgt
        return params, state


def blob_to_array(bp):
    if bp.has("shape"):
        shape = [int(d) for d in bp.shape.dim]
    else:
        shape = [d for d in (bp.num, bp.channels, bp.height, bp.width)]
        # legacy 4D: strip leading 1s only if count matches without them
    data = bp.double_data if len(bp.double_data) else bp.data
    # no intermediate list(): the wire codec hands packed floats back as a
    # numpy array, and RepeatedField is already list-like — a 230MB
    # CaffeNet import must not pay a per-element Python copy here
    arr = np.asarray(data, np.float32)
    if shape and int(np.prod(shape)) == arr.size:
        arr = arr.reshape(shape)
    return arr


def array_to_blob(arr):
    bp = Message("BlobProto")
    bp.ensure("shape").dim.extend(int(d) for d in arr.shape)
    bp.data.extend_np(np.asarray(arr, np.float32).ravel())
    return bp
