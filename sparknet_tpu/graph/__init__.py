"""Graph compiler: NetParameter -> pure init/apply (replaces caffe::Net)."""

from .compiler import CompiledNet, filter_net, upgrade_v1, TRAIN, TEST
from .registry import register, get, Layer

__all__ = ["CompiledNet", "filter_net", "upgrade_v1", "TRAIN", "TEST",
           "register", "get", "Layer"]
