"""Graph compiler: NetParameter -> pure init/apply (replaces caffe::Net)."""

from .compiler import CompiledNet, filter_net, upgrade_v1, TRAIN, TEST
from .upgrade import upgrade_net, upgrade_v0, needs_v0_upgrade
from .registry import register, get, Layer

__all__ = ["CompiledNet", "filter_net", "upgrade_v1", "upgrade_net",
           "upgrade_v0", "needs_v0_upgrade", "TRAIN", "TEST",
           "register", "get", "Layer"]
