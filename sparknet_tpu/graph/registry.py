"""Layer registry: proto ``type`` string -> layer implementation class.

The TPU-native analog of Caffe's string-keyed layer factory
(reference layer_factory.cpp REGISTER_LAYER_CLASS, used by e.g.
java_data_layer.cpp:47). Here a layer implementation is a small Python class
whose ``apply`` builds jnp/lax ops — XLA supplies the kernels, so there is no
engine selection (the CAFFE/CUDNN split collapses).
"""

_REGISTRY = {}

# V1 (legacy) layer-type enum name -> V2 type string, for upgrading old nets
# (reference util/upgrade_proto.cpp UpgradeV1LayerType).
V1_TYPE_MAP = {
    "ABSVAL": "AbsVal", "ACCURACY": "Accuracy", "ARGMAX": "ArgMax",
    "BNLL": "BNLL", "CONCAT": "Concat", "CONTRASTIVE_LOSS": "ContrastiveLoss",
    "CONVOLUTION": "Convolution", "DATA": "Data",
    "DECONVOLUTION": "Deconvolution", "DROPOUT": "Dropout",
    "DUMMY_DATA": "DummyData", "EUCLIDEAN_LOSS": "EuclideanLoss",
    "ELTWISE": "Eltwise", "EXP": "Exp", "FLATTEN": "Flatten",
    "HDF5_DATA": "HDF5Data", "HDF5_OUTPUT": "HDF5Output",
    "HINGE_LOSS": "HingeLoss", "IM2COL": "Im2col", "IMAGE_DATA": "ImageData",
    "INFOGAIN_LOSS": "InfogainLoss", "INNER_PRODUCT": "InnerProduct",
    "LRN": "LRN", "MEMORY_DATA": "MemoryData",
    "MULTINOMIAL_LOGISTIC_LOSS": "MultinomialLogisticLoss", "MVN": "MVN",
    "POOLING": "Pooling", "POWER": "Power", "RELU": "ReLU",
    "SIGMOID": "Sigmoid", "SIGMOID_CROSS_ENTROPY_LOSS": "SigmoidCrossEntropyLoss",
    "SILENCE": "Silence", "SOFTMAX": "Softmax", "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "SPLIT": "Split", "SLICE": "Slice", "TANH": "TanH",
    "WINDOW_DATA": "WindowData", "THRESHOLD": "Threshold",
}


def register(cls):
    """Class decorator: register under ``cls.type_name`` (str or tuple)."""
    names = cls.type_name
    if isinstance(names, str):
        names = (names,)
    for n in names:
        if n in _REGISTRY:
            raise ValueError(f"duplicate layer type {n}")
        _REGISTRY[n] = cls
    return cls


def get(type_name):
    try:
        return _REGISTRY[type_name]
    except KeyError:
        raise KeyError(
            f"unknown layer type {type_name!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


def known(type_name):
    return type_name in _REGISTRY


class Layer:
    """Base layer implementation.

    One instance per layer in a compiled net, created at build time with the
    layer's proto (``lp``, a LayerParameter Message) and the inferred bottom
    shapes. ``apply`` is pure and traced under jit.

      param_shapes() -> [(shape, filler Message|None, lr_mult, decay_mult)]
      state_shapes() -> [(shape, init_value)]       # non-learnable (e.g. BN)
      apply(params, bottoms, train, rng) -> [tops]  # stateless layers
      apply(params, bottoms, train, rng, state) -> ([tops], new_state)

    ``loss_like`` marks layers whose top[0] joins the objective with default
    loss_weight 1 (Caffe: any *Loss layer).
    """

    type_name = None
    loss_like = False
    has_state = False
    needs_rng = False

    def __init__(self, lp, bottom_shapes, phase):
        self.lp = lp
        self.bottom_shapes = [tuple(s) for s in bottom_shapes]
        self.phase = phase  # 0 TRAIN, 1 TEST

    def param_shapes(self):
        return []

    def state_shapes(self):
        return []

    def out_shapes(self):
        raise NotImplementedError

    def apply(self, params, bottoms, train, rng):
        raise NotImplementedError

    # -- helpers -----------------------------------------------------------
    def canonical_axis(self, axis, ndim=None):
        ndim = ndim if ndim is not None else len(self.bottom_shapes[0])
        return axis + ndim if axis < 0 else axis
