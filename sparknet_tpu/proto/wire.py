"""Binary protobuf (wire-format) codec.

Reads/writes ``.caffemodel`` / ``.binaryproto`` / ``.solverstate`` files
(proto2 wire format) against the schema in ``schema.py`` — the checkpoint
interchange the reference exposes via ``load_weights_from_file`` /
``restore_solver_from_file`` (reference ccaffe.h:61-62, solver.cpp:447-521).

Unknown fields are skipped on read (forward compatibility), mirroring
protobuf semantics. Packed repeated floats (weight data) use numpy bulk
conversion so multi-hundred-MB models load fast.
"""

import struct

import numpy as np

from . import schema
from .message import Message

_WT_VARINT, _WT_64BIT, _WT_LEN, _WT_32BIT = 0, 1, 2, 5

_SCALAR_WIRETYPE = {
    "float": _WT_32BIT, "double": _WT_64BIT, "bool": _WT_VARINT,
    "int32": _WT_VARINT, "int64": _WT_VARINT, "uint32": _WT_VARINT,
    "uint64": _WT_VARINT, "string": _WT_LEN, "bytes": _WT_LEN,
}


def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _write_varint(out, value):
    if value < 0:
        value &= (1 << 64) - 1  # proto2 negative int32/64 -> 10-byte varint
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _skip(buf, pos, wt):
    if wt == _WT_VARINT:
        _, pos = _read_varint(buf, pos)
    elif wt == _WT_64BIT:
        pos += 8
    elif wt == _WT_LEN:
        n, pos = _read_varint(buf, pos)
        pos += n
    elif wt == _WT_32BIT:
        pos += 4
    else:
        raise ValueError(f"bad wire type {wt}")
    return pos


def _signed32(v):
    v &= (1 << 64) - 1
    v &= (1 << 32) - 1
    return v - (1 << 32) if v >= (1 << 31) else v


def _signed64(v):
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= (1 << 63) else v


def decode(buf, type_name):
    return _decode(memoryview(bytes(buf)), 0, len(buf), type_name)


def _decode(buf, pos, end, type_name):
    msg = Message(type_name)
    fields_by_num = {spec[0]: (name, spec)
                     for name, spec in schema.MESSAGES[type_name].items()}
    while pos < end:
        key, pos = _read_varint(buf, pos)
        fnum, wt = key >> 3, key & 7
        entry = fields_by_num.get(fnum)
        if entry is None:
            pos = _skip(buf, pos, wt)
            continue
        name, (num, ftype, label, default) = entry
        if schema.is_message(ftype):
            if wt != _WT_LEN:
                pos = _skip(buf, pos, wt)
                continue
            n, pos = _read_varint(buf, pos)
            sub = _decode(buf, pos, pos + n, ftype)
            pos += n
            if label == "opt":
                if msg.has(name):
                    getattr(msg, name).merge_from(sub)
                else:
                    setattr(msg, name, sub)
            else:
                getattr(msg, name).append(sub)
            continue
        scalar_wt = _WT_VARINT if schema.is_enum(ftype) else _SCALAR_WIRETYPE[ftype]
        if (wt == _WT_LEN and scalar_wt != _WT_LEN):
            if label == "opt":
                # wire-type mismatch on a non-repeated scalar: unknown field
                pos = _skip(buf, pos, wt)
                continue
            # packed repeated scalars
            n, pos = _read_varint(buf, pos)
            stop = pos + n
            tgt = getattr(msg, name)
            if ftype == "float":
                # stays numpy until someone needs list semantics
                # (RepeatedField lazy chunks) — the .caffemodel fast path
                tgt.extend_np(np.frombuffer(buf[pos:stop], dtype="<f4"))
                pos = stop
            elif ftype == "double":
                tgt.extend_np(np.frombuffer(buf[pos:stop], dtype="<f8"))
                pos = stop
            else:
                while pos < stop:
                    v, pos = _read_varint(buf, pos)
                    tgt.append(self_val(ftype, v))
            continue
        if wt != scalar_wt:
            pos = _skip(buf, pos, wt)
            continue
        value, pos = _read_scalar(buf, pos, wt, ftype)
        if label == "opt":
            setattr(msg, name, value)
        else:
            getattr(msg, name).append(value)
    return msg


def self_val(ftype, v):
    if ftype == "bool":
        return bool(v)
    if ftype == "int32":
        return _signed32(v)
    if ftype == "int64":
        return _signed64(v)
    return v


def _read_scalar(buf, pos, wt, ftype):
    if ftype == "float":
        v = struct.unpack_from("<f", buf, pos)[0]
        return v, pos + 4
    if ftype == "double":
        v = struct.unpack_from("<d", buf, pos)[0]
        return v, pos + 8
    if ftype in ("string", "bytes"):
        n, pos = _read_varint(buf, pos)
        raw = bytes(buf[pos:pos + n])
        return (raw.decode("utf-8", "replace") if ftype == "string" else raw), pos + n
    v, pos = _read_varint(buf, pos)
    if schema.is_enum(ftype):
        return v, pos
    return self_val(ftype, v), pos


def encode(msg):
    out = bytearray()
    _encode(msg, out)
    return bytes(out)


def _encode(msg, out):
    for name in msg.set_fields():
        num, ftype, label, default = msg.spec(name)
        values = getattr(msg, name)
        if label == "opt":
            values = [values]
        if not values:
            continue
        if schema.is_message(ftype):
            for v in values:
                body = bytearray()
                _encode(v, body)
                _write_varint(out, (num << 3) | _WT_LEN)
                _write_varint(out, len(body))
                out.extend(body)
        elif label == "rep_packed" and ftype in ("float", "double", "int64",
                                                 "int32", "uint32", "uint64"):
            body = bytearray()
            if ftype == "float":
                body.extend(np.asarray(values, dtype="<f4").tobytes())
            elif ftype == "double":
                body.extend(np.asarray(values, dtype="<f8").tobytes())
            else:
                for v in values:
                    _write_varint(body, v)
            _write_varint(out, (num << 3) | _WT_LEN)
            _write_varint(out, len(body))
            out.extend(body)
        else:
            for v in values:
                _encode_scalar(out, num, ftype, v)


def _encode_scalar(out, num, ftype, v):
    if schema.is_enum(ftype):
        _write_varint(out, (num << 3) | _WT_VARINT)
        _write_varint(out, int(v))
    elif ftype == "float":
        _write_varint(out, (num << 3) | _WT_32BIT)
        out.extend(struct.pack("<f", v))
    elif ftype == "double":
        _write_varint(out, (num << 3) | _WT_64BIT)
        out.extend(struct.pack("<d", v))
    elif ftype in ("string", "bytes"):
        raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        _write_varint(out, (num << 3) | _WT_LEN)
        _write_varint(out, len(raw))
        out.extend(raw)
    elif ftype == "bool":
        _write_varint(out, (num << 3) | _WT_VARINT)
        _write_varint(out, 1 if v else 0)
    else:
        _write_varint(out, (num << 3) | _WT_VARINT)
        _write_varint(out, int(v))


def load(path, type_name):
    with open(path, "rb") as f:
        return decode(f.read(), type_name)


def dump(msg, path):
    with open(path, "wb") as f:
        f.write(encode(msg))
