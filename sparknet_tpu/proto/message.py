"""Schema-aware dynamic protobuf message objects.

``Message("LayerParameter")`` behaves like the generated protobuf class the
reference's JVM side uses (``caffe.Caffe.LayerParameter``): attribute access
returns set values or proto2 defaults, repeated fields are lists, and
``has_*`` distinguishes set-vs-default (which Caffe's pooling layer setup
relies on, reference pooling_layer.cpp:21-36).
"""

import copy as _copy
import struct as _struct

import numpy as _np

from . import schema


class RepeatedField(list):
    """List that coerces scalar appends to the field's proto type (so e.g.
    float fields are f32-quantized no matter how values arrive).

    Packed numeric data (blob weights — tens of millions of floats for a
    CaffeNet) additionally lives in lazy numpy ``_chunks``: the wire codec
    appends raw arrays via ``extend_np`` and reads them back zero-copy via
    ``__array__``, so a .caffemodel import/export never materializes one
    Python float object per weight. Any list-style access materializes the
    chunks first, preserving exact list semantics."""

    __slots__ = ("_owner", "_ftype", "_chunks")

    def __init__(self, owner, ftype, values=()):
        self._owner = owner
        self._ftype = ftype
        self._chunks = None
        if isinstance(values, RepeatedField) and values._ftype == ftype:
            # same-type copy (Message.copy fast path): elements are already
            # coerced; share the immutable numpy chunks
            super().__init__(list.__iter__(values))
            if values._chunks:
                self._chunks = list(values._chunks)
        else:
            super().__init__(owner._coerce(ftype, v) for v in values)

    # -- numpy fast paths --------------------------------------------------
    def extend_np(self, arr):
        """Bulk extend from a numpy array of already-exact values (wire
        decode / array_to_blob). Stored as a chunk; materialized lazily."""
        if arr.size == 0:
            return
        if self._chunks is None:
            self._chunks = []
        self._chunks.append(arr)

    def __array__(self, dtype=None, copy=None):
        if self._chunks and not list.__len__(self):
            arr = self._chunks[0] if len(self._chunks) == 1 \
                else _np.concatenate(self._chunks)
            return _np.asarray(arr, dtype) if dtype is not None \
                else _np.asarray(arr)
        self._materialize()
        return _np.asarray(list(self), dtype=dtype)

    def _materialize(self):
        if self._chunks:
            chunks, self._chunks = self._chunks, None
            arr = chunks[0] if len(chunks) == 1 else _np.concatenate(chunks)
            list.extend(self, arr.tolist())

    # -- list protocol (chunk-aware) ---------------------------------------
    def __len__(self):
        n = list.__len__(self)
        if self._chunks:
            n += sum(c.size for c in self._chunks)
        return n

    def __iter__(self):
        self._materialize()
        return list.__iter__(self)

    def __getitem__(self, i):
        self._materialize()
        return list.__getitem__(self, i)

    def __delitem__(self, i):
        self._materialize()
        list.__delitem__(self, i)

    def __contains__(self, v):
        self._materialize()
        return list.__contains__(self, v)

    def __eq__(self, other):
        self._materialize()
        if isinstance(other, RepeatedField):
            other._materialize()
        return list.__eq__(self, other)

    def __ne__(self, other):
        return not self.__eq__(other)

    __hash__ = None

    def __repr__(self):
        self._materialize()
        return list.__repr__(self)

    def append(self, v):
        self._materialize()
        super().append(self._owner._coerce(self._ftype, v))

    def extend(self, values):
        self._materialize()
        super().extend(self._owner._coerce(self._ftype, v) for v in values)

    def insert(self, i, v):
        self._materialize()
        super().insert(i, self._owner._coerce(self._ftype, v))

    def pop(self, *a):
        self._materialize()
        return super().pop(*a)

    def remove(self, v):
        self._materialize()
        super().remove(v)

    def index(self, *a):
        self._materialize()
        return super().index(*a)

    def count(self, v):
        self._materialize()
        return super().count(v)

    def sort(self, **kw):
        self._materialize()
        super().sort(**kw)

    def reverse(self):
        self._materialize()
        super().reverse()

    def clear(self):
        self._chunks = None
        super().clear()

    def __setitem__(self, i, v):
        self._materialize()
        if isinstance(i, slice):
            v = [self._owner._coerce(self._ftype, x) for x in v]
        else:
            v = self._owner._coerce(self._ftype, v)
        super().__setitem__(i, v)

    def extend_raw(self, values):
        """Bulk extend without per-element coercion (wire decode fast path —
        values are already exact)."""
        self._materialize()
        super().extend(values)


class Message:
    __slots__ = ("_type", "_fields", "_frozen")

    def __init__(self, type_name, **kwargs):
        if type_name not in schema.MESSAGES:
            raise ValueError(f"unknown message type {type_name!r}")
        object.__setattr__(self, "_type", type_name)
        object.__setattr__(self, "_fields", {})
        object.__setattr__(self, "_frozen", False)
        for k, v in kwargs.items():
            setattr(self, k, v)

    # -- introspection -----------------------------------------------------
    @property
    def type_name(self):
        return self._type

    def spec(self, name):
        try:
            return schema.MESSAGES[self._type][name]
        except KeyError:
            raise AttributeError(f"{self._type} has no field {name!r}") from None

    def field_names(self):
        return schema.MESSAGES[self._type].keys()

    def set_fields(self):
        """Names of explicitly-set fields, in set order."""
        return list(self._fields.keys())

    def has(self, name):
        self.spec(name)
        v = self._fields.get(name)
        if v is None:
            return False
        return True if not isinstance(v, list) else len(v) > 0

    # -- attribute protocol ------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name.startswith("has_"):
            fname = name[4:]
            return lambda: self.has(fname)
        num, ftype, label, default = self.spec(name)
        if name in self._fields:
            return self._fields[name]
        if label != "opt":
            if self._frozen:
                return ()          # iterable, but appends impossible
            lst = RepeatedField(self, ftype)
            self._fields[name] = lst  # cached so appends stick
            return lst
        if schema.is_message(ftype):
            # protobuf semantics: reading an unset sub-message yields the
            # default instance (uncached, so has() remains False). It is
            # FROZEN: mutating it would otherwise vanish silently — build a
            # Message(...) and assign it to the parent field instead.
            m = Message(ftype)
            object.__setattr__(m, "_frozen", True)
            return m
        if default is not None:
            return default
        return schema.zero_value(ftype)

    def __setattr__(self, name, value):
        if self._frozen:
            raise AttributeError(
                f"cannot set {name!r} on the default (unset) "
                f"{self._type}: assign parent.field = Message({self._type!r},"
                f" ...) first")
        num, ftype, label, default = self.spec(name)
        if label != "opt":
            value = RepeatedField(self, ftype, value)
        elif value is None:
            self._fields.pop(name, None)
            return
        else:
            value = self._coerce(ftype, value)
        self._fields[name] = value

    def _coerce(self, ftype, value):
        if schema.is_message(ftype):
            if isinstance(value, Message):
                if value.type_name != ftype:
                    raise TypeError(f"expected {ftype}, got {value.type_name}")
                return value
            if isinstance(value, dict):
                return Message(ftype, **value)
            raise TypeError(f"expected {ftype} message, got {type(value)}")
        if schema.is_enum(ftype):
            if isinstance(value, str):
                return schema.ENUMS[ftype][value]
            return int(value)
        if ftype == "float":
            # proto2 'float' is 32-bit on the wire; quantize at set time so
            # text-parsed and wire-parsed values agree exactly.
            return _struct.unpack("<f", _struct.pack("<f", float(value)))[0]
        if ftype == "double":
            return float(value)
        if ftype in schema.INT_TYPES:
            return int(value)
        if ftype == "bool":
            return bool(value)
        if ftype == "string":
            return str(value)
        if ftype == "bytes":
            return bytes(value)
        raise TypeError(f"unknown field type {ftype}")

    # -- mutation helpers --------------------------------------------------
    def add(self, _field, **kwargs):
        """Append and return a new sub-message on a repeated message field."""
        name = _field
        num, ftype, label, default = self.spec(name)
        if label == "opt" or not schema.is_message(ftype):
            raise ValueError(f"{name} is not a repeated message field")
        msg = Message(ftype, **kwargs)
        getattr(self, name).append(msg)
        return msg

    def ensure(self, name):
        """Return the sub-message field, creating it if unset (mutable_* analog)."""
        num, ftype, label, default = self.spec(name)
        if not schema.is_message(ftype) or label != "opt":
            raise ValueError(f"{name} is not an optional message field")
        if name not in self._fields:
            self._fields[name] = Message(ftype)
        return self._fields[name]

    def clear(self, name):
        self._fields.pop(name, None)

    def copy(self):
        return _copy.deepcopy(self)

    def __deepcopy__(self, memo):
        new = Message(self._type)
        for name in self.set_fields():
            num, ftype, label, default = self.spec(name)
            val = self._fields[name]
            if label != "opt":
                if schema.is_message(ftype):
                    new._fields[name] = RepeatedField(
                        new, ftype,
                        [_copy.deepcopy(v, memo) for v in val])
                else:
                    # scalar repeated: same-ftype fast path (no re-coerce,
                    # numpy chunks shared instead of materialized)
                    new._fields[name] = RepeatedField(new, ftype, val)
            elif isinstance(val, Message):
                new._fields[name] = _copy.deepcopy(val, memo)
            else:
                new._fields[name] = val
        return new

    def merge_from(self, other):
        """Proto2 MergeFrom: scalars overwrite, repeateds concatenate,
        sub-messages merge recursively."""
        if other.type_name != self._type:
            raise TypeError(f"cannot merge {other.type_name} into {self._type}")
        for name in other.set_fields():
            num, ftype, label, default = self.spec(name)
            val = other._fields[name]
            if label != "opt":
                getattr(self, name).extend(
                    _copy.deepcopy(v) if isinstance(v, Message) else v
                    for v in val)
            elif schema.is_message(ftype) and name in self._fields:
                self._fields[name].merge_from(val)
            elif isinstance(val, Message):
                self._fields[name] = _copy.deepcopy(val)
            else:
                self._fields[name] = val

    # -- misc --------------------------------------------------------------
    def __eq__(self, other):
        if not isinstance(other, Message) or other.type_name != self._type:
            return NotImplemented
        names = set(self.set_fields()) | set(other.set_fields())
        for n in names:
            a, b = getattr(self, n), getattr(other, n)
            if a != b:
                return False
        return True

    def __repr__(self):
        from .text_format import dumps
        return f"<{self._type}\n{dumps(self)}>"

    def enum_name(self, field):
        """Symbolic name of an enum field's current value."""
        num, ftype, label, default = self.spec(field)
        val = getattr(self, field)
        for k, v in schema.ENUMS[ftype].items():
            if v == val:
                return k
        return str(val)
