"""Prototxt / binaryproto codecs for the Caffe protobuf dialect.

Replaces the reference's protobuf-java + native text-parse round trip
(reference ProtoLoader.scala, ccaffe.cpp:213-242) with a pure-Python,
schema-driven implementation. Stock ``.prototxt`` and ``.caffemodel``
files from the reference load unchanged.
"""

from .message import Message
from . import schema, text_format, wire
from .text_format import load as load_prototxt, loads as parse_prototxt
from .text_format import dump as save_prototxt, dumps as format_prototxt
from .wire import load as load_binaryproto, dump as save_binaryproto

__all__ = [
    "Message", "schema", "text_format", "wire",
    "load_prototxt", "parse_prototxt", "save_prototxt", "format_prototxt",
    "load_binaryproto", "save_binaryproto",
]
