"""Protobuf text-format (prototxt) reader/writer.

Replaces the reference's C++ round-trip service (the JVM called into native
code just to parse prototxt: reference ProtoLoader.scala:9-29 / ccaffe.cpp:213-242).
Here it is a direct recursive-descent parser over the schema in
``schema.py`` — stock Caffe ``.prototxt`` files load unchanged.
"""

import re

import numpy as np

from . import schema
from .message import Message

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<comment>\#[^\n]*)
      | (?P<brace>[{}])
      | (?P<colon>:)
      | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
      | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
      | (?P<number>[-+]?(?:\d+\.\d*(?:[eE][-+]?\d+)?|\.\d+(?:[eE][-+]?\d+)?
                        |\d+[eE][-+]?\d+|0[xX][0-9a-fA-F]+|\d+))
    )""",
    re.VERBOSE,
)

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "'": "'", "\\": "\\",
            "0": "\0", "a": "\a", "b": "\b", "f": "\f", "v": "\v"}


def _tokenize(text):
    pos, n = 0, len(text)
    while pos < n:
        m = _TOKEN.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                return
            raise ValueError(f"prototxt parse error at offset {pos}: "
                             f"{text[pos:pos+40]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "comment" or kind is None:
            continue
        yield kind, m.group(kind)


def _unquote(tok):
    body = tok[1:-1]
    out, i = [], 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            if nxt in _ESCAPES:
                out.append(_ESCAPES[nxt])
                i += 2
                continue
            if nxt.isdigit():  # octal escape
                j = i + 1
                while j < len(body) and j < i + 4 and body[j].isdigit():
                    j += 1
                out.append(chr(int(body[i + 1:j], 8)))
                i = j
                continue
        out.append(c)
        i += 1
    return "".join(out)


class _Parser:
    def __init__(self, text):
        self.toks = list(_tokenize(text))
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def next(self):
        tok = self.peek()
        self.i += 1
        return tok

    def expect(self, kind, value=None):
        k, v = self.next()
        if k != kind or (value is not None and v != value):
            raise ValueError(f"expected {value or kind}, got {v!r}")
        return v

    def parse_message(self, msg, top_level=False):
        while True:
            k, v = self.peek()
            if k is None:
                if not top_level:
                    raise ValueError("unexpected EOF inside message")
                return msg
            if k == "brace" and v == "}":
                if top_level:
                    raise ValueError("unbalanced '}'")
                self.next()
                return msg
            if k != "ident":
                raise ValueError(f"expected field name, got {v!r}")
            self.next()
            self._parse_field(msg, v)

    def _parse_field(self, msg, name):
        num, ftype, label, default = msg.spec(name)
        k, v = self.peek()
        if schema.is_message(ftype):
            if k == "colon":  # optional colon before submessage
                self.next()
                k, v = self.peek()
            self.expect("brace", "{")
            sub = Message(ftype)
            self.parse_message(sub)
            if label == "opt":
                setattr(msg, name, sub)
            else:
                getattr(msg, name).append(sub)
            return
        self.expect("colon")
        value = self._parse_scalar(ftype)
        if label == "opt":
            setattr(msg, name, value)
        else:
            getattr(msg, name).append(msg._coerce(ftype, value))

    def _parse_scalar(self, ftype):
        k, v = self.next()
        if ftype in ("string", "bytes"):
            if k != "string":
                raise ValueError(f"expected quoted string, got {v!r}")
            s = _unquote(v)
            return s.encode("utf-8") if ftype == "bytes" else s
        if ftype == "bool":
            if k == "ident":
                if v in ("true", "True"):
                    return True
                if v in ("false", "False"):
                    return False
                raise ValueError(f"bad bool {v!r}")
            return bool(int(v, 0))
        if schema.is_enum(ftype):
            if k == "ident":
                try:
                    return schema.ENUMS[ftype][v]
                except KeyError:
                    raise ValueError(f"bad enum value {v!r} for {ftype}") from None
            return int(v, 0)
        if ftype in ("float", "double"):
            if k == "ident" and v in ("inf", "nan"):
                return float(v)
            return float(v)
        if ftype in schema.INT_TYPES:
            return int(v, 0)
        raise ValueError(f"unhandled scalar type {ftype}")


def loads(text, type_name):
    """Parse prototxt ``text`` as a message of ``type_name``."""
    return _Parser(text).parse_message(Message(type_name), top_level=True)


def load(path, type_name):
    with open(path, "r") as f:
        return loads(f.read(), type_name)


def _fmt_scalar(ftype, value):
    if ftype in ("string", "bytes"):
        if isinstance(value, bytes):
            value = value.decode("utf-8", "backslashreplace")
        esc = value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        return f'"{esc}"'
    if ftype == "bool":
        return "true" if value else "false"
    if schema.is_enum(ftype):
        for k, v in schema.ENUMS[ftype].items():
            if v == value:
                return k
        return str(value)
    if ftype in ("float", "double"):
        if value == int(value) and abs(value) < 1e15:
            return str(int(value))
        if ftype == "float":
            for p in range(1, 10):  # shortest decimal that round-trips as f32
                s = f"{value:.{p}g}"
                if np.float32(s) == np.float32(value):
                    return s
        return repr(value)
    return str(value)


def dumps(msg, indent=0):
    """Render a Message as prototxt (fields in set order, Caffe style)."""
    pad = "  " * indent
    lines = []
    for name in msg.set_fields():
        num, ftype, label, default = msg.spec(name)
        values = getattr(msg, name)
        if label == "opt":
            values = [values]
        for v in values:
            if schema.is_message(ftype):
                lines.append(f"{pad}{name} {{")
                lines.append(dumps(v, indent + 1).rstrip("\n"))
                lines.append(f"{pad}}}")
            else:
                lines.append(f"{pad}{name}: {_fmt_scalar(ftype, v)}")
    return "\n".join(x for x in lines if x != "") + ("\n" if lines else "")


def dump(msg, path):
    with open(path, "w") as f:
        f.write(dumps(msg))
