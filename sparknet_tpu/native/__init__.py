"""Native pipeline library: build-on-first-use + ctypes bindings.

The reference shipped its native engine as a cmake-built libccaffe.so loaded
via JNA (CaffeLibrary.java:9); here the native surface is the host data
pipeline only (XLA owns device kernels), compiled lazily with g++ and loaded
via ctypes. Everything has a numpy fallback — ``available()`` says which
path is active.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "pipeline.cpp")
_SO = os.path.join(_DIR, "libsparknet_native.so")
_ABI = 3

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    # Compile to a per-pid temp file and rename atomically: concurrent
    # builders (pytest workers, multi-host on a shared FS) must never dlopen
    # a partially written .so, and rename() makes the publish atomic.
    tmp = f"{_SO}.build.{os.getpid()}"
    base = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17"]
    try:
        try:
            subprocess.run(base + ["-fopenmp", _SRC, "-o", tmp], check=True,
                           capture_output=True)
        except subprocess.CalledProcessError:   # no libgomp: single-threaded
            subprocess.run(base + [_SRC, "-o", tmp], check=True,
                           capture_output=True)
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or \
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                _build()
            lib = ctypes.CDLL(_SO)
            if lib.native_abi_version() != _ABI:
                _build()
                lib = ctypes.CDLL(_SO)
            _bind(lib)
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def _bind(lib):
    i64, i32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.transform_batch.argtypes = [
        u8p, i64, i64, i64, i64, i64, i32p, i32p, u8p, f32p,
        ctypes.c_int, ctypes.c_float, f32p]
    lib.transform_batch.restype = None
    lib.decode_cifar_records.argtypes = [u8p, i64, i64, u8p, i32p]
    lib.decode_cifar_records.restype = None
    lib.accumulate_sum.argtypes = [u8p, i64, i64, i64p]
    lib.accumulate_sum.restype = None
    lib.crc32c_update.argtypes = [u8p, i64, ctypes.c_uint32]
    lib.crc32c_update.restype = ctypes.c_uint32
    lib.snappy_uncompress.argtypes = [u8p, i64, u8p, i64]
    lib.snappy_uncompress.restype = i64


def available():
    return _load() is not None


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def transform_batch(images, crop, ys=None, xs=None, mirror=None, mean=None,
                    scale=1.0, full_mean=False):
    """uint8 (N,C,H,W) -> float32 (N,C,crop,crop); native when possible.

    mean: None | (C,) per-channel | (C,crop,crop) cropped mean image
    (subtracted after the mirror) | with full_mean=True a (C,H,W)
    source-size mean image subtracted at the crop-window source index
    before the mirror — the exact reference mean_file semantics
    (data_transformer.cpp:42-51).
    ys/xs: per-image int32 crop offsets (None -> 0: top-left/no crop).
    mirror: per-image uint8 flags (None -> no flips).
    """
    lib = _load()
    images = np.ascontiguousarray(images, np.uint8)
    n, c, h, w = images.shape
    if mean is not None:
        mean = np.ascontiguousarray(mean, np.float32)
        if mean.ndim == 1:
            mean_kind = 1
        elif full_mean:
            mean_kind = 3
            if mean.shape != (c, h, w):
                raise ValueError(
                    f"full mean shape {mean.shape} != {(c, h, w)}")
        else:
            mean_kind = 2
            if mean.shape != (c, crop, crop):
                raise ValueError(
                    f"mean shape {mean.shape} != {(c, crop, crop)}")
    else:
        mean_kind = 0
    if lib is not None:
        out = np.empty((n, c, crop, crop), np.float32)
        ys_a = np.ascontiguousarray(ys, np.int32) if ys is not None else None
        xs_a = np.ascontiguousarray(xs, np.int32) if xs is not None else None
        mir = np.ascontiguousarray(mirror, np.uint8) \
            if mirror is not None else None
        lib.transform_batch(
            _ptr(images, ctypes.c_uint8), n, c, h, w, crop,
            _ptr(ys_a, ctypes.c_int32) if ys_a is not None else None,
            _ptr(xs_a, ctypes.c_int32) if xs_a is not None else None,
            _ptr(mir, ctypes.c_uint8) if mir is not None else None,
            _ptr(mean, ctypes.c_float) if mean is not None else None,
            mean_kind, ctypes.c_float(scale), _ptr(out, ctypes.c_float))
        return out
    # numpy fallback
    out = np.empty((n, c, crop, crop), np.float32)
    for i in range(n):
        y0 = int(ys[i]) if ys is not None else 0
        x0 = int(xs[i]) if xs is not None else 0
        win = images[i, :, y0:y0 + crop, x0:x0 + crop].astype(np.float32)
        if mean_kind == 3:  # source-indexed subtract, then mirror
            win = win - mean[:, y0:y0 + crop, x0:x0 + crop]
        if mirror is not None and mirror[i]:
            win = win[:, :, ::-1]
        out[i] = win
    if mean_kind == 1:
        out -= mean.reshape(1, c, 1, 1)
    elif mean_kind == 2:
        out -= mean
    if scale != 1.0:
        out *= scale
    return out


def decode_cifar_records(raw, record):
    """Packed records -> (images uint8 (N, record-1), labels int32)."""
    raw = np.ascontiguousarray(raw, np.uint8)
    n = raw.size // record
    lib = _load()
    if lib is not None:
        images = np.empty((n, record - 1), np.uint8)
        labels = np.empty(n, np.int32)
        lib.decode_cifar_records(_ptr(raw, ctypes.c_uint8), n, record,
                                 _ptr(images, ctypes.c_uint8),
                                 _ptr(labels, ctypes.c_int32))
        return images, labels
    recs = raw[:n * record].reshape(n, record)
    return np.ascontiguousarray(recs[:, 1:]), recs[:, 0].astype(np.int32)


def accumulate_sum(images, acc):
    """Add sum-over-batch of uint8 (N,...) into int64 acc (...)."""
    images = np.ascontiguousarray(images, np.uint8)
    lib = _load()
    if lib is not None and acc.flags.c_contiguous:
        n = images.shape[0]
        chw = images.size // max(n, 1)
        if n:
            lib.accumulate_sum(_ptr(images, ctypes.c_uint8), n, chw,
                               _ptr(acc, ctypes.c_int64))
        return acc
    acc += images.astype(np.int64).sum(axis=0)
    return acc


def crc32c(data, crc=0):
    """Native crc32c (Castagnoli) with the leveldb.py (data, crc)
    semantics, or None when the native lib is unavailable."""
    lib = _load()
    if lib is None:
        return None
    if not len(data):
        return crc & 0xffffffff      # xor-in/xor-out cancel on empty input
    buf = np.frombuffer(data, np.uint8)      # zero-copy for bytes-likes
    return int(lib.crc32c_update(_ptr(buf, ctypes.c_uint8), len(buf), crc))


def snappy_uncompress(data, declared_len):
    """Decode a raw-Snappy payload to bytes via the native decoder.
    Returns None when the lib is unavailable OR the decode fails — the
    caller's pure-Python decoder is both the fallback and the error
    path with the descriptive diagnostics."""
    lib = _load()
    if lib is None:
        return None
    # a corrupt preamble could claim terabytes: max snappy expansion is
    # ~64/3 bytes out per byte in (a 3-byte copy-2 element emitting 64),
    # so anything past 24x + slack cannot be a valid stream
    if declared_len < 0 or declared_len > len(data) * 24 + 64:
        return None
    src = np.frombuffer(data, np.uint8)      # zero-copy for bytes-likes
    out = np.empty(declared_len, np.uint8)
    got = lib.snappy_uncompress(
        _ptr(src, ctypes.c_uint8), len(src),
        _ptr(out, ctypes.c_uint8), declared_len)
    if got != declared_len:
        return None
    return out.tobytes()
