// Native host-side data pipeline kernels.
//
// The TPU-native analog of the reference's native data path: Caffe ran
// decode/crop/mirror/mean in C++ worker threads (data_transformer.cpp:42-51,
// base_data_layer.cpp prefetch InternalThreadEntry :70-101) because the
// JVM/Python side could never keep the accelerator fed. Same economics here:
// these loops release the GIL (plain C called via ctypes) so the Python
// prefetch threads in sparknet_tpu.data.prefetch overlap transform with the
// device step.
//
// Build: sparknet_tpu/native/__init__.py compiles this with g++ -O3 on first
// use; pure-numpy fallbacks exist for every entry point.

#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// (n,c,h,w) uint8 -> (n,c,crop,crop) float32: per-image crop offsets
// (ys/xs), optional horizontal mirror, mean subtraction, scale.
// mean: nullptr | per-channel (mean_kind=1, c floats) | CHW image at
// the CROPPED size (mean_kind=2, c*crop*crop floats, subtracted after the
// mirror) | CHW image at the SOURCE size (mean_kind=3, c*h*w floats,
// subtracted at the source crop-window index before the mirror — the exact
// mean_file semantics of the reference data_transformer.cpp:42-51, where
// top[mirrored_index] = (src[data_index] - mean[data_index]) * scale).
void transform_batch(const uint8_t* in, int64_t n, int64_t c, int64_t h,
                     int64_t w, int64_t crop, const int32_t* ys,
                     const int32_t* xs, const uint8_t* mirror,
                     const float* mean, int mean_kind, float scale,
                     float* out) {
  const int64_t in_img = c * h * w;
  const int64_t out_img = c * crop * crop;
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* src = in + i * in_img;
    float* dst = out + i * out_img;
    const int64_t y0 = ys ? ys[i] : 0;
    const int64_t x0 = xs ? xs[i] : 0;
    const bool flip = mirror && mirror[i];
    for (int64_t ch = 0; ch < c; ++ch) {
      const uint8_t* splane = src + ch * h * w;
      float* dplane = dst + ch * crop * crop;
      const float* mplane =
          mean_kind == 2 ? mean + ch * crop * crop : nullptr;
      const float* fplane =
          mean_kind == 3 ? mean + ch * h * w : nullptr;
      const float mchan = mean_kind == 1 ? mean[ch] : 0.0f;
      for (int64_t y = 0; y < crop; ++y) {
        const uint8_t* __restrict srow = splane + (y0 + y) * w + x0;
        float* __restrict drow = dplane + y * crop;
        // branch-free inner loops so gcc vectorizes the u8->f32 convert
        if (fplane) {  // full-size mean, source-indexed (pre-mirror)
          const float* __restrict mrow = fplane + (y0 + y) * w + x0;
          if (!flip) {
            for (int64_t x = 0; x < crop; ++x)
              drow[x] = ((float)srow[x] - mrow[x]) * scale;
          } else {
            for (int64_t x = 0; x < crop; ++x)
              drow[x] = ((float)srow[crop - 1 - x] - mrow[crop - 1 - x])
                        * scale;
          }
        } else if (!flip && mplane) {
          const float* __restrict mrow = mplane + y * crop;
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[x] - mrow[x]) * scale;
        } else if (!flip) {
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[x] - mchan) * scale;
        } else if (mplane) {
          const float* __restrict mrow = mplane + y * crop;
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[crop - 1 - x] - mrow[x]) * scale;
        } else {
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[crop - 1 - x] - mchan) * scale;
        }
      }
    }
  }
}

// CIFAR binary records (1 label byte + c*h*w image bytes each) ->
// planar images + labels (the CifarLoader.scala:66-86 inner loop).
void decode_cifar_records(const uint8_t* raw, int64_t n, int64_t record,
                          uint8_t* images, int32_t* labels) {
  const int64_t img = record - 1;
  for (int64_t i = 0; i < n; ++i) {
    labels[i] = raw[i * record];
    std::memcpy(images + i * img, raw + i * record + 1, img);
  }
}

// uint8 (n,c,h,w) accumulate-sum into int64 (c,h,w) — the hot loop of
// streaming mean-image computation (ComputeMean.scala:10-37).
void accumulate_sum(const uint8_t* in, int64_t n, int64_t chw,
                    int64_t* acc) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* img = in + i * chw;
    for (int64_t j = 0; j < chw; ++j) acc[j] += img[j];
  }
}

// crc32c (Castagnoli), same (data, crc) semantics as the Python
// reference in data/leveldb.py: init/final xor inside, so chained calls
// pass the previous RESULT as crc. These entry points run GIL-released
// from multiple prefetch threads, so the table uses a C++11 magic
// static (guaranteed race-free one-time init).
struct Crc32cTable {
    uint32_t tab[256];
    Crc32cTable() {
        for (uint32_t n = 0; n < 256; n++) {
            uint32_t c = n;
            for (int k = 0; k < 8; k++)
                c = (c >> 1) ^ ((c & 1) ? 0x82f63b78u : 0u);
            tab[n] = c;
        }
    }
};

static const uint32_t* crc32c_table() {
    static const Crc32cTable t;
    return t.tab;
}

uint32_t crc32c_update(const uint8_t* data, int64_t len, uint32_t crc) {
    const uint32_t* tab = crc32c_table();
    uint32_t c = crc ^ 0xffffffffu;
    for (int64_t i = 0; i < len; i++)
        c = tab[(c ^ data[i]) & 0xff] ^ (c >> 8);
    return c ^ 0xffffffffu;
}

// Snappy raw-format decode (the LevelDB block codec): varint32 length
// preamble then literal/copy elements. Returns the decoded length, or
// -1 on malformed/overrunning input (callers fall back to the Python
// decoder, which raises a descriptive error). `out` must hold the
// preamble-declared length; overlapping copies run byte-wise (RLE).
int64_t snappy_uncompress(const uint8_t* in, int64_t in_len,
                          uint8_t* out, int64_t out_cap) {
    int64_t p = 0, o = 0;
    // varint32 preamble
    uint32_t declared = 0;
    int shift = 0;
    while (true) {
        if (p >= in_len || shift > 28) return -1;
        uint8_t b = in[p++];
        declared |= (uint32_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    if ((int64_t)declared != out_cap) return -1;
    while (p < in_len) {
        uint8_t tag = in[p++];
        int kind = tag & 3;
        if (kind == 0) {                       // literal
            int64_t ln = tag >> 2;
            if (ln >= 60) {                    // length in 1-4 bytes
                int nb = (int)(ln - 59);
                if (p + nb > in_len) return -1;
                ln = 0;
                for (int i = 0; i < nb; i++)
                    ln |= (int64_t)in[p + i] << (8 * i);
                p += nb;
            }
            ln += 1;
            if (p + ln > in_len || o + ln > out_cap) return -1;
            std::memcpy(out + o, in + p, (size_t)ln);
            p += ln;
            o += ln;
            continue;
        }
        int64_t ln, off;
        if (kind == 1) {                       // copy, 1-byte offset
            if (p >= in_len) return -1;
            ln = ((tag >> 2) & 0x7) + 4;
            off = ((int64_t)(tag >> 5) << 8) | in[p];
            p += 1;
        } else if (kind == 2) {                // copy, 2-byte offset
            if (p + 2 > in_len) return -1;
            ln = (tag >> 2) + 1;
            off = (int64_t)in[p] | ((int64_t)in[p + 1] << 8);
            p += 2;
        } else {                               // copy, 4-byte offset
            if (p + 4 > in_len) return -1;
            ln = (tag >> 2) + 1;
            off = (int64_t)in[p] | ((int64_t)in[p + 1] << 8)
                | ((int64_t)in[p + 2] << 16) | ((int64_t)in[p + 3] << 24);
            p += 4;
        }
        if (off <= 0 || off > o || o + ln > out_cap) return -1;
        int64_t start = o - off;
        if (off >= ln) {
            std::memcpy(out + o, out + start, (size_t)ln);
        } else {
            for (int64_t i = 0; i < ln; i++) out[o + i] = out[start + i];
        }
        o += ln;
    }
    return o == out_cap ? o : -1;
}

int native_abi_version() { return 3; }

}  // extern "C"
