// Native host-side data pipeline kernels.
//
// The TPU-native analog of the reference's native data path: Caffe ran
// decode/crop/mirror/mean in C++ worker threads (data_transformer.cpp:42-51,
// base_data_layer.cpp prefetch InternalThreadEntry :70-101) because the
// JVM/Python side could never keep the accelerator fed. Same economics here:
// these loops release the GIL (plain C called via ctypes) so the Python
// prefetch threads in sparknet_tpu.data.prefetch overlap transform with the
// device step.
//
// Build: sparknet_tpu/native/__init__.py compiles this with g++ -O3 on first
// use; pure-numpy fallbacks exist for every entry point.

#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// (n,c,h,w) uint8 -> (n,c,crop,crop) float32: per-image crop offsets
// (ys/xs), optional horizontal mirror, mean subtraction, scale.
// mean: nullptr | per-channel (mean_kind=1, c floats) | CHW image at
// the CROPPED size (mean_kind=2, c*crop*crop floats, subtracted after the
// mirror) | CHW image at the SOURCE size (mean_kind=3, c*h*w floats,
// subtracted at the source crop-window index before the mirror — the exact
// mean_file semantics of the reference data_transformer.cpp:42-51, where
// top[mirrored_index] = (src[data_index] - mean[data_index]) * scale).
void transform_batch(const uint8_t* in, int64_t n, int64_t c, int64_t h,
                     int64_t w, int64_t crop, const int32_t* ys,
                     const int32_t* xs, const uint8_t* mirror,
                     const float* mean, int mean_kind, float scale,
                     float* out) {
  const int64_t in_img = c * h * w;
  const int64_t out_img = c * crop * crop;
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* src = in + i * in_img;
    float* dst = out + i * out_img;
    const int64_t y0 = ys ? ys[i] : 0;
    const int64_t x0 = xs ? xs[i] : 0;
    const bool flip = mirror && mirror[i];
    for (int64_t ch = 0; ch < c; ++ch) {
      const uint8_t* splane = src + ch * h * w;
      float* dplane = dst + ch * crop * crop;
      const float* mplane =
          mean_kind == 2 ? mean + ch * crop * crop : nullptr;
      const float* fplane =
          mean_kind == 3 ? mean + ch * h * w : nullptr;
      const float mchan = mean_kind == 1 ? mean[ch] : 0.0f;
      for (int64_t y = 0; y < crop; ++y) {
        const uint8_t* __restrict srow = splane + (y0 + y) * w + x0;
        float* __restrict drow = dplane + y * crop;
        // branch-free inner loops so gcc vectorizes the u8->f32 convert
        if (fplane) {  // full-size mean, source-indexed (pre-mirror)
          const float* __restrict mrow = fplane + (y0 + y) * w + x0;
          if (!flip) {
            for (int64_t x = 0; x < crop; ++x)
              drow[x] = ((float)srow[x] - mrow[x]) * scale;
          } else {
            for (int64_t x = 0; x < crop; ++x)
              drow[x] = ((float)srow[crop - 1 - x] - mrow[crop - 1 - x])
                        * scale;
          }
        } else if (!flip && mplane) {
          const float* __restrict mrow = mplane + y * crop;
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[x] - mrow[x]) * scale;
        } else if (!flip) {
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[x] - mchan) * scale;
        } else if (mplane) {
          const float* __restrict mrow = mplane + y * crop;
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[crop - 1 - x] - mrow[x]) * scale;
        } else {
          for (int64_t x = 0; x < crop; ++x)
            drow[x] = ((float)srow[crop - 1 - x] - mchan) * scale;
        }
      }
    }
  }
}

// CIFAR binary records (1 label byte + c*h*w image bytes each) ->
// planar images + labels (the CifarLoader.scala:66-86 inner loop).
void decode_cifar_records(const uint8_t* raw, int64_t n, int64_t record,
                          uint8_t* images, int32_t* labels) {
  const int64_t img = record - 1;
  for (int64_t i = 0; i < n; ++i) {
    labels[i] = raw[i * record];
    std::memcpy(images + i * img, raw + i * record + 1, img);
  }
}

// uint8 (n,c,h,w) accumulate-sum into int64 (c,h,w) — the hot loop of
// streaming mean-image computation (ComputeMean.scala:10-37).
void accumulate_sum(const uint8_t* in, int64_t n, int64_t chw,
                    int64_t* acc) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* img = in + i * chw;
    for (int64_t j = 0; j < chw; ++j) acc[j] += img[j];
  }
}

int native_abi_version() { return 2; }

}  // extern "C"
