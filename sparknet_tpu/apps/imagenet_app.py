"""ImageNet/CaffeNet training driver — the reference ImageNetApp.scala.

Reference behavior: AlexNet-class CaffeNet, batch 256, 256x256 source
images, random 227x227 crop + mean subtraction on TRAIN (center crop on
TEST), mean image via ComputeMean, tau=50 local steps per round.
Data arrives as (image, label) record streams (reference: S3 tar archives
-> RDD; here: any iterator of (N,3,256,256) uint8 batches — see
sparknet_tpu.data.imagenet for the tar reader).
"""

import os
import time

import numpy as np

from ..proto import Message
from ..models import zoo
from ..data.transforms import transform_train, transform_test, compute_mean
from ..data.synthetic import class_gaussian_images
from ..parallel import make_mesh, DataParallelSolver, LocalSGDSolver

SOURCE_SIZE = 256
CROP = 227
BATCH = 256


class ImageNetApp:
    def __init__(self, num_workers=None, train_source=None, test_source=None,
                 num_classes=1000, strategy="local_sgd", tau=50, batch=BATCH,
                 log_path=None, seed=0, metrics_path=None):
        self.t0 = time.time()
        self.logf = open(log_path, "w") if log_path else None
        self.metrics_path = metrics_path
        # shared stream: app round/test events + solver obs accounting
        from ..utils.metrics import MetricsLogger
        self.metrics = MetricsLogger(metrics_path) if metrics_path else None
        from ..parallel import distributed_init
        distributed_init()      # no-op single-process (DEPLOY.md)
        mesh = make_mesh({"data": num_workers if num_workers else -1})
        self.num_workers = mesh.shape["data"]
        self.strategy = strategy
        self.batch = batch
        self.num_classes = num_classes
        self.rng = np.random.RandomState(seed)

        if train_source is None:
            self.log("no ImageNet source; using synthetic class-gaussians")
            train_source = _synthetic_source(self.rng, num_classes)
            test_source = _synthetic_source(
                np.random.RandomState(seed + 1), num_classes)
        self.train_source = train_source
        self.test_source = test_source

        self.log("computing mean image (ComputeMean.scala equivalent)")
        probe = [next(self.train_source) for _ in range(4)]
        self.mean_image = compute_mean(
            (b[0] for b in probe), (3, SOURCE_SIZE, SOURCE_SIZE))

        scale = 1 if strategy == "local_sgd" else self.num_workers
        net = zoo.caffenet(batch_size=batch * scale, num_classes=num_classes,
                           crop_size=CROP)
        solver_param = Message(
            "SolverParameter", base_lr=0.01, momentum=0.9,
            weight_decay=0.0005, lr_policy="step", gamma=0.1, stepsize=100000,
            display=0, random_seed=seed)
        if strategy == "local_sgd":
            self.solver = LocalSGDSolver(solver_param, mesh=mesh, tau=tau,
                                         net_param=net, log_fn=self.log,
                                         metrics=self.metrics)
        else:
            self.solver = DataParallelSolver(solver_param, mesh=mesh,
                                             net_param=net, log_fn=self.log,
                                             metrics=self.metrics)
        self.log(f"initialized: {self.num_workers} workers, "
                 f"strategy={strategy}, batch={batch * scale}")

    def log(self, msg):
        line = f"{time.time() - self.t0:9.2f}: {msg}"
        print(line)
        if self.logf:
            self.logf.write(line + "\n")
            self.logf.flush()

    # -- preprocessing (ImageNetApp.scala:155-169 / :117-131) --------------
    def _prep_train(self, images):
        return transform_train(images, CROP, mean=self.mean_image,
                               mirror=True, rng=self.rng)

    def _prep_test(self, images):
        return transform_test(images, CROP, mean=self.mean_image)

    def _collect(self, source, n, prep):
        imgs, labs = [], []
        have = 0
        while have < n:
            bi, bl = next(source)
            imgs.append(bi)
            labs.append(bl)
            have += len(bi)
        images = np.concatenate(imgs)[:n]
        labels = np.concatenate(labs)[:n]
        return prep(images), labels

    def _round_stream(self):
        """Per-round batches, produced in the prefetch worker: JPEG-decoded
        source batches -> native crop/mirror/mean transform, overlapping the
        device round (base_data_layer.cpp:70-101 economics)."""
        while True:
            if self.strategy == "local_sgd":
                tau = self.solver.tau
                d, l = self._collect(
                    self.train_source, tau * self.batch * self.num_workers,
                    self._prep_train)
                yield {
                    "data": d.reshape(self.num_workers, tau, self.batch,
                                      3, CROP, CROP)
                    .transpose(1, 0, 2, 3, 4, 5)
                    .reshape(tau, -1, 3, CROP, CROP),
                    "label": l.reshape(self.num_workers, tau, self.batch)
                    .transpose(1, 0, 2).reshape(tau, -1)}
            else:
                d, l = self._collect(self.train_source,
                                     self.batch * self.num_workers,
                                     self._prep_train)
                yield {"data": d, "label": l}

    # -- driver loop (ImageNetApp.scala:100-182) ---------------------------
    def run(self, num_rounds=10, test_every=10, test_iters=4,
            stall_seconds=1200.0):
        from ..data.prefetch import PrefetchIterator
        from ..utils.watchdog import Watchdog

        metrics = self.metrics
        steps = self.solver.tau if self.strategy == "local_sgd" else 1
        imgs_per_round = self.batch * self.num_workers * steps
        wd = Watchdog(stall_seconds=stall_seconds, metrics=metrics,
                      on_stall=lambda dt: self.log(
                          f"WATCHDOG: no round finished in {dt:.0f}s"),
                      on_nan=lambda v: self.log(f"WATCHDOG: loss = {v}"))
        batches = PrefetchIterator(self._round_stream(), depth=2,
                                   metrics=metrics, name="round_feed")
        try:
            with wd:
                for r in range(num_rounds):
                    if test_every and r % test_every == 0 and \
                            self.test_source:
                        def it():
                            bs = self.batch * (
                                1 if self.strategy == "local_sgd"
                                else self.num_workers)
                            while True:
                                d, l = self._collect(self.test_source, bs,
                                                     self._prep_test)
                                yield {"data": d, "label": l}
                        scores = self.solver.test(it(), num_iters=test_iters)
                        for k, v in scores.items():
                            v = float(np.asarray(v).mean())
                            self.log(f"round {r}: test {k} = {v:.4f}")
                            if metrics:
                                metrics.log("test", round=r, metric=k,
                                            value=v)
                    rt0 = time.perf_counter()
                    if self.strategy == "local_sgd":
                        loss = self.solver.train_round(next(batches))
                    else:
                        loss = self.solver.train_step(next(batches))
                    loss = float(loss)
                    dt = time.perf_counter() - rt0
                    wd.beat(loss)
                    self.log(f"round {r}: loss = {loss:.4f}")
                    if metrics:
                        metrics.log("round", round=r, loss=loss,
                                    iter=self.solver.iter,
                                    images_per_s=round(
                                        imgs_per_round / max(dt, 1e-9), 1))
        finally:
            batches.close()
            self.solver.close()     # flush step/comms summaries
            if metrics:
                metrics.close()
        return self.solver


def _synthetic_source(rng, num_classes, batch=64):
    """Endless (images uint8 (N,3,256,256), labels) batch generator."""
    def gen():
        while True:
            images, labels = class_gaussian_images(
                batch, shape=(3, SOURCE_SIZE, SOURCE_SIZE),
                num_classes=num_classes, seed=int(rng.randint(1 << 31)))
            img8 = np.clip(np.asarray(images) * 32 + 128, 0, 255) \
                .astype(np.uint8)
            yield img8, np.asarray(labels)
    return gen()
