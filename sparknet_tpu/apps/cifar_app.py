"""CIFAR-10 training driver — the reference CifarApp.scala, mesh-native.

The reference driver (CifarApp.scala:33-135): load CIFAR from binary
batches, build cifar10_full with JavaData layers, then loop
{broadcast weights -> each of N workers runs tau=10 local SGD steps on its
partition -> collect & average}, testing every 10 rounds. Here the loop body
is LocalSGDSolver.train_round — one XLA program per round whose only
collective is a pmean — or, with strategy="dp", per-step gradient pmean
(which the reference could not express at all between machines).

Timing log: elapsed-seconds-prefixed phases, like the reference's
training_log_<ts>.txt (CifarApp.scala:43-52).
"""

import os
import time

import numpy as np

from ..proto import Message
from ..models import zoo
from ..models.proto_loader import (load_net_prototxt,
                                   load_solver_prototxt_with_net,
                                   replace_data_layers)
from ..data.cifar import CifarDataset
from ..data.synthetic import class_gaussian_images
from ..parallel import make_mesh, DataParallelSolver, LocalSGDSolver

TRAIN_BATCH = 100   # cifar10_full_train_test.prototxt batch sizes
TEST_BATCH = 100
NUM_TEST = 10000


class CifarApp:
    """num_workers = size of the "data" mesh axis (the reference's Spark
    executor count, CifarApp.scala:34)."""

    def __init__(self, num_workers=None, data_dir=None, prototxt_dir=None,
                 strategy="local_sgd", tau=10, log_path=None, seed=None):
        self.t0 = time.time()
        self.logf = open(log_path, "w") if log_path else None
        mesh = make_mesh({"data": num_workers if num_workers else -1})
        self.num_workers = mesh.shape["data"]
        self.strategy = strategy

        # data: real CIFAR binaries if present, synthetic stand-in otherwise
        if data_dir and os.path.isdir(data_dir):
            self.log(f"loading CIFAR-10 from {data_dir}")
            self.data = CifarDataset(data_dir, seed=seed)
        else:
            self.log("no CIFAR data dir; using synthetic class-gaussians")
            self.data = _SyntheticCifar(seed=seed or 0)

        # net: stock prototxt (with data layers swapped like
        # ProtoLoader.replaceDataLayers) or the built-in zoo twin
        scale = 1 if strategy == "local_sgd" else self.num_workers
        per_worker = TRAIN_BATCH * scale
        if prototxt_dir:
            net = load_net_prototxt(os.path.join(
                prototxt_dir, "cifar10_full_train_test.prototxt"))
            net = replace_data_layers(net, per_worker, TEST_BATCH * scale,
                                      3, 32, 32)
            solver_param = load_solver_prototxt_with_net(
                os.path.join(prototxt_dir, "cifar10_full_solver.prototxt"),
                net)
        else:
            net = zoo.cifar10_full(batch_size=per_worker)
            solver_param = Message(
                "SolverParameter", base_lr=0.001, momentum=0.9,
                weight_decay=0.004, lr_policy="fixed", display=0,
                random_seed=seed if seed is not None else -1)

        if strategy == "local_sgd":
            self.solver = LocalSGDSolver(solver_param, mesh=mesh, tau=tau,
                                         net_param=net, log_fn=self.log)
        else:
            self.solver = DataParallelSolver(solver_param, mesh=mesh,
                                             net_param=net, log_fn=self.log)
        self.log(f"initialized: {self.num_workers} workers, "
                 f"strategy={strategy}")

    def log(self, msg):
        line = f"{time.time() - self.t0:9.2f}: {msg}"
        print(line)
        if self.logf:
            self.logf.write(line + "\n")
            self.logf.flush()

    # -- data feeds ---------------------------------------------------------
    def _train_arrays(self, n_images):
        imgs = self.data.train_images.astype(np.float32) - self.data.mean_image
        labs = self.data.train_labels
        idx = np.random.randint(0, len(imgs) - n_images + 1)
        return imgs[idx:idx + n_images], labs[idx:idx + n_images]

    def _tau_batches(self, tau):
        """(tau, workers*batch, ...) arrays: each worker's contiguous window
        of its partition (the MinibatchSampler random-window behavior)."""
        n = tau * TRAIN_BATCH * self.num_workers
        imgs, labs = self._train_arrays(n)
        # worker w gets a contiguous run of tau batches from its partition;
        # reorder to (tau, workers*batch) so shard_batch slices per worker
        imgs = imgs.reshape(self.num_workers, tau, TRAIN_BATCH, 3, 32, 32) \
            .transpose(1, 0, 2, 3, 4, 5) \
            .reshape(tau, self.num_workers * TRAIN_BATCH, 3, 32, 32)
        labs = labs.reshape(self.num_workers, tau, TRAIN_BATCH) \
            .transpose(1, 0, 2).reshape(tau, -1)
        return {"data": imgs, "label": labs}

    def _test_batch_size(self):
        # the TEST net's feed batch (global across the mesh for dp)
        return self.solver.test_net.feed_shapes()["data"][0] \
            if self.strategy == "local_sgd" \
            else self.solver.net.feed_shapes()["data"][0]

    def _test_iter(self):
        imgs = self.data.test_images.astype(np.float32) - self.data.mean_image
        labs = self.data.test_labels
        bs = self._test_batch_size()
        for i in range(0, len(imgs) // bs * bs, bs):
            yield {"data": imgs[i:i + bs], "label": labs[i:i + bs]}

    # -- the driver loop (CifarApp.scala:92-135) ---------------------------
    def run(self, num_rounds=100, test_every=10):
        for r in range(num_rounds):
            if r % test_every == 0:
                self.log("testing")
                n = min(len(self.data.test_images) // self._test_batch_size(),
                        100)
                scores = self.solver.test(self._test_iter(), num_iters=n)
                for k, v in scores.items():
                    self.log(f"round {r}: test {k} = "
                             f"{np.asarray(v).mean():.4f}")
            self.log("broadcasting weights & running workers")
            if self.strategy == "local_sgd":
                loss = self.solver.train_round(
                    self._tau_batches(self.solver.tau))
            else:
                imgs, labs = self._train_arrays(
                    TRAIN_BATCH * self.num_workers)
                loss = self.solver.train_step({"data": imgs, "label": labs})
            self.log(f"round {r}: loss = {float(loss):.4f}")
        return self.solver


class _SyntheticCifar:
    """CifarDataset-shaped stand-in when no binary data is available."""

    def __init__(self, n_train=2000, n_test=500, seed=0):
        ti, tl = class_gaussian_images(n_train, shape=(3, 32, 32),
                                       num_classes=10, seed=seed)
        vi, vl = class_gaussian_images(n_test, shape=(3, 32, 32),
                                       num_classes=10, seed=seed + 1)
        self.train_images = np.asarray(ti)
        self.train_labels = np.asarray(tl)
        self.test_images = np.asarray(vi)
        self.test_labels = np.asarray(vl)
        self.mean_image = self.train_images.astype(np.float64).mean(0) \
            .astype(np.float32)
