"""CIFAR-10 training driver — the reference CifarApp.scala, mesh-native.

The reference driver (CifarApp.scala:33-135): load CIFAR from binary
batches, build cifar10_full with JavaData layers, then loop
{broadcast weights -> each of N workers runs tau=10 local SGD steps on its
partition -> collect & average}, testing every 10 rounds. Here the loop body
is LocalSGDSolver.train_round — one XLA program per round whose only
collective is a pmean — or, with strategy="dp", per-step gradient pmean
(which the reference could not express at all between machines).

Timing log: elapsed-seconds-prefixed phases, like the reference's
training_log_<ts>.txt (CifarApp.scala:43-52).
"""

import os
import time

import numpy as np

from ..proto import Message
from ..models import zoo
from ..models.proto_loader import (load_net_prototxt,
                                   load_solver_prototxt_with_net,
                                   replace_data_layers)
from ..data.cifar import CifarDataset
from ..data.synthetic import class_gaussian_images
from ..parallel import make_mesh, DataParallelSolver, LocalSGDSolver

TRAIN_BATCH = 100   # cifar10_full_train_test.prototxt batch sizes
TEST_BATCH = 100
NUM_TEST = 10000


class CifarApp:
    """num_workers = size of the "data" mesh axis (the reference's Spark
    executor count, CifarApp.scala:34)."""

    def __init__(self, num_workers=None, data_dir=None, prototxt_dir=None,
                 strategy="local_sgd", tau=10, log_path=None, seed=None,
                 metrics_path=None, hosts=0):
        self.t0 = time.time()
        self.logf = open(log_path, "w") if log_path else None
        self.metrics_path = metrics_path
        # one metrics stream for the whole app: the solver's step/comms
        # accounting (sparknet_tpu.obs), the watchdog, and the app's own
        # round/test events share it, so `sparknet report` sees the run
        from ..utils.metrics import MetricsLogger
        self.metrics = MetricsLogger(metrics_path) if metrics_path else None
        self.rng = np.random.RandomState(seed)
        self._train_f32 = None
        from ..parallel import multihost
        multihost.init_runtime()    # no-op single-process (DEPLOY.md)
        self.hosts = int(hosts or 0)
        if self.hosts and strategy != "local_sgd":
            raise ValueError("--hosts (hierarchical fault domains) needs "
                             "strategy local_sgd")
        if self.hosts:
            import jax
            if jax.process_count() > 1:
                # one fault domain per process; auto_host_mesh picks the
                # collective or relay transport for this backend
                mesh = multihost.auto_host_mesh(
                    per_host=num_workers or None)
            else:
                total = num_workers if num_workers else \
                    len(jax.devices())
                if total % self.hosts:
                    raise ValueError(f"{total} workers not divisible by "
                                     f"{self.hosts} hosts")
                mesh = multihost.host_mesh(hosts=self.hosts,
                                           per_host=total // self.hosts)
            self.num_workers = int(np.prod(
                [mesh.shape[a] for a in mesh.axis_names]))
        else:
            mesh = make_mesh({"data": num_workers if num_workers else -1})
            self.num_workers = mesh.shape["data"]
        self.strategy = strategy

        # data: real CIFAR binaries if present, synthetic stand-in otherwise
        if data_dir and os.path.isdir(data_dir):
            self.log(f"loading CIFAR-10 from {data_dir}")
            self.data = CifarDataset(data_dir, seed=seed)
        else:
            self.log("no CIFAR data dir; using synthetic class-gaussians")
            self.data = _SyntheticCifar(seed=seed or 0)

        # input-pipeline levers (cli._apply_feed_flags / env):
        #   echo E      — each round's batch is served E times (data
        #                 echoing; CIFAR feeds are pre-transformed f32, so
        #                 echoes reuse the batch as-is)
        #   shard ingest — in a multi-process world, each host samples
        #                 ONLY its owned partition of the record index
        #                 space (data/ingest.py), instead of every host
        #                 re-reading the full set
        self.echo = max(1, int(os.environ.get("SPARKNET_ECHO", "1") or 1))
        self.shard_ingest = \
            os.environ.get("SPARKNET_SHARD_INGEST", "on") != "off"
        self.ingest = None
        if self.shard_ingest:
            import jax
            if jax.process_count() > 1:
                from ..data.ingest import IngestShard
                self.ingest = IngestShard(
                    len(self.data.train_images), jax.process_index(),
                    jax.process_count(), metrics=self.metrics)
                self.log(f"sharded ingest: host {self.ingest.host} owns "
                         f"{self.ingest.owned}/{len(self.data.train_images)}"
                         f" records")

        # net: stock prototxt (with data layers swapped like
        # ProtoLoader.replaceDataLayers) or the built-in zoo twin
        scale = 1 if strategy == "local_sgd" else self.num_workers
        per_worker = TRAIN_BATCH * scale
        if prototxt_dir:
            net = load_net_prototxt(os.path.join(
                prototxt_dir, "cifar10_full_train_test.prototxt"))
            net = replace_data_layers(net, per_worker, TEST_BATCH * scale,
                                      3, 32, 32)
            solver_param = load_solver_prototxt_with_net(
                os.path.join(prototxt_dir, "cifar10_full_solver.prototxt"),
                net)
        else:
            net = zoo.cifar10_full(batch_size=per_worker)
            solver_param = Message(
                "SolverParameter", base_lr=0.001, momentum=0.9,
                weight_decay=0.004, lr_policy="fixed", display=0,
                random_seed=seed if seed is not None else -1)

        if strategy == "local_sgd":
            self.solver = LocalSGDSolver(solver_param, mesh=mesh, tau=tau,
                                         net_param=net, log_fn=self.log,
                                         metrics=self.metrics,
                                         host_axis="host"
                                         if self.hosts else None)
        else:
            self.solver = DataParallelSolver(solver_param, mesh=mesh,
                                             net_param=net, log_fn=self.log,
                                             metrics=self.metrics)
        self.log(f"initialized: {self.num_workers} workers, "
                 f"strategy={strategy}")

    def log(self, msg):
        line = f"{time.time() - self.t0:9.2f}: {msg}"
        print(line)
        if self.logf:
            self.logf.write(line + "\n")
            self.logf.flush()

    # -- data feeds ---------------------------------------------------------
    def _train_arrays(self, n_images):
        if self._train_f32 is None:     # mean-subtract once, not per round
            self._train_f32 = self.data.train_images.astype(np.float32) \
                - self.data.mean_image
        imgs, labs = self._train_f32, self.data.train_labels
        sh = self._current_ingest()
        if sh is not None:
            # per-host sharded ingest: the same random contiguous window,
            # confined to (and wrapping within) this host's owned records
            start = self.rng.randint(0, sh.owned)
            idx = sh.take(start, n_images)
            return imgs[idx], labs[idx]
        n = len(imgs)
        # random contiguous window (MinibatchSampler.scala:20-21), modular
        # so a request larger than the dataset wraps instead of raising
        # (e.g. local_sgd tau*batch*workers on a small set)
        start = self.rng.randint(0, n)
        idx = (start + np.arange(n_images)) % n
        return imgs[idx], labs[idx]

    def _current_ingest(self):
        """This host's ingest shard, re-spread if the elastic host
        membership changed since it was built — ingest ownership follows
        data ownership through the same partition_owners rule."""
        sh = self.ingest
        if sh is None:
            return None
        el = getattr(self.solver, "elastic", None)
        if el is not None and el.unit == "host" and el.n == sh.hosts \
                and not np.array_equal(el.alive, sh.alive):
            sh = self.ingest = sh.respread(el.alive)
        return sh

    def _slot_owners(self):
        """Per-SLOT re-spread owners when elastic evictions are in
        force, or None when every slot draws fresh data. Worker-unit
        membership maps 1:1 to mesh slots; host-unit membership (the
        hierarchical mesh) expands each live host's rank over its
        device row. Relay-mode multi-process runs (policy world spans
        processes, mesh is local) never re-spread locally — the dead
        hosts are remote."""
        elastic = getattr(self.solver, "elastic", None)
        if elastic is None or elastic.live_count() >= elastic.n:
            return None
        shape = self.solver.mesh.shape
        per_host = shape["data"]
        n_slots = per_host * shape.get("host", 1)
        if elastic.unit == "host":
            if elastic.n != shape.get("host", 1):
                return None             # relay mode: remote membership
            owners_host = elastic.shard_owners()
            return [owners_host[s // per_host] * per_host + s % per_host
                    for s in range(n_slots)]
        return elastic.shard_owners()

    def _tau_batches(self, tau):
        """(tau, workers*batch, ...) arrays: each worker's contiguous window
        of its partition (the MinibatchSampler random-window behavior).

        With elastic membership armed and workers (or whole hosts, on
        the hierarchical mesh) evicted, the fresh data is drawn for the
        LIVE slots only — the re-partitioning of the dead workers'
        stream across the survivors — and dead mesh slots receive a
        survivor's copy, which the round's validity mask discards on
        device (resilience/elastic.py). Membership changes reach here
        with the prefetch queue's 1-2 round lag, exactly like batches
        already in flight when a real worker dies."""
        shape = self.solver.mesh.shape
        n_slots = shape["data"] * shape.get("host", 1)
        owners = self._slot_owners()
        if owners is not None:
            from ..resilience.elastic import expand_to_slots
            k = len(set(owners))        # live slots actually drawn for
            imgs, labs = self._train_arrays(tau * TRAIN_BATCH * k)
            si = list(imgs.reshape(k, tau, TRAIN_BATCH, 3, 32, 32))
            sl = list(labs.reshape(k, tau, TRAIN_BATCH))
            # owners name live slots by their mesh index; re-rank them
            # into the drawn (live-ordered) shard list
            rank = {s: i for i, s in enumerate(sorted(set(owners)))}
            owners = [rank[o] for o in owners]
            imgs = expand_to_slots(si, owners)
            labs = expand_to_slots(sl, owners)
        else:
            imgs, labs = self._train_arrays(tau * TRAIN_BATCH * n_slots)
            imgs = imgs.reshape(n_slots, tau, TRAIN_BATCH, 3, 32, 32)
            labs = labs.reshape(n_slots, tau, TRAIN_BATCH)
        # worker w gets a contiguous run of tau batches from its partition;
        # reorder to (tau, workers*batch) so shard_batch slices per worker
        imgs = imgs.transpose(1, 0, 2, 3, 4, 5) \
            .reshape(tau, n_slots * TRAIN_BATCH, 3, 32, 32)
        labs = labs.transpose(1, 0, 2).reshape(tau, -1)
        return {"data": imgs, "label": labs}

    def _test_batch_size(self):
        # the TEST net's feed batch (global across the mesh for dp)
        return self.solver.test_net.feed_shapes()["data"][0] \
            if self.strategy == "local_sgd" \
            else self.solver.net.feed_shapes()["data"][0]

    def run_test(self, max_iters=100):
        """Full test pass -> {score_name: float mean} (CifarApp.scala:98)."""
        n = min(len(self.data.test_images) // self._test_batch_size(),
                max_iters)
        scores = self.solver.test(self._test_iter(), num_iters=n)
        return {k: float(np.asarray(v).mean()) for k, v in scores.items()}

    def _test_iter(self):
        imgs = self.data.test_images.astype(np.float32) - self.data.mean_image
        labs = self.data.test_labels
        bs = self._test_batch_size()
        for i in range(0, len(imgs) // bs * bs, bs):
            yield {"data": imgs[i:i + bs], "label": labs[i:i + bs]}

    def _round_stream(self):
        """Infinite per-round batch generator — runs in the prefetch worker
        so host-side window sampling overlaps the device round (the
        base_data_layer.cpp:70-101 double-buffering, loader-push style)."""
        while True:
            if self.strategy == "local_sgd":
                yield self._tau_batches(self.solver.tau)
            else:
                imgs, labs = self._train_arrays(
                    TRAIN_BATCH * self.num_workers)
                yield {"data": imgs, "label": labs}

    # -- the driver loop (CifarApp.scala:92-135) ---------------------------
    def run(self, num_rounds=100, test_every=10, stall_seconds=600.0,
            snapshot_prefix=None, snapshot_every=0, resume=None,
            reshard="strict"):
        """``snapshot_prefix``/``snapshot_every``/``resume``/``reshard``
        mirror LocalSGDSolver.run: in a multi-process world only the
        designated writer commits (Solver._snapshot handles that), and
        resume="auto" with reshard="auto" is how a late `--grow` joiner
        bootstraps its weights from the running world's checkpoint
        (the manifest is stamped for the incumbents' world, so a
        cross-world reshard is exactly what the joiner needs)."""
        from ..data.prefetch import PrefetchIterator, EchoIterator
        from ..resilience.chaos import active_chaos
        from ..utils.watchdog import Watchdog
        from ..resilience import checkpoint

        if resume == "auto":
            if snapshot_prefix:
                checkpoint.resume_auto(self.solver, snapshot_prefix,
                                       log_fn=self.log, reshard=reshard)
            else:
                self.log("resume auto: no snapshot prefix; starting fresh")
        elif resume:
            self.solver.restore(resume, reshard=reshard)

        metrics = self.metrics
        steps_per_round = self.solver.tau \
            if self.strategy == "local_sgd" else 1
        imgs_per_round = TRAIN_BATCH * self.num_workers * steps_per_round
        wd = Watchdog(stall_seconds=stall_seconds, metrics=metrics,
                      on_stall=lambda dt: self.log(
                          f"WATCHDOG: no round finished in {dt:.0f}s"),
                      on_nan=lambda v: self.log(f"WATCHDOG: loss = {v}"))
        # slow_h2d chaos charges every FRESH round batch at the prefetch
        # hand-off (the app feeds raw host arrays to train_round, so this
        # is where "the wire" lives); echoed batches skip it — the
        # wall-clock edge the smoke-test echo run asserts
        ch = active_chaos()
        gate = None
        if ch is not None and getattr(ch, "slow_h2d", 0) > 0:
            def gate(b):
                vals = b.values() if isinstance(b, dict) else [b]
                ch.maybe_slow_h2d(nbytes=sum(
                    int(getattr(v, "nbytes", 0)) for v in vals))
                return b
        extra = {"echo": self.echo}
        if self.ingest is not None:
            extra["ingest_hosts"] = self.ingest.hosts
            extra["ingest_records"] = self.ingest.owned
        batches = PrefetchIterator(self._round_stream(), depth=2,
                                   transform=gate, metrics=metrics,
                                   name="round_feed", extra=extra)
        if self.echo > 1:
            batches = EchoIterator(batches, self.echo)
        try:
            with wd:
                for r in range(num_rounds):
                    if r % test_every == 0:
                        self.log("testing")
                        for k, v in self.run_test().items():
                            self.log(f"round {r}: test {k} = {v:.4f}")
                            if metrics:
                                metrics.log("test", round=r, metric=k,
                                            value=v)

                    self.log("broadcasting weights & running workers")
                    rt0 = time.perf_counter()
                    if self.strategy == "local_sgd":
                        loss = self.solver.train_round(next(batches))
                    else:
                        loss = self.solver.train_step(next(batches))
                    loss = float(loss)
                    dt = time.perf_counter() - rt0
                    wd.beat(loss)
                    line = f"round {r}: loss = {loss:.4f}"
                    d = getattr(self.solver, "last_divergence", None)
                    if d and d.get("mean") is not None:
                        # the paper's tau drift, measured at this round's
                        # averaging step (obs/divergence.py)
                        line += f", divergence = {d['mean']:.4g}"
                    self.log(line)
                    if metrics:
                        metrics.log("round", round=r, loss=loss,
                                    iter=self.solver.iter,
                                    lr=float(self.solver.lr_fn(
                                        self.solver.iter)),
                                    images_per_s=round(imgs_per_round
                                                       / max(dt, 1e-9), 1))
                    if snapshot_prefix and snapshot_every and \
                            (r + 1) % snapshot_every == 0:
                        self.solver.snapshot(prefix=snapshot_prefix)
        finally:
            batches.close()
            el = getattr(self.solver, "elastic", None)
            if el is not None and (el.evictions or el.readmissions):
                s = el.summary()
                self.log(f"elastic: {len(s['evictions'])} eviction(s), "
                         f"{len(s['readmissions'])} readmission(s); "
                         f"{s['live']}/{s['world']} workers live")
            h = getattr(self.solver, "health", None)
            if h is not None and h.alarms:
                s = h.summary()
                self.log(f"health: {s['alarms']} alarm(s); last: "
                         f"{s['last_alarm']}")
            self.solver.close()     # flush step/comms summaries
            if metrics:
                metrics.close()
        return self.solver


class _SyntheticCifar:
    """CifarDataset-shaped stand-in when no binary data is available."""

    def __init__(self, n_train=2000, n_test=500, seed=0):
        ti, tl = class_gaussian_images(n_train, shape=(3, 32, 32),
                                       num_classes=10, seed=seed)
        vi, vl = class_gaussian_images(n_test, shape=(3, 32, 32),
                                       num_classes=10, seed=seed + 1)
        self.train_images = np.asarray(ti)
        self.train_labels = np.asarray(tl)
        self.test_images = np.asarray(vi)
        self.test_labels = np.asarray(vl)
        self.mean_image = self.train_images.astype(np.float64).mean(0) \
            .astype(np.float32)
