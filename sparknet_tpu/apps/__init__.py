"""Training drivers — the L1 "apps" layer of the reference (CifarApp.scala,
ImageNetApp.scala), re-expressed over the mesh instead of a Spark cluster."""

from .cifar_app import CifarApp
from .imagenet_app import ImageNetApp

__all__ = ["CifarApp", "ImageNetApp"]
