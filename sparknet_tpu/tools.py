"""Dataset preparation tools — the reference's tools/ binaries.

  convert_cifar_data   examples/cifar10/convert_cifar_data.cpp: CIFAR-10
                       binary batches -> train/test Datum DBs
  compute_image_mean   tools/compute_image_mean.cpp: Datum DB -> mean image
                       .binaryproto (+ per-channel means printed)
  convert_imageset     tools/convert_imageset.cpp: listfile of
                       "relpath label" lines -> Datum DB (optional resize,
                       gray, shuffle, encoded passthrough)

Both DB backends are pure-Python: LMDB (data/lmdb.py) is the default
writer everywhere; convert_imageset also accepts backend="leveldb"
(data/leveldb.py), and every reader goes through data/db_source.open_db,
which reads either.
"""

import os

import numpy as np

from .data.lmdb import LMDBWriter
from .data.datum import array_to_datum, encoded_datum, datum_to_array
from .data.transforms import save_mean_binaryproto
from . import native

_CIFAR_SIZE = 32
_CIFAR_BYTES = 3 * _CIFAR_SIZE * _CIFAR_SIZE
_CIFAR_BATCH = 10000


def convert_cifar_data(input_folder, output_folder, log=print):
    """CIFAR-10 binary batches -> cifar10_{train,test}_lmdb of Datums,
    keys "%05d" in read order (convert_cifar_data.cpp:38-88)."""
    record = _CIFAR_BYTES + 1

    def write(db_path, files):
        with LMDBWriter(db_path) as w:
            idx = 0
            for f in files:
                raw = np.fromfile(os.path.join(input_folder, f), np.uint8)
                images, labels = native.decode_cifar_records(raw, record)
                images = images.reshape(-1, 3, _CIFAR_SIZE, _CIFAR_SIZE)
                for img, label in zip(images, labels):
                    w.put(b"%05d" % idx, array_to_datum(img, int(label)))
                    idx += 1
        return idx

    log("Writing Training data")
    n = write(os.path.join(output_folder, "cifar10_train_lmdb"),
              [f"data_batch_{i}.bin" for i in range(1, 6)])
    log(f"  {n} records")
    log("Writing Testing data")
    n = write(os.path.join(output_folder, "cifar10_test_lmdb"),
              ["test_batch.bin"])
    log(f"  {n} records")


def compute_image_mean(db_path, out_path=None, backend="lmdb", log=print):
    """Mean image over every Datum in a DB -> BlobProto .binaryproto
    (tools/compute_image_mean.cpp; native accumulate per record)."""
    from .data.db_source import open_db
    db = open_db(db_path, backend)
    acc = None
    count = 0
    for _, value in db.items():
        arr, _ = datum_to_array(value)
        if arr.dtype != np.uint8:
            arr = arr.astype(np.float64)
            acc = arr if acc is None else acc + arr
        else:
            if acc is None:
                acc = np.zeros(arr.shape, np.int64)
            native.accumulate_sum(arr[None], acc)
        count += 1
    db.close()
    if not count:
        raise ValueError(f"{db_path}: empty database")
    mean = (acc / count).astype(np.float32)
    if out_path:
        save_mean_binaryproto(mean, out_path)
        log(f"Write to {out_path}")
    for ch in range(mean.shape[0]):
        log(f"mean_value channel [{ch}]: {mean[ch].mean():.6g}")
    return mean


def make_synth_cifar(out_dir, n_train=50000, n_test=10000, seed=0,
                     noise=28.0, label_noise=0.0, log=print):
    """Write a CIFAR-10-format synthetic dataset (5 train .bin batches +
    test_batch.bin) of shape/texture-class images (see
    data/synthetic.shape_texture_images).  Stands in for the real bits the
    reference downloads in data/cifar10/get_cifar10.sh when the environment
    has no network egress; the files feed convert_cifar_data / CifarApp
    unchanged."""
    from .data.synthetic import shape_texture_images
    from .data.cifar import write_batch_file
    os.makedirs(out_dir, exist_ok=True)
    per = n_train // 5
    for b in range(5):
        imgs, labels = shape_texture_images(per, seed=seed + b, noise=noise,
                                            label_noise=label_noise)
        write_batch_file(os.path.join(out_dir, f"data_batch_{b + 1}.bin"),
                         imgs, labels)
        log(f"data_batch_{b + 1}.bin: {per} records")
    imgs, labels = shape_texture_images(n_test, seed=seed + 1000, noise=noise,
                                        label_noise=label_noise)
    write_batch_file(os.path.join(out_dir, "test_batch.bin"), imgs, labels)
    log(f"test_batch.bin: {n_test} records")


def convert_imageset(root_folder, list_file, db_path, resize_height=0,
                     resize_width=0, gray=False, shuffle=False,
                     encoded=False, seed=0, backend="lmdb", log=print):
    """Images listed as "relative/path label" lines -> Datum DB.

    Matches tools/convert_imageset.cpp keys ("%08d_<path>") and flags
    (--resize_height/width, --gray, --shuffle, --encoded, --backend
    lmdb/leveldb). Undecodable images are skipped with a warning, like the
    reference's ReadImageToDatum false return (and
    ScaleAndConvert.scala:22-26)."""
    from PIL import Image

    lines = []
    with open(list_file) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            path, _, label = line.rpartition(" ")
            lines.append((path, int(label)))
    if shuffle:
        np.random.RandomState(seed).shuffle(lines)
    log(f"A total of {len(lines)} images.")

    if backend == "leveldb":
        from .data.leveldb import LevelDBWriter as _Writer
    else:
        _Writer = LMDBWriter
    written = 0
    with _Writer(db_path) as w:
        for i, (rel, label) in enumerate(lines):
            full = os.path.join(root_folder, rel)
            try:
                if encoded and not (resize_height or resize_width or gray):
                    with open(full, "rb") as f:
                        raw = f.read()
                    datum = encoded_datum(raw, label)
                else:
                    img = Image.open(full)
                    img = img.convert("L" if gray else "RGB")
                    if resize_height and resize_width:
                        img = img.resize((resize_width, resize_height),
                                         Image.BILINEAR)
                    a = np.asarray(img, np.uint8)
                    if a.ndim == 2:
                        a = a[None]            # (1,H,W)
                    else:
                        a = a[:, :, ::-1].transpose(2, 0, 1)  # HWC RGB->CHW BGR
                    datum = array_to_datum(np.ascontiguousarray(a), label)
            except (OSError, ValueError) as e:
                log(f"Could not open or find file {full}: {e}")
                continue
            w.put(b"%08d_%s" % (i, rel.encode()), datum)
            written += 1
            if written % 1000 == 0:
                log(f"Processed {written} files.")
    log(f"Processed {written} files.")
    return written


def upgrade_net_proto(in_path, out_path, binary=False, log=print):
    """Any-vintage NetParameter file -> latest format
    (tools/upgrade_net_proto_text.cpp / upgrade_net_proto_binary.cpp:
    V0 upgrade + V1 upgrade + deprecated data-transform move, then write).

    binary=False reads/writes prototxt text; True reads/writes wire bytes
    (.caffemodel-style)."""
    from .proto import text_format, wire
    from .graph.upgrade import (needs_v0_upgrade, net_needs_data_upgrade,
                                upgrade_net)
    codec = wire if binary else text_format
    net = codec.load(in_path, "NetParameter")
    if not (needs_v0_upgrade(net) or len(net.layers)
            or net_needs_data_upgrade(net)):
        log(f"File already in latest proto format: {in_path}")
    net = upgrade_net(net)
    codec.dump(net, out_path)
    log(f"Wrote upgraded NetParameter {'binary' if binary else 'text'} "
        f"proto to {out_path}")
    return net


def upgrade_solver_proto(in_path, out_path, log=print):
    """Deprecated solver_type enum -> type string in a solver prototxt
    (tools/upgrade_solver_proto_text.cpp)."""
    from .proto import text_format
    from .graph.upgrade import solver_needs_type_upgrade, upgrade_solver
    sp = text_format.load(in_path, "SolverParameter")
    if not solver_needs_type_upgrade(sp):
        log(f"File already in latest proto format: {in_path}")
    sp = upgrade_solver(sp)
    text_format.dump(sp, out_path)
    log(f"Wrote upgraded SolverParameter text proto to {out_path}")
    return sp


def extract_features(model_path, blob_names, db_paths, num_batches,
                     weights_path=None, base_dir=None, backend="lmdb",
                     log=print):
    """Forward a TEST-phase net num_batches times and write the named
    blobs' per-image activations as float Datums, keys "%010d"
    (tools/extract_features.cpp:135-185; Datum channels/height/width
    follow the legacy 4-d blob accessors, so an (N, D) blob writes
    (D, 1, 1) features).

    blob_names / db_paths are parallel lists (the reference's
    comma-separated pairs). The net's own TEST data layer supplies input;
    its DB source is resolved relative to base_dir (default: the model
    file's directory, walking up like the CLI)."""
    import jax
    import jax.numpy as jnp
    from .proto import text_format, wire
    from .graph.compiler import CompiledNet, TEST
    from .graph.upgrade import upgrade_net
    from .data.db_source import resolve_db_feed

    if len(blob_names) != len(db_paths):
        raise ValueError("the number of blob names and dataset names "
                         "must be equal")
    net_param = upgrade_net(text_format.load(model_path, "NetParameter"))
    feed_shapes, src = resolve_db_feed(
        net_param, TEST,
        base_dir or os.path.dirname(os.path.abspath(model_path)), seed=0)
    if src is None:
        raise ValueError(
            f"{model_path}: no TEST data layer with a readable DB "
            "source (extract_features needs the net to feed itself)")

    try:
        net = CompiledNet(net_param, TEST, feed_shapes=feed_shapes)
        params, state = net.init(jax.random.PRNGKey(0))
        if weights_path:
            if weights_path.endswith(".h5"):
                from .solver import hdf5_io
                params = hdf5_io.load_net_hdf5(weights_path, net, params)
            else:
                params, state = net.load_netproto(
                    wire.load(weights_path, "NetParameter"), params, state)
        for b in blob_names:
            if b not in net.blob_shapes:
                raise ValueError(f"Unknown feature blob name {b} in the "
                                 f"network {model_path}")

        @jax.jit
        def forward(params, state, batch):
            blobs, _ = net.apply(params, state, batch, train=False)
            return {b: blobs[b] for b in blob_names}

        log("Extracting Features")
        if backend == "leveldb":
            from .data.leveldb import LevelDBWriter as _W
        else:
            _W = LMDBWriter
        writers = [_W(p) for p in db_paths]
        counts = [0] * len(blob_names)
        try:
            it = iter(src)
            for _ in range(num_batches):
                batch = {k: jnp.asarray(v) for k, v in next(it).items()}
                feats = forward(params, state, batch)
                for i, b in enumerate(blob_names):
                    arr = np.asarray(feats[b], np.float32)
                    n = arr.shape[0]
                    # legacy 4-d accessors: (N, C[, H[, W]]) -> (C, H, W)
                    chw = arr.reshape(n,
                                      arr.shape[1] if arr.ndim > 1 else 1,
                                      arr.shape[2] if arr.ndim > 2 else 1,
                                      -1)
                    for row in chw:
                        writers[i].put(b"%010d" % counts[i],
                                       array_to_datum(row))
                        counts[i] += 1
                        if counts[i] % 1000 == 0:
                            log(f"Extracted features of {counts[i]} query "
                                f"images for feature blob {b}")
        finally:
            for w in writers:
                w.close()
    finally:
        src.close()
    for b, c in zip(blob_names, counts):
        log(f"Extracted features of {c} query images for feature blob {b}")
    return counts
