"""`sparknet serve` — a continuous-batching inference tier over
resilient checkpoints.

engine.py   weights-only checkpoint loading into forward-only jits,
            one per padding bucket, with hot reload mid-serve
batcher.py  thread-safe request queue: continuous batching, pad-to-
            bucket, max-wait deadline, bounded-queue backpressure
server.py   stdlib HTTP front end (/predict /healthz /metrics) with
            graceful SIGTERM drain and the supervisor exit contract
loadgen.py  closed- and open-loop load generator (`sparknet serve-bench`)
fleet.py    `sparknet route` — lease-based replica membership over the
            heartbeat rendezvous, least-depth routing with retry-once
            failover, SLO autoscaling, canary rollout with rollback
"""

from .engine import ServeEngine, bucket_sizes, bucket_for
from .batcher import Batcher, RejectedError
from .server import ServeStats, serve_http
from .loadgen import run_loadgen
from .fleet import (ReplicaMember, Router, SLOAutoscaler,
                    CanaryController, route_http)

__all__ = ["ServeEngine", "bucket_sizes", "bucket_for", "Batcher",
           "RejectedError", "ServeStats", "serve_http", "run_loadgen",
           "ReplicaMember", "Router", "SLOAutoscaler",
           "CanaryController", "route_http"]
