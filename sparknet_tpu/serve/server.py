"""HTTP front end + serve loop for `sparknet serve`.

stdlib-only: a ThreadingHTTPServer owns the sockets (one handler
thread per connection), the MAIN thread runs serve_loop() — form a
batch, run the engine, fulfill the handler threads' Request events.
Endpoints:

  POST /predict   {"<feed blob>": [[...]...]} -> {"outputs": {...}}
                  (a bare list is taken as the first feed blob)
  GET  /healthz   loaded iter/model, buckets, feed shapes, queue depth
  GET  /metrics   latency percentiles + counters snapshot

Supervisor contract (DEPLOY.md "Serving"): SIGTERM/SIGINT stop
accepting (backpressure 429s), drain queued requests, exit
EXIT_OK(0). A checkpoint that cannot load exits EXIT_RECOVERY_ABORT(3)
before the socket ever opens, so an orchestrator's restart loop can
tell "bad checkpoint" from "crash".

Every batch emits schema-registered events (serve_request,
serve_batch, serve_reject, serve_reload, serve_summary) so `sparknet
report`/`monitor` render the serving section with no special cases.
"""

import json
import threading
import time

import numpy as np

from .batcher import RejectedError
from ..obs.tracing import STAGES_HEADER, TRACE_HEADER, encode_stages


def stage_breakdown(req, now):
    """Per-stage wall attribution for one fulfilled Request, in ms:
    queue (admission->dispatch), batch (dispatch->forward), infer
    (the forward itself), fulfill (forward->response write). Missing
    stamps collapse to zero-width stages (never negative, never NaN),
    so the sum always ≈ total — the decomposition invariant the
    tests pin."""
    t_enq = req.t_enq if req.t_enq is not None else req.t_submit
    t_dis = req.t_dispatch if req.t_dispatch is not None else t_enq
    t_f0 = req.t_fwd0 if req.t_fwd0 is not None else t_dis
    t_f1 = req.t_fwd1 if req.t_fwd1 is not None else t_f0
    t_done = req.t_done if req.t_done is not None else t_f1
    ms = lambda a, b: max(0.0, (b - a) * 1e3)  # noqa: E731
    return {
        "queue": ms(req.t_submit, t_dis),
        "batch": ms(t_dis, t_f0),
        "infer": ms(t_f0, t_f1),
        "fulfill": ms(t_f1, max(t_done, now)),
        "total": ms(req.t_submit, max(t_done, now)),
    }


class ServeStats:
    # spk: guarded-by-default=_lock
    def __init__(self, window=4096):
        import collections
        self._lock = threading.Lock()
        self.t0 = time.monotonic()
        self.lat_ms = collections.deque(maxlen=window)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.fill_sum = 0.0
        self.rejects = 0
        self.reloads = 0

    def record_batch(self, reqs, bucket, infer_ms):
        now = time.monotonic()
        with self._lock:
            self.batches += 1
            rows = sum(r.n for r in reqs)
            self.rows += rows
            self.requests += len(reqs)
            self.fill_sum += rows / float(bucket)
            for r in reqs:
                self.lat_ms.append((now - r.t_submit) * 1e3)

    def record_reject(self):              # spk: thread-entry
        with self._lock:
            self.rejects += 1

    def record_reload(self):
        with self._lock:
            self.reloads += 1

    def snapshot(self):                   # spk: thread-entry
        from ..obs.stepstats import percentiles
        with self._lock:
            lats = list(self.lat_ms)
            out = {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "rejects": self.rejects,
                "reloads": self.reloads,
                "uptime_s": round(time.monotonic() - self.t0, 3),
                "batch_fill": round(
                    self.fill_sum / self.batches, 4) if self.batches
                else None,
            }
        lat = {f"latency_ms_{k}": round(v, 3)
               for k, v in percentiles(lats).items()} if lats else {}
        out.update(lat)
        if out["uptime_s"] > 0:
            out["rps"] = round(out["requests"] / out["uptime_s"], 2)
        return out


def _make_handler(engine, batcher, stats, timeout_s, member=None,
                  metrics=None, tracer=None, replica=None):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # quiet access log
            pass

        def _send_json(self, code, obj, headers=None):
            body = json.dumps(obj).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                st = engine.status()
                st["status"] = "ok"
                st["queue_depth"] = batcher.depth()
                st["draining"] = batcher.draining()
                if member is not None:
                    # lease/membership fields (serve/fleet.py): the
                    # same truth the router reads from the beat
                    st.update(member.health())
                self._send_json(200, st)
            elif self.path == "/metrics":
                snap = stats.snapshot()
                snap["queue_depth"] = batcher.depth()
                snap.update(batcher.counters())
                self._send_json(200, snap)
            else:
                self._send_json(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path != "/predict":
                self._send_json(404, {"error": "unknown path"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, TypeError) as e:
                self._send_json(400, {"error": f"bad JSON: {e}"})
                return
            try:
                arrays, n = _parse_inputs(payload, engine.feed_shapes())
            except ValueError as e:
                self._send_json(400, {"error": str(e)})
                return
            # router-minted trace id rides the request header; the
            # value is "<id>;<attempt>" so retries share one id
            trace = None
            raw = self.headers.get(TRACE_HEADER)
            if raw:
                trace = raw.split(";", 1)[0].strip() or None
            try:
                req = batcher.submit(arrays, n=n, trace=trace)
            except RejectedError as e:
                stats.record_reject()
                self._send_json(429, {"error": str(e),
                                      "reason": e.reason,
                                      "queue_depth": e.queue_depth})
                return
            if not req.wait(timeout_s):
                self._send_json(504, {"error": "inference timed out"})
                return
            if req.error is not None:
                self._send_json(500, {"error": req.error})
                return
            stg = stage_breakdown(req, time.monotonic())
            hdrs = {STAGES_HEADER: encode_stages(stg)}
            if trace:
                hdrs[TRACE_HEADER] = trace
            self._send_json(200, {
                "outputs": {k: v.tolist() for k, v in req.result.items()},
                "iter": engine.status().get("iter"),
                "bucket": req.bucket,
                "latency_ms": round((req.t_done - req.t_submit) * 1e3, 3),
                "stages": {k: round(v, 3) for k, v in stg.items()},
            }, headers=hdrs)
            # replica-side exemplar: lets fleettrace place this
            # request on the replica track with the router's id
            if metrics is not None:
                verdict = tracer.decide(stg["total"]) if tracer \
                    is not None else "head"
                if verdict is not None:
                    metrics.log("serve_trace",
                                src=f"replica{replica}"
                                    if replica is not None else "replica",
                                trace=trace, replica=replica, code=200,
                                total_ms=round(stg["total"], 3),
                                queue_ms=round(stg["queue"], 3),
                                batch_ms=round(stg["batch"], 3),
                                infer_ms=round(stg["infer"], 3),
                                fulfill_ms=round(stg["fulfill"], 3),
                                tail=verdict == "tail")

    return Handler


def _parse_inputs(payload, feed_shapes):
    """JSON body -> ({feed blob -> ndarray}, rows). A bare list feeds
    the first (primary) blob; labels and other feeds default to
    zero-fill in the engine."""
    names = list(feed_shapes)
    if not names:
        raise ValueError("net has no feed blobs")
    if isinstance(payload, list):
        payload = {names[0]: payload}
    if not isinstance(payload, dict) or not payload:
        raise ValueError(
            f"expected a JSON object keyed by feed blob {names}")
    arrays, n = {}, None
    for k, v in payload.items():
        if k not in feed_shapes:
            raise ValueError(f"unknown feed blob {k!r} (have {names})")
        arr = np.asarray(v)
        per = tuple(feed_shapes[k])
        if arr.shape == per:        # single sample without batch dim
            arr = arr[None]
        if arr.shape[1:] != per:
            raise ValueError(
                f"feed {k!r}: per-sample shape {arr.shape[1:]} != {per}")
        if n is None:
            n = arr.shape[0]
        elif arr.shape[0] != n:
            raise ValueError("feed blobs disagree on row count")
        arrays[k] = arr
    return arrays, int(n)


def _run_batch(engine, batcher, stats, metrics, reqs, wait_ms,
               tracer=None, chaos=None, replica=None):
    """One engine step for one closed batch; fulfills every Request."""
    rows = sum(r.n for r in reqs)
    depth = batcher.depth()
    arrays = {}
    for name, per in engine.feed_shapes().items():
        if not any(name in r.arrays for r in reqs):
            continue                # engine zero-fills the whole feed
        parts = [np.asarray(r.arrays[name]) if name in r.arrays
                 else np.zeros((r.n,) + tuple(per))
                 for r in reqs]
        arrays[name] = np.concatenate(parts, axis=0)
    fwd0 = time.monotonic()
    for r in reqs:
        r.t_fwd0 = fwd0
    if chaos is not None and replica is not None:
        # injected slowness lands INSIDE the forward stage, matching
        # the sim (which inflates service time) — so "where did the
        # p99 go" names infer, the stage a slow accelerator shows as
        chaos.maybe_slow_replica(int(replica))
    t0 = time.perf_counter()
    try:
        out, bucket = engine.forward(arrays, n=rows)
    except Exception as e:          # net-level failure -> 500s, keep serving
        now = time.monotonic()
        for r in reqs:
            r.error = f"{type(e).__name__}: {e}"
            r.t_fwd1 = now
            r.t_done = now
            r.done.set()
        return
    infer_ms = (time.perf_counter() - t0) * 1e3
    off = 0
    now = time.monotonic()
    for r in reqs:
        r.result = {k: v[off:off + r.n] for k, v in out.items()}
        r.bucket = bucket
        r.t_fwd1 = now
        r.t_done = now
        off += r.n
        r.done.set()
    stats.record_batch(reqs, bucket, infer_ms)
    if metrics is not None:
        it = engine.status().get("iter")
        metrics.log("serve_batch", size=rows, requests=len(reqs),
                    bucket=bucket, fill=round(rows / float(bucket), 4),
                    queue_depth=depth, wait_ms=round(wait_ms, 3),
                    infer_ms=round(infer_ms, 3), iter=it)
        for r in reqs:
            lat_ms = (r.t_done - r.t_submit) * 1e3
            if tracer is not None and tracer.decide(lat_ms) is None:
                continue    # head-sampled out; tails always kept
            metrics.log("serve_request",
                        latency_ms=round(lat_ms, 3),
                        wait_ms=round(wait_ms, 3), rows=r.n,
                        bucket=bucket)


def serve_loop(engine, batcher, stats, metrics=None, policy=None,
               reload_poll_s=0.0, stop_event=None, idle_timeout=0.05,
               chaos=None, replica=None, tracer=None, log_fn=print):
    """The single consumer thread: batches, signals, hot reload, drain.
    Returns 0 after a clean drain (the supervisor contract)."""
    log = log_fn or (lambda *a: None)
    next_reload = time.monotonic() + reload_poll_s if reload_poll_s else None
    inject = chaos is not None and replica is not None
    draining = False
    served = 0
    while True:
        if not draining:
            action = policy.pending() if policy is not None else None
            if action is not None and "stop" in action:
                log("serve: stop requested; draining "
                    f"{batcher.pending()} queued request(s)")
                batcher.close()
                draining = True
            elif stop_event is not None and stop_event.is_set():
                log("serve: drain requested; draining "
                    f"{batcher.pending()} queued request(s)")
                batcher.close()
                draining = True
        if next_reload is not None and not draining \
                and time.monotonic() >= next_reload:
            if engine.poll_reload() is not None:
                stats.record_reload()
            next_reload = time.monotonic() + reload_poll_s
        reqs, wait_ms = batcher.next_batch(timeout=idle_timeout)
        if reqs:
            _run_batch(engine, batcher, stats, metrics, reqs, wait_ms,
                       tracer=tracer,
                       chaos=chaos if inject else None,
                       replica=replica if inject else None)
            served += len(reqs)
            if inject:
                # kill_replica fires AFTER the kill_req-th request is
                # fulfilled: the dispatch-then-die case the router's
                # retry-once must never double
                chaos.maybe_kill_replica_self(int(replica), served)
        elif draining and batcher.pending() == 0:
            return 0


def serve_http(engine, batcher, host="127.0.0.1", port=0, metrics=None,
               policy=None, reload_poll_s=0.0, stop_event=None,
               request_timeout_s=30.0, member=None, chaos=None,
               replica=None, tracer=None, log_fn=print):
    """Bind, announce, serve until drained; returns the exit code.
    With ``member`` (serve/fleet.py ReplicaMember) the replica leases
    into the fleet rendezvous once the socket is bound (the URL is in
    the beat payload) and its drain order rides ``stop_event``."""
    from http.server import ThreadingHTTPServer
    log = log_fn or (lambda *a: None)
    stats = ServeStats()
    handler = _make_handler(engine, batcher, stats, request_timeout_s,
                            member=member, metrics=metrics,
                            tracer=tracer, replica=replica)
    httpd = ThreadingHTTPServer((host, int(port)), handler)
    httpd.daemon_threads = True
    addr = f"http://{httpd.server_address[0]}:{httpd.server_address[1]}"
    if member is not None:
        member.start(url=addr)
        if stop_event is None:
            stop_event = member.drain_event
    st = engine.status()
    log(f"sparknet serve: listening on {addr} (iter {st.get('iter')}, "
        f"buckets {st.get('buckets')})")
    import sys
    sys.stdout.flush()      # the announce line gates smoke/loadgen start
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        rc = serve_loop(engine, batcher, stats, metrics=metrics,
                        policy=policy, reload_poll_s=reload_poll_s,
                        stop_event=stop_event, chaos=chaos,
                        replica=replica, tracer=tracer, log_fn=log)
    finally:
        httpd.shutdown()
        httpd.server_close()
        if member is not None:
            member.stop()
    snap = stats.snapshot()
    if metrics is not None:
        metrics.log("serve_summary", requests=snap.get("requests"),
                    rows=snap.get("rows"), batches=snap.get("batches"),
                    rejects=snap.get("rejects"),
                    reloads=snap.get("reloads"),
                    rps=snap.get("rps"),
                    latency_ms_p50=snap.get("latency_ms_p50"),
                    latency_ms_p95=snap.get("latency_ms_p95"),
                    latency_ms_p99=snap.get("latency_ms_p99"),
                    batch_fill=snap.get("batch_fill"),
                    uptime_s=snap.get("uptime_s"), drained=True)
    log(f"serve: drained cleanly after {snap.get('requests', 0)} "
        f"request(s); exiting 0")
    return rc
