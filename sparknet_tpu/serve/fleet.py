"""Fleet serving: leased replica membership, router failover, SLO
autoscaling, canary rollout (`sparknet route`).

`sparknet serve` is one process — one SIGKILL from zero availability.
This module replicates it on the SAME rendezvous machinery the training
side already trusts (resilience/heartbeat.py + elastic.py):

  ReplicaMember   one serve replica's end of the liveness protocol: a
                  HeartbeatCoordinator whose beat payload carries the
                  serving truth (url, queue depth, in-flight count,
                  checkpoint sha, drain state). Replicas lease into the
                  rendezvous dir exactly like training hosts; a late
                  replica picks the next id and leases in — the PR 12
                  grow-mid-run path, unchanged.
  Router          reads the leases (receipt-monotonic freshness, the
                  same NTP-step-immune rule view() uses), spreads
                  POST /predict by least queue depth over live
                  non-draining replicas, retries a FAILED dispatch once
                  on a different replica (never a fulfilled one — a
                  response received means no second dispatch), and
                  feeds lease expiry into a real ElasticPolicy: replica
                  failover IS eviction, no new liveness protocol.
  SLOAutoscaler   grow when p99 or queue depth breaches target for K
                  consecutive windows, shrink on sustained idle. Grow
                  is a DECISION (a ``scale`` event + log line an
                  orchestrator acts on by launching a replica that
                  leases itself in); shrink is executed by the router
                  writing drain-<r>.json, which the victim's beat cycle
                  picks up and turns into a graceful drain.
  CanaryController  when live replicas disagree on checkpoint sha
                  (a hot reload rolling out), split traffic by
                  percentage, watch per-sha error/p99 deltas, and
                  auto-rollback — pin traffic to the baseline sha —
                  on SLO breach. The DEPLOY.md flow, executable.

Everything observable flows through three closed-schema events
(``route``/``scale``/``canary``) plus the membership events the policy
already emits, so `sparknet report`/`monitor` render a serving fleet
with zero special cases. Clock/Dir seams are injectable: the same
Router runs against SimClock/MemDir in `sparknet simfleet --serve`
(sim/servefleet.py) and against the wall clock on metal.
"""

import inspect
import json
import threading

from ..obs.tracing import (STAGES_HEADER, TRACE_HEADER, StageReservoir,
                           decode_stages, encode_stages)
from ..resilience.elastic import ElasticPolicy, QuorumLost
from ..resilience.heartbeat import HeartbeatCoordinator
from ..resilience.seam import WALL_CLOCK, RealDir


def _drain_name(replica):
    return f"drain-{int(replica)}.json"


def http_post(url, body, timeout, headers=None):
    """The real dispatch half: POST ``body`` to ``url``/predict.
    Returns (status, payload bytes, None, stages) — stages is the
    replica's echoed X-Sparknet-Stages breakdown ({stage: ms}) or
    None; status -1 means NO response was received (connect refused,
    reset, timeout) — the only case a retry is provably
    safe-or-necessary for."""
    from urllib.error import HTTPError, URLError
    from urllib.request import Request, urlopen
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    try:
        req = Request(url.rstrip("/") + "/predict", data=body,
                      headers=hdrs)
        with urlopen(req, timeout=timeout) as r:
            return (r.status, r.read(), None,
                    decode_stages(r.headers.get(STAGES_HEADER)))
    except HTTPError as e:
        try:
            data = e.read()
        except OSError:
            data = b""
        return e.code, data, None, None
    except (URLError, OSError, TimeoutError):
        return -1, b"", None, None


class ReplicaMember:
    """One serve replica's lease into the fleet rendezvous.

    The beat payload is gathered fresh per beat (every interval_s) from
    the live batcher/engine, so the router's view of queue depth and
    drain state is never older than one heartbeat. The same beat cycle
    polls for the router's drain-<replica>.json order and fires
    ``drain_event`` — the stop_event the serve loop already honors —
    so scale-down rides the existing graceful-drain path."""

    def __init__(self, directory, replica, replicas=None, engine=None,
                 batcher=None, url=None, interval_s=0.5, lease_s=3.0,
                 metrics=None, log_fn=print, clock=None, dirops=None):
        self.replica = int(replica)
        n = max(int(replicas or 0), self.replica + 1)
        self.engine = engine
        self.batcher = batcher
        self.url = url
        self.log = log_fn or (lambda *a: None)
        self.drain_event = threading.Event()
        self.coord = HeartbeatCoordinator(
            directory, host=self.replica, n_hosts=n,
            interval_s=interval_s, lease_s=lease_s, metrics=metrics,
            log_fn=log_fn, clock=clock, dirops=dirops,
            payload_fn=self._payload)

    def _payload(self):
        """The serving fields of this replica's lease record."""
        if not self.drain_event.is_set() and \
                self.coord.dirops.exists(_drain_name(self.replica)):
            self.log(f"serve: drain order for replica {self.replica} "
                     "found in the rendezvous; draining")
            self.drain_event.set()
        st = self.engine.status() if self.engine is not None else {}
        sha = st.get("sha")
        if isinstance(sha, dict):
            # the manifest's sha256 entry is per-file; the MODEL blob
            # sha is the weights identity the canary split keys on
            sha = sha.get("model")
        st = dict(st, sha=sha)
        draining = self.drain_event.is_set() or (
            self.batcher.draining() if self.batcher is not None else False)
        return {"url": self.url,
                "queue_depth": (self.batcher.depth()
                                if self.batcher is not None else 0),
                "in_flight": (self.batcher.pending()
                              if self.batcher is not None else 0),
                "draining": bool(draining),
                "sha": st.get("sha"), "iter": st.get("iter")}

    def start(self, url=None):
        """Lease in (removing any stale drain order a previous
        incarnation of this replica id left behind)."""
        if url is not None:
            self.url = url
        self.coord.dirops.remove(_drain_name(self.replica))
        self.coord.start()
        return self

    def stop(self):
        self.coord.stop()

    def health(self):
        """Lease/membership fields for GET /healthz — the same truth
        the router reads from the beat, so humans and the router can
        never disagree about this replica's state."""
        rec = self.coord.dirops.read_json(
            self.coord._hb_name(self.replica)) or {}
        age = max(0.0, self.coord.clock.time()
                  - float(rec.get("stamp", 0.0))) if rec else None
        return {"replica": self.replica,
                "world": self.coord.n,
                "lease_age_s": None if age is None else round(age, 3),
                "lease_s": self.coord.lease_s,
                "draining": bool(self.drain_event.is_set() or (
                    self.batcher.draining()
                    if self.batcher is not None else False))}


class SLOAutoscaler:
    """Window-hysteresis scaling decisions off the router's own
    measurements. Single-threaded: only the router's window loop calls
    observe()."""

    def __init__(self, p99_ms=500.0, depth=32, windows=3, idle_windows=10,
                 min_replicas=1, max_replicas=8, metrics=None,
                 log_fn=print):
        self.p99_ms = float(p99_ms)
        self.depth = int(depth)
        self.windows = max(1, int(windows))
        self.idle_windows = max(1, int(idle_windows))
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = int(max_replicas)
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self._breach = 0
        self._idle = 0
        self.decisions = []     # [(window, action), ...]

    def observe(self, stats, live):
        """One router window -> None | "grow" | "shrink". ``stats``:
        the router's window_stats() dict; ``live``: live replica
        count."""
        p99 = stats.get("p99_ms")
        depth = stats.get("queue_depth") or 0
        # a paging burn rate (obs/tracing.py BurnRateLedger) is an
        # EARLIER breach signal than the raw p99 gate: the fast window
        # confirms the budget is burning right now, before enough slow
        # windows accumulate for the p99 threshold to trip
        burn_page = (stats.get("burn") or {}).get("alert") == "page"
        breach = (p99 is not None and p99 > self.p99_ms) \
            or depth > self.depth or burn_page
        idle = stats.get("requests", 0) == 0 and depth == 0
        self._breach = self._breach + 1 if breach else 0
        self._idle = self._idle + 1 if idle else 0
        action = reason = None
        if self._breach >= self.windows:
            if live < self.max_replicas:
                action = "grow"
                if p99 is not None and p99 > self.p99_ms:
                    reason = "p99_breach"
                elif depth > self.depth:
                    reason = "depth_breach"
                else:
                    reason = "burn_rate"
            self._breach = 0     # re-arm either way (hysteresis)
        elif self._idle >= self.idle_windows:
            if live > self.min_replicas:
                action, reason = "shrink", "sustained_idle"
            self._idle = 0
        if action is None:
            return None
        self.decisions.append((stats.get("window"), action))
        self.log(f"route: scale {action} ({reason}): live {live}, "
                 f"p99 {p99} ms (target {self.p99_ms:g}), "
                 f"depth {depth} (target {self.depth}) for "
                 f"{self.windows} window(s)")
        if self.metrics is not None:
            self.metrics.log("scale", action=action, reason=reason,
                             live=int(live), p99_ms=p99,
                             queue_depth=int(depth),
                             breach_windows=self.windows,
                             target=(self.max_replicas if action == "grow"
                                     else self.min_replicas))
        return action


class CanaryController:
    """Percentage traffic split across two checkpoint shas with
    auto-rollback. choose()/record() are called from handler threads
    (locked); observe_shas()/evaluate() only from the window loop."""
    # spk: guarded-by-default=_lock

    def __init__(self, pct=20.0, min_requests=20, max_err_delta=0.05,
                 max_p99_delta_ms=500.0, promote_windows=5,
                 metrics=None, log_fn=print):
        self.pct = float(pct)
        self.min_requests = max(1, int(min_requests))
        self.max_err_delta = float(max_err_delta)
        self.max_p99_delta_ms = float(max_p99_delta_ms)
        self.promote_windows = max(1, int(promote_windows))
        self.metrics = metrics       # spk: unguarded (set once, append-only sink)
        self.log = log_fn or (lambda *a: None)   # spk: unguarded (immutable)
        self._lock = threading.Lock()
        self.baseline_sha = None          # spk: guarded-by=_lock
        self.canary_sha = None            # spk: guarded-by=_lock
        self.rolled_back = set()          # spk: guarded-by=_lock
        self._counter = 0                 # spk: guarded-by=_lock
        self._stats = {}                  # spk: guarded-by=_lock
        self._healthy = 0                 # spk: guarded-by=_lock
        self.rollbacks = 0                # spk: guarded-by=_lock

    def _fresh(self):
        return {"ok": 0, "err": 0, "lat": []}

    def observe_shas(self, shas):         # spk: thread-entry
        """Window-loop: the distinct checkpoint shas currently live.
        A second sha starts a canary; the canary sha disappearing ends
        it; the baseline sha disappearing (full rollout done outside
        the canary flow) promotes."""
        ev = None
        with self._lock:
            shas = [s for s in shas if s]
            if self.baseline_sha is None:
                if shas:
                    self.baseline_sha = shas[0]
                return
            if self.baseline_sha not in shas and shas:
                # the old world is gone; whatever serves now is baseline
                self.baseline_sha = self.canary_sha \
                    if self.canary_sha in shas else shas[0]
                self.canary_sha = None
                self._stats = {}
            if self.canary_sha is None:
                cand = [s for s in shas if s != self.baseline_sha
                        and s not in self.rolled_back]
                if cand:
                    self.canary_sha = cand[0]
                    self._stats = {self.baseline_sha: self._fresh(),
                                   self.canary_sha: self._fresh()}
                    self._healthy = 0
                    ev = dict(action="start", sha=self.canary_sha,
                              baseline_sha=self.baseline_sha,
                              pct=self.pct)
            elif self.canary_sha not in shas:
                ev = dict(action="end", sha=self.canary_sha,
                          baseline_sha=self.baseline_sha,
                          reason="sha_gone")
                self.canary_sha = None
        if ev is not None:
            self._emit(**ev)

    def choose(self):                     # spk: thread-entry
        """Preferred sha for the next request, or None (no canary in
        flight). Deterministic stride split: every round(100/pct)-th
        request goes to the canary."""
        with self._lock:
            if self.canary_sha is None or self.pct <= 0:
                return self.baseline_sha if self.rolled_back else None
            self._counter += 1
            stride = max(1, int(round(100.0 / self.pct)))
            if self._counter % stride == 0:
                return self.canary_sha
            return self.baseline_sha

    def record(self, sha, code, latency_ms):   # spk: thread-entry
        """One routed response attributed to the sha that served it."""
        with self._lock:
            st = self._stats.get(sha)
            if st is None:
                return
            if code == 200:
                st["ok"] += 1
                if len(st["lat"]) < 4096:
                    st["lat"].append(float(latency_ms))
            elif code != 429:        # backpressure is not a canary fault
                st["err"] += 1

    def _emit(self, **fields):
        self.log("route: canary " + " ".join(
            f"{k}={v}" for k, v in fields.items()))
        if self.metrics is not None:
            self.metrics.log(
                "canary", action=fields.get("action"),
                sha=fields.get("sha"),
                baseline_sha=fields.get("baseline_sha"),
                pct=fields.get("pct"), reason=fields.get("reason"),
                requests=fields.get("requests"),
                err_rate=fields.get("err_rate"),
                base_err_rate=fields.get("base_err_rate"),
                p99_ms=fields.get("p99_ms"),
                base_p99_ms=fields.get("base_p99_ms"))

    def evaluate(self):                   # spk: thread-entry
        """Window-loop: compare per-sha error rate and p99; rollback on
        breach, promote after promote_windows healthy windows with
        enough canary traffic. Returns "rollback"/"promote"/None."""
        from ..obs.stepstats import percentiles
        ev = verdict = None
        with self._lock:
            if self.canary_sha is None:
                return None
            can = self._stats.get(self.canary_sha, self._fresh())
            base = self._stats.get(self.baseline_sha, self._fresh())
            n_can = can["ok"] + can["err"]
            n_base = base["ok"] + base["err"]
            if n_can < self.min_requests:
                return None
            err_rate = can["err"] / n_can
            base_err = base["err"] / n_base if n_base else 0.0
            p99 = round(percentiles(can["lat"])["p99"], 3) \
                if can["lat"] else None
            base_p99 = round(percentiles(base["lat"])["p99"], 3) \
                if base["lat"] else None
            breach = err_rate - base_err > self.max_err_delta
            if p99 is not None and base_p99 is not None:
                breach = breach or \
                    (p99 - base_p99 > self.max_p99_delta_ms)
            fields = dict(sha=self.canary_sha,
                          baseline_sha=self.baseline_sha,
                          requests=n_can, err_rate=round(err_rate, 4),
                          base_err_rate=round(base_err, 4), p99_ms=p99,
                          base_p99_ms=base_p99, pct=self.pct)
            if breach:
                verdict = "rollback"
                self.rolled_back.add(self.canary_sha)
                self.rollbacks += 1
                self.canary_sha = None
                self._stats = {}
                ev = dict(action="rollback",
                          reason=("err_delta" if err_rate - base_err
                                  > self.max_err_delta else "p99_delta"),
                          **fields)
            else:
                self._healthy += 1
                if self._healthy >= self.promote_windows:
                    verdict = "promote"
                    self.baseline_sha = self.canary_sha
                    self.canary_sha = None
                    self._stats = {}
                    ev = dict(action="promote", reason="slo_healthy",
                              **fields)
        if ev is not None:
            if ev["action"] == "rollback":
                # the greppable contract line (DEPLOY.md runbook)
                self.log(f"route: canary_rollback sha={ev['sha']} — "
                         "traffic pinned to baseline "
                         f"{ev['baseline_sha']}")
            self._emit(**ev)
        return verdict

    def pinned_sha(self):                 # spk: thread-entry
        """The sha dispatch must prefer after a rollback (None before
        any rollback — normal least-depth routing)."""
        with self._lock:
            return self.baseline_sha if self.rolled_back else None

    def summary(self):                    # spk: thread-entry
        with self._lock:
            return {"baseline_sha": self.baseline_sha,
                    "canary_sha": self.canary_sha, "pct": self.pct,
                    "rollbacks": self.rollbacks,
                    "rolled_back": sorted(self.rolled_back)}


class Router:
    """The routing tier: lease-derived membership + least-queue-depth
    dispatch + retry-once failover.

    Thread contract: HTTP handler threads call dispatch()/status()/
    stats_snapshot(); the single window loop calls poll()/
    window_stats()/request_drain(). The lease table and counters are
    guarded by ``_lock``; the ElasticPolicy is touched ONLY from the
    window loop (poll), so membership transitions never race dispatch —
    dispatch reads the lease snapshot, which is what actually gates
    traffic."""

    def __init__(self, directory, replicas=1, lease_s=3.0, quorum=1,
                 canary=None, metrics=None, log_fn=print, clock=None,
                 dirops=None, post_fn=None, retry=True, tracer=None,
                 slo=None):
        self.dir = str(directory)
        self.clock = WALL_CLOCK if clock is None else clock
        self.dirops = RealDir(self.dir) if dirops is None else dirops
        self.lease_s = float(lease_s)
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self.post_fn = http_post if post_fn is None else post_fn
        self.retry = bool(retry)
        self.canary = canary
        # request tracing (obs/tracing.py): the router mints the trace
        # id, closes the loop on the replica's echoed stage breakdown
        # (net = total − server-reported), and keeps per-stage
        # reservoirs for /metrics and the p99 decomposition. ``slo``
        # is an optional BurnRateLedger fed from dispatch outcomes.
        self.tracer = tracer
        self.slo = slo
        self.stages = StageReservoir()
        try:
            params = inspect.signature(self.post_fn).parameters
            self._post_headers = "headers" in params or any(
                p.kind == inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        except (TypeError, ValueError):
            self._post_headers = False
        self.policy = ElasticPolicy(
            n_workers=max(1, int(replicas)), quorum=max(1, int(quorum)),
            evict_after=1, readmit_after=0, metrics=metrics,
            log_fn=log_fn, unit="replica")
        self.quorum_lost = False
        self._t0_mono = self.clock.monotonic()
        self._window = 0
        self._lock = threading.Lock()
        self._leases = {}                 # spk: guarded-by=_lock
        self._seen = {}                   # spk: guarded-by=_lock
        self._inflight = {}               # spk: guarded-by=_lock
        self._sent = {}                   # spk: guarded-by=_lock
        self._win_lat = []                # spk: guarded-by=_lock
        self._win_reqs = 0                # spk: guarded-by=_lock
        self._win_errs = 0                # spk: guarded-by=_lock
        self._rr = 0                      # spk: guarded-by=_lock
        self._trace_n = 0                 # spk: guarded-by=_lock
        self._by_replica = {}             # spk: guarded-by=_lock
        self.requests = 0                 # spk: guarded-by=_lock
        self.ok = 0                       # spk: guarded-by=_lock
        self.rejected = 0                 # spk: guarded-by=_lock
        self.errors = 0                   # spk: guarded-by=_lock
        self.retries = 0                  # spk: guarded-by=_lock
        self.no_replica = 0               # spk: guarded-by=_lock

    # -- membership (window loop only) --------------------------------------
    def poll(self):
        """Refresh the lease table and drive the ElasticPolicy:
        an expired lease is an eviction (reason lease_expired), a fresh
        lease from an unknown or evicted id is an admission (the PR 12
        grow path / a rejoin). Returns the live replica ids."""
        mono = self.clock.monotonic()
        wall = self.clock.time()
        recs = {}
        for name in self.dirops.glob("hb-*.json"):
            rec = self.dirops.read_json(name)
            if rec is not None and isinstance(rec.get("host"), int):
                recs[rec["host"]] = rec
        fresh = {}
        with self._lock:
            for r, rec in recs.items():
                key = (rec.get("seq"), rec.get("stamp"))
                seen = self._seen.get(r)
                if seen is None or seen[0] != key:
                    # receipt-monotonic freshness, seeded from the wall
                    # stamp on first sight so a ghost lease reads old
                    init = max(0.0, wall - float(rec.get("stamp", 0.0))) \
                        if seen is None else 0.0
                    seen = (key, mono, init)
                    self._seen[r] = seen
                    # a fresh beat carries a fresh queue_depth: what we
                    # dispatched since the previous beat is now counted
                    # in it, so the local correction resets
                    self._sent.pop(r, None)
                age = seen[2] + (mono - seen[1])
                if age <= self.lease_s:
                    fresh[r] = rec
            self._leases = dict(fresh)
            for r in list(self._seen):
                if r not in recs:
                    self._seen.pop(r)     # reaped/removed lease file
            self._window += 1
            w = self._window
        grace = mono - self._t0_mono <= self.lease_s
        for r in self.policy.live():
            if r not in fresh and not grace:
                try:
                    self.policy.evict(r, w, "lease_expired")
                except QuorumLost:
                    # a routing tier with zero capacity serves 503s —
                    # it does not exit; capacity can lease back in
                    self.quorum_lost = True
        for r in sorted(fresh):
            if r >= self.policy.n:
                self.policy.admit(r, w, via="grow")
                self.quorum_lost = False
            elif not self.policy.alive[r]:
                self.policy.admit(r, w, via="rejoin")
                self.quorum_lost = False
        if self.quorum_lost and \
                all(r in fresh for r in self.policy.live()):
            # the eviction that tripped quorum was REFUSED (the policy
            # raises before marking dead), so a returning beat shows up
            # as an already-live replica, not an admission: fresh
            # leases under every live id mean capacity is back
            self.quorum_lost = False
            self.log("route: capacity leased back in; quorum restored")
        if self.canary is not None:
            live = set(self.policy.live())
            self.canary.observe_shas(sorted(
                {rec.get("sha") for r, rec in fresh.items()
                 if r in live and rec.get("sha")}))
        return self.policy.live()

    def request_drain(self, replica=None):
        """Order a replica to drain (scale-down): write the drain file
        its beat cycle polls. Default victim: the highest live
        non-draining replica. Returns the victim id or None."""
        if replica is None:
            with self._lock:
                cands = [r for r, rec in self._leases.items()
                         if not rec.get("draining")]
            replica = max(cands) if cands else None
        if replica is None:
            return None
        self.dirops.write_json(_drain_name(replica), {
            "replica": int(replica), "stamp": self.clock.time()})
        self.log(f"route: drain ordered for replica {replica}")
        return int(replica)

    # -- dispatch (handler threads) ----------------------------------------
    def pick(self, exclude=(), sha=None):
        """Least-queue-depth live, non-draining replica (advertised
        depth plus this router's own in-flight count toward it — the
        advertised number is up to one heartbeat stale). ``sha``
        restricts to replicas serving that checkpoint."""
        with self._lock:
            leases = dict(self._leases)
            inflight = dict(self._inflight)
            sent = dict(self._sent)
        live = set(self.policy.live())
        cands = []
        for r, rec in leases.items():
            if r in exclude or r not in live or rec.get("draining") \
                    or not rec.get("url"):
                continue
            if sha is not None and rec.get("sha") != sha:
                continue
            depth = int(rec.get("queue_depth") or 0) \
                + int(rec.get("in_flight") or 0) + inflight.get(r, 0) \
                + sent.get(r, 0)
            cands.append((depth, r, rec))
        if not cands:
            return None
        best = min(c[0] for c in cands)
        mins = sorted(c for c in cands if c[0] == best)
        # round-robin among equal depths: advertised depth is up to one
        # heartbeat stale, so a fixed tie-break would herd every
        # dispatch in the window onto one replica
        with self._lock:
            self._rr += 1
            rr = self._rr
        _, r, rec = mins[rr % len(mins)]
        return r, rec.get("url"), rec.get("sha")

    def dispatch(self, body, timeout=30.0, want_headers=False):
        """Route one POST /predict body. Returns (status, payload
        bytes) — or (status, payload, echo-headers dict) with
        ``want_headers`` so the router front end can re-echo the
        replica's stage breakdown to the client. Transport failure (no
        response) retries ONCE on a different replica; any received
        response — including errors — is final (a fulfilled request is
        never doubled). No live non-draining replica -> 503
        immediately, never a hang.

        Mints one trace id per request and propagates it to every
        attempt via the X-Sparknet-Trace header (value
        "<id>;<attempt>" — retries share the id); collects one span
        per attempt plus the replica's echoed stage breakdown so a
        traced request attributes its milliseconds end to end."""
        t0 = self.clock.monotonic()
        with self._lock:
            self._trace_n += 1
            trace = f"t{self._trace_n:08x}"
        want_sha = self.canary.choose() if self.canary is not None \
            else None
        tried = []
        spans = []
        code, data, replica, sha = -1, b"", None, None
        sim_lat_ms = None
        stages_resp = None
        for attempt in (1, 2):
            picked = self.pick(exclude=tried, sha=want_sha)
            if picked is None and want_sha is not None:
                # no replica on the preferred sha: availability beats
                # the split — fall back to any live replica
                picked = self.pick(exclude=tried)
            if picked is None:
                break
            replica, url, sha = picked
            tried.append(replica)
            with self._lock:
                self._inflight[replica] = \
                    self._inflight.get(replica, 0) + 1
                self._sent[replica] = self._sent.get(replica, 0) + 1
                self._by_replica[replica] = \
                    self._by_replica.get(replica, 0) + 1
            att0 = self.clock.monotonic()
            try:
                # post_fn may return (code, body) — the legacy HTTP
                # transport shape — (code, body, latency_ms) from a
                # simulated replica (sim/servefleet.py) whose service
                # time is computed, not slept, or (code, body,
                # latency_ms, stages) when the replica echoes its
                # stage breakdown
                if self._post_headers:
                    res = self.post_fn(
                        url, body, timeout,
                        headers={TRACE_HEADER: f"{trace};{attempt}"})
                else:
                    res = self.post_fn(url, body, timeout)
                code, data = res[0], res[1]
                att_lat = None
                if len(res) > 2 and res[2] is not None:
                    sim_lat_ms = att_lat = float(res[2])
                if len(res) > 3:
                    stages_resp = res[3]
            finally:
                with self._lock:
                    n = self._inflight.get(replica, 1) - 1
                    if n <= 0:
                        self._inflight.pop(replica, None)
                    else:
                        self._inflight[replica] = n
            if att_lat is None:
                att_lat = (self.clock.monotonic() - att0) * 1e3
            spans.append({"replica": int(replica), "code": int(code),
                          "start_ms": round((att0 - t0) * 1e3, 3),
                          "dur_ms": round(att_lat, 3)})
            if code == 200 or not self.retry:
                break
            if code not in (-1, 429):
                break       # a response arrived: final, never re-sent
        latency_ms = sim_lat_ms if sim_lat_ms is not None \
            else (self.clock.monotonic() - t0) * 1e3
        retried = len(tried) > 1
        if not tried:
            code, data = 503, json.dumps(
                {"error": "no live replica",
                 "reason": "all_draining_or_dead"}).encode("utf-8")
        elif code == -1:
            code, data = 503, json.dumps(
                {"error": f"replica {replica} unreachable",
                 "reason": "replica_unreachable"}).encode("utf-8")
        with self._lock:
            self.requests += 1
            self._win_reqs += 1
            if code == 200:
                self.ok += 1
                if len(self._win_lat) < 65536:
                    self._win_lat.append(latency_ms)
            elif code == 429:
                self.rejected += 1
            else:
                self.errors += 1
                self._win_errs += 1
            if retried:
                self.retries += 1
            if not tried:
                self.no_replica += 1
        # close the tracing loop: net = router total − server-reported
        server_ms = net_ms = None
        stg = None
        if code == 200 and stages_resp:
            server_ms = stages_resp.get("total")
            if server_ms is not None:
                net_ms = max(0.0, latency_ms - float(server_ms))
            stg = {"net": net_ms,
                   "queue": stages_resp.get("queue"),
                   "batch": stages_resp.get("batch"),
                   "infer": stages_resp.get("infer"),
                   "fulfill": stages_resp.get("fulfill"),
                   "total": latency_ms}
            self.stages.add(stg)
        if self.slo is not None:
            self.slo.record(self.clock.monotonic(),
                            self.slo.good(code, latency_ms))
        if self.canary is not None and sha is not None:
            self.canary.record(sha, code, latency_ms)
        if self.metrics is not None:
            self.metrics.log("route", replica=replica, code=int(code),
                             attempts=len(tried), retried=retried,
                             latency_ms=round(latency_ms, 3), sha=sha)
            verdict = self.tracer.decide(latency_ms) \
                if self.tracer is not None else None
            if verdict is not None:
                rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
                self.metrics.log(
                    "serve_trace", src="router", trace=trace,
                    replica=replica, code=int(code),
                    attempts=len(tried), retried=retried,
                    total_ms=round(latency_ms, 3),
                    server_ms=rnd(server_ms), net_ms=rnd(net_ms),
                    queue_ms=rnd(stg["queue"]) if stg else None,
                    batch_ms=rnd(stg["batch"]) if stg else None,
                    infer_ms=rnd(stg["infer"]) if stg else None,
                    fulfill_ms=rnd(stg["fulfill"]) if stg else None,
                    tail=verdict == "tail", spans=spans)
        if want_headers:
            echo = {TRACE_HEADER: trace}
            if stages_resp:
                echo[STAGES_HEADER] = encode_stages(stages_resp)
            return code, data, echo
        return code, data

    # -- observation --------------------------------------------------------
    def window_stats(self):
        """Swap out and summarize this window's dispatch measurements
        (window loop only); feeds the SLO autoscaler."""
        from ..obs.stepstats import percentiles
        with self._lock:
            lats, self._win_lat = self._win_lat, []
            reqs, self._win_reqs = self._win_reqs, 0
            errs, self._win_errs = self._win_errs, 0
            depth = max((int(rec.get("queue_depth") or 0)
                         + int(rec.get("in_flight") or 0)
                         for rec in self._leases.values()), default=0)
            w = self._window
        out = {"window": w, "requests": reqs, "errors": errs,
               "queue_depth": depth,
               "p99_ms": (round(percentiles(lats)["p99"], 3)
                          if lats else None)}
        if self.slo is not None:
            # evaluated once per window (not per request) so the
            # slo_burn event volume rides the window cadence; the
            # autoscaler reads the verdict as an earlier breach signal
            out["burn"] = self.slo.evaluate(self.clock.monotonic())
        return out

    def stats_snapshot(self):             # spk: thread-entry
        with self._lock:
            by_rep = dict(self._by_replica)
            out = {"requests": self.requests, "ok": self.ok,
                   "rejected": self.rejected, "errors": self.errors,
                   "retries": self.retries,
                   "no_replica": self.no_replica,
                   "live": self.policy.live_count()}
        out["retry_rate"] = round(out["retries"]
                                  / out["requests"], 4) \
            if out["requests"] else 0.0
        total = sum(by_rep.values())
        out["dispatch_share"] = {
            str(r): round(n / total, 4)
            for r, n in sorted(by_rep.items())} if total else {}
        out["stages"] = self.stages.snapshot()
        if self.slo is not None:
            out["slo_burn"] = self.slo.snapshot()
        return out

    def status(self):                     # spk: thread-entry
        """GET /healthz: the router's membership truth."""
        with self._lock:
            leases = {r: dict(rec) for r, rec in self._leases.items()}
            w = self._window
        out = {"status": "ok", "window": w,
               "live": self.policy.live(), "world": self.policy.n,
               "quorum_lost": self.quorum_lost,
               "replicas": {str(r): {
                   k: rec.get(k) for k in
                   ("url", "queue_depth", "in_flight", "draining",
                    "sha", "iter", "round")} for r, rec in
                   sorted(leases.items())}}
        out["stages_p99"] = self.stages.p99()
        if self.slo is not None:
            out["slo_burn"] = self.slo.snapshot()
        if self.canary is not None:
            out["canary"] = self.canary.summary()
        return out


def _make_router_handler(router, timeout_s):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # quiet access log
            pass

        def _send(self, code, body, ctype="application/json",
                  headers=None):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code, obj):
            self._send(code, json.dumps(obj).encode("utf-8"))

        def do_GET(self):
            if self.path == "/healthz":
                st = router.status()
                # loadgen discovers feed shapes through the router:
                # proxy a baseline replica's /healthz feeds — during a
                # canary (or after a rollback) an idle canary replica
                # may be the least-loaded one, and advertising its
                # shapes would steer every client into the minority
                # (or rolled-back) model
                want = None
                if router.canary is not None:
                    want = router.canary.pinned_sha() or \
                        router.canary.summary()["baseline_sha"]
                picked = router.pick(sha=want) if want is not None \
                    else None
                if picked is None:
                    picked = router.pick()
                if picked is not None:
                    try:
                        from urllib.request import urlopen
                        with urlopen(picked[1].rstrip("/") + "/healthz",
                                     timeout=timeout_s) as r:
                            rep = json.loads(r.read())
                        for k in ("feeds", "buckets", "iter", "model"):
                            if k in rep:
                                st[k] = rep[k]
                    except (OSError, ValueError):
                        pass
                self._send_json(200, st)
            elif self.path == "/metrics":
                self._send_json(200, router.stats_snapshot())
            else:
                self._send_json(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path != "/predict":
                self._send_json(404, {"error": "unknown path"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            code, data, hdrs = router.dispatch(
                body, timeout=timeout_s, want_headers=True)
            self._send(code, data, headers=hdrs)

    return Handler


def route_http(router, autoscaler=None, host="127.0.0.1", port=0,
               window_s=1.0, policy=None, stop_event=None,
               request_timeout_s=30.0, max_windows=None, log_fn=print):
    """Bind the router front end, run the membership/SLO window loop
    until a stop signal, drain, return 0 — the same supervisor
    contract `sparknet serve` honors."""
    from http.server import ThreadingHTTPServer
    log = log_fn or (lambda *a: None)
    handler = _make_router_handler(router, request_timeout_s)
    httpd = ThreadingHTTPServer((host, int(port)), handler)
    httpd.daemon_threads = True
    addr = f"http://{httpd.server_address[0]}:{httpd.server_address[1]}"
    live = router.poll()
    log(f"sparknet route: listening on {addr} ({len(live)} replica(s) "
        f"live of world {router.policy.n}, lease {router.lease_s:g}s)")
    import sys
    sys.stdout.flush()      # the announce line gates smoke/loadgen start
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    windows = 0
    try:
        while True:
            action = policy.pending() if policy is not None else None
            if action is not None and "stop" in action:
                log("route: stop requested; draining")
                break
            if stop_event is not None and stop_event.is_set():
                break
            if max_windows is not None and windows >= max_windows:
                break
            router.clock.sleep(window_s)
            router.poll()
            stats = router.window_stats()
            if autoscaler is not None:
                decision = autoscaler.observe(
                    stats, live=router.policy.live_count())
                if decision == "shrink":
                    router.request_drain()
            if router.canary is not None:
                router.canary.evaluate()
            windows += 1
    finally:
        httpd.shutdown()
        httpd.server_close()
    snap = router.stats_snapshot()
    log(f"route: drained cleanly after {snap['requests']} request(s) "
        f"({snap['ok']} ok, {snap['rejected']} rejected, "
        f"{snap['errors']} errors, {snap['retries']} retried); "
        "exiting 0")
    return 0
