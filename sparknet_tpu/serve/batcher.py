"""Continuous batching with deadline flush and backpressure.

HTTP handler threads submit() requests; the single serve-loop thread
pulls next_batch(). Batching policy (the Orca/clipper recipe):

* a batch closes as soon as max_batch rows are queued, OR when the
  OLDEST queued request has waited max_wait — so p99 at low load is
  bounded by max_wait instead of starving for a full batch;
* the queue is bounded: a submit() that finds queue_limit rows already
  waiting is rejected immediately (RejectedError -> HTTP 429 + a
  serve_reject event) instead of building an unbounded latency tail —
  backpressure the supervisor/load-balancer can see;
* drain(): close() rejects new arrivals while next_batch() keeps
  returning whatever is queued, so SIGTERM finishes in-flight work.

Lock discipline is annotation-checked (`sparknet lint` SPK201-207):
shared fields are guarded by the Condition's lock, and metrics events
are emitted OUTSIDE it (emitting does file I/O; SPK206).
"""

import collections
import threading
import time


class RejectedError(RuntimeError):
    """Queue full (or draining) — the 429 of the serving tier."""

    def __init__(self, reason, queue_depth, limit):
        super().__init__(
            f"request rejected ({reason}): queue {queue_depth}/{limit}")
        self.reason = reason
        self.queue_depth = queue_depth
        self.limit = limit


class Request:
    """One submitted request: input arrays + a completion event the
    handler thread waits on. ``result``/``error`` are written by the
    serve loop strictly before ``done.set()``, and only read after
    ``done.wait()`` returns — the Event is the fence."""

    __slots__ = ("arrays", "n", "t_submit", "done", "result", "error",
                 "t_done", "bucket", "trace", "t_enq", "t_dispatch",
                 "t_fwd0", "t_fwd1")

    def __init__(self, arrays, n, trace=None):
        self.arrays = arrays
        self.n = int(n)
        self.t_submit = time.monotonic()   # admission stamp
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.t_done = None
        self.bucket = None
        # request-tracing fields (obs/tracing.py): the router-minted
        # trace id plus per-stage monotonic stamps. Each stamp is
        # written by exactly one thread strictly before done.set().
        self.trace = trace
        self.t_enq = None        # queued in the batcher
        self.t_dispatch = None   # popped into a batch
        self.t_fwd0 = None       # engine.forward started
        self.t_fwd1 = None       # engine.forward returned

    def wait(self, timeout=None):
        return self.done.wait(timeout)


class Batcher:
    def __init__(self, max_batch=8, max_wait_s=0.005, queue_limit=64,
                 metrics=None):
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self.metrics = metrics
        self._cv = threading.Condition()
        self._q = collections.deque()     # spk: guarded-by=_cv
        self._rows = 0                    # spk: guarded-by=_cv
        self._closed = False              # spk: guarded-by=_cv
        self._submitted = 0               # spk: guarded-by=_cv
        self._rejected = 0                # spk: guarded-by=_cv

    def submit(self, arrays, n=1, trace=None):  # spk: thread-entry
        """Queue one request from a handler thread; returns the Request
        to wait on, or raises RejectedError when over queue_limit or
        draining (emitting the serve_reject event)."""
        req = Request(arrays, n, trace=trace)
        reject = None
        with self._cv:
            if self._closed:
                # distinct from queue_full so a router (serve/fleet.py)
                # and `sparknet report` can tell planned drain from
                # overload backpressure
                reject = ("replica_draining", self._rows)
            elif self._rows + req.n > self.queue_limit:
                reject = ("queue_full", self._rows)
            else:
                self._submitted += 1
                req.t_enq = time.monotonic()
                self._q.append(req)
                self._rows += req.n
                self._cv.notify()
        if reject is not None:
            reason, depth = reject
            with self._cv:
                self._rejected += 1
            if self.metrics is not None:
                self.metrics.log("serve_reject", reason=reason,
                                 queue_depth=depth,
                                 limit=self.queue_limit)
            raise RejectedError(reason, depth, self.queue_limit)
        return req

    def next_batch(self, timeout=0.05):
        """Serve-loop side: block up to ``timeout`` for work, then
        apply the close-on-full / close-on-deadline policy. Returns
        (requests, wait_ms) — possibly ([], 0.0) so the caller can poll
        signals and reload between batches."""
        with self._cv:
            if not self._q:
                self._cv.wait(timeout)
                if not self._q:
                    return [], 0.0
            while self._rows < self.max_batch:
                oldest = self._q[0].t_submit
                remain = self.max_wait_s - (time.monotonic() - oldest)
                if remain <= 0:
                    break
                self._cv.wait(remain)
                if not self._q:
                    return [], 0.0
            out, rows = [], 0
            while self._q and rows + self._q[0].n <= self.max_batch:
                req = self._q.popleft()
                out.append(req)
                rows += req.n
            if not out and self._q:
                # single request wider than max_batch can never fit
                req = self._q.popleft()
                out.append(req)
                rows = req.n
            self._rows -= rows
        now = time.monotonic()
        for req in out:
            req.t_dispatch = now     # queue -> batch stage boundary
        wait_ms = (now - out[0].t_submit) * 1e3 if out else 0.0
        return out, wait_ms

    def depth(self):                      # spk: thread-entry
        """Queued rows right now (handler threads read this for
        /metrics)."""
        with self._cv:
            return self._rows

    def counters(self):                   # spk: thread-entry
        with self._cv:
            return {"submitted": self._submitted,
                    "rejected": self._rejected}

    def close(self):
        """Stop accepting new requests (drain mode); wakes the loop."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def draining(self):                   # spk: thread-entry
        """True once close() ran — surfaced on /healthz and in the
        replica's lease payload so the router stops picking this
        replica within one beat."""
        with self._cv:
            return self._closed

    def pending(self):
        """Requests still queued (the drain loop runs until zero)."""
        with self._cv:
            return len(self._q)
