"""Closed- and open-loop load generation against a `sparknet serve`
endpoint (`sparknet serve-bench`).

Closed loop — N workers each keep exactly one request in flight:
measures the server's capacity (throughput at full pipeline). Open
loop — requests arrive on a fixed-rate clock REGARDLESS of completions
(the honest way to measure latency under load: a closed loop slows its
own arrival rate when the server stalls, hiding the tail — the
coordinated-omission trap). Both emit `bench` rows through the metrics
stream, so serve latency lands in the same stream bench.py writes.
"""

import json
import threading
import time

import numpy as np

from ..obs.tracing import STAGES_HEADER, decode_stages


def _discover(url, timeout=5.0):
    """GET /healthz -> feed shapes the payload must match."""
    from urllib.request import urlopen
    with urlopen(url.rstrip("/") + "/healthz", timeout=timeout) as r:
        return json.loads(r.read())


def _make_payload(feeds, rows, seed=0):
    rs = np.random.RandomState(seed)
    body = {}
    for name, per in feeds.items():
        if "label" in name or not per:
            continue              # labels zero-fill server-side
        body[name] = rs.randn(rows, *per).round(4).tolist()
    if not body:                  # label-only nets still need one feed
        name, per = next(iter(feeds.items()))
        body[name] = rs.randint(0, 10, (rows, *per)).tolist()
    return json.dumps(body).encode("utf-8")


class _Recorder:
    # spk: guarded-by-default=_lock
    def __init__(self):
        self._lock = threading.Lock()
        self.lat_ms = []
        self.srv_ms = []    # server-attributed (echoed stage header)
        self.net_ms = []    # client-observed minus server-attributed
        self.ok = 0
        self.rejected = 0
        self.errors = 0
        self.dropped = 0

    def add(self, code, ms, server_ms=None):  # spk: thread-entry
        with self._lock:
            if code == 200:
                self.ok += 1
                self.lat_ms.append(ms)
                if server_ms is not None:
                    self.srv_ms.append(float(server_ms))
                    self.net_ms.append(
                        max(0.0, ms - float(server_ms)))
            elif code == 429:
                self.rejected += 1
            else:
                self.errors += 1

    def drop(self):                       # spk: thread-entry
        with self._lock:
            self.dropped += 1

    def summary(self):
        from ..obs.stepstats import percentiles
        with self._lock:
            lats = list(self.lat_ms)
            srv = list(self.srv_ms)
            net = list(self.net_ms)
            out = {"ok": self.ok, "rejected": self.rejected,
                   "errors": self.errors, "dropped": self.dropped}
        out["requests"] = out["ok"] + out["rejected"] + out["errors"]
        if lats:
            out.update({f"latency_ms_{k}": round(v, 3)
                        for k, v in percentiles(lats).items()})
            out["latency_ms_mean"] = round(float(np.mean(lats)), 3)
            out["latency_ms_max"] = round(float(np.max(lats)), 3)
        if srv:
            # server-attributed vs network/client share: when these
            # disagree with the client-observed numbers, the missing
            # milliseconds are on the wire or in the client, not in
            # the server's batcher/forward path
            out.update({f"server_ms_{k}": round(v, 3)
                        for k, v in percentiles(srv).items()})
            out.update({f"net_ms_{k}": round(v, 3)
                        for k, v in percentiles(net).items()})
        return out


def _fire(url, payload, rec, timeout):
    from urllib.request import urlopen, Request
    from urllib.error import HTTPError, URLError
    t0 = time.perf_counter()
    server_ms = None
    try:
        req = Request(url.rstrip("/") + "/predict", data=payload,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=timeout) as r:
            code = r.status
            r.read()
            stg = decode_stages(r.headers.get(STAGES_HEADER))
            if stg:
                server_ms = stg.get("total")
    except HTTPError as e:
        code = e.code
        e.read()
    except (URLError, OSError, TimeoutError):
        code = -1
    rec.add(code, (time.perf_counter() - t0) * 1e3,
            server_ms=server_ms)


def run_loadgen(url, mode="closed", concurrency=4, rate=50.0,
                duration_s=5.0, rows=1, seed=0, timeout=10.0,
                metrics=None, log_fn=print):
    """One load-generation run -> summary dict (also printed and, with
    ``metrics``, emitted as a `bench` row)."""
    log = log_fn or (lambda *a: None)
    health = _discover(url, timeout=timeout)
    feeds = {k: tuple(v) for k, v in (health.get("feeds") or {}).items()}
    payload = _make_payload(feeds, rows, seed=seed)
    rec = _Recorder()
    t_start = time.perf_counter()
    if mode == "closed":
        stop = time.perf_counter() + duration_s

        def worker():
            while time.perf_counter() < stop:
                _fire(url, payload, rec, timeout)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(int(concurrency))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    elif mode == "open":
        # fixed-rate arrivals; a bounded dispatch pool so a stalled
        # server surfaces as drops, not an unbounded thread pile-up
        gate = threading.Semaphore(max(4 * int(concurrency), 64))
        period = 1.0 / float(rate)
        next_t = time.perf_counter()
        end = next_t + duration_s
        live = []
        while True:
            now = time.perf_counter()
            if now >= end:
                break
            if now < next_t:
                time.sleep(min(next_t - now, 0.01))
                continue
            next_t += period
            if not gate.acquire(blocking=False):
                rec.drop()
                continue

            def one():
                try:
                    _fire(url, payload, rec, timeout)
                finally:
                    gate.release()

            t = threading.Thread(target=one, daemon=True)
            t.start()
            live.append(t)
        for t in live:
            t.join(timeout)
    else:
        raise ValueError(f"unknown loadgen mode {mode!r}")
    wall = time.perf_counter() - t_start
    out = rec.summary()
    out.update({"mode": mode, "rows": rows, "duration_s": round(wall, 3),
                "url": url})
    out["rps"] = round(out["ok"] / wall, 2) if wall > 0 else None
    if mode == "closed":
        out["concurrency"] = int(concurrency)
    else:
        out["offered_rps"] = float(rate)
    log(f"serve-bench[{mode}]: {out['ok']} ok / "
        f"{out['rejected']} rejected / {out['errors']} errors in "
        f"{out['duration_s']}s -> {out['rps']} req/s, "
        f"p50={out.get('latency_ms_p50')} "
        f"p95={out.get('latency_ms_p95')} "
        f"p99={out.get('latency_ms_p99')} ms")
    if "server_ms_p99" in out:
        log(f"serve-bench[{mode}]: server share "
            f"p50={out['server_ms_p50']} p95={out['server_ms_p95']} "
            f"p99={out['server_ms_p99']} ms; network/client "
            f"p50={out['net_ms_p50']} p95={out['net_ms_p95']} "
            f"p99={out['net_ms_p99']} ms")
    if metrics is not None:
        metrics.log("bench", kind="serve", **out)
    return out
