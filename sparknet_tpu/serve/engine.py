"""Weights-only serving engine over resilient checkpoints.

The training side writes sha256-stamped atomic manifests
(resilience/checkpoint.py); the engine consumes them through
load_model_only — the optimizer-state file is never read, so a
snapshot whose .solverstate was pruned or torn still serves.

The forward path is a TEST-phase CompiledNet jitted once PER PADDING
BUCKET (powers of two up to --max_batch): every request batch is
padded up to the nearest bucket, so the jit cache holds at most
log2(max_batch)+1 entries no matter what batch sizes arrive — the
same bounded-recompile discipline `sparknet lint` SPK102 enforces on
training feeds. The jit takes (params, state, batch) and returns only
the output blobs — params flow in every call and are reused, which is
exactly the eval shape SPK105 exempts from donation.

Hot reload: poll_reload() re-reads `<prefix>.latest.json` between
batches; when the manifest names a newer model blob that verifies, the
new weights are loaded OFF the serving path and swapped in under the
status lock as one reference assignment — in-flight batches keep the
params they captured, later batches see the new ones, and a torn or
corrupt manifest/blob keeps the old weights serving.
"""

import time

import numpy as np


def bucket_sizes(max_batch):
    """Powers-of-two padding buckets, max_batch always included last."""
    sizes, b = [], 1
    while b < int(max_batch):
        sizes.append(b)
        b *= 2
    sizes.append(int(max_batch))
    return sizes


def bucket_for(n, sizes):
    """Smallest bucket >= n, or None when n exceeds the largest."""
    for b in sizes:
        if n <= b:
            return b
    return None


def _feed_dtype(name, shape):
    if len(shape) <= 1 or "label" in name:
        return np.int32
    return np.float32


_FEED_TYPES = ("JavaData", "Data", "DummyData", "Input", "MemoryData",
               "HDF5Data", "ImageData", "WindowData")


def deploy_net_param(net_param):
    """Train prototxt -> deploy net: drop loss/accuracy layers (their
    logit bottoms become net outputs — the blobs /predict returns) and
    feed layers nothing consumes afterwards (the label feed). A net
    that is already deploy-shaped passes through unchanged."""
    np_ = net_param.copy()
    kept = [lp for lp in np_.layer
            if "loss" not in lp.type.lower()
            and "accuracy" not in lp.type.lower()]
    used = set()
    for lp in kept:
        used.update(str(b) for b in lp.bottom)
    kept = [lp for lp in kept
            if lp.type not in _FEED_TYPES or not len(lp.top)
            or any(str(t) in used for t in lp.top)]
    np_.layer.clear()
    for lp in kept:
        np_.layer.append(lp)
    return np_


class ServeEngine:
    def __init__(self, prefix, net_param=None, max_batch=8,
                 metrics=None, log_fn=print):
        import threading
        self.prefix = prefix
        self.max_batch = int(max_batch)
        self.buckets = bucket_sizes(self.max_batch)
        self.metrics = metrics
        self.log = log_fn or (lambda *a: None)
        self._net_param = net_param       # template; None -> from checkpoint
        self._lock = threading.Lock()
        self._params = None               # spk: guarded-by=_lock
        self._state = None                # spk: guarded-by=_lock
        self._loaded = None               # spk: guarded-by=_lock
        self._reloads = 0                 # spk: guarded-by=_lock
        self._nets = {}                   # bucket -> CompiledNet (serve thread)
        self._fwd = {}                    # bucket -> jitted forward
        self._base = None                 # probe net: shapes + weight loading
        self._base_shapes = None          # feed blob -> full-batch shape

    # -- loading -----------------------------------------------------------

    def load(self):
        """Initial weights-only load; raises ValueError (naming the
        manifest) when no servable model blob exists."""
        from ..resilience import checkpoint
        model_path, entry = checkpoint.load_model_only(
            self.prefix, log_fn=self.log)
        params, state = self._load_params(model_path)
        with self._lock:
            self._params, self._state = params, state
            self._loaded = entry
        self.log(f"serve: loaded iter {entry.get('iter')} "
                 f"from {model_path}")
        return entry

    def _load_params(self, model_path):
        """(params, state) from one model blob. Builds the probe net on
        first use — for binaryproto checkpoints the blob is a full
        NetParameter, so no --model prototxt is needed."""
        import jax
        from ..proto import wire
        from ..graph.compiler import CompiledNet, TEST
        if model_path.endswith(".h5"):
            if self._net_param is None:
                raise ValueError(
                    f"checkpoint {model_path} is HDF5 (weights only, no "
                    "net structure) — pass --model <deploy prototxt>")
            net_proto = None
        else:
            net_proto = wire.load(model_path, "NetParameter")
            if self._net_param is None:
                self._net_param = net_proto.copy()
        if self._base is None:
            self._net_param = deploy_net_param(self._net_param)
            self._base = CompiledNet(self._net_param.copy(), TEST)
            self._base_shapes = {
                n: tuple(s) for n, s in self._base.feed_shapes().items()}
        params, state = self._base.init(jax.random.PRNGKey(0))
        if net_proto is None:
            from ..solver import hdf5_io
            params = hdf5_io.load_net_hdf5(model_path, self._base, params)
        else:
            params, state = self._base.load_netproto(net_proto, params,
                                                     state)
        return params, state

    # -- per-bucket compiled forwards --------------------------------------

    def _bucket_net(self, b):
        from ..graph.compiler import CompiledNet, TEST
        net = self._nets.get(b)
        if net is None:
            np_b = self._net_param.copy()
            # deploy nets size their net-level inputs from input_shape;
            # feed layers take the feed_shapes override — rewrite both
            # to this bucket's leading dim
            for s in np_b.input_shape:
                if len(s.dim):
                    s.dim[0] = b
            for i in range(0, len(np_b.input_dim), 4):
                np_b.input_dim[i] = b
            shapes = {n: (b,) + tuple(base[1:])
                      for n, base in self._base_shapes.items()}
            net = CompiledNet(np_b, TEST, feed_shapes=shapes)
            self._nets[b] = net
        return net

    def _bucket_fwd(self, b):
        import jax
        fwd = self._fwd.get(b)
        if fwd is None:
            net = self._bucket_net(b)
            outs = list(net.output_blobs)

            def run(params, state, batch):
                blobs, _ = net.apply(params, state, batch, train=False)
                return {k: blobs[k] for k in outs if k in blobs}

            fwd = jax.jit(run)
            self._fwd[b] = fwd
        return fwd

    def warmup(self):
        """Trace every bucket once so first requests don't pay compile."""
        for b in self.buckets:
            self.forward({}, n=b)

    def feed_shapes(self):
        """{feed blob -> per-sample shape (leading dim stripped)}."""
        if self._base_shapes is None:
            raise RuntimeError("engine not loaded")
        return {n: tuple(s[1:]) for n, s in self._base_shapes.items()}

    # -- forward -----------------------------------------------------------

    def forward(self, batch, n=None):
        """Pad ``batch`` ({feed blob -> array, leading dim = rows}) to
        its bucket, run the bucket's jit, slice outputs back to the
        real row count. Missing feed blobs (labels on a train-style
        prototxt) are zero-filled. Returns (outputs, bucket)."""
        if n is None:
            n = max((int(np.shape(v)[0]) for v in batch.values()),
                    default=1)
        b = bucket_for(n, self.buckets)
        if b is None:
            raise ValueError(
                f"batch of {n} rows exceeds max_batch={self.max_batch}")
        padded = {}
        for name, base in self._base_shapes.items():
            target = (b,) + tuple(base[1:])
            dt = _feed_dtype(name, base)
            arr = batch.get(name)
            if arr is None:
                padded[name] = np.zeros(target, dt)
                continue
            arr = np.asarray(arr, dt)
            if arr.shape[1:] != target[1:]:
                raise ValueError(
                    f"feed {name!r}: per-sample shape {arr.shape[1:]} "
                    f"!= expected {target[1:]}")
            if arr.shape[0] < b:
                pad = np.zeros((b - arr.shape[0],) + target[1:], dt)
                arr = np.concatenate([arr, pad], axis=0)
            padded[name] = arr
        fwd = self._bucket_fwd(b)
        with self._lock:
            params, state = self._params, self._state
        out = fwd(params, state, padded)
        res = {}
        for k, v in out.items():
            a = np.asarray(v)
            # batch-shaped outputs are sliced back to the real rows;
            # scalars (a train prototxt's loss over the padded batch)
            # pass through untouched
            res[k] = a[:n] if a.ndim and a.shape[0] == b else a
        return res, b

    # -- hot reload --------------------------------------------------------

    def poll_reload(self):
        """Swap in the manifest's newest servable weights when they
        differ from what is loaded; returns the new entry or None.
        Every failure path (torn manifest, missing/corrupt blob) keeps
        the current weights serving."""
        from ..resilience import checkpoint
        man = checkpoint.load_manifest(self.prefix)
        latest = (man or {}).get("latest")
        if not isinstance(latest, dict):
            return None
        with self._lock:
            loaded = self._loaded
        if loaded is not None and \
                latest.get("iter") == loaded.get("iter") and \
                latest.get("sha256") == loaded.get("sha256"):
            return None
        try:
            model_path, entry = checkpoint.load_model_only(
                self.prefix, log_fn=self.log)
        except (OSError, ValueError) as e:
            self.log(f"serve: reload skipped ({e}); keeping "
                     f"iter {None if loaded is None else loaded.get('iter')}")
            return None
        if loaded is not None and \
                entry.get("iter") == loaded.get("iter") and \
                entry.get("sha256") == loaded.get("sha256"):
            return None          # newest SERVABLE blob is what we have
        t0 = time.perf_counter()
        try:
            params, state = self._load_params(model_path)
        except (OSError, ValueError, KeyError) as e:
            self.log(f"serve: reload of {model_path} failed ({e}); "
                     "keeping current weights")
            return None
        from_iter = None if loaded is None else loaded.get("iter")
        with self._lock:
            self._params, self._state = params, state
            self._loaded = entry
            self._reloads += 1
        ms = (time.perf_counter() - t0) * 1e3
        self.log(f"serve: hot-reloaded iter {entry.get('iter')} "
                 f"(was {from_iter}) in {ms:.0f} ms")
        if self.metrics is not None:
            self.metrics.log("serve_reload", iter=entry.get("iter"),
                             from_iter=from_iter,
                             model=entry.get("model"), ms=round(ms, 3))
        return entry

    def status(self):        # spk: thread-entry
        """Snapshot for /healthz (called from HTTP handler threads)."""
        with self._lock:
            loaded, reloads = self._loaded, self._reloads
        return {
            "iter": None if loaded is None else loaded.get("iter"),
            "model": None if loaded is None else loaded.get("model"),
            "sha": None if loaded is None else loaded.get("sha256"),
            "reloads": reloads,
            "buckets": list(self.buckets),
            "feeds": {n: list(s) for n, s in self.feed_shapes().items()},
        }
