"""Device-resident dataset cache — the RDD-in-memory model, HBM edition.

SparkNet's apps keep the ENTIRE training set in cluster memory: CifarApp
loads all records into an RDD cached across executors and each worker
samples minibatches from its in-memory partition (CifarApp.scala:56-64,
MiniBatchSampler). The TPU-native analog is the dataset resident in HBM:
one bulk uint8 upload at startup (CIFAR-10: 150 MB of a v5e's 16 GB), then
each training step ships only a (B, k) int32 control array — the cursor
indices plus the host-drawn crop/mirror randomness, a few hundred BYTES —
and the jitted step gathers its batch and applies the reference transform
(device_transform.py) on-chip.

Why this matters on real hardware, not just this rig's remote-tunnel TPU:
host->HBM bandwidth is orders of magnitude below HBM bandwidth, and a
blocking per-step device_put serializes transfer with compute. With the
dataset resident, steady-state H2D is O(batch) control words, so the input
pipeline can never be the bottleneck — the exact property SparkNet bought
by caching RDDs (its Spark stages read no HDFS after the first epoch).

Cursor semantics match the reference data layer: sequential read order
with wrap-around (data_layer.cpp:40-45), rand_skip consumed at source
construction. TEST passes restart from record 0 (fresh `iter()` per test,
as the CLI has always done).
"""

import os

import numpy as np

from .datum import datum_to_array


def _chunk_bytes():
    """Upload chunk size — the ONE definition shared by the uploader and
    maybe_device_cache's 2x-headroom gate, so the gate's single-put-vs-
    chunked decision always matches the path actually taken."""
    return int(float(os.environ.get("SPARKNET_CACHE_CHUNK_MB", "32"))
               * (1 << 20))


class DeviceCachedSource:
    """Wrap a device-mode DatumBatchSource: bulk-load every record to the
    device, then yield per-step control arrays instead of pixel batches.

    Feed protocol (all through one packed int32 array so a step costs ONE
    tiny device_put):
      {data_top}#ctl : (B, k) int32 — columns [idx][, y, x][, flip] per
      the transform config; device_fn() gathers images/labels from the
      resident arrays and applies the on-device transform.
    The label blob is produced on-device from the same indices, so the
    host feeds nothing else (its check_batch override is None).
    """

    def __init__(self, dbsource, device=None, metrics=None, emit_every=100):
        import jax
        if not dbsource.device_mode:
            raise ValueError("DeviceCachedSource needs a device-mode source")
        self.inner = dbsource
        # hit/miss gauge into the shared metrics stream (next to the
        # prefetch queue gauges): every batch served from the resident
        # arrays is a hit; misses only happen when promotion was refused
        # (maybe_device_cache logs that refusal as an all-miss event)
        self.metrics = metrics
        self.emit_every = max(1, int(emit_every))
        self.hits = 0
        self.source = dbsource.source
        self.batch_size = dbsource.batch_size
        self.data_top = dbsource.data_top
        self.label_top = dbsource.label_top
        self.record_shape = dbsource.record_shape
        self.shape = dbsource.shape
        self._devt = dbsource._devt
        self._ctl_key = f"{self.data_top}#ctl"
        self._img_key = f"{self.data_top}#cacheimg"
        self._lab_key = f"{self.data_top}#cachelab"

        n = len(dbsource.db)
        labels = np.empty(n, np.int32)
        arrs = None
        for i, (_, value) in enumerate(dbsource.db.items()):
            arr, labels[i] = datum_to_array(value)
            if arrs is None:
                arrs = np.empty((n,) + self.record_shape, arr.dtype)
            arrs[i] = arr.reshape(self.record_shape)
        self.num_records = n
        # bulk H2D once; steady-state steps transfer ~nothing. The upload
        # goes up in bounded chunks rather than one giant device_put: a
        # multi-hundred-MB single RPC is exactly what flaky host->device
        # links (observed: the remote tunnel) hang on, and chunking also
        # bounds peak host pinned memory on real hardware.
        rec_bytes = int(np.prod(self.record_shape)) * arrs.itemsize + 4
        per = max(1, _chunk_bytes() // rec_bytes)
        if n > per:
            import jax.numpy as jnp
            parts = [jax.device_put(arrs[s0:s0 + per], device)
                     for s0 in range(0, n, per)]
            self._images = jnp.concatenate(parts, axis=0)
            del parts              # transient 2x HBM only during assembly
        else:
            self._images = jax.device_put(arrs, device)
        self._labels = jax.device_put(labels, device)
        self._start = dbsource._skip % n
        dbsource.db.close()
        self._gauge(resident=True)

    def _gauge(self, **extra):
        if self.metrics is None:
            return
        self.metrics.log("device_cache", source=self.source,
                         records=self.num_records, nbytes=self.nbytes,
                         hits=self.hits, misses=0, hit_rate=1.0, **extra)

    @property
    def nbytes(self):
        if self._images is None:
            return 0
        return self._images.nbytes + self._labels.nbytes

    @property
    def device_mode(self):
        return True

    @property
    def num_batches(self):
        return max(1, self.num_records // self.batch_size)

    def _ctl_columns(self):
        t = self._devt.h
        cols = 1
        if t.crop_size:
            cols += 2
        if t.mirror:
            cols += 1
        return cols

    def __iter__(self):
        """Infinite per-step control stream: sequential cursor + the host
        rng's crop/mirror draws (same rng, same order as the streaming
        device mode — the augmentation stream is identical).

        The resident arrays ride along in every batch dict as ARGUMENTS to
        the jitted step rather than closure constants: an already-on-device
        array costs nothing to pass, while a closed-over multi-hundred-MB
        constant gets embedded into the HLO where XLA's constant handling
        can stall compilation for tens of minutes (observed on the 383 MB
        imagenet-shaped cache; the 150 MB CIFAR cache merely compiled
        slowly)."""
        n, b = self.num_records, self.batch_size
        pos = self._start
        self._start = 0
        while True:
            idx = (pos + np.arange(b)) % n
            pos = (pos + b) % n
            cols = [idx.astype(np.int32)]
            aux = self._devt.aux(b, self.record_shape)
            ky, kx, kf = self._devt.ky, self._devt.kx, self._devt.kf
            if ky in aux:
                cols += [aux[ky], aux[kx]]
            if kf in aux:
                cols.append(aux[kf].astype(np.int32))
            self.hits += 1
            if self.hits % self.emit_every == 0:
                self._gauge()
            yield {self._ctl_key: np.stack(cols, axis=1),
                   self._img_key: self._images,
                   self._lab_key: self._labels}

    @property
    def device_fn(self):
        """fn(batch)->batch for Solver.set_input_transform: unpack the ctl
        array, gather the resident records (arriving as batch entries, see
        __iter__), transform on-device."""
        import jax.numpy as jnp
        t = self._devt.h
        ctl_key, img_key, lab_key = \
            self._ctl_key, self._img_key, self._lab_key
        data_top, label_top = self.data_top, self.label_top
        ky, kx, kf = self._devt.ky, self._devt.kx, self._devt.kf
        has_crop, has_flip = bool(t.crop_size), bool(t.mirror)
        inner_fn = self._devt.device_fn()

        def fn(batch):
            batch = dict(batch)
            ctl = batch.pop(ctl_key)
            images = batch.pop(img_key)
            labels = batch.pop(lab_key)
            idx = ctl[:, 0]
            feed = {data_top: jnp.take(images, idx, axis=0),
                    label_top: jnp.take(labels, idx, axis=0)}
            col = 1
            if has_crop:
                feed[ky], feed[kx] = ctl[:, col], ctl[:, col + 1]
                col += 2
            if has_flip:
                feed[kf] = ctl[:, col]
            out = inner_fn(feed)
            out.update(batch)      # extra host-fed blobs pass through
            return out

        return fn

    @property
    def raw_feed_overrides(self):
        """check_batch overrides: the tiny ctl array plus the (free,
        already-resident) cache arrays; the net's data/label blobs are
        produced on-device (None = not host-fed)."""
        over = {self.data_top: None, self.label_top: None,
                self._ctl_key: (self.batch_size, self._ctl_columns()),
                self._img_key: (self.num_records,) + self.record_shape,
                self._lab_key: (self.num_records,)}
        return over

    def close(self):
        if self.hits % self.emit_every:
            self._gauge()              # final partial-window gauge
        self._images = self._labels = None


def _log_miss_mode(metrics, src, reason, **extra):
    """Promotion refused: every batch will stream through the host — an
    all-miss ``device_cache`` gauge with the reason, so a report can tell
    'cache never engaged' apart from 'no gauge at all'."""
    if metrics is None:
        return
    metrics.log("device_cache", source=getattr(src, "source", "?"),
                resident=False, reason=reason, hits=0,
                misses=getattr(src, "num_records", None), hit_rate=0.0,
                **extra)


def maybe_device_cache(src, budget_mb=2048, iter_size=1, metrics=None):
    """Promote a device-mode DatumBatchSource to a DeviceCachedSource when
    the whole dataset fits the HBM budget; otherwise return it unchanged
    (the streaming device-transform path still applies).

    Refuses under iter_size > 1 (Solver.step stacks micro-batch dicts on
    the HOST, which would read the resident arrays back and re-upload
    iter_size copies per step) and under multi-process JAX (the resident
    arrays are whole-dataset, not per-host batch slices, so the per-host
    check_batch slicing rule doesn't apply to them)."""
    if src is None or not getattr(src, "device_mode", False):
        return src
    if not hasattr(src, "db"):
        return src
    if int(iter_size) > 1:
        _log_miss_mode(metrics, src, "iter_size")
        return src
    import jax
    if jax.process_count() > 1:
        _log_miss_mode(metrics, src, "multiprocess")
        return src
    # size from the first record's ACTUAL dtype — float_data datums decode
    # to float32, 4x the uint8 pixel estimate
    arr, _ = datum_to_array(next(src.db.items())[1])
    est = len(src.db) * (arr.size * arr.itemsize + 4)
    # the chunked upload path (datasets > one chunk) transiently holds
    # parts + their concatenation in HBM, so gate on ~2x for it — a
    # dataset near the budget must not OOM where a single device_put
    # would have fit
    needed = est * 2 if est > _chunk_bytes() else est
    if needed > budget_mb * (1 << 20):
        _log_miss_mode(metrics, src, "over_budget", est_bytes=est,
                       budget_mb=budget_mb)
        return src
    return DeviceCachedSource(src, metrics=metrics)
