"""Minibatch sampling over a partition's batch stream.

Behavioral port of reference MinibatchSampler.scala: from a stream of
``totalNumBatches`` minibatches, sample a random *contiguous window* of
``numSampledBatches`` (start index uniform in [0, total - sampled],
MinibatchSampler.scala:20-21) and iterate it. The reference's dual
image/label callback trick (:28-60) existed only because Caffe pulled
images and labels through two separate C callbacks against one iterator;
with dict batches there is nothing to keep in lock-step.

partition_owners() is the elastic re-sharding rule (resilience/
elastic.py): when workers are evicted from the mesh, each dead slot's
data partition is re-assigned to a survivor round-robin, so the stream
keeps being consumed by the workers that can still train on it.
"""

import numpy as np


class MinibatchSampler:
    def __init__(self, batches, total_num_batches, num_sampled_batches,
                 rng=None):
        """batches: iterable of batch dicts (or (images, labels) tuples)."""
        rng = rng or np.random
        self.total = int(total_num_batches)
        self.start = int(rng.randint(0, total_num_batches
                                     - num_sampled_batches + 1))
        self.num_sampled = num_sampled_batches
        self._it = iter(batches)
        self._pos = -1
        self._emitted = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._emitted >= self.num_sampled:
            raise StopIteration
        target = self.start + self._emitted
        while self._pos < target:
            try:
                batch = next(self._it)
            except StopIteration:
                # a bare StopIteration here would read as "window done"
                # (and, inside a generator, PEP 479's opaque
                # RuntimeError) — the stream lied about its length, say
                # so with the numbers
                raise ValueError(
                    f"batch stream exhausted after {self._pos + 1} "
                    f"batches; the sampled window [{self.start}, "
                    f"{self.start + self.num_sampled}) needs "
                    f"{target + 1} (total_num_batches={self.total})"
                ) from None
            self._pos += 1
        self._emitted += 1
        return batch


def partition_owners(num_partitions, alive):
    """Map every data partition (one per mesh slot) to the live worker
    that consumes it: live slots own their partition; dead slots'
    partitions are re-assigned round-robin across the survivors.

    >>> partition_owners(4, [True, False, True, False])
    array([0, 0, 2, 2])
    """
    alive = np.asarray(alive, bool).ravel()
    if len(alive) != int(num_partitions):
        raise ValueError(f"alive mask has {len(alive)} entries for "
                         f"{num_partitions} partitions")
    live = np.nonzero(alive)[0]
    if len(live) == 0:
        raise ValueError("no live workers to own the partitions")
    owners = np.empty(int(num_partitions), np.int64)
    j = 0
    for p in range(int(num_partitions)):
        if alive[p]:
            owners[p] = p
        else:
            owners[p] = live[j % len(live)]
            j += 1
    return owners


def reshard_owners(n_from, n_to):
    """Cross-world re-partitioning rule (resilience/checkpoint.py's
    reshard_for_world): carry ``n_from`` data partitions written by
    world W1 onto world W2's ``n_to`` slots, reusing partition_owners
    in both directions so resharding and eviction follow ONE rule.

    Shrink (n_to < n_from): W1's world with the trailing slots dead —
    owners[p] is the W2 slot that inherits W1 partition p, so every
    old partition stays owned:

    >>> reshard_owners(4, 2)
    array([0, 1, 0, 1])

    Grow (n_to > n_from): W2's world where only the first n_from slots
    arrive with data — owners[s] is the W1 partition new slot s
    bootstraps from:

    >>> reshard_owners(2, 4)
    array([0, 1, 0, 1])

    Same size is the identity (bit-for-bit restore, no re-spread). The
    returned array always has max(n_from, n_to) entries — the larger
    world's slot count.
    """
    a, b = int(n_from), int(n_to)
    if a <= 0 or b <= 0:
        raise ValueError(f"worlds need at least one slot "
                         f"(got {n_from} -> {n_to})")
    n = max(a, b)
    k = min(a, b)
    alive = np.zeros(n, bool)
    alive[:k] = True
    return partition_owners(n, alive)
