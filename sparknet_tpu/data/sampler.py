"""Minibatch sampling over a partition's batch stream.

Behavioral port of reference MinibatchSampler.scala: from a stream of
``totalNumBatches`` minibatches, sample a random *contiguous window* of
``numSampledBatches`` (start index uniform in [0, total - sampled],
MinibatchSampler.scala:20-21) and iterate it. The reference's dual
image/label callback trick (:28-60) existed only because Caffe pulled
images and labels through two separate C callbacks against one iterator;
with dict batches there is nothing to keep in lock-step.
"""

import numpy as np


class MinibatchSampler:
    def __init__(self, batches, total_num_batches, num_sampled_batches,
                 rng=None):
        """batches: iterable of batch dicts (or (images, labels) tuples)."""
        rng = rng or np.random
        self.start = int(rng.randint(0, total_num_batches
                                     - num_sampled_batches + 1))
        self.num_sampled = num_sampled_batches
        self._it = iter(batches)
        self._pos = -1
        self._emitted = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._emitted >= self.num_sampled:
            raise StopIteration
        target = self.start + self._emitted
        while self._pos < target:
            batch = next(self._it)
            self._pos += 1
        self._emitted += 1
        return batch
