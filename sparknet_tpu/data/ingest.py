"""Per-host sharded ingest — each host reads only the records it owns.

The paper's design flaw was funneling all *weight* traffic through one
Spark driver; a single-reader ingest funnels all *data* traffic through
one host the same way. IngestShard splits the record index space into one
partition per host of the multi-host runtime and maps partitions to live
hosts with ``sampler.partition_owners`` — the SAME ownership rule that
drives eviction re-spread and cross-world checkpoint resharding — so
ingest bandwidth scales with the fleet and elastic membership changes
move data ownership and ingest ownership together, by construction.

A shard is immutable; ``respread(alive)`` derives the successor shard for
a new membership mask. Reads are index-space only (``take``): the caller
owns the actual record storage, which keeps this reusable across the
in-memory CIFAR arrays, LMDB cursors, and anything else indexable.
"""

import numpy as np

from .sampler import partition_owners


class IngestShard:
    """The record indices one host reads, under a live-host mask.

    num_records: total records in the (globally shared) dataset.
    host/hosts:  this host's index and the world size (one partition per
                 host slot).
    alive:       optional bool mask over host slots (default: all live);
                 dead slots' partitions fold onto survivors per
                 partition_owners.
    metrics:     optional MetricsLogger; emits closed ``ingest`` events
                 (kind=init/respread at construction, throttled kind=read
                 from take()) so the smoke test can assert from the
                 metrics stream that a host touched only owned records.
    """

    def __init__(self, num_records, host, hosts, alive=None, metrics=None,
                 _kind="init"):
        self.num_records = int(num_records)
        self.host = int(host)
        self.hosts = int(hosts)
        if alive is None:
            alive = np.ones(self.hosts, bool)
        self.alive = np.asarray(alive, bool).copy()
        owners = partition_owners(self.hosts, self.alive)
        self.partitions = [p for p in range(self.hosts)
                           if owners[p] == self.host]
        n, H = self.num_records, self.hosts
        chunks = [np.arange(p * n // H, (p + 1) * n // H)
                  for p in self.partitions]
        self.indices = (np.concatenate(chunks) if chunks
                        else np.empty(0, np.int64)).astype(np.int64)
        self.owned = len(self.indices)
        self._metrics = metrics
        self._reads = 0
        self._emit(_kind)

    def _emit(self, kind, lo=-1, hi=-1):
        if self._metrics is not None:
            self._metrics.log(
                "ingest", kind=kind, host=self.host, hosts=self.hosts,
                partitions=len(self.partitions), records=self.owned,
                lo=int(lo), hi=int(hi), reads=self._reads)

    def take(self, start, count, emit_every=25):
        """``count`` record indices from the owned set, starting at owned
        position ``start`` and wrapping modulo the shard (the same
        wrap-around cursor discipline as db_source._records, confined to
        owned records)."""
        if self.owned == 0:
            raise ValueError(
                f"host {self.host} owns no records "
                f"({self.num_records} records over {self.hosts} hosts)")
        pos = (int(start) + np.arange(int(count))) % self.owned
        idx = self.indices[pos]
        self._reads += 1
        if self._reads % max(1, emit_every) == 1:
            self._emit("read", lo=idx.min(), hi=idx.max())
        return idx

    def respread(self, alive):
        """Successor shard for a new live-host mask (elastic evict/admit):
        survivors inherit dead hosts' partitions round-robin, exactly as
        data ownership re-spreads."""
        return IngestShard(self.num_records, self.host, self.hosts,
                           alive=alive, metrics=self._metrics,
                           _kind="respread")

    def describe(self):
        return {"host": self.host, "hosts": self.hosts,
                "partitions": len(self.partitions), "records": self.owned}
