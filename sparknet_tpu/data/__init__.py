"""Host-side data loading (replaces the reference's RDD -> JNA callback
path with loader-push into device memory)."""

from .cifar import CifarDataset, read_batch_file, write_batch_file
from .sampler import MinibatchSampler
from .synthetic import class_gaussian_images, batch_stream

__all__ = ["CifarDataset", "read_batch_file", "write_batch_file",
           "MinibatchSampler", "class_gaussian_images", "batch_stream"]
