"""Host-side data loading (replaces the reference's RDD -> JNA callback
path with loader-push into device memory)."""

from .cifar import CifarDataset, read_batch_file, write_batch_file
from .sampler import MinibatchSampler, partition_owners
from .synthetic import class_gaussian_images, batch_stream
from .lmdb import LMDBReader, LMDBWriter
from .datum import array_to_datum, datum_to_array, encoded_datum
from .db_source import DatumBatchSource, build_db_feed, open_db
from .transforms import (DataTransformer, load_mean_binaryproto,
                         save_mean_binaryproto)

__all__ = ["CifarDataset", "read_batch_file", "write_batch_file",
           "MinibatchSampler", "partition_owners",
           "class_gaussian_images", "batch_stream",
           "LMDBReader", "LMDBWriter", "array_to_datum", "datum_to_array",
           "encoded_datum", "DatumBatchSource", "build_db_feed", "open_db",
           "DataTransformer", "load_mean_binaryproto",
           "save_mean_binaryproto"]
