"""Pure-Python LevelDB — reader (full DB: SSTables + MANIFEST + WAL) and a
single-table writer, dependency-free.

The reference opens Datum databases through either backend
(``db.cpp:10-22`` dispatch; ``db_leveldb.cpp:8-19`` with block_size 64 KiB)
and only ever walks a cursor sequentially from the first key
(``data_reader.cpp``), so the contract here is ordered iteration over the
live key space — not point lookups under concurrent writers.

Like `lmdb.py`, this implements the *file format* from the public on-disk
layout (google/leveldb ``doc/table_format.md`` + ``doc/log_format.md`` +
``doc/impl.md``), not by wrapping a native library:

- ``CURRENT`` names the live ``MANIFEST-NNNNNN``; the manifest is a record
  log of VersionEdits that accumulate the set of live ``.ldb``/``.sst``
  table files per level, the active WAL number, and the last sequence.
- Table files are SSTables: 4 KiB-default blocks of prefix-compressed
  key/value entries with a restart array, each block followed by a 5-byte
  trailer (compression type + masked crc32c); an index block maps last-keys
  to block handles; a 48-byte footer holds the metaindex/index handles and
  the magic number. Block compression is Snappy (type 1) or none (type 0);
  a pure-Python Snappy decoder below handles both the literal and all
  three copy element kinds.
- Keys inside tables and the WAL are *internal keys*: user_key + 8-byte
  (sequence<<8 | type) trailer; type 1 = value, 0 = deletion. Iteration
  merges all sources by (key asc, sequence desc) and keeps the newest
  non-deleted version of each key — so partially-compacted DBs read
  correctly.
- A freshly written, never-compacted DB may hold every record only in the
  write-ahead ``.log`` (32 KiB-framed WriteBatch records); the reader
  replays any live WAL into a memtable and merges it like a level.

Checksum verification mirrors ``leveldb::ReadOptions::verify_checksums``
(default off); the writer always emits correct masked crc32c.

The writer produces the minimal valid DB a real leveldb would open: one
level-0 table, a one-edit manifest, CURRENT, and an empty WAL. Entries are
buffered and sorted at close (caffe writes "%08d"-style ascending keys, but
order is not assumed), matching `LMDBWriter`'s buffering contract.
"""

import heapq
import os
import struct

_MAGIC = 0xdb4775248b80fb57
_BLOCK_LOG = 32768          # log_format.md framing block
_HEADER = 7                 # crc(4) + length(2) + type(1)
_FULL, _FIRST, _MIDDLE, _LAST = 1, 2, 3, 4
_TYPE_DELETION, _TYPE_VALUE = 0, 1
_MASK_DELTA = 0xa282ead8
_COMPARATOR = b"leveldb.BytewiseComparator"


# ---------------------------------------------------------------- varints
# shared LEB128 codec (the proto wire codec's — one implementation to fix)
from ..proto.wire import _read_varint as _get_varint  # noqa: E402
from ..proto.wire import _write_varint as _put_varint  # noqa: E402


# ---------------------------------------------------------------- crc32c

_CRC_TABLE = []
_c = 0
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82f63b78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)
del _c, _n


def _crc32c_py(data, crc=0):
    c = crc ^ 0xffffffff
    tab = _CRC_TABLE
    for b in data:
        c = tab[(c ^ b) & 0xff] ^ (c >> 8)
    return c ^ 0xffffffff


def crc32c(data, crc=0):
    # the native kernel (native/pipeline.cpp crc32c_update) wins past a
    # few dozen bytes; the ctypes call itself costs ~1us, so tiny inputs
    # (the 1-byte record-type prefixes) stay in Python
    if len(data) >= 64:
        from .. import native
        c = native.crc32c(data, crc)
        if c is not None:
            return c
    return _crc32c_py(data, crc)


def crc_mask(crc):
    """leveldb stores crcs "masked" so crcs-of-crcs stay well distributed."""
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xffffffff


def crc_unmask(masked):
    rot = (masked - _MASK_DELTA) & 0xffffffff
    return ((rot >> 17) | (rot << 15)) & 0xffffffff


# ---------------------------------------------------------------- snappy

def snappy_decompress(data):
    """Full Snappy format decoder: varint32 length preamble, then literal
    (00), copy-1 (01), copy-2 (10), copy-4 (11) elements; copies may
    overlap their own output (RLE-style) so those run byte-wise.

    Dispatches to the native decoder (native/pipeline.cpp
    snappy_uncompress) when the lazily-built library is available — the
    block decode is the hot loop of LevelDB streaming; the pure-Python
    path below is the always-available fallback and the executable spec."""
    n, p = _get_varint(data, 0)
    from .. import native
    out_native = native.snappy_uncompress(data, n)
    if out_native is not None:
        return out_native
    out = bytearray()
    while p < len(data):
        tag = data[p]
        p += 1
        kind = tag & 3
        if kind == 0:                       # literal
            ln = tag >> 2
            if ln >= 60:                    # big literal: length in 1-4 bytes
                nb = ln - 59
                ln = int.from_bytes(data[p:p + nb], "little")
                p += nb
            ln += 1
            out += data[p:p + ln]
            p += ln
            continue
        if kind == 1:                       # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[p]
            p += 1
        elif kind == 2:                     # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[p:p + 2], "little")
            p += 2
        else:                               # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[p:p + 4], "little")
            p += 4
        start = len(out) - off
        if off >= ln:                       # disjoint: one slice copy
            out += out[start:start + ln]
        else:                               # overlapping: byte-wise
            for i in range(ln):
                out.append(out[start + i])
    if len(out) != n:
        raise ValueError(f"snappy: got {len(out)} bytes, expected {n}")
    return bytes(out)


def snappy_compress(data):
    """Valid (if unambitious) Snappy: the whole payload as literals. Every
    decoder accepts it; our own DBs exercise the type-1 block path with a
    single fast slice-copy on read."""
    buf = bytearray()
    _put_varint(buf, len(data))
    p = 0
    while p < len(data):
        chunk = data[p:p + (1 << 16)]
        ln = len(chunk) - 1
        if ln < 60:
            buf.append(ln << 2)
        else:
            nb = (ln.bit_length() + 7) // 8
            buf.append((59 + nb) << 2)
            buf += ln.to_bytes(nb, "little")
        buf += chunk
        p += len(chunk)
    return bytes(buf)


# ---------------------------------------------------------------- record log

class LogWriter:
    """log_format.md framing: records fragmented across 32 KiB blocks."""

    def __init__(self, f):
        self.f = f
        self._block_off = 0

    def add_record(self, data):
        data = memoryview(bytes(data))
        first = True
        while True:
            left = _BLOCK_LOG - self._block_off
            if left < _HEADER:
                self.f.write(b"\0" * left)
                self._block_off = 0
                left = _BLOCK_LOG
            avail = left - _HEADER
            frag = data[:avail]
            data = data[len(frag):]
            end = len(data) == 0
            t = (_FULL if first and end else _FIRST if first
                 else _LAST if end else _MIDDLE)
            crc = crc_mask(crc32c(frag, crc32c(bytes([t]))))
            self.f.write(struct.pack("<IHB", crc, len(frag), t))
            self.f.write(frag)
            self._block_off += _HEADER + len(frag)
            first = False
            if end:
                return


def log_records(data, verify=False):
    """Yield whole records from log-framed bytes (a MANIFEST or WAL)."""
    pending = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        block_left = _BLOCK_LOG - (pos % _BLOCK_LOG)
        if block_left < _HEADER:
            pos += block_left            # trailer padding
            continue
        if pos + _HEADER > n:
            return                       # truncated tail (crashed writer)
        crc, length, t = struct.unpack_from("<IHB", data, pos)
        if t == 0 and length == 0:
            pos += block_left            # zero-fill: pre-allocated space
            continue
        frag = data[pos + _HEADER:pos + _HEADER + length]
        if len(frag) < length:
            return                       # truncated record
        if verify and crc_unmask(crc) != crc32c(frag, crc32c(bytes([t]))):
            raise ValueError(f"log record crc mismatch at offset {pos}")
        pos += _HEADER + length
        if t == _FULL:
            yield bytes(frag)
        elif t == _FIRST:
            pending = bytearray(frag)
        elif t == _MIDDLE:
            pending += frag
        elif t == _LAST:
            pending += frag
            yield bytes(pending)
            pending = bytearray()
        else:
            raise ValueError(f"bad log record type {t}")


# ---------------------------------------------------------------- blocks

def _block_entries(data):
    """Prefix-compressed entries of one (decompressed) block."""
    if len(data) < 4:
        return
    num_restarts = struct.unpack_from("<I", data, len(data) - 4)[0]
    end = len(data) - 4 - 4 * num_restarts
    p = 0
    key = b""
    while p < end:
        shared, p = _get_varint(data, p)
        non_shared, p = _get_varint(data, p)
        vlen, p = _get_varint(data, p)
        key = key[:shared] + data[p:p + non_shared]
        p += non_shared
        yield key, data[p:p + vlen]
        p += vlen


class _BlockBuilder:
    def __init__(self, restart_interval=16):
        self.buf = bytearray()
        self.restarts = [0]
        self.interval = restart_interval
        self.count = 0
        self.last_key = b""

    def add(self, key, value):
        shared = 0
        if self.count % self.interval == 0:
            if self.count:
                self.restarts.append(len(self.buf))
        else:
            m = min(len(key), len(self.last_key))
            while shared < m and key[shared] == self.last_key[shared]:
                shared += 1
        _put_varint(self.buf, shared)
        _put_varint(self.buf, len(key) - shared)
        _put_varint(self.buf, len(value))
        self.buf += key[shared:]
        self.buf += value
        self.last_key = key
        self.count += 1

    def finish(self):
        out = bytearray(self.buf)
        restarts = self.restarts if self.count else [0]
        for r in restarts:
            out += struct.pack("<I", r)
        out += struct.pack("<I", len(restarts))
        return bytes(out)

    def __len__(self):
        return len(self.buf)


# ---------------------------------------------------------------- tables

def _read_block(data, offset, size, verify=False):
    raw = data[offset:offset + size]
    ctype = data[offset + size]
    if verify:
        # block crcs cover contents then the type byte, in write order
        stored = struct.unpack_from("<I", data, offset + size + 1)[0]
        if crc_unmask(stored) != crc32c(bytes([ctype]), crc32c(raw)):
            raise ValueError(f"block crc mismatch at {offset}")
    if ctype == 1:
        return snappy_decompress(raw)
    return bytes(raw)


def table_entries(data, verify=False):
    """Yield (internal_key, value) from an SSTable's bytes, in key order."""
    if len(data) < 48 or \
            struct.unpack("<Q", data[-8:])[0] != _MAGIC:
        raise ValueError("not an SSTable (bad footer magic)")
    p = len(data) - 48
    _mi_off, p = _get_varint(data, p)
    _mi_size, p = _get_varint(data, p)
    ix_off, p = _get_varint(data, p)
    ix_size, p = _get_varint(data, p)
    index = _read_block(data, ix_off, ix_size, verify)
    for _key, handle in _block_entries(index):
        off, q = _get_varint(handle, 0)
        size, q = _get_varint(handle, q)
        yield from _block_entries(_read_block(data, off, size, verify))


def _table_versions(path, verify=False):
    """[(user_key, seq, vtype, value)] from one table file, key order."""
    with open(path, "rb") as f:
        data = f.read()
    out = []
    for ikey, value in table_entries(data, verify):
        tag = struct.unpack("<Q", ikey[-8:])[0]
        out.append((ikey[:-8], tag >> 8, tag & 0xff, value))
    return out


class _TableWriter:
    def __init__(self, f, block_size=4096, compress=True):
        self.f = f
        self.block_size = block_size
        self.compress = compress
        self.block = _BlockBuilder()
        self.index = []                 # (last_key, offset, size)
        self.offset = 0
        self.first_key = self.last_key = None

    def add(self, ikey, value):
        if self.first_key is None:
            self.first_key = ikey
        self.last_key = ikey
        self.block.add(ikey, value)
        if len(self.block) >= self.block_size:
            self._flush()

    def _write_block(self, contents):
        if self.compress:
            payload, ctype = snappy_compress(contents), 1
        else:
            payload, ctype = contents, 0
        crc = crc_mask(crc32c(bytes([ctype]), crc32c(payload)))
        self.f.write(payload)
        self.f.write(struct.pack("<BI", ctype, crc))
        handle = (self.offset, len(payload))
        self.offset += len(payload) + 5
        return handle

    def _flush(self):
        if not self.block.count:
            return
        handle = self._write_block(self.block.finish())
        self.index.append((self.block.last_key, handle))
        self.block = _BlockBuilder()

    def finish(self):
        self._flush()
        meta_handle = self._write_block(_BlockBuilder().finish())
        ixb = _BlockBuilder(restart_interval=1)
        for last_key, (off, size) in self.index:
            hv = bytearray()
            _put_varint(hv, off)
            _put_varint(hv, size)
            ixb.add(last_key, bytes(hv))
        index_handle = self._write_block(ixb.finish())
        footer = bytearray()
        for v in (*meta_handle, *index_handle):
            _put_varint(footer, v)
        footer += b"\0" * (40 - len(footer))
        footer += struct.pack("<Q", _MAGIC)
        self.f.write(footer)
        return self.offset + 48


# ---------------------------------------------------------------- manifest

def _decode_version_edit(rec):
    """VersionEdit tags we act on: 2 log_number, 6 deleted file,
    7 new file; the other standard tags (1,3,4,5,9) are parsed and
    skipped. Truly unknown tags raise: varint-framed records can't be
    skipped without knowing their field structure, and guessing would
    silently corrupt every later field in the edit — matching leveldb's
    own VersionEdit::DecodeFrom, which also rejects unknown tags
    (version_edit.cc). Notably tag 8 (kLargeValueRef, removed pre-1.0)
    is rejected here just as it is upstream."""
    p = 0
    out = {"new": [], "deleted": [], "log_number": None}
    while p < len(rec):
        tag, p = _get_varint(rec, p)
        if tag == 1:                     # comparator name
            n, p = _get_varint(rec, p)
            p += n
        elif tag == 2:
            out["log_number"], p = _get_varint(rec, p)
        elif tag == 9:                   # prev log number
            _, p = _get_varint(rec, p)
        elif tag == 3:                   # next file number
            _, p = _get_varint(rec, p)
        elif tag == 4:                   # last sequence
            _, p = _get_varint(rec, p)
        elif tag == 5:                   # compact pointer
            _, p = _get_varint(rec, p)
            n, p = _get_varint(rec, p)
            p += n
        elif tag == 6:
            level, p = _get_varint(rec, p)
            num, p = _get_varint(rec, p)
            out["deleted"].append((level, num))
        elif tag == 7:
            level, p = _get_varint(rec, p)
            num, p = _get_varint(rec, p)
            _size, p = _get_varint(rec, p)
            n, p = _get_varint(rec, p)
            p += n                       # smallest internal key
            n, p = _get_varint(rec, p)
            p += n                       # largest internal key
            out["new"].append((level, num))
        else:
            raise ValueError(
                f"unknown VersionEdit tag {tag} (varint framing makes "
                f"unknown tags unskippable; is this DB from a forked or "
                f"pre-1.0 leveldb?)")
    return out


def _encode_version_edit(log_number, next_file, last_seq, new_files):
    buf = bytearray()
    _put_varint(buf, 1)
    _put_varint(buf, len(_COMPARATOR))
    buf += _COMPARATOR
    _put_varint(buf, 2)
    _put_varint(buf, log_number)
    _put_varint(buf, 3)
    _put_varint(buf, next_file)
    _put_varint(buf, 4)
    _put_varint(buf, last_seq)
    for level, num, size, smallest, largest in new_files:
        _put_varint(buf, 7)
        _put_varint(buf, level)
        _put_varint(buf, num)
        _put_varint(buf, size)
        _put_varint(buf, len(smallest))
        buf += smallest
        _put_varint(buf, len(largest))
        buf += largest
    return bytes(buf)


# ---------------------------------------------------------------- reader

class LevelDBReader:
    """Ordered iteration over a LevelDB directory. API mirrors LMDBReader:
    items()/keys()/get()/len()/close(), context manager, iter."""

    def __init__(self, path, verify_checksums=False):
        self.path = path
        self.verify = verify_checksums
        cur = os.path.join(path, "CURRENT")
        with open(cur) as f:
            manifest = f.read().strip()
        with open(os.path.join(path, manifest), "rb") as f:
            mdata = f.read()
        files = {}                      # (level, num) -> True
        log_number = 0
        for rec in log_records(mdata, verify=self.verify):
            edit = _decode_version_edit(rec)
            if edit["log_number"] is not None:
                log_number = edit["log_number"]
            for lv_num in edit["deleted"]:
                files.pop(lv_num, None)
            for lv_num in edit["new"]:
                files[lv_num] = True
        self._tables = []
        for level, num in sorted(files):
            for ext in (".ldb", ".sst"):
                p = os.path.join(path, f"{num:06d}{ext}")
                if os.path.exists(p):
                    self._tables.append(p)
                    break
            else:
                raise FileNotFoundError(
                    f"{path}: live table {num:06d} missing")
        # WALs at least as new as the manifest's log_number hold memtable
        # entries not yet in any table (impl.md recovery)
        self._memtable = {}
        for fn in sorted(os.listdir(path)):
            if fn.endswith(".log"):
                try:
                    num = int(fn.split(".")[0])
                except ValueError:
                    continue
                if num >= log_number:
                    self._replay_wal(os.path.join(path, fn))
        self._decoded = None
        self._cacheable = None
        self._len = None

    def _replay_wal(self, path):
        with open(path, "rb") as f:
            data = f.read()
        for rec in log_records(data, verify=self.verify):
            seq = struct.unpack_from("<Q", rec, 0)[0]
            count = struct.unpack_from("<I", rec, 8)[0]
            p = 12
            for i in range(count):
                vtype = rec[p]
                p += 1
                klen, p = _get_varint(rec, p)
                key = rec[p:p + klen]
                p += klen
                value = b""
                if vtype == _TYPE_VALUE:
                    vlen, p = _get_varint(rec, p)
                    value = rec[p:p + vlen]
                    p += vlen
                old = self._memtable.get(key)
                if old is None or old[0] <= seq + i:
                    self._memtable[key] = (seq + i, vtype, value)

    def _sources(self):
        # table files are immutable, so decode each once and iterate the
        # cached version lists on every items() pass (a Datum source walks
        # the whole DB once per epoch; re-decompressing per pass would
        # dominate the input pipeline). The cache is bounded: DBs whose
        # table files exceed SPARKNET_LEVELDB_CACHE_MB (default 1024)
        # re-decode per pass instead of pinning the dataset in host RAM.
        if self._decoded is None and self._cacheable is None:
            budget = float(os.environ.get("SPARKNET_LEVELDB_CACHE_MB",
                                          "1024")) * (1 << 20)
            self._cacheable = sum(
                os.path.getsize(p) for p in self._tables) <= budget
        if self._decoded is None and self._cacheable:
            self._decoded = [_table_versions(p, self.verify)
                             for p in self._tables]
        if self._decoded is not None:
            srcs = [iter(t) for t in self._decoded]
        else:
            srcs = [iter(_table_versions(p, self.verify))
                    for p in self._tables]
        if self._memtable:
            srcs.append(iter(sorted(
                (k, s, t, v) for k, (s, t, v) in self._memtable.items())))
        return srcs

    def items(self):
        """(key, value) in key order — newest live version of each key."""
        merged = heapq.merge(*self._sources(),
                             key=lambda e: (e[0], -e[1]))
        prev = None
        for key, _seq, vtype, value in merged:
            if key == prev:
                continue                 # older version shadowed
            prev = key
            if vtype == _TYPE_VALUE:
                yield key, value

    def keys(self):
        for k, _ in self.items():
            yield k

    def get(self, key):
        if isinstance(key, str):
            key = key.encode()
        for k, v in self.items():
            if k == key:
                return v
            if k > key:
                return None
        return None

    def __len__(self):
        if self._len is None:
            self._len = sum(1 for _ in self.items())
        return self._len

    def close(self):
        self._memtable = {}
        self._tables = []
        self._decoded = None
        self._cacheable = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self):
        return self.items()


# ---------------------------------------------------------------- writer

class LevelDBWriter:
    """Buffering writer producing a minimal real DB: one level-0 table
    (000005.ldb), MANIFEST-000004 + CURRENT, and an empty WAL 000006.log.
    put() order is preserved as sequence order; keys sort at close."""

    def __init__(self, path, block_size=4096, compress=True):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.block_size = block_size
        self.compress = compress
        self._entries = []
        self._closed = False

    def put(self, key, value):
        if self._closed:
            raise ValueError("put() on a closed LevelDBWriter")
        if isinstance(key, str):
            key = key.encode()
        if isinstance(value, str):
            value = value.encode()
        self._entries.append((bytes(key), bytes(value)))

    def close(self):
        # idempotent: an explicit close() followed by the context
        # manager's __exit__ (or any double close) must not rewrite the
        # DB from the now-empty entry list
        if self._closed:
            return
        self._closed = True
        seq = {}
        for i, (k, _) in enumerate(self._entries):
            seq[k] = i + 1               # later puts shadow earlier ones
        versions = sorted(
            ((k, seq[k], v) for i, (k, v) in enumerate(self._entries)
             if seq[k] == i + 1),
            key=lambda e: (e[0], -e[1]))
        table_path = os.path.join(self.path, "000005.ldb")
        with open(table_path, "wb") as f:
            tw = _TableWriter(f, self.block_size, self.compress)
            for k, s, v in versions:
                tw.add(k + struct.pack("<Q", (s << 8) | _TYPE_VALUE), v)
            size = tw.finish()
        smallest = tw.first_key or b""
        largest = tw.last_key or b""
        last_seq = len(self._entries)
        edit = _encode_version_edit(
            log_number=6, next_file=7, last_seq=last_seq,
            new_files=[(0, 5, size, smallest, largest)] if versions else [])
        with open(os.path.join(self.path, "MANIFEST-000004"), "wb") as f:
            LogWriter(f).add_record(edit)
        with open(os.path.join(self.path, "000006.log"), "wb"):
            pass
        tmp = os.path.join(self.path, "CURRENT.tmp")
        with open(tmp, "w") as f:
            f.write("MANIFEST-000004\n")
        os.replace(tmp, os.path.join(self.path, "CURRENT"))
        self._entries = []

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
