"""DB-backed Datum batch sources — the host side of the reference DataLayer.

The reference's Data layer owns a DB cursor that walks records sequentially
and wraps at the end (data_layer.cpp:14-60, db_lmdb.cpp LMDBCursor), with
``rand_skip`` advancing the cursor once at startup and a DataTransformer
applying crop/mirror/scale/mean per record. On TPU the graph is pure, so
this runs host-side: a `DatumBatchSource` yields ready feed dicts that the
training loop (or a PrefetchIterator wrapping it) device_puts into the
compiled step.
"""

import os

import numpy as np

from .lmdb import LMDBReader
from .datum import datum_to_array
from .transforms import DataTransformer


def open_db(source, backend="lmdb"):
    """DataParameter.DB -> reader. The reference supports LEVELDB and LMDB
    (db.hpp GetDB); here LMDB is native and LevelDB is unsupported (its
    snappy-compressed SSTables need a native dependency this environment
    deliberately avoids) — convert with `sparknet convert_imageset`."""
    if isinstance(backend, int):
        backend = {0: "leveldb", 1: "lmdb"}[backend]
    backend = backend.lower()
    if backend == "lmdb":
        return LMDBReader(source)
    raise NotImplementedError(
        f"backend {backend!r}: only LMDB databases are readable "
        "(re-create LevelDB sources with `sparknet convert_imageset`)")


class DatumBatchSource:
    """Infinite batched iterator over a Datum database.

    Yields {data_top: float32 (B,C,ch,cw), label_top: int32 (B,)} feed
    dicts. Sequential wrap-around read order matches the reference cursor
    (data_layer.cpp:40-45: "restarting data prefetching from start").
    """

    def __init__(self, source, batch_size, phase=0, transform_param=None,
                 backend="lmdb", rand_skip=0, base_dir="", seed=None,
                 data_top="data", label_top="label"):
        self.source = source
        self.batch_size = int(batch_size)
        self.data_top, self.label_top = data_top, label_top
        rng = np.random.RandomState(seed)
        self.transformer = DataTransformer(transform_param, phase=phase,
                                           base_dir=base_dir, rng=rng)
        self.db = open_db(source, backend)
        if len(self.db) == 0:
            raise ValueError(f"{source}: empty database")
        # rand_skip: advance the cursor once by rand() % rand_skip
        # (data_layer.cpp DataLayerSetUp)
        self._skip = int(rng.randint(0, rand_skip)) if rand_skip else 0
        first = next(self.db.items())[1]
        arr, _ = datum_to_array(first)
        self.record_shape = arr.shape if arr.ndim == 3 \
            else (1, 1, int(arr.size))
        self.shape = (self.batch_size,) + \
            self.transformer.output_shape(self.record_shape)

    @property
    def num_batches(self):
        """Batches per full pass (ragged tail wraps, as in the reference)."""
        return max(1, len(self.db) // self.batch_size)

    def _records(self):
        skip = self._skip
        self._skip = 0
        while True:
            for _, value in self.db.items():
                if skip:
                    skip -= 1
                    continue
                yield datum_to_array(value)

    def __iter__(self):
        rec = self._records()
        c, h, w = self.record_shape
        while True:
            arrs = []
            labels = np.empty(self.batch_size, np.int32)
            for i in range(self.batch_size):
                arr, labels[i] = next(rec)
                arrs.append(arr.reshape(c, h, w))
            batch = np.stack(arrs)  # uint8, or float32 for float_data nets
            yield {self.data_top: self.transformer(batch),
                   self.label_top: labels}

    def close(self):
        self.db.close()


def phase_data_layers(net_param, phase):
    """Data-source layers of `net_param` active in `phase` (after the same
    include/exclude filtering FilterNet applies, net.cpp:287)."""
    from ..graph.compiler import filter_net
    out = []
    for lp in filter_net(net_param, phase).layer:
        if lp.type in ("Data", "ImageData"):
            out.append(lp)
    return out


def build_db_feed(net_param, phase, base_dir="", seed=None):
    """If the net's phase-filtered Data layer points at an existing LMDB,
    return (feed_shapes, source); else (None, None) — the caller falls back
    to synthetic feeds. This is what lets `sparknet train --solver
    cifar10_full_solver.prototxt` run the reference's most basic flow:
    stock prototxt -> real records -> trained net."""
    for lp in phase_data_layers(net_param, phase):
        if lp.type != "Data" or not lp.has("data_param"):
            continue
        dp = lp.data_param
        source = dp.source
        if base_dir and not os.path.isabs(source):
            source = os.path.join(base_dir, source)
        if not os.path.exists(_db_file(source)):
            continue
        tops = list(lp.top)
        src = DatumBatchSource(
            source, int(dp.batch_size), phase=phase,
            transform_param=lp.transform_param
            if lp.has("transform_param") else None,
            backend=int(dp.backend) if dp.has("backend") else "lmdb",
            rand_skip=int(dp.rand_skip), base_dir=base_dir, seed=seed,
            data_top=tops[0], label_top=tops[1] if len(tops) > 1 else "label")
        shapes = {tops[0]: src.shape}
        if len(tops) > 1:
            shapes[tops[1]] = (src.batch_size,)
        return shapes, src
    return None, None


def _db_file(source):
    return os.path.join(source, "data.mdb") if not source.endswith(".mdb") \
        else source
