"""DB-backed Datum batch sources — the host side of the reference DataLayer.

The reference's Data layer owns a DB cursor that walks records sequentially
and wraps at the end (data_layer.cpp:14-60, db_lmdb.cpp LMDBCursor), with
``rand_skip`` advancing the cursor once at startup and a DataTransformer
applying crop/mirror/scale/mean per record. On TPU the graph is pure, so
this runs host-side: a `DatumBatchSource` yields ready feed dicts that the
training loop (or a PrefetchIterator wrapping it) device_puts into the
compiled step.
"""

import os

import numpy as np

from .lmdb import LMDBReader
from .datum import datum_to_array
from .transforms import DataTransformer


def open_db(source, backend="lmdb"):
    """DataParameter.DB -> reader (db.cpp:10-22 GetDB dispatch). Both
    backends read through pure-Python format implementations: LMDB B+tree
    pages (lmdb.py) and LevelDB SSTables+MANIFEST+WAL with snappy blocks
    (leveldb.py). backend=None sniffs the directory layout."""
    if isinstance(backend, int):
        backend = {0: "leveldb", 1: "lmdb"}[backend]
    if backend is None:
        backend = "leveldb" if os.path.exists(
            os.path.join(source, "CURRENT")) else "lmdb"
    backend = backend.lower()
    if backend == "lmdb":
        return LMDBReader(source)
    if backend == "leveldb":
        from .leveldb import LevelDBReader
        return LevelDBReader(source)
    raise ValueError(f"unknown DB backend {backend!r}")


class DatumBatchSource:
    """Infinite batched iterator over a Datum database.

    Yields {data_top: float32 (B,C,ch,cw), label_top: int32 (B,)} feed
    dicts. Sequential wrap-around read order matches the reference cursor
    (data_layer.cpp:40-45: "restarting data prefetching from start").
    """

    def __init__(self, source, batch_size, phase=0, transform_param=None,
                 backend="lmdb", rand_skip=0, base_dir="", seed=None,
                 data_top="data", label_top="label", device_transform=False,
                 retry=None):
        self.source = source
        # transient-IO resilience: record reads go through a jittered
        # backoff RetryPolicy (SPARKNET_IO_RETRIES attempts by default, 0
        # disables) and the process-wide chaos injector exercises the path
        from ..resilience.retry import retry_from_env
        from ..resilience.chaos import active_chaos
        self._retry = retry if retry is not None else retry_from_env()
        self._chaos = active_chaos()
        self.batch_size = int(batch_size)
        self.data_top, self.label_top = data_top, label_top
        rng = np.random.RandomState(seed)
        self.transformer = DataTransformer(transform_param, phase=phase,
                                           base_dir=base_dir, rng=rng)
        # device mode: yield the raw uint8 records + host-drawn crop/mirror
        # randomness; the jitted step applies crop/mirror/mean on-chip
        # (device_transform.py — a transfer-bound link ships 3.2-4x fewer
        # bytes this way). The DeviceTransformer shares self.transformer's
        # config AND rng, so both modes see the same augmentation stream.
        self.device_mode = bool(device_transform)
        if self.device_mode:
            from .device_transform import DeviceTransformer
            self._devt = DeviceTransformer(self.transformer,
                                           data_top=data_top)
        self.db = open_db(source, backend)
        if len(self.db) == 0:
            raise ValueError(f"{source}: empty database")
        # rand_skip: advance the cursor once by rand() % rand_skip
        # (data_layer.cpp DataLayerSetUp)
        self._skip = int(rng.randint(0, rand_skip)) if rand_skip else 0
        first = next(self.db.items())[1]
        arr, _ = datum_to_array(first)
        self.record_shape = arr.shape if arr.ndim == 3 \
            else (1, 1, int(arr.size))
        self.shape = (self.batch_size,) + \
            self.transformer.output_shape(self.record_shape)
        # optional compressed wire format (device mode only): host-side
        # pre-crop and/or lossless bit-pack per SPARKNET_WIRE, decoded by
        # a wrapped device_fn (data/wire.py). raw mode = no codec = the
        # previous feed byte for byte.
        self._codec = None
        if self.device_mode:
            from .wire import WireCodec, wire_mode_from_env, \
                wire_bits_from_env
            mode = wire_mode_from_env()
            if mode != "raw":
                self._codec = WireCodec(
                    self._devt, self.record_shape, mode=mode,
                    bits=wire_bits_from_env(), sample=arr)

    @property
    def num_records(self):
        return len(self.db)

    @property
    def num_batches(self):
        """Batches per full pass (ragged tail wraps, as in the reference)."""
        return max(1, len(self.db) // self.batch_size)

    def _records(self):
        """Sequential wrap-around record stream. A transient IO error
        mid-cursor restarts the DB iterator and fast-forwards to the
        record that failed, under the retry policy's backoff/budget — a
        flaky read costs a re-walk, not the run."""
        pos = self._skip            # index of the next record this pass
        self._skip = 0
        attempt = 0
        while True:
            try:
                seen = 0
                for _, value in self.db.items():
                    if seen < pos:
                        seen += 1
                        continue
                    if self._chaos is not None:
                        self._chaos.maybe_io_error(self.source)
                    arr = datum_to_array(value)
                    seen += 1
                    pos += 1
                    if pos >= len(self.db):
                        pos = 0     # wrap ("restarting data prefetching")
                    attempt = 0     # progress resets the per-read attempts
                    yield arr
                pos = 0             # clean end of pass
            except OSError as e:
                if self._retry is None:
                    raise
                attempt += 1
                self._retry.record_failure(e, attempt, where=self.source)

    def __iter__(self):
        rec = self._records()
        c, h, w = self.record_shape
        while True:
            arrs = []
            labels = np.empty(self.batch_size, np.int32)
            for i in range(self.batch_size):
                arr, labels[i] = next(rec)
                arrs.append(arr.reshape(c, h, w))
            batch = np.stack(arrs)  # uint8, or float32 for float_data nets
            if self.device_mode:
                out = {self.data_top: batch, self.label_top: labels,
                       **self._devt.aux(self.batch_size, self.record_shape)}
                yield self._codec.encode(out) if self._codec else out
            else:
                yield {self.data_top: self.transformer(batch),
                       self.label_top: labels}

    def fresh_aux(self):
        """New host-side crop/mirror draws for one batch (data echoing:
        each echo of a transferred batch gets distinct augmentation)."""
        return self._devt.aux(self.batch_size, self.record_shape)

    @property
    def wire(self):
        """The active WireCodec, or None (raw wire / host mode)."""
        return self._codec

    @property
    def device_fn(self):
        """Jittable on-device transform (device mode only), wire-aware."""
        if self._codec is not None:
            return self._codec.device_fn()
        return self._devt.device_fn()

    @property
    def raw_feed_overrides(self):
        """check_batch shape overrides for the raw feed (device mode),
        reflecting the SHIPPED wire shapes when a codec is active."""
        if self._codec is not None:
            return self._codec.raw_overrides(self.batch_size)
        return self._devt.raw_overrides(self.batch_size, self.record_shape)

    def close(self):
        self.db.close()


def phase_data_layers(net_param, phase):
    """Data-source layers of `net_param` active in `phase` (after the same
    include/exclude filtering FilterNet applies, net.cpp:287)."""
    from ..graph.compiler import filter_net
    out = []
    for lp in filter_net(net_param, phase).layer:
        if lp.type in ("Data", "ImageData", "HDF5Data", "WindowData"):
            out.append(lp)
    return out


def _resolve(path, base_dir):
    return os.path.join(base_dir, path) \
        if base_dir and not os.path.isabs(path) else path


def build_db_feed(net_param, phase, base_dir="", seed=None,
                  device_transform=False):
    """If the net's phase-filtered data layer points at an existing source
    (Data -> LMDB, ImageData -> listfile, HDF5Data -> list-of-h5), return
    (feed_shapes, source); else (None, None) — the caller falls back to
    synthetic feeds. This is what lets `sparknet train --solver
    cifar10_full_solver.prototxt` run the reference's most basic flow:
    stock prototxt -> real records -> trained net."""
    from .file_sources import (ImageDataSource, HDF5DataSource,
                               WindowDataSource)
    for lp in phase_data_layers(net_param, phase):
        tops = list(lp.top)
        tp = lp.transform_param if lp.has("transform_param") else None
        if lp.type == "Data" and lp.has("data_param"):
            dp = lp.data_param
            source = _resolve(dp.source, base_dir)
            backend = int(dp.backend) if dp.has("backend") else None
            if not _db_exists(source, backend):
                continue
            src = DatumBatchSource(
                source, int(dp.batch_size), phase=phase, transform_param=tp,
                backend=backend,
                rand_skip=int(dp.rand_skip), base_dir=base_dir, seed=seed,
                data_top=tops[0],
                label_top=tops[1] if len(tops) > 1 else "label",
                device_transform=device_transform)
        elif lp.type == "ImageData" and lp.has("image_data_param"):
            ip = lp.image_data_param
            source = _resolve(ip.source, base_dir)
            if not os.path.exists(source):
                continue
            src = ImageDataSource(
                source, int(ip.batch_size), phase=phase, transform_param=tp,
                root_folder=_resolve(ip.root_folder, base_dir)
                if ip.root_folder else base_dir,
                new_height=int(ip.new_height), new_width=int(ip.new_width),
                is_color=bool(int(ip.is_color)), shuffle=bool(int(ip.shuffle)),
                rand_skip=int(ip.rand_skip), base_dir=base_dir, seed=seed,
                data_top=tops[0],
                label_top=tops[1] if len(tops) > 1 else "label")
        elif lp.type == "WindowData" and lp.has("window_data_param"):
            wp = lp.window_data_param
            source = _resolve(wp.source, base_dir)
            if not os.path.exists(source):
                continue
            if wp.has("cache_images") and bool(int(wp.cache_images)):
                import warnings
                warnings.warn(
                    f"layer {lp.name!r}: cache_images is ignored — "
                    "WindowDataSource decodes per sampled window (the "
                    "deliberate no-cache choice, file_sources.py), so "
                    "expect per-window decode cost", stacklevel=2)
            src = WindowDataSource(
                source, int(wp.batch_size), phase=phase, transform_param=tp,
                fg_threshold=float(wp.fg_threshold),
                bg_threshold=float(wp.bg_threshold),
                fg_fraction=float(wp.fg_fraction),
                context_pad=int(wp.context_pad),
                crop_mode=wp.crop_mode,
                root_folder=_resolve(wp.root_folder, base_dir)
                if wp.root_folder else base_dir,
                base_dir=base_dir, seed=seed, data_top=tops[0],
                label_top=tops[1] if len(tops) > 1 else "label")
        elif lp.type == "HDF5Data" and lp.has("hdf5_data_param"):
            hp = lp.hdf5_data_param
            source = _resolve(hp.source, base_dir)
            if not os.path.exists(source):
                continue
            src = HDF5DataSource(source, int(hp.batch_size), tops,
                                 shuffle=bool(int(hp.shuffle)), seed=seed)
            return dict(src.shape), src
        else:
            continue
        shapes = {tops[0]: src.shape}
        if len(tops) > 1:
            shapes[tops[1]] = (src.batch_size,)
        return shapes, src
    return None, None


def resolve_db_feed(net_param, phase, start_dir, seed=None,
                    device_transform=False):
    """build_db_feed with the CLI's walk-up source resolution: stock
    prototxt sources are caffe-root-relative, so try start_dir, then each
    parent, until a readable source appears. -> (shapes, src), or
    (None, None) when the net has no phase data layer or no source
    resolves at any level."""
    if not phase_data_layers(net_param, phase):
        return None, None
    d = os.path.abspath(start_dir or ".")
    while True:
        shapes, src = build_db_feed(net_param, phase, d, seed=seed,
                                    device_transform=device_transform)
        if src is not None:
            return shapes, src
        parent = os.path.dirname(d)
        if parent == d:
            return None, None
        d = parent


def _db_file(source):
    return os.path.join(source, "data.mdb") if not source.endswith(".mdb") \
        else source


def _db_exists(source, backend):
    """Does a readable DB of the declared (or sniffed) backend live here?"""
    if backend in (0, "leveldb"):
        return os.path.exists(os.path.join(source, "CURRENT"))
    if backend in (1, "lmdb"):
        return os.path.exists(_db_file(source))
    return os.path.exists(_db_file(source)) or \
        os.path.exists(os.path.join(source, "CURRENT"))
