"""ImageNet-style loader: tar archives of JPEGs + label map.

Behavioral port of reference ImageNetLoader.scala: list archives (S3 there,
filesystem/glob here — zero-egress), build a filename->label map from a
``train.txt``-style file (:41-54: lines "n01440764_10026.JPEG 0"), stream
each tar's entries into (jpeg bytes, label) records (:56-86), then decode +
force-resize like ScaleAndConvert.scala (:16-27 — undecodable images are
silently dropped, :22-26) and pack fixed-size minibatches dropping the
ragged tail (:30-76).
"""

import glob
import io
import os
import tarfile

import numpy as np

SOURCE_SIZE = 256


def load_label_map(path):
    """"<filename> <int label>" lines -> {basename_without_ext: label}."""
    labels = {}
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                name = os.path.basename(parts[0])
                labels[os.path.splitext(name)[0]] = int(parts[1])
    return labels


def _decode_resize(jpeg_bytes, size):
    """JPEG/PNG bytes -> (3, size, size) uint8 CHW, or None if undecodable
    (ScaleAndConvert drops those)."""
    try:
        from PIL import Image
        img = Image.open(io.BytesIO(jpeg_bytes)).convert("RGB")
        img = img.resize((size, size))   # force-resize, aspect be damned —
        # exactly what Thumbnailator forceSize did (ScaleAndConvert.scala:20)
        arr = np.asarray(img, np.uint8)
        return arr.transpose(2, 0, 1)
    except Exception:
        return None


def stream_tar_records(tar_path, label_map, size=SOURCE_SIZE):
    """Yield (image CHW uint8, label) from one tar archive."""
    with tarfile.open(tar_path) as tf:
        for member in tf:
            if not member.isfile():
                continue
            key = os.path.splitext(os.path.basename(member.name))[0]
            if label_map is not None and key not in label_map:
                continue
            data = tf.extractfile(member).read()
            img = _decode_resize(data, size)
            if img is None:
                continue    # dropped, like ScaleAndConvert.scala:22-26
            yield img, (label_map[key] if label_map is not None else 0)


class ImageNetLoader:
    """archive_glob -> endless stream of (images (N,3,S,S) uint8, labels)."""

    def __init__(self, archive_glob, labels_path=None, batch_size=256,
                 size=SOURCE_SIZE, loop=True, shard_index=0, num_shards=1):
        self.paths = sorted(glob.glob(archive_glob))
        if not self.paths:
            raise FileNotFoundError(f"no archives match {archive_glob!r}")
        # per-host sharding of the archive list (replaces RDD partitioning)
        self.paths = self.paths[shard_index::num_shards]
        self.label_map = load_label_map(labels_path) if labels_path else None
        self.batch_size = batch_size
        self.size = size
        self.loop = loop

    def __iter__(self):
        imgs, labs = [], []
        while True:
            for path in self.paths:
                for img, lab in stream_tar_records(path, self.label_map,
                                                   self.size):
                    imgs.append(img)
                    labs.append(lab)
                    if len(imgs) == self.batch_size:
                        yield (np.stack(imgs),
                               np.asarray(labs, np.int32))
                        imgs, labs = [], []
            if not self.loop:
                return   # ragged tail dropped (ScaleAndConvert.scala:48)
