"""On-device DataTransformer — crop/mirror/mean/scale inside the jitted step.

The host path (transforms.DataTransformer -> native transform_batch) ships
float32 *crops* to the device: for CaffeNet that is 227*227*3*4 = 618 KB per
image. On a transfer-bound link (any real host->HBM path, and especially the
remote tunnel this rig trains over) the winning layout is the reference's
own storage layout: ship the raw uint8 source batch (256*256*3 = 196 KB per
image, 3.2x less; 4x less for uncropped CIFAR records) and apply the
reference transform semantics (data_transformer.cpp:42-51:
``top[mirrored_index] = (src[data_index] - mean[data_index]) * scale``)
on-chip, where XLA fuses them into the first conv's input pipeline.

The split of responsibilities keeps the reference's per-record randomness
exactly where it lives in Caffe (host-side ``Rand()`` in the data layer's
transform call) while moving the bandwidth-heavy work on-device:

  host:   draws per-image crop offsets and mirror flags — tiny int arrays
          (a few bytes/image) riding along with the uint8 batch;
  device: gathers the crop windows (vmapped ``lax.dynamic_slice``), applies
          the mirror, subtracts the mean (full mean source-indexed *before*
          the mirror, per-channel mean after — both per the reference), and
          scales.

Bit-exactness against the native host kernel (native/pipeline.cpp
transform_batch) on identical offsets/flags is asserted by
tests/test_device_transform.py; the two paths share the same float32
operation order so they agree exactly, not just approximately.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .transforms import DataTransformer


def aux_keys(data_top):
    """Names of the host-side randomness arrays riding with ``data_top``.
    '#' keeps them out of any legal prototxt blob namespace."""
    return (f"{data_top}#y", f"{data_top}#x", f"{data_top}#flip")


class DeviceTransformer:
    """Device-side twin of a (configured) DataTransformer.

    Wraps the host transformer for its parsed TransformationParameter state
    (scale / mirror / crop_size / mean_file XOR mean_value, phase) and its
    RandomState — the aux draws below consume the rng in the same order as
    DataTransformer.__call__, so a source switched between host and device
    modes sees the identical augmentation stream.
    """

    def __init__(self, host_transformer, data_top="data"):
        self.h = host_transformer
        self.data_top = data_top
        self.ky, self.kx, self.kf = aux_keys(data_top)

    # -- host side ---------------------------------------------------------
    def aux(self, n, record_shape):
        """Per-batch randomness: {aux_key: int array} for ``n`` images of
        ``record_shape`` (C,H,W). TRAIN draws random offsets/flips, TEST
        uses the center window — exactly DataTransformer.__call__'s draws."""
        h_, w_ = record_shape[1], record_shape[2]
        t = self.h
        out = {}
        crop = t.crop_size
        if crop:
            if t.phase == 0:
                ys = t.rng.randint(0, h_ - crop + 1, n).astype(np.int32)
                xs = t.rng.randint(0, w_ - crop + 1, n).astype(np.int32)
            else:
                ys = np.full(n, (h_ - crop) // 2, np.int32)
                xs = np.full(n, (w_ - crop) // 2, np.int32)
            out[self.ky], out[self.kx] = ys, xs
        if t.mirror:
            out[self.kf] = t.rng.randint(0, 2, n).astype(np.uint8)
        return out

    def raw_overrides(self, batch_size, record_shape):
        """check_batch shape overrides for the raw (pre-transform) feed:
        the uint8 source extent plus the aux arrays."""
        over = {self.data_top: (batch_size,) + tuple(record_shape)}
        for k in self.aux(0, record_shape):
            over[k] = (batch_size,)
        return over

    # -- device side -------------------------------------------------------
    def device_fn(self, precropped=False):
        """-> pure fn(batch dict) -> batch dict, jit-traceable and
        shape-polymorphic over the batch dim (works under shard_map slices
        and lax.scan micro-batches). Consumes ``data_top`` (+ aux keys),
        passes every other entry (labels, extra feeds) through.

        ``precropped``: the wire codec already sliced the crop window from
        the uint8 source on the host (data/wire.py), so skip the crop
        gather — but still consume the y/x aux to slice the full-size mean
        at the ORIGINAL source coordinates, keeping the float32 op order
        (and output bits) identical to the uncropped path: slicing uint8
        then casting equals casting then slicing.
        """
        t = self.h
        crop = t.crop_size
        scale = t.scale
        full_mean = t.full_mean
        mean = None if t.mean is None else jnp.asarray(t.mean, jnp.float32)
        data_top, ky, kx, kf = self.data_top, self.ky, self.kx, self.kf

        def fn(batch):
            batch = dict(batch)
            x = batch.pop(data_top)
            c = x.shape[1]
            out = x.astype(jnp.float32)
            flips = batch.pop(kf, None)
            if crop:
                ys = batch.pop(ky)
                xs = batch.pop(kx)

                if not precropped:
                    def win(img, y, x0):
                        return lax.dynamic_slice(img, (0, y, x0),
                                                 (c, crop, crop))
                    out = jax.vmap(win)(out, ys, xs)
                if mean is not None and full_mean:
                    # source-indexed mean window, subtracted pre-mirror
                    out = out - jax.vmap(
                        lambda y, x0: lax.dynamic_slice(
                            mean, (0, y, x0), (c, crop, crop)))(ys, xs)
                if flips is not None:
                    out = jnp.where(flips[:, None, None, None] != 0,
                                    out[..., ::-1], out)
            else:
                if mean is not None and full_mean:
                    out = out - mean[None]
                if flips is not None:
                    out = jnp.where(flips[:, None, None, None] != 0,
                                    out[..., ::-1], out)
            if mean is not None and not full_mean:
                m = mean
                if m.shape[0] == 1 and c > 1:
                    m = jnp.broadcast_to(m, (c,))
                out = out - m.reshape(1, -1, 1, 1)
            if scale != 1.0:
                out = out * scale
            batch[data_top] = out
            return batch

        return fn


def build_device_transformer(tp, phase=0, base_dir="", rng=None,
                             data_top="data"):
    """TransformationParameter -> DeviceTransformer (parsing — incl. the
    mean_file binaryproto load — delegated to the host DataTransformer)."""
    host = DataTransformer(tp, phase=phase, base_dir=base_dir, rng=rng)
    return DeviceTransformer(host, data_top=data_top)
