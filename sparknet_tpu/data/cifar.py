"""CIFAR-10 binary-format loader.

Behavioral port of reference CifarLoader.scala: reads the five
``data_batch_N.bin`` files (each record = 1 label byte + 3072 CHW image
bytes) plus ``test_batch.bin``, shuffles the train set by a permutation, and
computes the mean image over the train set (CifarLoader.scala:58-64). Arrays
are numpy (N, 3, 32, 32) uint8 — vectorized, not per-byte loops.
"""

import os
import glob

import numpy as np

HEIGHT = WIDTH = 32
CHANNELS = 3
SIZE = CHANNELS * HEIGHT * WIDTH
RECORD = 1 + SIZE


def read_batch_file(path):
    """One .bin file -> (images uint8 (N,3,32,32), labels int32 (N,))."""
    raw = np.fromfile(path, np.uint8)
    if raw.size % RECORD:
        raise ValueError(f"{path}: size {raw.size} not a multiple of {RECORD}")
    from .. import native
    images, labels = native.decode_cifar_records(raw, RECORD)
    return images.reshape(-1, CHANNELS, HEIGHT, WIDTH), labels


def write_batch_file(path, images, labels):
    """Inverse of read_batch_file (test fixtures / format round-trip)."""
    images = np.asarray(images, np.uint8).reshape(-1, SIZE)
    labels = np.asarray(labels, np.uint8).reshape(-1, 1)
    np.concatenate([labels, images], axis=1).tofile(path)


class CifarDataset:
    """Train/test arrays + mean image, shuffled like the reference loader."""

    def __init__(self, path, seed=None):
        files = sorted(glob.glob(os.path.join(path, "*.bin")))
        test_files = [f for f in files
                      if os.path.basename(f) == "test_batch.bin"]
        if not test_files:
            raise FileNotFoundError(f"no test_batch.bin under {path}")
        train_files = [f for f in files if f not in test_files]
        imgs, labs = zip(*(read_batch_file(f) for f in train_files))
        self.train_images = np.concatenate(imgs)
        self.train_labels = np.concatenate(labs)
        self.test_images, self.test_labels = read_batch_file(test_files[0])
        rng = np.random.RandomState(seed)
        perm = rng.permutation(len(self.train_images))
        self.train_images = self.train_images[perm]
        self.train_labels = self.train_labels[perm]
        # mean image over the train set, float32 CHW
        self.mean_image = self.train_images.astype(np.float64) \
            .mean(axis=0).astype(np.float32)

    def minibatches(self, batch_size, train=True, subtract_mean=True,
                    scale=1.0, drop_ragged=True):
        """Yield {'data','label'} batches; ragged tail dropped like the
        reference's fixed-size minibatch packing (ScaleAndConvert.scala:48)."""
        images = self.train_images if train else self.test_images
        labels = self.train_labels if train else self.test_labels
        n = len(images) // batch_size * batch_size if drop_ragged \
            else len(images)
        for i in range(0, n, batch_size):
            x = images[i:i + batch_size].astype(np.float32)
            if subtract_mean:
                x = x - self.mean_image
            if scale != 1.0:
                x = x * scale
            yield {"data": x, "label": labels[i:i + batch_size]}
