"""Background prefetch — the reference's double-buffered loader threads.

Caffe's BasePrefetchingDataLayer ran an InternalThread pumping batches
through a prefetch_free_/prefetch_full_ BlockingQueue pair
(base_data_layer.cpp:70-101, data_layers.hpp:91-93). Same structure: a
bounded queue (depth = the number of in-flight buffers), worker thread(s)
running the host-side produce fn (decode/transform — which release the GIL
in the native pipeline), and optionally jax.device_put so host->HBM copies
overlap the running step.
"""

import queue
import threading


_END = object()


class PrefetchIterator:
    """Wrap a batch iterator (or factory) with N background workers.

    depth: max buffered batches (2 = classic double buffering).
    transform: optional fn(batch)->batch run in the worker (e.g. the crop/
               mean native transform, or jax.device_put for H2D overlap).
    workers > 1 preserves NO ordering guarantees (like the reference's
    single reader it defaults to 1, which does).
    metrics: optional utils.metrics.MetricsLogger; queue-depth gauges are
             emitted as ``prefetch`` events every ``emit_every`` consumer
             gets (and once at close). An empty queue at get time means the
             consumer is about to block on the producer — a sustained
             empty_frac near 1.0 says the input pipeline, not the device,
             is the bound.
    """

    def __init__(self, source, depth=2, transform=None, workers=1,
                 metrics=None, name="prefetch", emit_every=100):
        self._q = queue.Queue(maxsize=depth)
        self._transform = transform
        self._stop = threading.Event()
        self._done = False
        self._error = None
        self._src_lock = threading.Lock()
        # workers > 1 share one upstream iterator; the lock checker
        # (`sparknet lint`, SPK201) verifies every next() holds the lock
        self._source = iter(source)     # spk: guarded-by=_src_lock
        self._metrics = metrics
        self._name = name
        self._emit_every = max(1, emit_every)
        self._depth = depth
        self._gets = 0
        self._depth_sum = 0
        self._empty_gets = 0
        self._stats_emitted = False
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"sparknet-prefetch-{i}")
            for i in range(workers)]
        self._live_lock = threading.Lock()
        self._live = len(self._threads)  # spk: guarded-by=_live_lock
        for t in self._threads:
            t.start()

    def _run(self):
        try:
            while not self._stop.is_set():
                with self._src_lock:
                    try:
                        item = next(self._source)
                    except StopIteration:
                        break
                if self._transform is not None:
                    item = self._transform(item)
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:     # surfaced on the consumer side
            self._error = e
        finally:
            with self._live_lock:
                self._live -= 1
                if self._live == 0:
                    self._q.put(_END)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            # exhausted or closed: re-raise the worker error (if any)
            # instead of blocking forever on an empty queue
            if self._error is not None:
                raise self._error
            raise StopIteration
        d = self._q.qsize()          # approximate, fine for a gauge
        self._gets += 1
        self._depth_sum += d
        if d == 0:
            self._empty_gets += 1
        if self._metrics is not None and self._gets % self._emit_every == 0:
            self._emit_stats()
        item = self._q.get()
        if item is _END:
            self._done = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def stats(self):
        """Queue-depth gauges over the consumer's gets so far."""
        g = self._gets
        return {"name": self._name, "gets": g, "depth_cap": self._depth,
                "depth_mean": round(self._depth_sum / g, 3) if g else None,
                "empty_frac": round(self._empty_gets / g, 3) if g else None}

    def _emit_stats(self):
        self._metrics.log("prefetch", **self.stats())

    def close(self):
        if self._metrics is not None and self._gets \
                and not self._stats_emitted:
            self._stats_emitted = True
            self._emit_stats()
        self._done = True
        self._stop.set()
        # drain so producers blocked on put() can exit; a worker error that
        # already surfaced stays in self._error for subsequent __next__
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
