"""Background prefetch — the reference's double-buffered loader threads.

Caffe's BasePrefetchingDataLayer ran an InternalThread pumping batches
through a prefetch_free_/prefetch_full_ BlockingQueue pair
(base_data_layer.cpp:70-101, data_layers.hpp:91-93). Same structure: a
bounded queue (depth = the number of in-flight buffers), worker thread(s)
running the host-side produce fn (decode/transform — which release the GIL
in the native pipeline), and optionally jax.device_put so host->HBM copies
overlap the running step.

Two feed-path companions live here because they slot into the same
iterator chain:

  H2DStager    — a prefetch ``transform`` that turns "device_put in the
                 worker" into true double buffering: each put is
                 dispatched non-blocking into a rotating slot and only
                 the (slots+1)-th oldest transfer is waited on, so batch
                 N+1's H2D copy runs while step N computes, with bounded
                 in-flight HBM.
  EchoIterator — data echoing (Choi et al.): serve each upstream batch E
                 times, optionally swapping in fresh crop/mirror aux
                 draws per echo so the device sees E distinct
                 augmentations of one transferred payload.
"""

import collections
import queue
import threading
import time


_END = object()
_ERR = object()     # a worker died; the queue stays FIFO so items the
                    # worker produced before failing still arrive first


class PrefetchIterator:
    """Wrap a batch iterator (or factory) with N background workers.

    depth: max buffered batches (2 = classic double buffering).
    transform: optional fn(batch)->batch run in the worker (e.g. the crop/
               mean native transform, or an H2DStager for H2D overlap).
    workers > 1 preserves NO ordering guarantees (like the reference's
    single reader it defaults to 1, which does).
    metrics: optional utils.metrics.MetricsLogger; queue-depth gauges are
             emitted as ``prefetch`` events every ``emit_every`` consumer
             gets (and once at close). An empty queue at get time means the
             consumer is about to block on the producer — a sustained
             empty_frac near 1.0 says the input pipeline, not the device,
             is the bound.
    extra: optional static fields (echo factor, wire mode, ingest shard)
           merged into stats() and the ``prefetch`` event.

    A worker exception is propagated to the consumer exactly once, with
    the original traceback, after any batches produced before the failure;
    iteration then ends (StopIteration). The failing worker also stops its
    siblings, so a poisoned source cannot wedge a workers>1 pool on a full
    queue.
    """

    def __init__(self, source, depth=2, transform=None, workers=1,
                 metrics=None, name="prefetch", emit_every=100, extra=None):
        self._q = queue.Queue(maxsize=depth)
        self._transform = transform
        self._stop = threading.Event()
        self._done = False
        self._error = None
        self._src_lock = threading.Lock()
        # workers > 1 share one upstream iterator; the lock checker
        # (`sparknet lint`, SPK201) verifies every next() holds the lock
        self._source = iter(source)     # spk: guarded-by=_src_lock
        self._metrics = metrics
        self._name = name
        self._emit_every = max(1, emit_every)
        self._extra = dict(extra) if extra else {}
        self._depth = depth
        self._gets = 0
        self._depth_sum = 0
        self._empty_gets = 0
        self._stats_emitted = False
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"sparknet-prefetch-{i}")
            for i in range(workers)]
        self._live_lock = threading.Lock()
        self._live = len(self._threads)  # spk: guarded-by=_live_lock
        for t in self._threads:
            t.start()

    def _run(self):
        try:
            while not self._stop.is_set():
                with self._src_lock:
                    try:
                        item = next(self._source)
                    except StopIteration:
                        break
                if self._transform is not None:
                    item = self._transform(item)
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:     # surfaced on the consumer side
            if self._error is None:    # first failure wins
                self._error = e
            self._stop.set()           # release siblings blocked on put()
            # the stop flag just disarmed the normal put loop, so push the
            # sentinel with its own bounded retry (consumer may lag or may
            # already be closed)
            while not self._done:
                try:
                    self._q.put(_ERR, timeout=0.1)
                    break
                except queue.Full:
                    continue
        finally:
            with self._live_lock:
                self._live -= 1
                if self._live == 0:
                    self._q.put(_END)

    def __iter__(self):
        return self

    def _finish(self):
        # exactly-once error propagation: hand the exception object (its
        # __traceback__ points at the worker frame) to the first raiser,
        # then clear it so later calls see a plain end-of-stream
        self._done = True
        err, self._error = self._error, None
        if err is not None:
            raise err
        raise StopIteration

    def __next__(self):
        if self._done:
            self._finish()
        d = self._q.qsize()          # approximate, fine for a gauge
        self._gets += 1
        self._depth_sum += d
        if d == 0:
            self._empty_gets += 1
        if self._metrics is not None and self._gets % self._emit_every == 0:
            self._emit_stats()
        item = self._q.get()
        if item is _END or item is _ERR:
            self._finish()
        return item

    def stats(self):
        """Queue-depth gauges over the consumer's gets so far."""
        g = self._gets
        out = {"name": self._name, "gets": g, "depth_cap": self._depth,
               "depth_mean": round(self._depth_sum / g, 3) if g else None,
               "empty_frac": round(self._empty_gets / g, 3) if g else None}
        out.update(self._extra)
        return out

    def _emit_stats(self):
        self._metrics.log("prefetch", **self.stats())

    def close(self):
        if self._metrics is not None and self._gets \
                and not self._stats_emitted:
            self._stats_emitted = True
            self._emit_stats()
        self._done = True
        self._stop.set()
        # drain so producers blocked on put() can exit; an unconsumed
        # worker error is dropped — the consumer chose to stop first
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class H2DStager:
    """Rotating-slot async H2D staging, used as a prefetch ``transform``.

    ``jax.device_put`` only *dispatches* a copy; the old inline-put path
    still serialized feeds whenever the worker produced faster than the
    link, because nothing bounded how the puts queued behind each other.
    The stager keeps up to ``slots`` transfers in flight: each call
    dispatches the new batch non-blocking, then waits on the transfer that
    is now slots+1 deep — i.e. one the consumer is about to need anyway —
    so the wait overlaps the running step instead of preceding it, and
    staged HBM stays bounded at slots+1 batches.

    Safe from multiple prefetch workers (counters are lock-guarded);
    ``chaos`` hooks ChaosMonkey.maybe_slow_h2d so the smoke test can make
    the wire artificially slow.
    """

    def __init__(self, slots=2, metrics=None, name="h2d", emit_every=50,
                 chaos=None):
        import jax
        self._jax = jax
        self.slots = max(1, int(slots))
        self._metrics = metrics
        self._name = name
        self._emit_every = max(1, emit_every)
        self._chaos = chaos
        self._lock = threading.Lock()
        self._ring = collections.deque()    # spk: guarded-by=_lock
        self._puts = 0                      # spk: guarded-by=_lock
        self._bytes = 0                     # spk: guarded-by=_lock
        self._dispatch_s = 0.0              # spk: guarded-by=_lock
        self._wait_s = 0.0                  # spk: guarded-by=_lock

    @staticmethod
    def _nbytes(batch):
        vals = batch.values() if isinstance(batch, dict) else [batch]
        return sum(int(getattr(v, "nbytes", 0)) for v in vals)

    def __call__(self, batch):
        nbytes = self._nbytes(batch)
        if self._chaos is not None:
            self._chaos.maybe_slow_h2d(nbytes=nbytes)
        put = self._jax.device_put
        t0 = time.perf_counter()
        if isinstance(batch, dict):
            staged = {k: put(v) for k, v in batch.items()}
            leaves = list(staged.values())
        else:
            staged = put(batch)
            leaves = [staged]
        t1 = time.perf_counter()
        with self._lock:
            self._ring.append(leaves)
            oldest = self._ring.popleft() \
                if len(self._ring) > self.slots else None
        t2 = time.perf_counter()
        if oldest is not None:
            for leaf in oldest:
                leaf.block_until_ready()
        t3 = time.perf_counter()
        with self._lock:
            self._puts += 1
            self._bytes += nbytes
            self._dispatch_s += t1 - t0
            self._wait_s += t3 - t2
            puts = self._puts
            emit = (self._metrics is not None
                    and puts % self._emit_every == 0)
            snap = self._stats_locked() if emit else None
        if emit:
            self._metrics.log(
                "h2d_stage", name=snap["name"], puts=snap["puts"],
                bytes=snap["bytes"], kb_per_item=snap["kb_per_item"],
                dispatch_ms=snap["dispatch_ms"], wait_ms=snap["wait_ms"],
                in_flight=snap["in_flight"], slots=snap["slots"])
        return staged

    def _stats_locked(self):        # spk: holds=_lock
        p = self._puts
        return {
            "name": self._name, "puts": p, "bytes": self._bytes,
            "kb_per_item": round(self._bytes / p / 1024.0, 1) if p else 0.0,
            "dispatch_ms": round(self._dispatch_s / p * 1e3, 3) if p else 0.0,
            "wait_ms": round(self._wait_s / p * 1e3, 3) if p else 0.0,
            "in_flight": len(self._ring), "slots": self.slots}

    def stats(self):
        with self._lock:
            return self._stats_locked()

    def flush(self):
        """Block the remaining in-flight transfers (end of run)."""
        with self._lock:
            pending, self._ring = list(self._ring), collections.deque()
        for leaves in pending:
            for leaf in leaves:
                leaf.block_until_ready()


class EchoIterator:
    """Serve each upstream batch ``echo`` times (data echoing).

    fresh_aux: optional fn(batch)->{aux_key: array} giving NEW host-side
    crop/mirror draws for every echo after the first, so each echo is a
    distinct augmentation of the same transferred pixels. Echoes shallow-
    copy the batch dict and swap only the tiny aux arrays — the staged
    pixel payload is reused by reference, which is the whole point.

    echo == 1 is a strict passthrough: no extra rng draws, no copies, so
    the E=1 trajectory is bit-identical to the unwrapped pipeline.
    Delegates close()/stats() to the wrapped iterator.
    """

    def __init__(self, source, echo, fresh_aux=None):
        self._inner = source
        self._it = iter(source)
        self.echo = max(1, int(echo))
        self._fresh_aux = fresh_aux
        self._base = None
        self._left = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self.echo == 1:
            return next(self._it)
        if self._left > 0:
            self._left -= 1
            b = self._base
            if self._fresh_aux is not None and isinstance(b, dict):
                b = dict(b)
                b.update(self._fresh_aux(self._base))
            return b
        self._base = next(self._it)
        self._left = self.echo - 1
        return self._base

    def stats(self):
        inner = getattr(self._inner, "stats", None)
        out = dict(inner()) if inner is not None else {}
        out["echo"] = self.echo
        return out

    def close(self):
        inner = getattr(self._inner, "close", None)
        if inner is not None:
            inner()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
