"""Background prefetch — the reference's double-buffered loader threads.

Caffe's BasePrefetchingDataLayer ran an InternalThread pumping batches
through a prefetch_free_/prefetch_full_ BlockingQueue pair
(base_data_layer.cpp:70-101, data_layers.hpp:91-93). Same structure: a
bounded queue (depth = the number of in-flight buffers), worker thread(s)
running the host-side produce fn (decode/transform — which release the GIL
in the native pipeline), and optionally jax.device_put so host->HBM copies
overlap the running step.
"""

import queue
import threading


_END = object()


class PrefetchIterator:
    """Wrap a batch iterator (or factory) with N background workers.

    depth: max buffered batches (2 = classic double buffering).
    transform: optional fn(batch)->batch run in the worker (e.g. the crop/
               mean native transform, or jax.device_put for H2D overlap).
    workers > 1 preserves NO ordering guarantees (like the reference's
    single reader it defaults to 1, which does).
    """

    def __init__(self, source, depth=2, transform=None, workers=1):
        self._q = queue.Queue(maxsize=depth)
        self._transform = transform
        self._stop = threading.Event()
        self._done = False
        self._error = None
        self._source = iter(source)
        self._src_lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"sparknet-prefetch-{i}")
            for i in range(workers)]
        self._live = len(self._threads)
        self._live_lock = threading.Lock()
        for t in self._threads:
            t.start()

    def _run(self):
        try:
            while not self._stop.is_set():
                with self._src_lock:
                    try:
                        item = next(self._source)
                    except StopIteration:
                        break
                if self._transform is not None:
                    item = self._transform(item)
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:     # surfaced on the consumer side
            self._error = e
        finally:
            with self._live_lock:
                self._live -= 1
                if self._live == 0:
                    self._q.put(_END)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            # exhausted or closed: re-raise the worker error (if any)
            # instead of blocking forever on an empty queue
            if self._error is not None:
                raise self._error
            raise StopIteration
        item = self._q.get()
        if item is _END:
            self._done = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def close(self):
        self._done = True
        self._stop.set()
        # drain so producers blocked on put() can exit; a worker error that
        # already surfaced stays in self._error for subsequent __next__
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
