"""Input transforms — the reference DataTransformer + app closures.

Replaces caffe/src/caffe/data_transformer.cpp (crop/mirror/scale/mean,
:42-51) and the per-image Scala preprocessing closures
(ImageNetApp.scala:155-169: random 227x227 crop + mean subtraction; test
variant :117-131: center crop). Vectorized over the whole batch — the
reference looped per image per pixel in a JVM closure.
"""

import numpy as np

from .. import native


def transform_train(images, crop, mean=None, mirror=True, rng=None,
                    scale=1.0):
    """Fused random-crop + mirror + mean-subtract + scale, one native pass
    over the batch (the data_transformer.cpp TRAIN path). mean must be
    per-channel (C,) or already cropped (C,crop,crop)."""
    rng = rng or np.random
    n, c, h, w = images.shape
    ys = rng.randint(0, h - crop + 1, size=n).astype(np.int32)
    xs = rng.randint(0, w - crop + 1, size=n).astype(np.int32)
    flips = rng.randint(0, 2, size=n).astype(np.uint8) if mirror else None
    return native.transform_batch(images, crop, ys=ys, xs=xs, mirror=flips,
                                  mean=_crop_mean(mean, c, crop),
                                  scale=scale)


def transform_test(images, crop, mean=None, scale=1.0):
    """Fused center-crop + mean-subtract (the TEST path)."""
    n, c, h, w = images.shape
    ys = np.full(n, (h - crop) // 2, np.int32)
    xs = np.full(n, (w - crop) // 2, np.int32)
    return native.transform_batch(images, crop, ys=ys, xs=xs,
                                  mean=_crop_mean(mean, c, crop),
                                  scale=scale)


def _crop_mean(mean, c, crop):
    if mean is None:
        return None
    mean = np.asarray(mean, np.float32)
    if mean.ndim == 3 and mean.shape[-2:] != (crop, crop):
        mh, mw = mean.shape[-2:]
        y, x = (mh - crop) // 2, (mw - crop) // 2
        mean = np.ascontiguousarray(mean[:, y:y + crop, x:x + crop])
    return mean


def random_crop(images, crop, rng=None, mirror=False):
    """(N, C, H, W) -> (N, C, crop, crop) with per-image random offsets
    (+ optional per-image horizontal mirror, data_transformer.cpp:42-51)."""
    rng = rng or np.random
    n, c, h, w = images.shape
    if h == crop and w == crop:
        out = images
    else:
        ys = rng.randint(0, h - crop + 1, size=n)
        xs = rng.randint(0, w - crop + 1, size=n)
        out = np.empty((n, c, crop, crop), images.dtype)
        for i in range(n):   # per-image offsets; the copy dominates anyway
            out[i] = images[i, :, ys[i]:ys[i] + crop, xs[i]:xs[i] + crop]
    if mirror:
        flips = rng.randint(0, 2, size=n).astype(bool)
        out = out.copy() if out is images else out
        out[flips] = out[flips, :, :, ::-1]
    return out


def center_crop(images, crop):
    """Deterministic center crop (TEST phase, ImageNetApp.scala:117-131)."""
    n, c, h, w = images.shape
    y, x = (h - crop) // 2, (w - crop) // 2
    return images[:, :, y:y + crop, x:x + crop]


def subtract_mean(images, mean_image):
    """float32 output; mean may be a full CHW image (mean_file) or
    per-channel values (mean_value)."""
    images = np.asarray(images, np.float32)
    mean = np.asarray(mean_image, np.float32)
    if mean.ndim == 1:   # per-channel
        mean = mean.reshape(-1, 1, 1)
    if mean.ndim == 3 and mean.shape[-2:] != images.shape[-2:]:
        # mean image larger than crop: use its center window (caffe requires
        # equal dims after crop; data_transformer.cpp does the same check)
        mh, mw = mean.shape[-2:]
        h, w = images.shape[-2:]
        y, x = (mh - h) // 2, (mw - w) // 2
        mean = mean[:, y:y + h, x:x + w]
    return images - mean


def load_mean_binaryproto(path):
    """.binaryproto BlobProto -> (C,H,W) float32 mean image
    (data_transformer.cpp:19-28 mean_file load)."""
    from ..proto import wire
    blob = wire.load(path, "BlobProto")
    data = np.asarray(blob.data, np.float32)
    if blob.has("shape"):
        shape = tuple(int(d) for d in blob.shape.dim)
    else:
        shape = (int(blob.num), int(blob.channels), int(blob.height),
                 int(blob.width))
    data = data.reshape([d for d in shape if d] or [-1])
    if data.ndim == 4:       # legacy num=1 leading axis
        data = data[0]
    return data


def save_mean_binaryproto(mean, path):
    """(C,H,W) float32 -> .binaryproto BlobProto with legacy NCHW dims
    (what tools/compute_image_mean.cpp writes)."""
    from ..proto import Message, wire
    mean = np.asarray(mean, np.float32)
    c, h, w = mean.shape
    blob = Message("BlobProto", num=1, channels=c, height=h, width=w)
    blob.data.extend_np(mean.ravel())
    wire.dump(blob, path)


class DataTransformer:
    """TransformationParameter-driven batch transform — the configuration
    surface of the reference DataTransformer (data_transformer.cpp:19-51):
    scale, mirror, crop_size, mean_file XOR mean_value, with TRAIN = random
    crop + random mirror and TEST = center crop + random mirror (caffe
    mirrors in both phases when mirror:true)."""

    def __init__(self, tp=None, phase=0, base_dir="", rng=None):
        import os
        self.phase = phase
        self.rng = rng or np.random.RandomState()
        self.scale = float(tp.scale) if tp is not None else 1.0
        self.mirror = bool(tp.mirror) if tp is not None else False
        self.crop_size = int(tp.crop_size) if tp is not None else 0
        self.mean = None
        self.full_mean = False
        if tp is not None and tp.has("mean_file"):
            if list(tp.mean_value):
                raise ValueError(
                    "specify either mean_file or mean_value, not both "
                    "(data_transformer.cpp CHECK)")
            path = tp.mean_file
            if base_dir and not os.path.isabs(path):
                path = os.path.join(base_dir, path)
            self.mean = load_mean_binaryproto(path)
            self.full_mean = True
        elif tp is not None and list(tp.mean_value):
            self.mean = np.asarray([float(v) for v in tp.mean_value],
                                   np.float32)

    def output_shape(self, record_shape):
        c, h, w = record_shape
        s = self.crop_size or None
        return (c, s or h, s or w)

    def __call__(self, images):
        """uint8/float (N,C,H,W) -> float32 (N,C,crop,crop), or (N,C,H,W)
        when crop_size is 0 (caffe crops are always square; uncropped
        records keep their full, possibly non-square, extent)."""
        images = np.asarray(images)
        n, c, h, w = images.shape
        if not self.crop_size:
            # whole-image path, vectorized (the native kernel is a
            # crop-window kernel; without a crop there's nothing to gather)
            out = images.astype(np.float32)
            mean = self.mean
            if mean is not None and self.full_mean:
                out -= mean[None]          # source-index == full image
            if self.mirror:
                flips = self.rng.randint(0, 2, n).astype(bool)
                out[flips] = out[flips][:, :, :, ::-1]
            if mean is not None and not self.full_mean:
                if mean.ndim == 1 and len(mean) not in (1, c):
                    raise ValueError(
                        f"mean_value count {len(mean)} != channels {c}")
                out -= mean.reshape(1, -1, 1, 1)
            if self.scale != 1.0:
                out *= self.scale
            return out
        crop = self.crop_size
        if self.crop_size:
            if self.phase == 0:  # TRAIN: random offsets
                ys = self.rng.randint(0, h - crop + 1, n).astype(np.int32)
                xs = self.rng.randint(0, w - crop + 1, n).astype(np.int32)
            else:                # TEST: center
                ys = np.full(n, (h - crop) // 2, np.int32)
                xs = np.full(n, (w - crop) // 2, np.int32)
        else:
            ys = xs = None
        flips = self.rng.randint(0, 2, n).astype(np.uint8) \
            if self.mirror else None
        mean = self.mean
        if mean is not None and mean.ndim == 1 and len(mean) not in (1, c):
            raise ValueError(
                f"mean_value count {len(mean)} != channels {c}")
        if mean is not None and mean.ndim == 1 and len(mean) == 1:
            mean = np.repeat(mean, c)
        if images.dtype == np.uint8:
            return native.transform_batch(
                images, crop, ys=ys, xs=xs, mirror=flips, mean=mean,
                scale=self.scale, full_mean=self.full_mean)
        # float records (float_data datums): numpy path
        out = np.empty((n, c, crop, crop), np.float32)
        for i in range(n):
            y0 = int(ys[i]) if ys is not None else 0
            x0 = int(xs[i]) if xs is not None else 0
            win = images[i, :, y0:y0 + crop, x0:x0 + crop].astype(np.float32)
            if mean is not None and self.full_mean:
                win = win - mean[:, y0:y0 + crop, x0:x0 + crop]
            if flips is not None and flips[i]:
                win = win[:, :, ::-1]
            out[i] = win
        if mean is not None and not self.full_mean:
            out -= mean.reshape(1, -1, 1, 1)
        if self.scale != 1.0:
            out *= self.scale
        return out


def compute_mean(image_iter, shape):
    """Streaming mean image over an iterator of (N, C, H, W) uint8 arrays —
    the ComputeMean.scala:10-37 accumulator without the RDD."""
    acc = np.zeros(shape, np.int64)
    count = 0
    for batch in image_iter:
        native.accumulate_sum(np.asarray(batch), acc)
        count += len(batch)
    if count == 0:
        raise ValueError("empty image stream")
    return (acc / count).astype(np.float32)
