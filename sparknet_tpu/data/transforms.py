"""Input transforms — the reference DataTransformer + app closures.

Replaces caffe/src/caffe/data_transformer.cpp (crop/mirror/scale/mean,
:42-51) and the per-image Scala preprocessing closures
(ImageNetApp.scala:155-169: random 227x227 crop + mean subtraction; test
variant :117-131: center crop). Vectorized over the whole batch — the
reference looped per image per pixel in a JVM closure.
"""

import numpy as np

from .. import native


def transform_train(images, crop, mean=None, mirror=True, rng=None,
                    scale=1.0):
    """Fused random-crop + mirror + mean-subtract + scale, one native pass
    over the batch (the data_transformer.cpp TRAIN path). mean must be
    per-channel (C,) or already cropped (C,crop,crop)."""
    rng = rng or np.random
    n, c, h, w = images.shape
    ys = rng.randint(0, h - crop + 1, size=n).astype(np.int32)
    xs = rng.randint(0, w - crop + 1, size=n).astype(np.int32)
    flips = rng.randint(0, 2, size=n).astype(np.uint8) if mirror else None
    return native.transform_batch(images, crop, ys=ys, xs=xs, mirror=flips,
                                  mean=_crop_mean(mean, c, crop),
                                  scale=scale)


def transform_test(images, crop, mean=None, scale=1.0):
    """Fused center-crop + mean-subtract (the TEST path)."""
    n, c, h, w = images.shape
    ys = np.full(n, (h - crop) // 2, np.int32)
    xs = np.full(n, (w - crop) // 2, np.int32)
    return native.transform_batch(images, crop, ys=ys, xs=xs,
                                  mean=_crop_mean(mean, c, crop),
                                  scale=scale)


def _crop_mean(mean, c, crop):
    if mean is None:
        return None
    mean = np.asarray(mean, np.float32)
    if mean.ndim == 3 and mean.shape[-2:] != (crop, crop):
        mh, mw = mean.shape[-2:]
        y, x = (mh - crop) // 2, (mw - crop) // 2
        mean = np.ascontiguousarray(mean[:, y:y + crop, x:x + crop])
    return mean


def random_crop(images, crop, rng=None, mirror=False):
    """(N, C, H, W) -> (N, C, crop, crop) with per-image random offsets
    (+ optional per-image horizontal mirror, data_transformer.cpp:42-51)."""
    rng = rng or np.random
    n, c, h, w = images.shape
    if h == crop and w == crop:
        out = images
    else:
        ys = rng.randint(0, h - crop + 1, size=n)
        xs = rng.randint(0, w - crop + 1, size=n)
        out = np.empty((n, c, crop, crop), images.dtype)
        for i in range(n):   # per-image offsets; the copy dominates anyway
            out[i] = images[i, :, ys[i]:ys[i] + crop, xs[i]:xs[i] + crop]
    if mirror:
        flips = rng.randint(0, 2, size=n).astype(bool)
        out = out.copy() if out is images else out
        out[flips] = out[flips, :, :, ::-1]
    return out


def center_crop(images, crop):
    """Deterministic center crop (TEST phase, ImageNetApp.scala:117-131)."""
    n, c, h, w = images.shape
    y, x = (h - crop) // 2, (w - crop) // 2
    return images[:, :, y:y + crop, x:x + crop]


def subtract_mean(images, mean_image):
    """float32 output; mean may be a full CHW image (mean_file) or
    per-channel values (mean_value)."""
    images = np.asarray(images, np.float32)
    mean = np.asarray(mean_image, np.float32)
    if mean.ndim == 1:   # per-channel
        mean = mean.reshape(-1, 1, 1)
    if mean.ndim == 3 and mean.shape[-2:] != images.shape[-2:]:
        # mean image larger than crop: use its center window (caffe requires
        # equal dims after crop; data_transformer.cpp does the same check)
        mh, mw = mean.shape[-2:]
        h, w = images.shape[-2:]
        y, x = (mh - h) // 2, (mw - w) // 2
        mean = mean[:, y:y + h, x:x + w]
    return images - mean


def compute_mean(image_iter, shape):
    """Streaming mean image over an iterator of (N, C, H, W) uint8 arrays —
    the ComputeMean.scala:10-37 accumulator without the RDD."""
    acc = np.zeros(shape, np.int64)
    count = 0
    for batch in image_iter:
        native.accumulate_sum(np.asarray(batch), acc)
        count += len(batch)
    if count == 0:
        raise ValueError("empty image stream")
    return (acc / count).astype(np.float32)
