"""File-backed batch sources: ImageData, HDF5Data, MemoryData.

Host-side equivalents of the reference's non-DB data layers — each yields
ready feed dicts, like DatumBatchSource, for the training loop (or a
PrefetchIterator) to device_put:

  ImageDataSource   image_data_layer.cpp: listfile of "path label" lines,
                    optional resize/gray, shuffle-on-epoch, transform_param
  HDF5DataSource    hdf5_data_layer.cpp: source file listing .h5 files whose
                    datasets are keyed by top name; row shuffle per file
  MemoryDataSource  memory_data_layer.cpp: in-memory arrays via Reset()

The graph-side shape stubs live in ops/feed.py; build_feed (db_source.py)
dispatches a net's data layers to these classes.
"""

import os

import numpy as np

from .transforms import DataTransformer


class ImageDataSource:
    """Infinite batched iterator over a listfile of images.

    Matches reference ImageDataLayer: lines are "relative/path label";
    new_height/new_width force-resize; is_color selects RGB vs gray;
    shuffle reshuffles the line order on every epoch wrap (ShuffleImages);
    rand_skip advances once at startup; transform_param applies
    crop/mirror/scale/mean per batch. Images are decoded to CHW BGR uint8,
    the reference's OpenCV convention, so stock mean files line up.
    """

    def __init__(self, source, batch_size, phase=0, transform_param=None,
                 root_folder="", new_height=0, new_width=0, is_color=True,
                 shuffle=False, rand_skip=0, base_dir="", seed=None,
                 data_top="data", label_top="label"):
        from PIL import Image       # decode dependency kept out of import
        self._Image = Image
        self.source = source
        self.batch_size = int(batch_size)
        self.root = root_folder
        self.new_height, self.new_width = int(new_height), int(new_width)
        if (self.new_height > 0) != (self.new_width > 0):
            raise ValueError("new_height and new_width must be set together "
                             "(image_data_layer.cpp CHECK)")
        self.is_color = bool(is_color)
        self.shuffle = bool(shuffle)
        self.data_top, self.label_top = data_top, label_top
        self.rng = np.random.RandomState(seed)
        self.transformer = DataTransformer(transform_param, phase=phase,
                                           base_dir=base_dir, rng=self.rng)
        self.lines = []
        with open(source) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                path, _, label = line.rpartition(" ")
                self.lines.append((path, int(label)))
        if not self.lines:
            raise ValueError(f"{source}: empty image list")
        if self.shuffle:
            self.rng.shuffle(self.lines)
        self._skip = int(self.rng.randint(0, rand_skip)) if rand_skip else 0
        first = self._read(self.lines[0][0])
        self.record_shape = first.shape
        self.shape = (self.batch_size,) + \
            self.transformer.output_shape(self.record_shape)

    @property
    def num_records(self):
        return len(self.lines)

    @property
    def num_batches(self):
        return max(1, len(self.lines) // self.batch_size)

    def _read(self, rel):
        img = self._Image.open(os.path.join(self.root, rel))
        img = img.convert("RGB" if self.is_color else "L")
        if self.new_height and self.new_width:
            img = img.resize((self.new_width, self.new_height),
                             self._Image.BILINEAR)
        a = np.asarray(img, np.uint8)
        if a.ndim == 2:
            return a[None]                      # (1,H,W)
        return np.ascontiguousarray(a[:, :, ::-1].transpose(2, 0, 1))

    def _records(self):
        skip = self._skip
        self._skip = 0
        while True:
            for rel, label in self.lines:
                if skip:
                    skip -= 1
                    continue
                yield self._read(rel), label
            if self.shuffle:                    # reshuffle on wrap
                self.rng.shuffle(self.lines)

    def __iter__(self):
        rec = self._records()
        while True:
            arrs = []
            labels = np.empty(self.batch_size, np.int32)
            for i in range(self.batch_size):
                a, labels[i] = next(rec)
                if a.shape != self.record_shape:
                    raise ValueError(
                        f"image shape {a.shape} != first image "
                        f"{self.record_shape}; set new_height/new_width to "
                        "force a common size")
                arrs.append(a)
            yield {self.data_top: self.transformer(np.stack(arrs)),
                   self.label_top: labels}

    def close(self):
        pass


class HDF5DataSource:
    """Infinite batched iterator over HDF5 files listed in ``source``.

    Matches reference HDF5DataLayer: every top name is a dataset in each
    file; batches are sliced along axis 0; ``shuffle`` permutes the file
    order and the rows within each file per epoch. No transform_param (the
    reference layer has none). Labels come through as-is (float or int).
    """

    def __init__(self, source, batch_size, tops, shuffle=False, seed=None):
        import h5py
        self._h5py = h5py
        self.source = source
        self.batch_size = int(batch_size)
        self.tops = list(tops)
        self.shuffle = bool(shuffle)
        self.rng = np.random.RandomState(seed)
        with open(source) as f:
            self.files = [ln.strip() for ln in f if ln.strip()]
        if not self.files:
            raise ValueError(f"{source}: lists no HDF5 files")
        base = os.path.dirname(os.path.abspath(source))
        self.files = [p if os.path.isabs(p) else os.path.join(base, p)
                      for p in self.files]
        self.shapes = {}
        self._count = 0
        for p in self.files:
            with h5py.File(p, "r") as f:
                n = None
                for t in self.tops:
                    if t not in f:
                        raise KeyError(f"{p}: no dataset {t!r}")
                    if n is None:
                        n = f[t].shape[0]
                        self._count += n
                    elif f[t].shape[0] != n:
                        raise ValueError(f"{p}: dataset {t!r} rows "
                                         f"{f[t].shape[0]} != {n}")
                    self.shapes.setdefault(t, tuple(f[t].shape[1:]))
        self.shape = {t: (self.batch_size,) + s
                      for t, s in self.shapes.items()}

    @property
    def num_records(self):
        return self._count

    @property
    def num_batches(self):
        return max(1, self._count // self.batch_size)

    def _rows(self):
        files = list(self.files)
        while True:
            if self.shuffle:
                self.rng.shuffle(files)
            for p in files:
                with self._h5py.File(p, "r") as f:
                    data = {t: np.asarray(f[t]) for t in self.tops}
                n = len(data[self.tops[0]])
                order = self.rng.permutation(n) if self.shuffle \
                    else np.arange(n)
                for i in order:
                    yield {t: data[t][i] for t in self.tops}

    def __iter__(self):
        rows = self._rows()
        while True:
            batch = [next(rows) for _ in range(self.batch_size)]
            yield {t: np.stack([b[t] for b in batch]) for t in self.tops}

    def close(self):
        pass


class MemoryDataSource:
    """In-memory array feed (reference MemoryDataLayer::Reset). Batches
    cycle over the arrays; Reset() swaps them (sizes must stay divisible
    by batch_size, like the reference CHECK)."""

    def __init__(self, batch_size, data=None, labels=None,
                 data_top="data", label_top="label"):
        self.batch_size = int(batch_size)
        self.data_top, self.label_top = data_top, label_top
        self._pos = 0
        self.data = self.labels = None
        if data is not None:
            self.reset(data, labels)

    def reset(self, data, labels):
        data = np.asarray(data)
        labels = np.asarray(labels)
        if len(data) != len(labels):
            raise ValueError(f"data rows {len(data)} != labels {len(labels)}")
        if len(data) % self.batch_size:
            raise ValueError(
                f"size {len(data)} not divisible by batch {self.batch_size} "
                "(memory_data_layer.cpp CHECK on AddMatVector/Reset)")
        self.data, self.labels = data, labels
        self._pos = 0

    @property
    def num_records(self):
        return 0 if self.data is None else len(self.data)

    def __iter__(self):
        if self.data is None:
            raise RuntimeError("MemoryDataSource: call reset(data, labels) "
                               "before iterating")
        while True:
            i = self._pos
            self._pos = (self._pos + self.batch_size) % len(self.data)
            yield {self.data_top:
                   self.data[i:i + self.batch_size].astype(np.float32),
                   self.label_top:
                   self.labels[i:i + self.batch_size].astype(np.int32)}

    def close(self):
        pass
