"""File-backed batch sources: ImageData, HDF5Data, MemoryData.

Host-side equivalents of the reference's non-DB data layers — each yields
ready feed dicts, like DatumBatchSource, for the training loop (or a
PrefetchIterator) to device_put:

  ImageDataSource   image_data_layer.cpp: listfile of "path label" lines,
                    optional resize/gray, shuffle-on-epoch, transform_param
  HDF5DataSource    hdf5_data_layer.cpp: source file listing .h5 files whose
                    datasets are keyed by top name; row shuffle per file
  MemoryDataSource  memory_data_layer.cpp: in-memory arrays via Reset()

The graph-side shape stubs live in ops/feed.py; build_feed (db_source.py)
dispatches a net's data layers to these classes.
"""

import os

import numpy as np

from .transforms import DataTransformer


def _decode_chw_bgr(Image, path, color=True, resize=None):
    """Decode to CHW uint8, BGR channel order (the reference's OpenCV
    convention, so stock mean files line up); gray -> (1, H, W)."""
    img = Image.open(path)
    img = img.convert("RGB" if color else "L")
    if resize:
        img = img.resize(resize, Image.BILINEAR)
    a = np.asarray(img, np.uint8)
    if a.ndim == 2:
        return a[None]
    return np.ascontiguousarray(a[:, :, ::-1].transpose(2, 0, 1))


class ImageDataSource:
    """Infinite batched iterator over a listfile of images.

    Matches reference ImageDataLayer: lines are "relative/path label";
    new_height/new_width force-resize; is_color selects RGB vs gray;
    shuffle reshuffles the line order on every epoch wrap (ShuffleImages);
    rand_skip advances once at startup; transform_param applies
    crop/mirror/scale/mean per batch. Images are decoded to CHW BGR uint8,
    the reference's OpenCV convention, so stock mean files line up.
    """

    def __init__(self, source, batch_size, phase=0, transform_param=None,
                 root_folder="", new_height=0, new_width=0, is_color=True,
                 shuffle=False, rand_skip=0, base_dir="", seed=None,
                 data_top="data", label_top="label"):
        from PIL import Image       # decode dependency kept out of import
        self._Image = Image
        self.source = source
        self.batch_size = int(batch_size)
        self.root = root_folder
        self.new_height, self.new_width = int(new_height), int(new_width)
        if (self.new_height > 0) != (self.new_width > 0):
            raise ValueError("new_height and new_width must be set together "
                             "(image_data_layer.cpp CHECK)")
        self.is_color = bool(is_color)
        self.shuffle = bool(shuffle)
        self.data_top, self.label_top = data_top, label_top
        # transient-IO resilience: image decodes retry with backoff (a
        # flaky NFS read costs a sleep, not the round); chaos injects
        from ..resilience.retry import retry_from_env
        from ..resilience.chaos import active_chaos
        self._retry = retry_from_env()
        self._chaos = active_chaos()
        self.rng = np.random.RandomState(seed)
        self.transformer = DataTransformer(transform_param, phase=phase,
                                           base_dir=base_dir, rng=self.rng)
        self.lines = []
        with open(source) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                path, _, label = line.rpartition(" ")
                self.lines.append((path, int(label)))
        if not self.lines:
            raise ValueError(f"{source}: empty image list")
        if self.shuffle:
            self.rng.shuffle(self.lines)
        self._skip = int(self.rng.randint(0, rand_skip)) if rand_skip else 0
        first = self._read(self.lines[0][0])
        self.record_shape = first.shape
        self.shape = (self.batch_size,) + \
            self.transformer.output_shape(self.record_shape)

    @property
    def num_records(self):
        return len(self.lines)

    @property
    def num_batches(self):
        return max(1, len(self.lines) // self.batch_size)

    def _read(self, rel):
        return _decode_chw_bgr(
            self._Image, os.path.join(self.root, rel), color=self.is_color,
            resize=(self.new_width, self.new_height)
            if self.new_height and self.new_width else None)

    def _read_resilient(self, rel):
        def read():
            if self._chaos is not None:
                self._chaos.maybe_io_error(rel)
            return self._read(rel)
        if self._retry is None:
            return read()
        return self._retry.call(read, where=rel)

    def _records(self):
        skip = self._skip
        self._skip = 0
        while True:
            for rel, label in self.lines:
                if skip:
                    skip -= 1
                    continue
                yield self._read_resilient(rel), label
            if self.shuffle:                    # reshuffle on wrap
                self.rng.shuffle(self.lines)

    def __iter__(self):
        rec = self._records()
        while True:
            arrs = []
            labels = np.empty(self.batch_size, np.int32)
            for i in range(self.batch_size):
                a, labels[i] = next(rec)
                if a.shape != self.record_shape:
                    raise ValueError(
                        f"image shape {a.shape} != first image "
                        f"{self.record_shape}; set new_height/new_width to "
                        "force a common size")
                arrs.append(a)
            yield {self.data_top: self.transformer(np.stack(arrs)),
                   self.label_top: labels}

    def close(self):
        pass


class HDF5DataSource:
    """Infinite batched iterator over HDF5 files listed in ``source``.

    Matches reference HDF5DataLayer: every top name is a dataset in each
    file; batches are sliced along axis 0; ``shuffle`` permutes the file
    order and the rows within each file per epoch. No transform_param (the
    reference layer has none). Labels come through as-is (float or int).
    """

    def __init__(self, source, batch_size, tops, shuffle=False, seed=None):
        import h5py
        self._h5py = h5py
        self.source = source
        self.batch_size = int(batch_size)
        self.tops = list(tops)
        self.shuffle = bool(shuffle)
        self.rng = np.random.RandomState(seed)
        with open(source) as f:
            self.files = [ln.strip() for ln in f if ln.strip()]
        if not self.files:
            raise ValueError(f"{source}: lists no HDF5 files")
        base = os.path.dirname(os.path.abspath(source))
        self.files = [p if os.path.isabs(p) else os.path.join(base, p)
                      for p in self.files]
        self.shapes = {}
        self._count = 0
        for p in self.files:
            with h5py.File(p, "r") as f:
                n = None
                for t in self.tops:
                    if t not in f:
                        raise KeyError(f"{p}: no dataset {t!r}")
                    if n is None:
                        n = f[t].shape[0]
                        self._count += n
                    elif f[t].shape[0] != n:
                        raise ValueError(f"{p}: dataset {t!r} rows "
                                         f"{f[t].shape[0]} != {n}")
                    self.shapes.setdefault(t, tuple(f[t].shape[1:]))
        self.shape = {t: (self.batch_size,) + s
                      for t, s in self.shapes.items()}

    @property
    def num_records(self):
        return self._count

    @property
    def num_batches(self):
        return max(1, self._count // self.batch_size)

    def _load(self, p):
        with self._h5py.File(p, "r") as f:
            return {t: np.asarray(f[t]) for t in self.tops}

    def _rows(self):
        from ..resilience.retry import retry_from_env
        retry = retry_from_env()
        files = list(self.files)
        while True:
            if self.shuffle:
                self.rng.shuffle(files)
            for p in files:
                data = self._load(p) if retry is None \
                    else retry.call(self._load, p, where=p)
                n = len(data[self.tops[0]])
                order = self.rng.permutation(n) if self.shuffle \
                    else np.arange(n)
                for i in order:
                    yield {t: data[t][i] for t in self.tops}

    def __iter__(self):
        rows = self._rows()
        while True:
            batch = [next(rows) for _ in range(self.batch_size)]
            yield {t: np.stack([b[t] for b in batch]) for t in self.tops}

    def close(self):
        pass


class MemoryDataSource:
    """In-memory array feed (reference MemoryDataLayer::Reset). Batches
    cycle over the arrays; Reset() swaps them (sizes must stay divisible
    by batch_size, like the reference CHECK)."""

    def __init__(self, batch_size, data=None, labels=None,
                 data_top="data", label_top="label"):
        self.batch_size = int(batch_size)
        self.data_top, self.label_top = data_top, label_top
        self._pos = 0
        self.data = self.labels = None
        if data is not None:
            self.reset(data, labels)

    def reset(self, data, labels):
        data = np.asarray(data)
        labels = np.asarray(labels)
        if len(data) != len(labels):
            raise ValueError(f"data rows {len(data)} != labels {len(labels)}")
        if len(data) % self.batch_size:
            raise ValueError(
                f"size {len(data)} not divisible by batch {self.batch_size} "
                "(memory_data_layer.cpp CHECK on AddMatVector/Reset)")
        self.data, self.labels = data, labels
        self._pos = 0

    @property
    def num_records(self):
        return 0 if self.data is None else len(self.data)

    def __iter__(self):
        if self.data is None:
            raise RuntimeError("MemoryDataSource: call reset(data, labels) "
                               "before iterating")
        while True:
            i = self._pos
            self._pos = (self._pos + self.batch_size) % len(self.data)
            yield {self.data_top:
                   self.data[i:i + self.batch_size].astype(np.float32),
                   self.label_top:
                   self.labels[i:i + self.batch_size].astype(np.int32)}

    def close(self):
        pass


class WindowDataSource:
    """R-CNN window-file feed (reference window_data_layer.cpp).

    Window file format (window_data_layer.cpp:40-47)::

        # image_index
        img_path
        channels height width
        num_windows
        class_index overlap x1 y1 x2 y2     (num_windows lines)

    Windows with overlap >= fg_threshold are foreground (label must be
    > 0); overlap < bg_threshold are background with label forced to 0
    (:129-141). Each batch draws batch*(1-fg_fraction) background then
    batch*fg_fraction foreground windows uniformly at random (:260-267),
    crops each (optionally context-padded / squared, :306-330), warps to
    crop_size x crop_size with out-of-image extent zero-padded
    (:330-385), mirrors at random, and applies mean/scale from
    transform_param (after upgrade_data_transform the deprecated
    window_data_param fields land there). Images decode to CHW BGR like
    ImageDataSource, so stock mean files line up.
    """

    def __init__(self, source, batch_size, phase=0, transform_param=None,
                 fg_threshold=0.5, bg_threshold=0.5, fg_fraction=0.25,
                 context_pad=0, crop_mode="warp", root_folder="",
                 base_dir="", seed=None, data_top="data",
                 label_top="label"):
        from PIL import Image
        self._Image = Image
        self.source = source
        self.batch_size = int(batch_size)
        self.fg_fraction = float(fg_fraction)
        self.context_pad = int(context_pad)
        self.use_square = crop_mode == "square"
        self.root = root_folder
        self.rng = np.random.RandomState(seed)
        self.data_top, self.label_top = data_top, label_top
        self.transformer = DataTransformer(transform_param, phase=phase,
                                           base_dir=base_dir, rng=self.rng)
        self.crop = self.transformer.crop_size
        if not self.crop:
            raise ValueError(f"{source}: WindowData requires crop_size > 0 "
                             "(window_data_layer.cpp CHECK_GT)")

        self.images = []          # (abs_path, channels)
        self.fg, self.bg = [], []  # (image_idx, label, x1, y1, x2, y2)
        with open(source) as f:
            toks = f.read().split()
        i = 0
        while i < len(toks):
            if toks[i] != "#":
                raise ValueError(f"{source}: expected '#', got {toks[i]!r}")
            path = toks[i + 2]
            if self.root and not os.path.isabs(path):
                path = os.path.join(self.root, path)
            channels = int(toks[i + 3])
            nwin = int(toks[i + 6])
            img_idx = len(self.images)
            self.images.append((path, channels))
            i += 7
            for _ in range(nwin):
                label, overlap = int(toks[i]), float(toks[i + 1])
                box = tuple(int(v) for v in toks[i + 2:i + 6])
                if overlap >= fg_threshold:
                    if label <= 0:
                        raise ValueError(
                            f"{source}: foreground window with label "
                            f"{label} (CHECK_GT(label, 0))")
                    self.fg.append((img_idx, label) + box)
                elif overlap < bg_threshold:
                    self.bg.append((img_idx, 0) + box)
                i += 6
        if not self.images:
            raise ValueError(f"{source}: no images")
        self.channels = self.images[0][1]
        self.shape = (self.batch_size, self.channels, self.crop, self.crop)

    @property
    def num_records(self):
        return len(self.fg) + len(self.bg)

    @property
    def num_batches(self):
        return max(1, self.num_records // self.batch_size)

    def _read(self, idx):
        # decode per window (the reference's default; its cache_images
        # byte-cache is an opt-in we don't carry) — an unbounded decoded
        # cache would OOM on real R-CNN window files (~20k images)
        path, channels = self.images[idx]
        return _decode_chw_bgr(self._Image, path, color=channels == 3)

    def _window(self, win, do_mirror):
        """One warped, padded, mean-subtracted (C, crop, crop) float32."""
        img_idx, label, x1, y1, x2, y2 = win
        img = self._read(img_idx)
        c, ih, iw = img.shape
        crop = self.crop
        pad_w = pad_h = 0
        out_w = out_h = crop
        if self.context_pad > 0 or self.use_square:
            context_scale = crop / float(crop - 2 * self.context_pad)
            half_h = (y2 - y1 + 1) / 2.0
            half_w = (x2 - x1 + 1) / 2.0
            cx, cy = x1 + half_w, y1 + half_h
            if self.use_square:
                half_h = half_w = max(half_h, half_w)
            x1 = int(round(cx - half_w * context_scale))
            x2 = int(round(cx + half_w * context_scale))
            y1 = int(round(cy - half_h * context_scale))
            y2 = int(round(cy + half_h * context_scale))
            unclipped_h, unclipped_w = y2 - y1 + 1, x2 - x1 + 1
            pad_x1, pad_y1 = max(0, -x1), max(0, -y1)
            pad_x2, pad_y2 = max(0, x2 - iw + 1), max(0, y2 - ih + 1)
            x1, x2 = x1 + pad_x1, x2 - pad_x2
            y1, y2 = y1 + pad_y1, y2 - pad_y2
            scale_x = crop / float(unclipped_w)
            scale_y = crop / float(unclipped_h)
            out_w = int(round((x2 - x1 + 1) * scale_x))
            out_h = int(round((y2 - y1 + 1) * scale_y))
            pad_x1 = int(round(pad_x1 * scale_x))
            pad_x2 = int(round(pad_x2 * scale_x))
            pad_y1 = int(round(pad_y1 * scale_y))
            pad_h = pad_y1
            # mirrored windows mirror their padding too (:371-376)
            pad_w = pad_x2 if do_mirror else pad_x1
            out_h = min(out_h, crop - pad_h)
            out_w = min(out_w, crop - pad_w)
        roi = img[:, y1:y2 + 1, x1:x2 + 1]
        pil = self._Image.fromarray(
            roi.transpose(1, 2, 0) if c == 3 else roi[0])
        pil = pil.resize((out_w, out_h), self._Image.BILINEAR)
        warped = np.asarray(pil, np.uint8)
        warped = warped.transpose(2, 0, 1) if c == 3 else warped[None]
        if do_mirror:
            warped = warped[:, :, ::-1]
        canvas = np.zeros((c, self.crop, self.crop), np.float32)
        canvas[:, pad_h:pad_h + out_h, pad_w:pad_w + out_w] = warped
        t = self.transformer
        if t.mean is not None and t.full_mean:
            moff = (t.mean.shape[-1] - crop) // 2
            mean_roi = t.mean[:, moff:moff + crop, moff:moff + crop]
            # mean subtracted only where the warped window landed
            # (zero padding stays zero, :399-409 indexes mean per pixel)
            region = np.zeros_like(canvas)
            region[:, pad_h:pad_h + out_h, pad_w:pad_w + out_w] = \
                mean_roi[:, pad_h:pad_h + out_h, pad_w:pad_w + out_w]
            canvas -= region
        elif t.mean is not None:
            region = np.zeros_like(canvas)
            region[:, pad_h:pad_h + out_h, pad_w:pad_w + out_w] = \
                t.mean[:, None, None]
            canvas -= region
        return canvas * t.scale

    def __iter__(self):
        n_fg = int(self.batch_size * self.fg_fraction)
        counts = [self.batch_size - n_fg, n_fg]    # bg first, then fg
        while True:
            data = np.empty(self.shape, np.float32)
            labels = np.empty(self.batch_size, np.int32)
            item = 0
            for is_fg, pool in ((0, self.bg), (1, self.fg)):
                for _ in range(counts[is_fg]):
                    if not pool:
                        raise ValueError(
                            f"{self.source}: no "
                            f"{'foreground' if is_fg else 'background'} "
                            "windows to sample")
                    win = pool[self.rng.randint(len(pool))]
                    do_mirror = bool(self.transformer.mirror
                                     and self.rng.randint(2))
                    data[item] = self._window(win, do_mirror)
                    labels[item] = win[1]
                    item += 1
            yield {self.data_top: data, self.label_top: labels}

    def close(self):
        pass
