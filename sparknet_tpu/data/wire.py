"""Compressed wire formats for the host->device feed.

The device-transform split (device_transform.py) already ships raw uint8
records instead of float32 crops — 3.2-4x fewer bytes. This module is the
next turn of the same screw, for links where the H2D wire is the bound
(BENCH_r04: pure transfer ~62 img/s at 192 KB/image vs an 11,913 img/s
device step):

  precrop  — the host slices each record's crop window (using the SAME
             y/x draws that ride along as aux arrays) before shipping, so
             the wire carries crop^2 pixels instead of src^2. Exact
             integer uint8 slicing, no float math: the device path skips
             its crop gather but still slices the full-size mean at the
             ORIGINAL y/x and mirrors on-device, so the float32 op order
             — and therefore every output bit — is unchanged
             (DeviceTransformer.device_fn(precropped=True)).
             CaffeNet geometry: 256^2 -> 227^2 is 1.27x.
  pack     — lossless bit-pack for low-entropy sources: when every pixel
             value fits in 1/2/4 bits, 8/4/2 pixels share each shipped
             byte; the device unpacks with shifts/masks before the
             transform. The bit width is fixed ONCE (explicitly, or
             inferred from a sample record batch) so shipped shapes are
             static — no recompiles — and a later batch that exceeds the
             width raises instead of clipping: the pack is lossless or it
             is an error. Width 8 is the raw passthrough.

``precrop+pack`` composes both: a 2-bit source at CaffeNet geometry ships
~37.8 KB/image vs the 192 KB raw wire — 5.1x, and >= the 3x target with
room to spare. Gated by SPARKNET_WIRE / `--wire` (default: raw, the
previous behavior, byte for byte).

Echo interaction: data echoing re-draws crop/mirror aux per echo of one
shipped batch — impossible once the crop window is baked into the wire,
so echo>1 refuses precrop modes at the CLI rather than silently shipping
identical crops.
"""

import os

import numpy as np

WIRE_MODES = ("raw", "precrop", "pack", "precrop+pack")
PACK_WIDTHS = (1, 2, 4, 8)


def wire_mode_from_env(default="raw"):
    """SPARKNET_WIRE -> validated wire mode (typos are an error: a
    misspelled lever silently measuring the baseline would fake an A/B)."""
    mode = os.environ.get("SPARKNET_WIRE", "").strip().lower() or default
    if mode not in WIRE_MODES:
        raise ValueError(f"SPARKNET_WIRE={mode!r}: expected one of "
                         f"{', '.join(WIRE_MODES)}")
    return mode


def wire_bits_from_env():
    """SPARKNET_WIRE_BITS -> explicit pack width (None = infer from a
    sample batch at codec construction)."""
    raw = os.environ.get("SPARKNET_WIRE_BITS", "").strip()
    if not raw:
        return None
    bits = int(raw)
    if bits not in PACK_WIDTHS:
        raise ValueError(f"SPARKNET_WIRE_BITS={bits}: expected one of "
                         f"{PACK_WIDTHS}")
    return bits


def infer_pack_bits(sample):
    """Smallest lossless pack width for ``sample``'s value range. A sample
    understates the global max at your own risk: encode() raises on the
    first out-of-range batch (set SPARKNET_WIRE_BITS to be explicit)."""
    mx = int(np.max(sample)) if np.size(sample) else 0
    for bits in PACK_WIDTHS:
        if mx < (1 << bits):
            return bits
    return 8


class WireCodec:
    """Host-side encode + device-side decode around a DeviceTransformer.

    encode() runs where the source yields (host, prefetch worker);
    device_fn() wraps the transformer's jitted transform with the
    matching unpack, so the solver's input-transform hook sees one
    composed fn. raw_overrides() gives check_batch the SHIPPED shapes —
    the solver's h2d byte accounting (tree_bytes of the fed batch) then
    reflects actual wire bytes with no extra plumbing.
    """

    def __init__(self, devt, record_shape, mode="raw", bits=None,
                 sample=None):
        if mode not in WIRE_MODES:
            raise ValueError(f"wire mode {mode!r}: expected one of "
                             f"{', '.join(WIRE_MODES)}")
        self.devt = devt
        self.record_shape = tuple(int(d) for d in record_shape)
        self.mode = mode
        crop = devt.h.crop_size
        # precrop with no crop configured degenerates to raw shipping
        self.precrop = "precrop" in mode and bool(crop)
        self._crop = int(crop) if crop else 0
        self.packing = "pack" in mode
        if self.packing:
            if bits is None:
                if sample is None:
                    raise ValueError(
                        "pack wire mode needs an explicit bit width "
                        "(SPARKNET_WIRE_BITS / --wire-bits) or a sample "
                        "record batch to infer one from")
                bits = infer_pack_bits(sample)
            if bits not in PACK_WIDTHS:
                raise ValueError(f"pack width {bits}: expected one of "
                                 f"{PACK_WIDTHS}")
            if bits == 8:
                self.packing = False    # raw passthrough
        self.bits = int(bits) if self.packing else 8
        c, h, w = self.record_shape
        if self.precrop:
            self.image_shape = (c, self._crop, self._crop)
        else:
            self.image_shape = (c, h, w)
        self._flat_n = int(np.prod(self.image_shape))
        if self.packing:
            self._per_byte = 8 // self.bits
            self._pad = (-self._flat_n) % self._per_byte
            self.wire_shape = ((self._flat_n + self._pad) // self._per_byte,)
        else:
            self.wire_shape = self.image_shape

    # -- host side ---------------------------------------------------------
    def encode(self, batch):
        """Feed dict (device-mode layout: uint8 pixels + aux draws) ->
        same dict with the pixel blob re-encoded for the wire. Aux arrays
        always ship unchanged: the device needs the ORIGINAL y/x for the
        full-mean window even when the crop itself happened here."""
        data_top = self.devt.data_top
        x = batch[data_top]
        if self.precrop:
            ys, xs = batch[self.devt.ky], batch[self.devt.kx]
            crop = self._crop
            out = np.empty((len(x), x.shape[1], crop, crop), x.dtype)
            for i in range(len(x)):
                out[i] = x[i, :, ys[i]:ys[i] + crop, xs[i]:xs[i] + crop]
            x = out
        if self.packing:
            x = self._pack(x)
        if x is not batch[data_top]:
            batch = dict(batch)
            batch[data_top] = x
        return batch

    def _pack(self, x):
        mx = int(x.max(initial=0))
        if mx >= (1 << self.bits):
            raise ValueError(
                f"wire pack width {self.bits} is not lossless for this "
                f"batch (max value {mx}); set SPARKNET_WIRE_BITS to a "
                f"wider width or drop the pack mode")
        flat = np.ascontiguousarray(x, np.uint8).reshape(len(x), -1)
        if self._pad:
            flat = np.concatenate(
                [flat, np.zeros((len(x), self._pad), np.uint8)], axis=1)
        vals = flat.reshape(len(x), -1, self._per_byte).astype(np.uint16)
        shifts = (np.arange(self._per_byte, dtype=np.uint16) * self.bits)
        # each field occupies disjoint bits, so the sum fits a byte
        return (vals << shifts).sum(axis=2).astype(np.uint8)

    # -- device side -------------------------------------------------------
    def device_fn(self, inner=None):
        """Composed jittable fn: unpack (if packing) then the transform.
        ``inner`` overrides the transform stage (bench wraps a dtype
        cast); default is the transformer's precrop-aware device fn."""
        if inner is None:
            inner = self.devt.device_fn(precropped=self.precrop)
        if not self.packing:
            return inner
        import jax.numpy as jnp
        bits, per_byte = self.bits, self._per_byte
        flat_n, shape = self._flat_n, self.image_shape
        mask = (1 << bits) - 1
        data_top = self.devt.data_top

        def fn(batch):
            batch = dict(batch)
            p = batch.pop(data_top)
            shifts = jnp.arange(per_byte, dtype=jnp.uint8) * bits
            vals = (p[:, :, None] >> shifts[None, None, :]) & mask
            flat = vals.reshape(p.shape[0], -1)[:, :flat_n]
            batch[data_top] = flat.reshape((p.shape[0],) + shape)
            return inner(batch)

        return fn

    def raw_overrides(self, batch_size):
        """check_batch shape overrides for the SHIPPED feed."""
        over = self.devt.raw_overrides(batch_size, self.record_shape)
        over[self.devt.data_top] = (batch_size,) + tuple(self.wire_shape)
        return over

    # -- accounting --------------------------------------------------------
    @property
    def raw_kb_per_image(self):
        """The uncompressed device-mode wire (raw uint8 record)."""
        return int(np.prod(self.record_shape)) / 1024.0

    @property
    def kb_per_image(self):
        """Shipped pixel bytes per image under this codec."""
        return int(np.prod(self.wire_shape)) / 1024.0

    def describe(self):
        row = {"wire": self.mode,
               "h2d_kb_per_image": round(self.kb_per_image, 2),
               "wire_reduction": round(
                   self.raw_kb_per_image / max(self.kb_per_image, 1e-9), 2)}
        if self.packing:
            row["wire_bits"] = self.bits
        return row
