"""Datum record codec — the value type of Caffe LMDB/LevelDB databases.

``Datum`` (reference caffe/src/caffe/proto/caffe.proto:30-44) is what
``convert_imageset``/``convert_cifar_data`` write and what the DataLayer's
reader decodes (data_layer.cpp:14-60 via DataTransformer). Fields:
1 channels, 2 height, 3 width, 4 data (bytes, CHW uint8), 5 label,
6 float_data (repeated float, used instead of `data` by some exporters),
7 encoded (bool: `data` holds a compressed image, JPEG/PNG).

The generic schema-driven codec in ``sparknet_tpu.proto.wire`` handles
Datum too; this module adds a hand-rolled single-pass parser because datum
decode sits on the training hot path (one parse per image per epoch) and
the generic path's Message construction is ~10x the cost of the tag walk.
"""

import numpy as np

from ..proto.wire import encode as _wire_encode


class DatumError(ValueError):
    pass


def parse_datum(buf):
    """bytes -> (channels, height, width, data, float_data, label, encoded).

    data is a bytes view (CHW uint8) or None; float_data is a float32 array
    or None. Unknown fields are skipped (proto2 forward compatibility)."""
    channels = height = width = label = 0
    data = None
    floats = []
    encoded = False
    pos, end = 0, len(buf)
    while pos < end:
        tag = buf[pos]
        pos += 1
        if tag & 0x80:  # multi-byte tag: fields >15 don't exist in Datum,
            shift = 7   # but skip them correctly anyway
            while buf[pos - 1] & 0x80:
                tag |= (buf[pos] & 0x7F) << shift
                shift += 7
                pos += 1
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            if field == 1:
                channels = v
            elif field == 2:
                height = v
            elif field == 3:
                width = v
            elif field == 5:
                label = v - (1 << 64) if v >= (1 << 63) else v
            elif field == 7:
                encoded = bool(v)
        elif wt == 2:  # length-delimited
            n = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                n |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            chunk = buf[pos:pos + n]
            pos += n
            if field == 4:
                data = chunk
            elif field == 6:  # packed float_data
                floats.append(np.frombuffer(chunk, "<f4"))
        elif wt == 5:  # 32-bit: unpacked float_data
            if field == 6:
                floats.append(np.frombuffer(buf[pos:pos + 4], "<f4"))
            pos += 4
        elif wt == 1:
            pos += 8
        else:
            raise DatumError(f"unsupported wire type {wt} in Datum")
    float_data = np.concatenate(floats) if floats else None
    return channels, height, width, data, float_data, label, encoded


def datum_to_array(buf):
    """Serialized Datum -> (CHW array, label).

    Raw data -> uint8; float_data -> float32; encoded (JPEG/PNG) -> decoded
    to uint8 CHW in BGR channel order, matching the reference's OpenCV
    decode path (io.cpp DecodeDatumToCVMat + CVMatToDatum store BGR)."""
    c, h, w, data, float_data, label, encoded = parse_datum(buf)
    if encoded:
        import io as _io
        from PIL import Image
        img = Image.open(_io.BytesIO(bytes(data))).convert("RGB")
        rgb = np.asarray(img, np.uint8)           # HWC RGB
        arr = rgb[:, :, ::-1].transpose(2, 0, 1)  # CHW BGR
        return np.ascontiguousarray(arr), label
    if data is not None and len(data):
        arr = np.frombuffer(bytes(data), np.uint8)
        if c and h and w:
            arr = arr.reshape(c, h, w)
        return arr, label
    if float_data is not None:
        arr = float_data
        if c and h and w:
            arr = arr.reshape(c, h, w)
        return arr, label
    raise DatumError("Datum has neither data nor float_data")


def encoded_datum(image_bytes, label=0, dims=(0, 0, 0)):
    """Compressed (JPEG/PNG) image bytes -> Datum bytes with encoded=true
    (what convert_imageset --encoded writes; io.cpp ReadImageToDatum)."""
    out = bytearray()
    c, h, w = dims
    _tag_varint(out, 1, c)
    _tag_varint(out, 2, h)
    _tag_varint(out, 3, w)
    out += b"\x22" + _varint(len(image_bytes)) + image_bytes
    _tag_varint(out, 5, label)
    _tag_varint(out, 7, 1)
    return bytes(out)


def array_to_datum(arr, label=0):
    """CHW array -> Datum bytes (uint8 -> `data`, float -> `float_data`)."""
    out = bytearray()
    arr = np.asarray(arr)
    if arr.ndim != 3:
        raise DatumError(f"expected CHW array, got shape {arr.shape}")
    c, h, w = arr.shape
    _tag_varint(out, 1, c)
    _tag_varint(out, 2, h)
    _tag_varint(out, 3, w)
    if arr.dtype == np.uint8:
        raw = np.ascontiguousarray(arr).tobytes()
        out += b"\x22" + _varint(len(raw)) + raw       # field 4, wt 2
        _tag_varint(out, 5, label)
    else:
        packed = np.ascontiguousarray(arr, "<f4").tobytes()
        _tag_varint(out, 5, label)
        out += b"\x32" + _varint(len(packed)) + packed  # field 6 packed
    return bytes(out)


def datum_message(buf):
    """Full schema-driven decode to a Message (slow path, for tools)."""
    from ..proto import wire
    return wire.decode(buf, "Datum")


def message_to_bytes(msg):
    return _wire_encode(msg)


def _varint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag_varint(out, field, value):
    if value:
        out += bytes([field << 3]) + _varint(value)
