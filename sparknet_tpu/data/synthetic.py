"""Synthetic datasets for tests and benchmarks (no-network environments).

Two generators:

* ``class_gaussian_images`` — class-conditional Gaussian images; learnable
  by a convnet, so training tests can assert better-than-chance accuracy
  without real CIFAR/ImageNet bits.
* ``shape_texture_images`` — a *convergence-grade* CIFAR-shaped surrogate:
  ten geometry/texture classes (disk, ring, square, diamond, stripes at two
  orientations, checkerboard, cross, triangle, disk pair) rendered with
  random affine pose, random stripe frequency/phase, random foreground AND
  background colors, and heavy pixel noise.  Class identity is carried by
  shape alone — color statistics are identical across classes — so a linear
  model can't shortcut and a convnet's accuracy climbs over thousands of
  SGD steps, giving the stock cifar10_full schedule a real trajectory to
  show in environments where the actual CIFAR-10 bits are unobtainable
  (zero-egress; reference fetches them in data/cifar10/get_cifar10.sh).
"""

import numpy as np


def class_gaussian_images(n, shape=(3, 32, 32), num_classes=10, seed=0,
                          signal=2.0):
    """(images float32 (n, *shape), labels int32): per-class mean patterns
    plus unit noise."""
    rs = np.random.RandomState(seed)
    protos = rs.randn(num_classes, *shape).astype(np.float32)
    labels = rs.randint(0, num_classes, size=n).astype(np.int32)
    images = (signal * protos[labels]
              + rs.randn(n, *shape).astype(np.float32))
    return images, labels


def shape_texture_images(n, seed=0, size=32, noise=28.0, num_classes=10,
                         chunk=2048, label_noise=0.0):
    """(images uint8 (n, 3, size, size) CHW, labels int32 (n,)).

    Ten shape/texture classes under random rotation (±26°), scale,
    translation, colors and noise.  Orientation stays informative (stripe
    classes 4/5 differ by it), so rotation is bounded rather than uniform.

    ``label_noise`` > 0 is the HARD mode for convergence experiments:
    that fraction of RETURNED labels is resampled uniformly after
    rendering (images keep their true class), capping attainable test
    accuracy at (1-p) + p/K — e.g. 0.73 at p=0.3, K=10 — so strategy
    comparisons run in a contested 60-75% plateau region instead of the
    ~95% band where everything looks the same.
    """
    if num_classes > 10:
        raise ValueError("only 10 shape classes are defined")
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, num_classes, n).astype(np.int32)
    ys, xs = np.mgrid[0:size, 0:size]
    base_u = ((xs + 0.5) / size * 2 - 1).astype(np.float32)
    base_v = ((ys + 0.5) / size * 2 - 1).astype(np.float32)
    imgs = np.empty((n, 3, size, size), np.uint8)
    eps = 0.09

    def soft(x):                       # smooth indicator of x > 0
        return 1.0 / (1.0 + np.exp(np.clip(-x / eps, -30, 30)))

    for i0 in range(0, n, chunk):
        i1 = min(n, i0 + chunk)
        b = i1 - i0
        lab = labels[i0:i1]
        th = rs.uniform(-0.45, 0.45, b).astype(np.float32)
        sc = rs.uniform(0.45, 0.85, b).astype(np.float32)
        tx = rs.uniform(-0.25, 0.25, b).astype(np.float32)
        ty = rs.uniform(-0.25, 0.25, b).astype(np.float32)
        freq = rs.uniform(5.5, 9.5, b).astype(np.float32)
        ph = rs.uniform(0, 2 * np.pi, b).astype(np.float32)
        # same color law for every class: color carries zero class signal
        fg = rs.uniform(110, 255, (b, 3)).astype(np.float32)
        bg = rs.uniform(0, 145, (b, 3)).astype(np.float32)
        c, s = np.cos(th)[:, None, None], np.sin(th)[:, None, None]
        u0 = base_u[None] - tx[:, None, None]
        v0 = base_v[None] - ty[:, None, None]
        u = (c * u0 + s * v0) / sc[:, None, None]
        v = (-s * u0 + c * v0) / sc[:, None, None]
        rho = np.sqrt(u * u + v * v)
        m = np.zeros((b, size, size), np.float32)
        for k in range(num_classes):
            idx = np.where(lab == k)[0]
            if not idx.size:
                continue
            U, V, R = u[idx], v[idx], rho[idx]
            F, P = freq[idx][:, None, None], ph[idx][:, None, None]
            if k == 0:                                  # disk
                mk = soft(0.72 - R)
            elif k == 1:                                # ring
                mk = soft(0.80 - R) * soft(R - 0.42)
            elif k == 2:                                # square
                mk = soft(0.62 - np.maximum(np.abs(U), np.abs(V)))
            elif k == 3:                                # diamond
                mk = soft(0.85 - (np.abs(U) + np.abs(V)))
            elif k == 4:                                # horizontal stripes
                mk = soft(np.sin(F * V + P)) * soft(0.85 - R)
            elif k == 5:                                # vertical stripes
                mk = soft(np.sin(F * U + P)) * soft(0.85 - R)
            elif k == 6:                                # checkerboard
                mk = soft(np.sin(F * U + P) * np.sin(F * V + P)) \
                    * soft(0.80 - np.maximum(np.abs(U), np.abs(V)))
            elif k == 7:                                # cross
                bar = np.maximum(soft(0.22 - np.abs(U)),
                                 soft(0.22 - np.abs(V)))
                mk = bar * soft(0.80 - np.maximum(np.abs(U), np.abs(V)))
            elif k == 8:                                # triangle (apex up)
                mk = soft((V + 0.60) * 0.65 - np.abs(U)) * soft(0.55 - V)
            else:                                       # two disks
                d1 = np.sqrt((U - 0.45) ** 2 + V * V)
                d2 = np.sqrt((U + 0.45) ** 2 + V * V)
                mk = np.maximum(soft(0.32 - d1), soft(0.32 - d2))
            m[idx] = mk
        pix = bg[:, :, None, None] + (fg - bg)[:, :, None, None] * m[:, None]
        pix += rs.randn(b, 3, size, size).astype(np.float32) * noise
        imgs[i0:i1] = np.clip(pix, 0, 255).astype(np.uint8)
    if label_noise > 0:
        labels = labels.copy()
        flip = rs.rand(n) < label_noise
        labels[flip] = rs.randint(0, num_classes,
                                  int(flip.sum())).astype(np.int32)
    return imgs, labels


def batch_stream(images, labels, batch_size, loop=True, seed=0,
                 key_data="data", key_label="label"):
    """Shuffled minibatch dict stream; reshuffles each epoch."""
    rs = np.random.RandomState(seed)
    n = len(images) // batch_size * batch_size
    if n == 0:
        raise ValueError(f"batch_size {batch_size} > dataset size "
                         f"{len(images)}: stream would be empty")
    while True:
        perm = rs.permutation(len(images))[:n]
        for i in range(0, n, batch_size):
            idx = perm[i:i + batch_size]
            yield {key_data: images[idx], key_label: labels[idx]}
        if not loop:
            return


def bigram_corpus(vocab_size=512, seed=0, concentration=0.3):
    """Learnable synthetic token stream: a fixed random bigram transition
    table (Dirichlet rows, peaked by ``concentration``) — the LM analog of
    shape_texture_images. A model that learns the table reaches the
    table's conditional entropy; an untrained one sits at ln(vocab).
    Returns (sample_fn(n_seqs, seq_len, rng) -> int32 (n, S+1), the exact
    per-token cross-entropy floor in nats)."""
    rs = np.random.RandomState(seed)
    probs = rs.dirichlet([concentration] * vocab_size, size=vocab_size)
    # asymptotic floor: row entropies weighted by the chain's STATIONARY
    # distribution (tokens past the uniform first position converge to
    # it), not by a uniform predecessor — H = -sum_i pi_i sum_j P_ij ln P_ij
    pi = np.full(vocab_size, 1.0 / vocab_size)
    for _ in range(200):
        nxt = pi @ probs
        if np.abs(nxt - pi).max() < 1e-12:
            pi = nxt
            break
        pi = nxt
    row_ent = -(probs * np.log(np.maximum(probs, 1e-12))).sum(1)
    floor = float(pi @ row_ent)
    cum = np.cumsum(probs, axis=1)
    cum[:, -1] = 1.0   # float cumsum can end at 1-eps; u above it would
    #                    index one past the vocab

    def sample(n, seq_len, rng):
        toks = np.empty((n, seq_len + 1), np.int32)
        toks[:, 0] = rng.randint(0, vocab_size, n)
        for t in range(seq_len):
            u = rng.rand(n)
            rows = cum[toks[:, t]]
            toks[:, t + 1] = (rows < u[:, None]).sum(1)
        return toks

    return sample, floor


def lm_batch_stream(vocab_size, batch_size, seq_len, seed=0,
                    concentration=0.3):
    """Infinite {"data", "label"} feed dicts from bigram_corpus (label =
    next token). -> (iterator, loss_floor_nats)."""
    sample, floor = bigram_corpus(vocab_size, seed=seed,
                                  concentration=concentration)
    rng = np.random.RandomState(seed + 1)

    def gen():
        while True:
            toks = sample(batch_size, seq_len, rng)
            yield {"data": toks[:, :-1], "label": toks[:, 1:]}

    return gen(), floor
