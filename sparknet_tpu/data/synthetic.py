"""Synthetic datasets for tests and benchmarks (no-network environments).

Class-conditional Gaussian images: learnable by a convnet, so training tests
can assert better-than-chance accuracy without real CIFAR/ImageNet bits.
"""

import numpy as np


def class_gaussian_images(n, shape=(3, 32, 32), num_classes=10, seed=0,
                          signal=2.0):
    """(images float32 (n, *shape), labels int32): per-class mean patterns
    plus unit noise."""
    rs = np.random.RandomState(seed)
    protos = rs.randn(num_classes, *shape).astype(np.float32)
    labels = rs.randint(0, num_classes, size=n).astype(np.int32)
    images = (signal * protos[labels]
              + rs.randn(n, *shape).astype(np.float32))
    return images, labels


def batch_stream(images, labels, batch_size, loop=True, seed=0,
                 key_data="data", key_label="label"):
    """Shuffled minibatch dict stream; reshuffles each epoch."""
    rs = np.random.RandomState(seed)
    n = len(images) // batch_size * batch_size
    if n == 0:
        raise ValueError(f"batch_size {batch_size} > dataset size "
                         f"{len(images)}: stream would be empty")
    while True:
        perm = rs.permutation(len(images))[:n]
        for i in range(0, n, batch_size):
            idx = perm[i:i + batch_size]
            yield {key_data: images[idx], key_label: labels[idx]}
        if not loop:
            return
