"""Pure-Python LMDB (Lightning Memory-Mapped Database) reader and writer.

The reference trains its stock prototxts from LMDB databases of serialized
``Datum`` records (reference caffe/src/caffe/util/db_lmdb.cpp:1-35 opens the
env read-only and walks a cursor; layers/data_layer.cpp:14-60 consumes the
cursor sequentially, wrapping at the end). This module implements the LMDB
*file format* directly — a memory-mapped B+tree — so the same databases are
readable (and writable, for ``convert_imageset``-style tools and test
fixtures) with no native liblmdb dependency.

Format notes (byte layout of lmdb's mdb.c, little-endian, 64-bit):

  page header (16 bytes)          meta page body (after header)
    0  u64 pgno                      0  u32 magic     = 0xBEEFC0DE
    8  u16 pad                       4  u32 version   = 1
    10 u16 flags                     8  u64 fixed-map address
    12 u16 lower | u32 n_overflow   16  u64 mapsize
    14 u16 upper                    24  MDB_db[2] (FREE, MAIN; 48 B each)
                                   120  u64 last_pg
  MDB_db (48 bytes)                128  u64 txnid
    0  u32 pad (FREE slot: psize)
    4  u16 flags    6  u16 depth
    8  u64 branch_pages   16 u64 leaf_pages   24 u64 overflow_pages
    32 u64 entries        40 u64 root (0xFFFF.. = empty)

  node (8-byte header at even offsets; page ptr array after page header,
  nodes allocated downward from `upper`):
    0 u16 lo   2 u16 hi   4 u16 flags   6 u16 ksize   8 key...
    branch: child pgno = lo | hi<<16 | flags<<32, data none
    leaf:   datasize   = lo | hi<<16; flags & 0x01 (BIGDATA) -> key is
            followed by a u64 pgno of an overflow page run; else by data.
  overflow page run: first page has header {pgno, flags=0x04, n_overflow};
    payload starts at byte 16 and runs contiguously across the whole span.

The two meta pages (pgno 0, 1) alternate by txnid; readers take the one
with the larger txnid. Caffe databases store keys like "00042" /
"00000042_name.jpg" — lexicographically ordered, which the bulk writer
below requires (it builds the tree bottom-up in one pass).
"""

import mmap
import os
import struct

_MAGIC = 0xBEEFC0DE
_VERSION = 1
_P_INVALID = 0xFFFFFFFFFFFFFFFF

_P_BRANCH = 0x01
_P_LEAF = 0x02
_P_OVERFLOW = 0x04
_P_META = 0x08
_P_LEAF2 = 0x20

_F_BIGDATA = 0x01
_F_DUPDATA = 0x04

_PAGEHDRSZ = 16
_NODESZ = 8

_page_hdr = struct.Struct("<QHHHH")          # pgno, pad, flags, lower, upper
_node_hdr = struct.Struct("<HHHH")           # lo, hi, flags, ksize
_db_rec = struct.Struct("<IHHQQQQQ")         # pad, flags, depth, branch,
                                             # leaf, overflow, entries, root
_meta_hdr = struct.Struct("<IIQQ")           # magic, version, address, mapsize


def _data_path(path):
    """An LMDB "database" is a directory holding data.mdb (the default
    MDB_NOSUBDIR-less layout caffe uses); accept the file itself too."""
    if os.path.isdir(path):
        return os.path.join(path, "data.mdb")
    return path


class LMDBError(ValueError):
    pass


class LMDBReader:
    """Read-only cursor over one LMDB file's MAIN database.

    Usage::

        with LMDBReader("examples/cifar10/cifar10_train_lmdb") as db:
            for key, value in db.items():
                ...
    """

    def __init__(self, path):
        self.path = _data_path(path)
        self._f = open(self.path, "rb")
        try:
            self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:
            self._f.close()
            raise LMDBError(f"{self.path}: empty or unmappable file")
        self._read_meta()

    # -- structure ---------------------------------------------------------

    def _read_meta(self):
        best = None
        for pgno in (0, 1):
            off = pgno * 4096  # meta pages are at file start regardless of
            # psize: page 1 lives at offset psize, but psize is only known
            # from meta 0 — read meta 0 first, then meta 1 at its true spot.
            if pgno == 1:
                off = self._psize
            hdr = self._mm[off:off + _PAGEHDRSZ]
            if len(hdr) < _PAGEHDRSZ:
                continue
            _, _, flags, _, _ = _page_hdr.unpack(hdr)
            if not flags & _P_META:
                raise LMDBError(f"{self.path}: page {pgno} is not a meta page")
            body = self._mm[off + _PAGEHDRSZ:off + _PAGEHDRSZ + 136]
            magic, version, _, mapsize = _meta_hdr.unpack(body[:24])
            if magic != _MAGIC:
                raise LMDBError(f"{self.path}: bad magic {magic:#x}")
            if version != _VERSION:
                raise LMDBError(f"{self.path}: unsupported version {version}")
            free = _db_rec.unpack(body[24:72])
            main = _db_rec.unpack(body[72:120])
            last_pg, txnid = struct.unpack("<QQ", body[120:136])
            if pgno == 0:
                self._psize = free[0] or 4096
            if best is None or txnid >= best[0]:
                best = (txnid, main, last_pg)
        if best is None:
            raise LMDBError(f"{self.path}: no valid meta page")
        self.txnid, main, self.last_pg = best
        (_, self.db_flags, self.depth, self.branch_pages, self.leaf_pages,
         self.overflow_pages, self.entries, self.root) = main

    def _page(self, pgno):
        off = pgno * self._psize
        if off + self._psize > len(self._mm):
            raise LMDBError(f"{self.path}: page {pgno} beyond EOF")
        return off

    def _page_nodes(self, off):
        """Yield node offsets of a branch/leaf page at file offset `off`."""
        _, _, flags, lower, upper = _page_hdr.unpack(
            self._mm[off:off + _PAGEHDRSZ])
        n = (lower - _PAGEHDRSZ) >> 1
        ptrs = struct.unpack("<%dH" % n,
                             self._mm[off + _PAGEHDRSZ:off + _PAGEHDRSZ
                                      + 2 * n])
        return flags, [off + p for p in ptrs]

    def _leaf_value(self, noff):
        lo, hi, flags, ksize = _node_hdr.unpack(self._mm[noff:noff + _NODESZ])
        key = bytes(self._mm[noff + _NODESZ:noff + _NODESZ + ksize])
        dsize = lo | (hi << 16)
        if flags & _F_DUPDATA:
            raise LMDBError("dupsort databases are not supported")
        if flags & _F_BIGDATA:
            (ovpg,) = struct.unpack(
                "<Q", self._mm[noff + _NODESZ + ksize:
                               noff + _NODESZ + ksize + 8])
            ooff = self._page(ovpg)
            _, _, oflags, pages_lo, pages_hi = _page_hdr.unpack(
                self._mm[ooff:ooff + _PAGEHDRSZ])
            if not oflags & _P_OVERFLOW:
                raise LMDBError(f"page {ovpg}: expected overflow page")
            start = ooff + _PAGEHDRSZ
            value = bytes(self._mm[start:start + dsize])
        else:
            start = noff + _NODESZ + ksize
            value = bytes(self._mm[start:start + dsize])
        return key, value

    # -- public API --------------------------------------------------------

    def __len__(self):
        return self.entries

    def items(self):
        """Yield (key, value) bytes pairs in key order (a full cursor walk,
        db_lmdb.cpp LMDBCursor::Next equivalent)."""
        if self.root == _P_INVALID:
            return
        stack = [self.root]
        # depth-first, left-to-right; branch children pushed reversed
        while stack:
            off = self._page(stack.pop())
            flags, nodes = self._page_nodes(off)
            if flags & _P_LEAF2:
                raise LMDBError("MDB_DUPFIXED leaf2 pages not supported")
            if flags & _P_BRANCH:
                kids = []
                for noff in nodes:
                    lo, hi, nflags, _ = _node_hdr.unpack(
                        self._mm[noff:noff + _NODESZ])
                    kids.append(lo | (hi << 16) | (nflags << 32))
                stack.extend(reversed(kids))
            elif flags & _P_LEAF:
                for noff in nodes:
                    yield self._leaf_value(noff)
            else:
                raise LMDBError(f"unexpected page flags {flags:#x}")

    def keys(self):
        for k, _ in self.items():
            yield k

    def get(self, key):
        """Point lookup by binary search down the tree."""
        if isinstance(key, str):
            key = key.encode()
        if self.root == _P_INVALID:
            return None
        pgno = self.root
        for _ in range(self.depth + 1):
            off = self._page(pgno)
            flags, nodes = self._page_nodes(off)
            if flags & _P_BRANCH:
                # find rightmost child whose separator <= key; node 0's key
                # is empty by convention (always <= key)
                chosen = None
                for noff in nodes:
                    lo, hi, nflags, ksize = _node_hdr.unpack(
                        self._mm[noff:noff + _NODESZ])
                    sep = bytes(self._mm[noff + _NODESZ:
                                         noff + _NODESZ + ksize])
                    child = lo | (hi << 16) | (nflags << 32)
                    if ksize == 0 or sep <= key:
                        chosen = child
                    else:
                        break
                pgno = chosen
            elif flags & _P_LEAF:
                for noff in nodes:
                    k, v = self._leaf_value(noff)
                    if k == key:
                        return v
                return None
            else:
                raise LMDBError(f"unexpected page flags {flags:#x}")
        raise LMDBError("tree deeper than declared depth")

    def close(self):
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self):
        return self.items()


class LMDBWriter:
    """Single-pass bulk writer: collects records, builds the B+tree
    bottom-up on close. Keys must be unique; they are sorted on close, so
    insertion order is free (caffe's sequential "%05d"/"%08d_..." keys are
    already sorted). The resulting file is a valid single-txn LMDB env."""

    def __init__(self, path, psize=4096):
        self.dir = path
        self.psize = psize
        self.nodemax = (((psize - _PAGEHDRSZ) // 2) & ~1) - 2  # mdb.c
        self._items = []
        self._closed = False

    def put(self, key, value):
        if isinstance(key, str):
            key = key.encode()
        if isinstance(value, str):
            value = value.encode()
        if len(key) > 511:  # mdb_env_get_maxkeysize default
            raise LMDBError(f"key too long ({len(key)} > 511)")
        self._items.append((bytes(key), bytes(value)))

    # -- tree construction -------------------------------------------------

    def _new_page(self):
        """Returns (pgno, buf). buf is psize bytes, header filled on seal."""
        buf = bytearray(self.psize)
        self._pages.append(buf)
        return len(self._pages) + 1, buf  # pgnos 0,1 are the metas

    def _seal(self, buf, pgno, flags, ptrs_nodes):
        """Write header + ptr array + nodes (already placed)."""
        lower = _PAGEHDRSZ + 2 * len(ptrs_nodes)
        upper = min(ptrs_nodes) if ptrs_nodes else self.psize
        _page_hdr.pack_into(buf, 0, pgno, 0, flags, lower, upper)
        struct.pack_into("<%dH" % len(ptrs_nodes), buf, _PAGEHDRSZ,
                         *ptrs_nodes)

    def _build_level(self, entries, leaf):
        """Pack (key, payload) entries into pages; returns [(pgno, firstkey)].

        leaf payloads are either (b"data", None) inline or (None, ovpgno,
        dsize) for big data; branch payloads are child pgnos."""
        out = []
        page_nodes = []   # (key, node_bytes)
        used = 0

        def flush():
            nonlocal page_nodes, used
            if not page_nodes:
                return
            pgno, buf = self._new_page()
            ptrs = []
            top = self.psize
            for key, nb in page_nodes:
                top -= len(nb) + (len(nb) & 1)  # EVEN alignment
                buf[top:top + len(nb)] = nb
                ptrs.append(top)
            self._seal(buf, pgno, _P_LEAF if leaf else _P_BRANCH, ptrs)
            self._stat["leaf" if leaf else "branch"] += 1
            out.append((page_nodes[0][0], pgno))
            page_nodes, used = [], 0

        for i, (key, payload) in enumerate(entries):
            if leaf:
                kind = payload[0]
                if kind == "inline":
                    data = payload[1]
                    nb = _node_hdr.pack(len(data) & 0xFFFF, len(data) >> 16,
                                        0, len(key)) + key + data
                else:  # overflow
                    ovpg, dsize = payload[1], payload[2]
                    nb = _node_hdr.pack(dsize & 0xFFFF, dsize >> 16,
                                        _F_BIGDATA, len(key)) + key \
                        + struct.pack("<Q", ovpg)
            else:
                child = payload
                k = b"" if not page_nodes else key  # node 0 key is empty
                nb = _node_hdr.pack(child & 0xFFFF, (child >> 16) & 0xFFFF,
                                    (child >> 32) & 0xFFFF, len(k)) + k
            need = 2 + len(nb) + (len(nb) & 1)
            if page_nodes and _PAGEHDRSZ + used + need > self.psize:
                flush()
                if not leaf:
                    # re-encode with empty node-0 key for the new page
                    k = b""
                    nb = _node_hdr.pack(child & 0xFFFF,
                                        (child >> 16) & 0xFFFF,
                                        (child >> 32) & 0xFFFF, len(k)) + k
                    need = 2 + len(nb) + (len(nb) & 1)
            page_nodes.append((key, nb))
            used += need
        flush()
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._items.sort(key=lambda kv: kv[0])
        for i in range(1, len(self._items)):
            if self._items[i][0] == self._items[i - 1][0]:
                raise LMDBError(
                    f"duplicate key {self._items[i][0]!r}")
        self._pages = []
        self._stat = {"leaf": 0, "branch": 0, "overflow": 0}

        # leaves (+ overflow runs as encountered)
        leaf_entries = []
        for key, value in self._items:
            if _NODESZ + len(key) + len(value) > self.nodemax:
                npages = (_PAGEHDRSZ + len(value) + self.psize - 1) \
                    // self.psize
                first_buf = bytearray(self.psize)
                self._pages.append(first_buf)
                ovpg = len(self._pages) + 1
                # overflow header: pgno, flags=P_OVERFLOW, page count in the
                # 32-bit field that aliases lower/upper
                struct.pack_into("<QHHI", first_buf, 0, ovpg, 0,
                                 _P_OVERFLOW, npages)
                span = bytearray()
                span += value[:self.psize - _PAGEHDRSZ]
                first_buf[_PAGEHDRSZ:_PAGEHDRSZ + len(span)] = span
                rest = value[self.psize - _PAGEHDRSZ:]
                for p in range(1, npages):
                    b = bytearray(self.psize)
                    chunk = rest[(p - 1) * self.psize:p * self.psize]
                    b[:len(chunk)] = chunk
                    self._pages.append(b)
                self._stat["overflow"] += npages
                leaf_entries.append((key, ("big", ovpg, len(value))))
            else:
                leaf_entries.append((key, ("inline", value)))

        depth = 0
        root = _P_INVALID
        if leaf_entries:
            # each level is [(first_key_of_subtree, pgno)], built bottom-up
            level = self._build_level(leaf_entries, leaf=True)
            depth = 1
            while len(level) > 1:
                level = self._build_level(level, leaf=False)
                depth += 1
            root = level[0][1]

        last_pg = len(self._pages) + 1
        file_pages = last_pg + 1
        mapsize = file_pages * self.psize

        meta = bytearray(self.psize)
        main = _db_rec.pack(0, 0, depth, self._stat["branch"],
                            self._stat["leaf"], self._stat["overflow"],
                            len(self._items), root)
        free = _db_rec.pack(self.psize, 0, 0, 0, 0, 0, 0, _P_INVALID)
        body = _meta_hdr.pack(_MAGIC, _VERSION, 0, mapsize) + free + main \
            + struct.pack("<QQ", last_pg, 1)
        meta[_PAGEHDRSZ:_PAGEHDRSZ + len(body)] = body

        os.makedirs(self.dir, exist_ok=True)
        with open(os.path.join(self.dir, "data.mdb"), "wb") as f:
            for pgno in (0, 1):
                m = bytearray(meta)
                _page_hdr.pack_into(m, 0, pgno, 0, _P_META, 0, 0)
                f.write(m)
            f.write(b"".join(bytes(p) for p in self._pages))
        # lock.mdb exists in every real env dir; readers ignore its content
        lock = os.path.join(self.dir, "lock.mdb")
        if not os.path.exists(lock):
            open(lock, "wb").close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
