"""Shape/structure layers (reference common_layers.hpp zoo): pure jnp
reshuffles — XLA folds most of these into layout changes, so they cost
nothing at runtime.
"""

import numpy as np
import jax.numpy as jnp

from ..graph.registry import Layer, register


@register
class Softmax(Layer):
    type_name = "Softmax"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        self.axis = self.canonical_axis(lp.softmax_param.axis)

    def out_shapes(self):
        return [self.bottom_shapes[0]]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        x = x - jnp.max(x, axis=self.axis, keepdims=True)
        e = jnp.exp(x)
        return [e / jnp.sum(e, axis=self.axis, keepdims=True)]


@register
class Concat(Layer):
    type_name = "Concat"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        cp = lp.concat_param
        # legacy concat_dim honored when axis unset (concat_layer.cpp)
        axis = cp.axis if cp.has("axis") or not cp.has("concat_dim") \
            else cp.concat_dim
        self.axis = self.canonical_axis(int(axis))

    def out_shapes(self):
        shape = list(self.bottom_shapes[0])
        shape[self.axis] = sum(s[self.axis] for s in self.bottom_shapes)
        return [tuple(shape)]

    def apply(self, params, bottoms, train, rng):
        return [jnp.concatenate(bottoms, axis=self.axis)]


@register
class Slice(Layer):
    type_name = "Slice"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        sp = lp.slice_param
        axis = sp.axis if sp.has("axis") or not sp.has("slice_dim") \
            else sp.slice_dim
        self.axis = self.canonical_axis(int(axis))
        self.n_tops = len(lp.top)
        dim = bottom_shapes[0][self.axis]
        points = list(sp.slice_point)
        if points:
            assert len(points) == self.n_tops - 1
            bounds = [0] + [int(p) for p in points] + [dim]
        else:
            assert dim % self.n_tops == 0
            step = dim // self.n_tops
            bounds = list(range(0, dim + 1, step))
        self.bounds = bounds

    def out_shapes(self):
        base = list(self.bottom_shapes[0])
        outs = []
        for i in range(self.n_tops):
            s = list(base)
            s[self.axis] = self.bounds[i + 1] - self.bounds[i]
            outs.append(tuple(s))
        return outs

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        outs = []
        for i in range(self.n_tops):
            idx = [slice(None)] * x.ndim
            idx[self.axis] = slice(self.bounds[i], self.bounds[i + 1])
            outs.append(x[tuple(idx)])
        return outs


@register
class Split(Layer):
    """Fan-out a blob to several tops. Caffe inserts these to sum gradients
    at fan-out points (util/insert_splits.cpp); under autodiff the fan-out
    gradient accumulation is automatic, so this is pure aliasing."""

    type_name = "Split"

    def out_shapes(self):
        return [self.bottom_shapes[0]] * max(1, len(self.lp.top))

    def apply(self, params, bottoms, train, rng):
        return [bottoms[0]] * max(1, len(self.lp.top))


@register
class Flatten(Layer):
    type_name = "Flatten"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        fp = lp.flatten_param
        nd = len(bottom_shapes[0])
        self.axis = self.canonical_axis(fp.axis)
        self.end_axis = self.canonical_axis(fp.end_axis)

    def out_shapes(self):
        s = self.bottom_shapes[0]
        mid = int(np.prod(s[self.axis:self.end_axis + 1], dtype=np.int64))
        return [tuple(s[:self.axis]) + (mid,) + tuple(s[self.end_axis + 1:])]

    def apply(self, params, bottoms, train, rng):
        return [bottoms[0].reshape(self.out_shapes()[0])]


@register
class Reshape(Layer):
    """Caffe reshape semantics (reshape_layer.cpp): dim 0 copies the bottom
    dim, one dim may be -1 (inferred); axis/num_axes bound the replaced span."""

    type_name = "Reshape"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        rp = lp.reshape_param
        bshape = list(bottom_shapes[0])
        nd = len(bshape)
        axis = rp.axis + nd + 1 if rp.axis < 0 else rp.axis
        num_axes = rp.num_axes
        end = nd if num_axes == -1 else axis + num_axes
        spec = [int(d) for d in rp.shape.dim] if rp.has("shape") else []
        replaced = bshape[axis:end]
        out_mid = []
        infer = -1
        for i, d in enumerate(spec):
            if d == 0:
                out_mid.append(replaced[i])
            elif d == -1:
                infer = i
                out_mid.append(1)
            else:
                out_mid.append(d)
        total = int(np.prod(bshape, dtype=np.int64))
        fixed = int(np.prod(bshape[:axis], dtype=np.int64)) * \
            int(np.prod(out_mid, dtype=np.int64)) * \
            int(np.prod(bshape[end:], dtype=np.int64))
        if infer >= 0:
            out_mid[infer] = total // fixed
        self.new_shape = tuple(bshape[:axis]) + tuple(out_mid) + \
            tuple(bshape[end:])
        assert int(np.prod(self.new_shape, dtype=np.int64)) == total, \
            f"reshape count mismatch {bshape} -> {self.new_shape}"

    def out_shapes(self):
        return [self.new_shape]

    def apply(self, params, bottoms, train, rng):
        return [bottoms[0].reshape(self.new_shape)]


@register
class Eltwise(Layer):
    type_name = "Eltwise"

    PROD, SUM, MAX = 0, 1, 2

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        ep = lp.eltwise_param
        self.op = int(ep.operation)
        coeff = list(ep.coeff)
        if coeff and len(coeff) != len(bottom_shapes):
            raise ValueError("eltwise coeff count must equal bottom count")
        self.coeff = coeff or [1.0] * len(bottom_shapes)

    def out_shapes(self):
        return [self.bottom_shapes[0]]

    def apply(self, params, bottoms, train, rng):
        if self.op == self.PROD:
            y = bottoms[0]
            for b in bottoms[1:]:
                y = y * b
        elif self.op == self.SUM:
            y = self.coeff[0] * bottoms[0]
            for c, b in zip(self.coeff[1:], bottoms[1:]):
                y = y + c * b
        else:
            y = bottoms[0]
            for b in bottoms[1:]:
                y = jnp.maximum(y, b)
        return [y]


@register
class Tile(Layer):
    type_name = "Tile"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        tp = lp.tile_param
        self.axis = self.canonical_axis(tp.axis)
        self.tiles = int(tp.tiles)

    def out_shapes(self):
        s = list(self.bottom_shapes[0])
        s[self.axis] *= self.tiles
        return [tuple(s)]

    def apply(self, params, bottoms, train, rng):
        reps = [1] * bottoms[0].ndim
        reps[self.axis] = self.tiles
        return [jnp.tile(bottoms[0], reps)]


@register
class ArgMax(Layer):
    type_name = "ArgMax"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        ap = lp.argmax_param
        self.out_max_val = bool(ap.out_max_val)
        self.top_k = int(ap.top_k)
        self.has_axis = ap.has("axis")
        self.axis = self.canonical_axis(ap.axis) if self.has_axis else None

    def out_shapes(self):
        s = self.bottom_shapes[0]
        if self.has_axis:
            out = list(s)
            out[self.axis] = self.top_k
            return [tuple(out)]
        k = self.top_k
        return [(s[0], 2 if self.out_max_val else 1, k)]

    def apply(self, params, bottoms, train, rng):
        import jax
        x = bottoms[0]
        if self.has_axis:
            moved = jnp.moveaxis(x, self.axis, -1)
            vals, idx = jax.lax.top_k(moved, self.top_k)
            pick = vals if self.out_max_val else idx.astype(x.dtype)
            return [jnp.moveaxis(pick, -1, self.axis).astype(x.dtype)]
        flat = x.reshape(x.shape[0], -1)
        vals, idx = jax.lax.top_k(flat, self.top_k)
        idxf = idx.astype(x.dtype)
        if self.out_max_val:
            return [jnp.stack([idxf, vals.astype(x.dtype)], axis=1)]
        return [idxf[:, None, :]]


@register
class Reduction(Layer):
    type_name = "Reduction"

    SUM, ASUM, SUMSQ, MEAN = 1, 2, 3, 4

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        rp = lp.reduction_param
        self.op = int(rp.operation)
        self.axis = self.canonical_axis(rp.axis)
        self.coeff = float(rp.coeff)

    def out_shapes(self):
        return [tuple(self.bottom_shapes[0][:self.axis])]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        axes = tuple(range(self.axis, x.ndim))
        if self.op == self.SUM:
            y = jnp.sum(x, axis=axes)
        elif self.op == self.ASUM:
            y = jnp.sum(jnp.abs(x), axis=axes)
        elif self.op == self.SUMSQ:
            y = jnp.sum(x * x, axis=axes)
        else:
            y = jnp.mean(x, axis=axes)
        return [y * self.coeff]


@register
class Silence(Layer):
    """Consumes bottoms, produces nothing (silence_layer.cpp)."""

    type_name = "Silence"

    def out_shapes(self):
        return []

    def apply(self, params, bottoms, train, rng):
        return []


@register
class BatchReindex(Layer):
    """top = bottom[0] gathered by the (static-length) index blob bottom[1]
    (batch_reindex_layer.cpp)."""

    type_name = "BatchReindex"

    def out_shapes(self):
        return [tuple(self.bottom_shapes[1][:1]) +
                tuple(self.bottom_shapes[0][1:])]

    def apply(self, params, bottoms, train, rng):
        return [jnp.take(bottoms[0], bottoms[1].astype(jnp.int32), axis=0)]


@register
class Filter(Layer):
    """Selects batch items whose selector is nonzero (filter_layer.cpp),
    with CAPACITY-PADDED semantics — the documented deviation from Caffe.

    bottom[0..k-1] are the blobs to filter; bottom[k] is the selector:
    shape (N,) or (N, 1, ...) (singleton trailing dims, Reshape's CHECK).
    Caffe shrinks top batch to the selected count — a data-dependent
    shape, which XLA's static-shape compilation model cannot express.
    Here each top keeps the FULL input batch N: selected items are
    compacted to the front in stable order (matching Caffe's
    indices_to_forward_ order) and the tail rows are zero. One OPTIONAL
    extra top (declare k+1 tops) receives the valid count as a scalar so
    downstream consumers can mask: the standard XLA capacity-padding
    discipline (the same trick ops/moe.py uses for expert overflow).

    Backward is jax autodiff of the gather: cotangents scatter home to
    selected rows, zeros elsewhere — exactly filter_layer.cpp's
    Backward_cpu, with no hand-written index bookkeeping."""

    type_name = "Filter"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        sel = bottom_shapes[-1]
        if any(d != 1 for d in sel[1:]):
            raise ValueError(
                f"{lp.name}: selector dims past the first must be "
                f"singletons, got {tuple(sel)}")
        n = sel[0]
        for i, s in enumerate(bottom_shapes[:-1]):
            if s[0] != n:
                raise ValueError(
                    f"{lp.name}: bottom {i} batch {s[0]} != selector "
                    f"batch {n}")
        ndata = len(bottom_shapes) - 1
        if len(lp.top) not in (ndata, ndata + 1):
            raise ValueError(
                f"{lp.name}: Filter needs {ndata} tops (or {ndata + 1} "
                f"with the valid-count top), got {len(lp.top)}")
        self._with_count = len(lp.top) == ndata + 1

    def out_shapes(self):
        shapes = [tuple(s) for s in self.bottom_shapes[:-1]]
        if self._with_count:
            shapes.append(())
        return shapes

    def apply(self, params, bottoms, train, rng):
        sel = bottoms[-1].reshape(bottoms[-1].shape[0])
        keep = sel != 0
        n = keep.shape[0]
        # stable compaction: kept indices first, original order preserved
        order = jnp.argsort(jnp.logical_not(keep), stable=True)
        kmask = keep[order]                       # first count rows True
        tops = []
        for x in bottoms[:-1]:
            y = jnp.take(x, order, axis=0)
            y = y * kmask.reshape((n,) + (1,) * (y.ndim - 1)).astype(y.dtype)
            tops.append(y)
        if self._with_count:
            tops.append(jnp.sum(keep.astype(jnp.int32)))
        return tops
