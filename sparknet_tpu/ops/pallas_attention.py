"""Pallas flash-attention kernel for TPU.

The per-chip complement to parallel.ring: ring attention distributes the
sequence across chips; THIS kernel computes each chip's local attention
without ever materializing the (S, S) score matrix — the flash recurrence
(running max m, denominator l, unnormalized accumulator acc) over K/V
blocks streamed through VMEM, with the MXU doing the two matmuls per block.
K/V arrive in (block_k, D) tiles via a third, sequential grid dimension, so
VMEM usage is O(block) regardless of S (verified to S=32k on one v5e chip).

Forward is a pallas kernel; backward recomputes through the dense path
(jax.custom_vjp) — fine at training block sizes, while the kernel shines
for long-context inference/eval. Interpret mode (CPU tests) engages
automatically off-TPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.ring import dense_attention

NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
               causal, scale):
    _, bq, d = q_ref.shape
    bk = k_ref.shape[1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: skip K/V blocks wholly above the diagonal
    live = (ki * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _step():
        qb = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        sc = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            sc = jnp.where(q_pos >= k_pos, sc, NEG_INF)
        m_prev = m_ref[:, :1]                       # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, vb, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"sequence {s} must divide blocks "
                         f"({block_q}, {block_k})")
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    kernel = functools.partial(_fa_kernel, causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q, s // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
            pltpu.VMEM((block_q, 128), jnp.float32),   # m (lane-replicated)
            pltpu.VMEM((block_q, 128), jnp.float32),   # l
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


def _should_interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128):
    """Flash attention (B, H, S, D) -> (B, H, S, D); exact, O(block) VMEM.
    scale defaults to 1/sqrt(D)."""
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          _should_interpret())


def _fwd(q, k, v, causal, scale, block_q, block_k):
    return flash_attention(q, k, v, causal, scale, block_q, block_k), \
        (q, k, v)


def _bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: dense_attention(q, k, v, causal=causal, scale=scale),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
