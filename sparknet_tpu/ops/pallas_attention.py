"""Pallas flash-attention kernel for TPU — forward AND blockwise backward.

The per-chip complement to parallel.ring: ring attention distributes the
sequence across chips; THIS kernel computes each chip's local attention
without ever materializing the (S, S) score matrix — the flash recurrence
(running max m, denominator l, unnormalized accumulator acc) over K/V
blocks streamed through VMEM, with the MXU doing the two matmuls per block.
K/V arrive in (block_k, D) tiles via a third, sequential grid dimension, so
VMEM usage is O(block) regardless of S.

Training memory is O(block) too: the forward additionally emits the
per-row logsumexp (LSE, lane-replicated like jax's own TPU kernel), and
the backward re-derives each probability block as P = exp(S - LSE) inside
two pallas kernels — dQ with K/V streamed innermost, dK/dV with Q/dO
streamed innermost (the FlashAttention-2 recurrences):

    delta_i = rowsum(dO_i * O_i)                (recomputed per block visit)
    P_ij    = exp(scale * Q_i K_j^T - LSE_i)
    dV_j   += P_ij^T dO_i
    dS_ij   = P_ij * (dO_i V_j^T - delta_i)
    dQ_i   += scale * dS_ij K_j
    dK_j   += scale * dS_ij^T Q_i

Interpret mode (CPU tests) engages automatically off-TPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
               *, causal, scale):
    _, bq, d = q_ref.shape
    bk = k_ref.shape[1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: skip K/V blocks wholly above the diagonal
    live = (ki * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _step():
        qb = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        sc = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            sc = jnp.where(q_pos >= k_pos, sc, NEG_INF)
        m_prev = m_ref[:, :1]                       # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, vb, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _fit_block(block, s):
    """Largest divisor of the sequence <= the requested block, preferring
    sublane-aligned (multiple-of-8) divisors; the grid's K/V dimension is
    sequential, so a collapsed block size pays dispatch latency per tile
    (the 20x in flash_attention's docstring)."""
    block = min(block, s)
    if s % block == 0:
        return block
    largest = 1
    for d in range(block, 0, -1):
        if s % d == 0:
            if d % 8 == 0:
                return d
            largest = max(largest, d)
    if largest < 8 and s > 64:
        # e.g. prime S: the only divisors are 1/S — a 1-row block means
        # S^2 sequential kernel dispatches (near-hang), worse than failing
        raise ValueError(
            f"sequence {s} has no usable flash block divisor "
            f"<= {block}; pad the sequence to a multiple of 128")
    return largest


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, s, d = q.shape
    block_q = _fit_block(block_q, s)
    block_k = _fit_block(block_k, s)
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    kernel = functools.partial(_fa_kernel, causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q, s // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, LANES),
                         lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),       # acc
            pltpu.VMEM((block_q, LANES), jnp.float32),   # m (lane-replicated)
            pltpu.VMEM((block_q, LANES), jnp.float32),   # l
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d), lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
               acc_ref, *, causal, scale):
    _, bq, d = q_ref.shape
    bk = k_ref.shape[1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live = (ki * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _step():
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        dob = do_ref[0].astype(jnp.float32)
        ob = o_ref[0].astype(jnp.float32)
        delta = jnp.sum(dob * ob, axis=1, keepdims=True)        # (bq, 1)
        sc = scale * jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            sc = jnp.where(q_pos >= k_pos, sc, NEG_INF)
        p = jnp.exp(sc - lse_ref[0][:, :1])                     # (bq, bk)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[:] += scale * jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref, dv_ref,
                dk_acc, dv_acc, *, causal, scale):
    _, bq, d = q_ref.shape
    bk = k_ref.shape[1]
    ki = pl.program_id(1)       # note: grid is (bh, j, i) here
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (ki * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _step():
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        dob = do_ref[0].astype(jnp.float32)
        ob = o_ref[0].astype(jnp.float32)
        delta = jnp.sum(dob * ob, axis=1, keepdims=True)
        sc = scale * jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            sc = jnp.where(q_pos >= k_pos, sc, NEG_INF)
        p = jnp.exp(sc - lse_ref[0][:, :1])
        # dV_j += P^T dO
        dv_acc[:] += jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dK_j += scale * dS^T Q
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal, scale, block_q, block_k,
                    interpret):
    b, h, s, d = q.shape
    block_q = _fit_block(block_q, s)
    block_k = _fit_block(block_k, s)
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    dof = g.reshape(b * h, s, d)
    of = o.reshape(b * h, s, d)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0))
    lse_spec = pl.BlockSpec((1, block_q, LANES),
                            lambda bh, i, j: (bh, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale),
        grid=(b * h, s // block_q, s // block_k),   # K/V innermost
        in_specs=[q_spec, k_spec, k_spec, q_spec, q_spec, lse_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, of, lse)

    # second kernel iterates (bh, j, i): Q/dO stream innermost
    qT_spec = pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0))
    kT_spec = pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0))
    lseT_spec = pl.BlockSpec((1, block_q, LANES),
                             lambda bh, j, i: (bh, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale),
        grid=(b * h, s // block_k, s // block_q),
        in_specs=[qT_spec, kT_spec, kT_spec, qT_spec, qT_spec, lseT_spec],
        out_specs=[kT_spec, kT_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, s, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, of, lse)
    return (dq.reshape(b, h, s, d), dk.reshape(b, h, s, d),
            dv.reshape(b, h, s, d))


def _should_interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512):
    """Flash attention (B, H, S, D) -> (B, H, S, D); exact, O(block) VMEM
    in both forward and backward. scale defaults to 1/sqrt(D).

    Default blocks are 512x512: the grid's K/V dimension is sequential,
    so small blocks are dispatch-latency-bound — at S=32k, 512x512 runs
    the train-grad step 20x faster than 128x128 on a v5e (149 ms vs
    3.1 s) while still using O(block^2) VMEM (~1 MB of scores). Blocks
    clamp to S for short sequences."""
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            _should_interpret())
    return out


def _fwd(q, k, v, causal, scale, block_q, block_k):
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              _should_interpret())
    return out, (q, k, v, out, lse)


def _bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_backward(q, k, v, o, lse, g, causal, scale, block_q,
                           block_k, _should_interpret())


flash_attention.defvjp(_fwd, _bwd)
