"""Layer implementations (registered by import side effect).

The TPU-native layer zoo replacing reference caffe/src/caffe/layers/* —
jnp/lax expressions traced into one XLA program; kernels come from XLA
(MXU for conv/matmul), not hand-written CUDA.
"""

from . import (  # noqa: F401
    convolution,
    pooling,
    lrn,
    dense,
    activations,
    normalization,
    structural,
    losses,
    feed,
    attention,
    moe,
    python_layer,
)
