"""User-defined layers: the ``type: "Python"`` escape hatch.

Rebuilds the reference's custom-layer mechanism (layer_factory.cpp:202
GetPythonLayer + include/caffe/python_layer.hpp): a prototxt layer

    layer {
      type: 'Python'
      python_param { module: 'mylayers'  layer: 'MyLayer'
                     param_str: '{"k": 3}' }
    }

imports ``module`` (which must be importable: on PYTHONPATH / sys.path,
or on the colon-separated SPARKNET_PYTHON_LAYER_PATH), instantiates class
``layer`` and drives it through the net build — without touching the
framework. As in the reference, a Python layer is NOT automatically a
loss layer; give it an explicit ``loss_weight`` (python_layer.hpp has no
type()-based loss detection either — see linreg.prototxt's comment).

The user class is TPU-first, so the interface is PURE — jnp in, jnp out,
traced under jit — which collapses the reference's four imperative
blob-mutation hooks into shape inference + one forward:

    class MyLayer:
        def setup(self, bottom_shapes):           # optional; param_str,
            ...                                   # phase, name already set
        def reshape(self, bottom_shapes):         # required
            return [top_shape, ...]               # (a tuple = ONE shape)
        def forward(self, params, bottoms):       # required; pure jnp.
            return [tops]                         # (or one array)
        def param_shapes(self):                   # optional learnable
            return [(shape, filler_msg_or_None, lr_mult, decay_mult)]

``backward`` does not exist: gradients come from jax autodiff of
``forward`` (the reference made users hand-write Backward_cpu against
mutable diff blobs). ``forward`` may take a third ``train`` argument to
distinguish phases. Registering a layer under its OWN type string —
the richer alternative to type:"Python" — is public API too:

    from sparknet_tpu import Layer, register_layer
    @register_layer
    class MyOp(Layer):
        type_name = "MyOp"
        ...
"""

import importlib
import inspect
import os
import sys

from ..graph.registry import Layer, register


def _load_user_class(module_name, class_name):
    extra = [p for p in
             os.environ.get("SPARKNET_PYTHON_LAYER_PATH", "").split(":")
             if p]
    added = [p for p in extra if p not in sys.path]
    sys.path[:0] = added
    try:
        try:
            mod = importlib.import_module(module_name)
        except ImportError as e:
            raise ImportError(
                f"python_param.module {module_name!r} not importable "
                f"({e}); put it on PYTHONPATH or "
                f"SPARKNET_PYTHON_LAYER_PATH") from e
    finally:
        for p in added:
            sys.path.remove(p)
    try:
        return getattr(mod, class_name)
    except AttributeError:
        raise AttributeError(
            f"module {module_name!r} has no class "
            f"{class_name!r} (python_param.layer)") from None


@register
class PythonLayer(Layer):
    type_name = "Python"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        pp = lp.python_param
        if not pp.module or not pp.layer:
            raise ValueError(
                f"{lp.name}: python_param needs module and layer")
        cls = _load_user_class(pp.module, pp.layer)
        obj = cls()
        # reference python_layer.hpp LayerSetUp: param_str is set on the
        # instance before setup() runs; phase/name are handy extras
        obj.param_str = pp.param_str
        obj.phase = phase
        obj.name = lp.name
        if hasattr(obj, "setup"):
            obj.setup(self.bottom_shapes)
        if not hasattr(obj, "reshape") or not hasattr(obj, "forward"):
            raise TypeError(
                f"{lp.name}: {pp.module}.{pp.layer} must define "
                "reshape(bottom_shapes) and forward(params, bottoms)")
        tops = obj.reshape(self.bottom_shapes)
        if isinstance(tops, tuple):                # one bare shape tuple
            tops = [tops]
        self._top_shapes = [tuple(s) for s in tops]
        want, got = len(lp.top), len(self._top_shapes)
        if want != got:
            raise ValueError(
                f"{lp.name}: reshape() returned {got} top shape(s) for "
                f"{want} declared top(s)")
        self._obj = obj
        fwd_params = inspect.signature(obj.forward).parameters
        self._fwd_takes_train = len(fwd_params) >= 3

    def param_shapes(self):
        if not hasattr(self._obj, "param_shapes"):
            return []
        from ..proto import Message
        out = []
        for shape, filler, lr, decay in self._obj.param_shapes():
            if isinstance(filler, dict):       # plain-dict convenience
                filler = Message("FillerParameter", **filler)
            out.append((tuple(shape), filler, lr, decay))
        return out

    def out_shapes(self):
        return self._top_shapes

    def apply(self, params, bottoms, train, rng):
        if self._fwd_takes_train:
            out = self._obj.forward(params, bottoms, train)
        else:
            out = self._obj.forward(params, bottoms)
        if not isinstance(out, (list, tuple)):
            out = [out]
        return list(out)
