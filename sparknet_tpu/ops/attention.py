"""Multi-head self-attention — the long-context extension layer.

Not in the CNN-era reference (SURVEY.md section 5: no attention anywhere);
this is the sparknet_tpu-native layer that the sequence-parallel machinery
(parallel.ring) plugs into. Bottom blob: (B, S, E). Fused QKV projection
keeps one large MXU matmul; when the net is traced inside a sequence-sharded
shard_map (parallel.context provides a "seq" axis) and attention_param.ring
is set, the core switches to ring attention over the mesh — the layer code
is identical on 1 chip and on a 64-way ring.
"""

import jax.numpy as jnp

from ..proto import Message
from ..graph.registry import Layer, register
from ..parallel import context
from ..parallel.ring import ring_attention, dense_attention
from .convolution import _param_mults


@register
class Attention(Layer):
    type_name = "Attention"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.attention_param
        self.p = p
        b, s, e = bottom_shapes[0]
        self.embed = int(e)
        self.num_heads = int(p.num_heads)
        self.head_dim = int(p.head_dim) if p.has("head_dim") \
            else self.embed // self.num_heads
        self.causal = bool(p.causal)
        self.ring = bool(p.ring)
        self.flash = bool(p.flash)
        self.inner = self.num_heads * self.head_dim

    def param_shapes(self):
        mults = _param_mults(self.lp, 4)
        # unlike stock Caffe layers (default constant-0), an attention with
        # zero projections is a degenerate identity-killer — default xavier
        wf = self.p.weight_filler if self.p.has("weight_filler") \
            else Message("FillerParameter", type="xavier")
        return [
            ((3 * self.inner, self.embed), wf, *mults[0]),   # fused qkv
            ((3 * self.inner,), None, *mults[1]),
            ((self.embed, self.inner), wf, *mults[2]),       # out proj
            ((self.embed,), None, *mults[3]),
        ]

    def out_shapes(self):
        return [tuple(self.bottom_shapes[0])]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]                                   # (B, S, E)
        wqkv, bqkv, wo, bo = [p.astype(x.dtype) for p in params]
        b, s, _ = x.shape
        qkv = x @ wqkv.T + bqkv                          # (B, S, 3*H*D)
        qkv = qkv.reshape(b, s, 3, self.num_heads, self.head_dim)
        q, k, v = [jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3)]
        seq_axis = context.axis("seq")
        if self.ring and seq_axis is not None:
            o = ring_attention(q, k, v, seq_axis, causal=self.causal)
        elif self.flash and s % 128 == 0:
            from .pallas_attention import flash_attention
            o = flash_attention(q, k, v, self.causal)
        else:
            o = dense_attention(q, k, v, causal=self.causal)
        o = jnp.moveaxis(o, 2, 1).reshape(b, s, self.inner)
        return [o @ wo.T + bo]
