"""Normalization layers: BatchNorm (stateful), MVN, and LayerNorm.

BatchNorm matches reference batch_norm_layer.cpp: three non-learnable blobs
[running_mean*s, running_var*s, s] where s is the accumulated scale factor;
use_global_stats defaults to (phase == TEST) (:14-16); TRAIN normalizes by
batch statistics (biased var) and updates the moving blobs with
moving_average_fraction and the m/(m-1) bias correction. Running stats are
framework *state*, threaded functionally through the compiled step rather
than mutated in place.

MVN (mvn_layer.cpp) normalizes each sample (per channel, or across channels)
to zero mean and, optionally, unit variance with divisor (std + eps).

LayerNorm is a sparknet_tpu extension (no CNN-era reference twin): last-axis
normalization with learned gamma/beta, the transformer-block complement of
the Attention layer. Statistics in fp32 regardless of activation dtype (the
bf16 mixed-precision path keeps reductions exact).
"""

import numpy as np
import jax.numpy as jnp

from ..graph.registry import Layer, register


@register
class BatchNorm(Layer):
    type_name = "BatchNorm"
    has_state = True

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.batch_norm_param
        self.eps = float(p.eps)
        self.maf = float(p.moving_average_fraction)
        if p.has("use_global_stats"):
            self.use_global = bool(p.use_global_stats)
        else:
            self.use_global = (phase == 1)  # TEST
        self.channels = bottom_shapes[0][1]

    def state_shapes(self):
        c = self.channels
        return [((c,), 0.0), ((c,), 0.0), ((1,), 0.0)]

    def out_shapes(self):
        return [self.bottom_shapes[0]]

    def apply_stateful(self, params, state, bottoms, train, rng):
        x = bottoms[0]
        mean_b, var_b, scale_b = state
        axes = (0,) + tuple(range(2, x.ndim))
        if self.use_global or not train:
            s = scale_b[0]
            factor = jnp.where(s == 0, 0.0, 1.0 / jnp.where(s == 0, 1.0, s))
            mean = mean_b * factor
            var = var_b * factor
            new_state = state
        else:
            mean = jnp.mean(x, axis=axes)
            var = jnp.mean((x - _bcast(mean, x)) ** 2, axis=axes)
            m = x.size // self.channels
            correction = m / (m - 1) if m > 1 else 1.0
            new_state = [
                self.maf * mean_b + mean,
                self.maf * var_b + correction * var,
                self.maf * scale_b + 1.0,
            ]
        inv = 1.0 / jnp.sqrt(var + self.eps)
        y = (x - _bcast(mean, x)) * _bcast(inv, x)
        return [y], new_state


def _bcast(v, x):
    shape = [1] * x.ndim
    shape[1] = v.shape[0]
    return v.reshape(shape)


@register
class LayerNorm(Layer):
    type_name = "LayerNorm"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.layer_norm_param
        self.eps = float(p.eps)
        self.affine = bool(int(p.affine))
        self.dim = int(bottom_shapes[0][-1])

    def param_shapes(self):
        if not self.affine:
            return []
        from ..proto import Message
        from .convolution import _param_mults
        mults = _param_mults(self.lp, 2)
        ones = Message("FillerParameter", type="constant", value=1.0)
        return [((self.dim,), ones, *mults[0]),          # gamma
                ((self.dim,), None, *mults[1])]          # beta (zeros)

    def out_shapes(self):
        return [self.bottom_shapes[0]]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
        y = (xf - mean) / jnp.sqrt(var + self.eps)
        if self.affine:
            y = y * params[0].astype(jnp.float32) \
                + params[1].astype(jnp.float32)
        return [y.astype(x.dtype)]


@register
class MVN(Layer):
    type_name = "MVN"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.mvn_param
        self.normalize_variance = bool(p.normalize_variance)
        self.across_channels = bool(p.across_channels)
        self.eps = float(p.eps)

    def out_shapes(self):
        return [self.bottom_shapes[0]]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        axes = tuple(range(1, x.ndim)) if self.across_channels \
            else tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        y = x - mean
        if self.normalize_variance:
            std = jnp.sqrt(jnp.mean(y * y, axis=axes, keepdims=True))
            y = y / (std + self.eps)
        return [y]
