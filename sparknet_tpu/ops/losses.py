"""Loss and metric layers.

Each reproduces the corresponding reference layer's scalar semantics exactly
(normalization divisors included) so that loss curves and iters-to-accuracy
are comparable:
  SoftmaxWithLoss  softmax_loss_layer.cpp:51-82 (FLT_MIN clamp, /count or /outer)
  EuclideanLoss    euclidean_loss_layer.cpp (sum sq diff / 2N)
  HingeLoss        hinge_loss_layer.cpp (L1 / squared-L2 margin sum / N)
  SigmoidCrossEntropyLoss  sigmoid_cross_entropy_loss_layer.cpp (/N, stable form)
  MultinomialLogisticLoss  multinomial_logistic_loss_layer.cpp (1e-20 clamp)
  InfogainLoss     infogain_loss_layer.cpp (H matrix from file or bottom[2])
  ContrastiveLoss  contrastive_loss_layer.cpp (legacy_version switch)
  Accuracy         accuracy_layer.cpp (top-k membership, ignore_label)
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..graph.registry import Layer, register

FLT_MIN = np.float32(1.1754944e-38)
LOG_THRESHOLD = 1e-20


def _outer_inner(shape, axis):
    outer = int(np.prod(shape[:axis], dtype=np.int64))
    inner = int(np.prod(shape[axis + 1:], dtype=np.int64))
    return outer, inner


class _Loss(Layer):
    loss_like = True

    def out_shapes(self):
        return [()]


@register
class SoftmaxWithLoss(_Loss):
    type_name = "SoftmaxWithLoss"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        self.axis = self.canonical_axis(lp.softmax_param.axis)
        loss_param = lp.loss_param
        self.normalize = bool(loss_param.normalize)
        self.ignore_label = loss_param.ignore_label \
            if loss_param.has("ignore_label") else None

    def apply(self, params, bottoms, train, rng):
        x, label = bottoms[0], bottoms[1]
        outer, inner = _outer_inner(x.shape, self.axis)
        c = x.shape[self.axis]
        # softmax over self.axis, gathered at the label
        xm = jnp.moveaxis(x, self.axis, -1).reshape(outer * inner, c)
        lab = label.reshape(outer, inner)
        # label memory order is (outer, inner); xm rows are (outer, inner)
        # after moveaxis+reshape? moveaxis gives (outer..., inner..., C) ->
        # rows enumerate outer-major, inner-minor: matches (i * inner + j).
        lab_flat = lab.reshape(-1).astype(jnp.int32)
        logp = jax.nn.log_softmax(xm.astype(jnp.float32), axis=-1)
        # Caffe clamps prob at FLT_MIN -> logp at log(FLT_MIN)
        picked = jnp.maximum(
            jnp.take_along_axis(logp, lab_flat[:, None], axis=-1)[:, 0],
            np.log(FLT_MIN))
        if self.ignore_label is not None:
            valid = (lab_flat != self.ignore_label)
            picked = jnp.where(valid, picked, 0.0)
            count = jnp.maximum(jnp.sum(valid), 1)
        else:
            count = outer * inner
        total = -jnp.sum(picked)
        denom = count if self.normalize else outer
        return [total / denom]


@register
class EuclideanLoss(_Loss):
    type_name = "EuclideanLoss"

    def apply(self, params, bottoms, train, rng):
        a, b = bottoms[0], bottoms[1]
        n = a.shape[0]
        d = (a - b).astype(jnp.float32)
        return [jnp.sum(d * d) / (2.0 * n)]


@register
class HingeLoss(_Loss):
    type_name = "HingeLoss"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        self.norm = int(lp.hinge_loss_param.norm)  # 1=L1, 2=L2

    def apply(self, params, bottoms, train, rng):
        x, label = bottoms[0], bottoms[1]
        n = x.shape[0]
        flat = x.reshape(n, -1).astype(jnp.float32)
        lab = label.reshape(n).astype(jnp.int32)
        sign = jnp.ones_like(flat).at[jnp.arange(n), lab].set(-1.0)
        margins = jnp.maximum(0.0, 1.0 + sign * flat)
        if self.norm == 2:
            return [jnp.sum(margins * margins) / n]
        return [jnp.sum(margins) / n]


@register
class SigmoidCrossEntropyLoss(_Loss):
    type_name = "SigmoidCrossEntropyLoss"

    def apply(self, params, bottoms, train, rng):
        x, t = bottoms[0].astype(jnp.float32), bottoms[1].astype(jnp.float32)
        n = x.shape[0]
        # stable: loss = -[x*(t - (x>=0)) - log(1 + exp(x - 2x*(x>=0)))]
        pos = (x >= 0)
        loss = x * (t - pos) - jnp.log1p(jnp.exp(x - 2 * x * pos))
        return [-jnp.sum(loss) / n]


@register
class MultinomialLogisticLoss(_Loss):
    type_name = "MultinomialLogisticLoss"

    def apply(self, params, bottoms, train, rng):
        prob, label = bottoms[0], bottoms[1]
        n = prob.shape[0]
        flat = prob.reshape(n, -1).astype(jnp.float32)
        lab = label.reshape(n).astype(jnp.int32)
        p = jnp.take_along_axis(flat, lab[:, None], axis=1)[:, 0]
        return [-jnp.sum(jnp.log(jnp.maximum(p, LOG_THRESHOLD))) / n]


@register
class InfogainLoss(_Loss):
    type_name = "InfogainLoss"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        self.H = None
        src = lp.infogain_loss_param.source \
            if lp.has("infogain_loss_param") else None
        if len(bottom_shapes) < 3:
            if not src:
                raise ValueError("InfogainLoss needs a source file or 3rd bottom")
            from ..proto import wire
            blob = wire.load(src, "BlobProto")
            dims = list(blob.shape.dim) if blob.has("shape") else \
                [blob.num, blob.channels, blob.height, blob.width]
            self.H = np.asarray(list(blob.data), np.float32).reshape(
                [d for d in dims if d] or [-1])
            self.H = self.H.reshape(self.H.shape[-2], self.H.shape[-1]) \
                if self.H.ndim > 2 else self.H

    def apply(self, params, bottoms, train, rng):
        prob, label = bottoms[0], bottoms[1]
        H = jnp.asarray(self.H) if self.H is not None else bottoms[2]
        H = H.reshape(H.shape[-2], H.shape[-1]) if H.ndim > 2 else H
        n = prob.shape[0]
        flat = prob.reshape(n, -1).astype(jnp.float32)
        lab = label.reshape(n).astype(jnp.int32)
        logp = jnp.log(jnp.maximum(flat, LOG_THRESHOLD))
        rows = jnp.take(H.astype(jnp.float32), lab, axis=0)
        return [-jnp.sum(rows * logp) / n]


@register
class ContrastiveLoss(_Loss):
    type_name = "ContrastiveLoss"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.contrastive_loss_param
        self.margin = float(p.margin)
        self.legacy = bool(p.legacy_version)

    def apply(self, params, bottoms, train, rng):
        a, b, y = bottoms[0], bottoms[1], bottoms[2]
        n = a.shape[0]
        d = (a - b).astype(jnp.float32).reshape(n, -1)
        dist_sq = jnp.sum(d * d, axis=1)
        y = y.reshape(n).astype(jnp.float32)
        if self.legacy:
            dissim = jnp.maximum(self.margin - dist_sq, 0.0)
        else:
            dissim = jnp.maximum(self.margin - jnp.sqrt(dist_sq), 0.0) ** 2
        loss = y * dist_sq + (1.0 - y) * dissim
        return [jnp.sum(loss) / (2.0 * n)]


@register
class Accuracy(Layer):
    """Top-k accuracy metric (not part of the objective: loss_weight 0)."""

    type_name = "Accuracy"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        ap = lp.accuracy_param
        self.top_k = int(ap.top_k)
        self.axis = self.canonical_axis(ap.axis)
        self.ignore_label = ap.ignore_label if ap.has("ignore_label") else None

    def out_shapes(self):
        return [()]

    def apply(self, params, bottoms, train, rng):
        x, label = bottoms[0], bottoms[1]
        outer, inner = _outer_inner(x.shape, self.axis)
        c = x.shape[self.axis]
        xm = jnp.moveaxis(x, self.axis, -1).reshape(outer * inner, c)
        lab = label.reshape(-1).astype(jnp.int32)
        _, topk = jax.lax.top_k(xm, self.top_k)
        hit = jnp.any(topk == lab[:, None], axis=1)
        if self.ignore_label is not None:
            valid = lab != self.ignore_label
            correct = jnp.sum(jnp.where(valid, hit, False))
            count = jnp.maximum(jnp.sum(valid), 1)
        else:
            correct = jnp.sum(hit)
            count = outer * inner
        return [correct.astype(jnp.float32) / count]
