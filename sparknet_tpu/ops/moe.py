"""Switch-style mixture-of-experts FFN with expert parallelism.

sparknet_tpu extension (no reference twin — SURVEY.md section 2c lists
EP/MoE as absent from the CNN-era reference); the expert-parallel half of
the framework's distributed story, alongside dp (pmean), tp (gspmd) and
sp (ring/Ulysses).

Routing is top-1 (Switch Transformer) with a capacity limit: each token
goes to its argmax expert; an expert accepts at most
C = ceil(tokens/num_experts * capacity_factor) tokens and overflow tokens
pass through as zeros (the surrounding residual connection carries them).
Tops: [output] or [output, aux] where aux is the Switch load-balancing
loss (num_experts * sum_e fraction_e * mean_gate_e) — give the second top
a loss_weight to train against expert collapse.

Expert parallelism: under a mesh axis named "expert" (published via
parallel.context, like "seq" for ring attention) and
moe_param.expert_parallel, the (num_experts, capacity, embed) dispatch
buffer is exchanged with ONE tiled all_to_all so each device runs only
its own num_experts/ep_size experts, then a second all_to_all returns
expert outputs to their source tokens. Dispatch/combine are sort-based
scatter/gather (O(n log n + n*C), not an O(n^2) one-hot mask) and run
identically on 1 device and on an N-way expert mesh, so the two paths
agree exactly (tested).

EP shards compute, not just weights, when tokens arrive SHARDED along
the expert axis (batch or sequence dim split over the same mesh axis,
the usual dp-x-ep composition): routing/capacity math runs on the LOCAL
token count, so each device builds an (X, C/ep, E) dispatch buffer and
after the all_to_all runs its X/ep experts over ep*(C/ep) = C capacity
slots — per-device expert FLOPs drop ep-fold with the axis
(tested: test_moe.py asserts the traced buffer shape shrinks ep-fold on
an 8-way mesh, and that the token-sharded forward equals the
single-device forward). Capacity is enforced per SOURCE device (each
peer may send at most C_local = ceil(n_local/X * capacity_factor)
tokens to any one expert), which equals the global rule whenever
routing doesn't overflow; under overflow the drop priority is
per-device arrival order rather than global order. Tokens may also be
passed REPLICATED across the axis — then the layer still shards expert
weight memory (each device runs X/ep experts over every peer's
identical slots) but per-device FLOPs don't shrink; that mode is only
for weight-memory relief.

Weight blobs (expert-major so a GSPMD param_rule or shard_map in_spec can
shard dim 0 across the expert axis):
  router (num_experts, E) | w1 (num_experts, F, E) | b1 (num_experts, F)
  | w2 (num_experts, E, F) | b2 (num_experts, E)
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..proto import Message
from ..graph.registry import Layer, register
from ..parallel import context
from .convolution import _param_mults


@register
class MoE(Layer):
    type_name = "MoE"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.moe_param
        self.p = p
        b, s, e = bottom_shapes[0]
        self.embed = int(e)
        self.num_experts = int(p.num_experts)
        if self.num_experts < 2:
            raise ValueError(f"{lp.name}: moe_param.num_experts must be >= 2")
        self.hidden = int(p.hidden_dim) or 4 * self.embed
        self.capacity_factor = float(p.capacity_factor)
        self.expert_parallel = bool(int(p.expert_parallel))

    def _capacity(self, n):
        return max(1, math.ceil(n / self.num_experts * self.capacity_factor))

    def param_shapes(self):
        mults = _param_mults(self.lp, 5)
        X, E, F = self.num_experts, self.embed, self.hidden

        def xavier(fan_in):
            # explicit uniform(+-sqrt(3/fan)) — the generic xavier filler
            # would read fan_in off the FULL 3-d blob shape (F*E), not the
            # per-expert matmul contraction, under-scaling by sqrt(F)
            lim = math.sqrt(3.0 / fan_in)
            return Message("FillerParameter", type="uniform",
                           min=-lim, max=lim)

        wf = self.p.weight_filler if self.p.has("weight_filler") else None
        return [((X, E), wf or xavier(E), *mults[0]),       # router
                ((X, F, E), wf or xavier(E), *mults[1]),    # w1
                ((X, F), None, *mults[2]),                  # b1
                ((X, E, F), wf or xavier(F), *mults[3]),    # w2
                ((X, E), None, *mults[4])]                  # b2

    def out_shapes(self):
        shapes = [tuple(self.bottom_shapes[0])]
        if len(self.lp.top) > 1:
            shapes.append(())                     # aux load-balancing loss
        if len(self.lp.top) > 2:
            # routing diagnostics (stop-gradient): per-expert token
            # fractions + the overflow (dropped-token) fraction
            shapes.append((self.num_experts + 1,))
        return shapes

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        router, w1, b1, w2, b2 = params
        b, s, e = x.shape
        n = b * s
        X = self.num_experts
        xt = x.reshape(n, e)

        logits = xt.astype(jnp.float32) @ router.T.astype(jnp.float32)
        gates = jax.nn.softmax(logits, axis=-1)            # (n, X)
        idx = jnp.argmax(gates, axis=-1)                   # (n,)
        gate = jnp.take_along_axis(gates, idx[:, None], 1)[:, 0]

        # sort-based dispatch, O(n log n + n*C*e) — a dense (n, X, C)
        # one-hot mask would be O(n^2) at long-context token counts.
        # Stable sort by expert; rank within expert = position - first
        # occurrence; earlier tokens win capacity slots (same priority rule
        # as the reference Switch implementation's cumsum).
        C = self._capacity(n)
        order = jnp.argsort(idx, stable=True)              # (n,)
        idx_sorted = idx[order]
        starts = jnp.searchsorted(idx_sorted, jnp.arange(X))
        rank = jnp.arange(n) - starts[idx_sorted]
        keep_s = rank < C
        # dropped/overflow tokens route to a trash row past the buffer
        dest = jnp.where(keep_s, idx_sorted * C + rank, X * C)
        buf = jnp.zeros((X * C + 1, e), jnp.float32) \
            .at[dest].set(xt[order].astype(jnp.float32))
        xe = buf[:-1].reshape(X, C, e)

        ep_axis = context.axis("expert") if self.expert_parallel else None
        if ep_axis is not None:
            # (X, C_local, e): split expert-major across the mesh, gather
            # every peer's tokens for OUR experts along the capacity axis.
            # With tokens sharded along the axis C_local = C/ep and this
            # is the compute-sharded buffer; with tokens replicated it is
            # (X/ep, ep*C, e) and only weight memory shrinks.
            xe = lax.all_to_all(xe, ep_axis, split_axis=0, concat_axis=1,
                                tiled=True)
        # trace-time introspection for tests/tools: the per-device expert
        # workload is exactly this shape's product
        self._last_dispatch_shape = tuple(xe.shape)

        w1l, b1l, w2l, b2l = (w.astype(jnp.float32)
                              for w in (w1, b1, w2, b2))
        h = jax.nn.relu(jnp.einsum("xce,xfe->xcf", xe, w1l)
                        + b1l[:, None, :])
        ye = jnp.einsum("xcf,xef->xce", h, w2l) + b2l[:, None, :]

        if ep_axis is not None:
            ye = lax.all_to_all(ye, ep_axis, split_axis=1, concat_axis=0,
                                tiled=True)                # (X, C, e)

        # combine: gather each token's expert output back (dropped tokens
        # hit the zero trash row), weight by its gate
        inv = jnp.argsort(order, stable=True)              # token -> sorted pos
        token_slot = dest[inv]                             # (n,)
        padded = jnp.concatenate(
            [ye.reshape(X * C, e), jnp.zeros((1, e), jnp.float32)])
        y = padded[token_slot] * gate[:, None]
        tops = [y.reshape(b, s, e).astype(x.dtype)]
        if len(self.lp.top) > 1:
            # Switch aux loss: X * sum_e (token fraction)*(mean gate)
            frac = jnp.mean(jax.nn.one_hot(idx, X, dtype=jnp.float32),
                            axis=0)
            tops.append(jnp.asarray(X, jnp.float32)
                        * jnp.sum(frac * jnp.mean(gates, axis=0)))
            if len(self.lp.top) > 2:
                # diagnostics top [frac_0..frac_{X-1}, overflow_fraction]
                # — LOCAL statistics (this shard's tokens); training
                # drivers pmean/log them per step
                overflow = 1.0 - jnp.mean(keep_s.astype(jnp.float32))
                tops.append(lax.stop_gradient(
                    jnp.concatenate([frac, overflow[None]])))
        return tops
