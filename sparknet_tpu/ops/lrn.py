"""Local Response Normalization, both norm regions.

Reference lrn_layer.cpp:
  ACROSS_CHANNELS (:108-151): scale = k + (alpha/n) * sum_{window n over C} x^2,
    zero-padded at the channel edges; out = x * scale^-beta.
  WITHIN_CHANNEL (:28-62, :155-162): out = x * (1 + alpha * s)^-beta where s is
    an AVE-pool of x^2 with kernel local_size, stride 1, pad (n-1)/2 — using
    Caffe AVE pooling's pad-inclusive divisor, which this reuses from ops.pooling.
"""

import os

from jax import lax
import jax.numpy as jnp

from ..graph.registry import Layer, register


def _lrn_mode():
    # read the env var here (NOT via pallas_lrn.lrn_mode) so the default
    # xla path never imports pallas/mosaic at all
    return os.environ.get("SPARKNET_LRN", "xla").lower()
from .pooling import ave_pool, caffe_pool_geometry
from ..proto.message import Message


@register
class LRN(Layer):
    type_name = "LRN"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.lrn_param
        self.size = int(p.local_size)
        self.alpha = float(p.alpha)
        self.beta = float(p.beta)
        self.k = float(p.k)
        self.within = int(p.norm_region) == 1
        if self.within:
            pp = Message("PoolingParameter", pool="AVE",
                         kernel_size=self.size, stride=1,
                         pad=(self.size - 1) // 2)
            n, c, h, w = bottom_shapes[0]
            self.pool_geom = caffe_pool_geometry(pp, h, w)

    def out_shapes(self):
        return [self.bottom_shapes[0]]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        if self.within:
            kernel, stride, pad, out = self.pool_geom
            s = ave_pool(x * x, kernel, stride, pad, out)
            scale = 1.0 + self.alpha * s
        elif x.ndim == 4 and _lrn_mode() == "pallas":
            from .pallas_lrn import lrn_across
            return [lrn_across(x, self.size, self.alpha, self.beta, self.k)]
        else:
            half = (self.size - 1) // 2
            sq = x * x
            ssum = lax.reduce_window(
                sq, 0.0, lax.add,
                window_dimensions=(1, self.size, 1, 1),
                window_strides=(1, 1, 1, 1),
                padding=((0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)),
            )
            scale = self.k + (self.alpha / self.size) * ssum
        return [x * scale ** (-self.beta)]
