"""Convolution-family layers lowered to XLA's conv HLO.

Replaces Caffe's im2col+GEMM path (reference base_conv_layer.cpp,
util/im2col.cpp) with ``lax.conv_general_dilated`` — XLA tiles the conv
directly onto the MXU, so there is no materialized im2col buffer and no
hand-written GEMM. Grouped convolution (AlexNet conv2/4/5) maps to
``feature_group_count``.

Shape/param semantics match reference conv_layer.cpp / base_conv_layer.cpp:
  out = (in + 2*pad - kernel) / stride + 1      (floor)
  weight blob (num_output, C/group, kh, kw), bias blob (num_output,)
Deconvolution is the conv transpose (reference deconv_layer.cpp):
  out = stride * (in - 1) + kernel - 2*pad
with weight blob (C_in, num_output/group, kh, kw).
"""

import os

import numpy as np
from jax import lax
import jax.numpy as jnp

from ..graph.registry import Layer, register


def _conv_s2d():
    """Space-to-depth policy for strided shallow-channel stem convs:
    auto — rewrite when it's the measured win (group==1, square stride>1,
           few input channels: the CaffeNet/GoogLeNet conv1 shape class),
    on   — rewrite every eligible conv, off — never.

    A 3-channel 11x11/4 conv1 contracts 3 channels against the MXU's
    128-lane axis (<3% occupancy, PERF.md). Rewriting
    conv(x, W, stride b) == conv(s2d_b(x), W', stride 1) trades b*b more
    input channels (3 -> 48 at b=4) for 1/b the spatial extent per axis:
    the same FLOPs land on 16x fuller lanes (plus a ceil(k/b) fringe of
    zero taps). Weights stay in the stock (O, C, kh, kw) blob — the
    rewrite is a trace-time reshape, so checkpoints are unaffected."""
    return os.environ.get("SPARKNET_CONV_S2D", "off").lower()


def _conv_layout():
    """Layout policy for Convolution.apply, read per trace:
    auto  — NHWC only for grouped convs (measured +13% on CaffeNet; the
            feature-group split tiles along the minor/lane axis),
    nhwc  — every conv runs NHWC (boundary transposes cancel between
            adjacent convs under XLA),
    nchw  — every conv runs NCHW (the reference's native layout)."""
    return os.environ.get("SPARKNET_CONV_LAYOUT", "auto").lower()


def _pair(rep_field, h_field, w_field, lp_param, default):
    """Resolve Caffe's (repeated | _h/_w) spatial-param convention."""
    rep = list(rep_field)
    if lp_param.has(h_field) or lp_param.has(w_field):
        return int(getattr(lp_param, h_field)), int(getattr(lp_param, w_field))
    if len(rep) == 0:
        return default, default
    if len(rep) == 1:
        return int(rep[0]), int(rep[0])
    return int(rep[0]), int(rep[1])


def resolve_conv_geometry(cp):
    kh, kw = _pair(cp.kernel_size, "kernel_h", "kernel_w", cp, None)
    if kh is None:
        raise ValueError("convolution requires kernel_size")
    sh, sw = _pair(cp.stride, "stride_h", "stride_w", cp, 1)
    ph, pw = _pair(cp.pad, "pad_h", "pad_w", cp, 0)
    return (kh, kw), (sh, sw), (ph, pw)


def _param_mults(lp, n_blobs):
    """Per-blob (lr_mult, decay_mult) from the layer's ParamSpecs
    (reference net.cpp AppendParam; missing specs default to 1/1)."""
    out = []
    for i in range(n_blobs):
        if i < len(lp.param):
            out.append((lp.param[i].lr_mult, lp.param[i].decay_mult))
        else:
            out.append((1.0, 1.0))
    return out


@register
class Convolution(Layer):
    type_name = "Convolution"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        cp = lp.convolution_param
        self.cp = cp
        (self.kh, self.kw), (self.sh, self.sw), (self.ph, self.pw) = \
            resolve_conv_geometry(cp)
        self.group = int(cp.group)
        self.num_output = int(cp.num_output)
        self.bias_term = bool(cp.bias_term)
        n, c, h, w = bottom_shapes[0]
        if c % self.group or self.num_output % self.group:
            raise ValueError("channels must divide group")
        self.weight_shape = (self.num_output, c // self.group, self.kh, self.kw)

    def param_shapes(self):
        mults = _param_mults(self.lp, 2 if self.bias_term else 1)
        out = [(self.weight_shape, self.cp.weight_filler, *mults[0])]
        if self.bias_term:
            out.append(((self.num_output,), self.cp.bias_filler, *mults[1]))
        return out

    def out_shapes(self):
        n, c, h, w = self.bottom_shapes[0]
        oh = (h + 2 * self.ph - self.kh) // self.sh + 1
        ow = (w + 2 * self.pw - self.kw) // self.sw + 1
        return [(n, self.num_output, oh, ow)]

    def _s2d_eligible(self):
        s2d = _conv_s2d()
        if s2d == "off" or self.group != 1 or self.sh != self.sw \
                or self.sh < 2:
            return False
        c = self.weight_shape[1]
        if s2d == "on":
            return True
        # auto: stem-conv shape class — shallow input channels where lane
        # occupancy is the bottleneck and b*b*C still fits one 128-lane tile
        return c <= 8 and c * self.sh * self.sw <= 128

    def _s2d_conv(self, x, w):
        """conv(x, w, stride b) as conv(s2d_b(x), w', stride 1), exact."""
        b = self.sh
        n, c, h, wd = x.shape
        o = self.num_output
        kh2, kw2 = -self.kh % b, -self.kw % b     # pad kernel to mult of b
        KH, KW = self.kh + kh2, self.kw + kw2
        oh, ow = self.out_shapes()[0][2:]
        th, tw = (oh - 1) * b + KH, (ow - 1) * b + KW  # padded extents
        x = jnp.pad(x, ((0, 0), (0, 0),
                        (self.ph, max(th - h - self.ph, 0)),
                        (self.pw, max(tw - wd - self.pw, 0))))
        x = x[:, :, :th, :tw]
        x = x.reshape(n, c, th // b, b, tw // b, b) \
             .transpose(0, 1, 3, 5, 2, 4).reshape(n, c * b * b,
                                                  th // b, tw // b)
        w = jnp.pad(w, ((0, 0), (0, 0), (0, kh2), (0, kw2)))
        w = w.reshape(o, c, KH // b, b, KW // b, b) \
             .transpose(0, 1, 3, 5, 2, 4).reshape(o, c * b * b,
                                                  KH // b, KW // b)
        return lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def apply_raw(self, params, bottoms, train, rng):
        """The convolution WITHOUT its bias add, NCHW out. The fused-
        epilogue path (graph/compiler.py + ops/pallas_epilogue.py) calls
        this and applies bias+ReLU(+LRN) in one pallas pass."""
        x = bottoms[0]
        w = params[0].astype(x.dtype)
        if self._s2d_eligible():
            return self._s2d_conv(x, w)
        layout = _conv_layout()
        nhwc = self.group > 1 if layout == "auto" else layout == "nhwc"
        if nhwc:
            x, w = x.transpose(0, 2, 3, 1), w.transpose(2, 3, 1, 0)
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(self.sh, self.sw),
            padding=[(self.ph, self.ph), (self.pw, self.pw)],
            dimension_numbers=("NHWC", "HWIO", "NHWC") if nhwc
            else ("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.group,
        )
        if nhwc:
            y = y.transpose(0, 3, 1, 2)
        return y

    def apply(self, params, bottoms, train, rng):
        y = self.apply_raw(params, bottoms, train, rng)
        if self.bias_term:
            y = y + params[1].astype(y.dtype)[None, :, None, None]
        return [y]

    def apply_fissioned(self, params, branches, train, rng):
        """conv over a virtual concat (graph/fission.py): one partial conv
        per branch with the matching input-channel slice of the SAME
        weight blob, summed; bias added once. group==1 only (the same
        layout policy as apply — under "auto" that means NCHW here)."""
        w = params[0]
        nhwc = _conv_layout() == "nhwc"
        y = None
        off = 0
        for x in branches.parts:
            c = x.shape[1]
            wi = w[:, off:off + c].astype(x.dtype)
            off += c
            if nhwc:
                x, wi = x.transpose(0, 2, 3, 1), wi.transpose(2, 3, 1, 0)
            yi = lax.conv_general_dilated(
                x, wi,
                window_strides=(self.sh, self.sw),
                padding=[(self.ph, self.ph), (self.pw, self.pw)],
                dimension_numbers=("NHWC", "HWIO", "NHWC") if nhwc
                else ("NCHW", "OIHW", "NCHW"))
            y = yi if y is None else y + yi
        if nhwc:
            y = y.transpose(0, 3, 1, 2)
        if self.bias_term:
            y = y + params[1].astype(y.dtype)[None, :, None, None]
        return y


@register
class Deconvolution(Layer):
    type_name = "Deconvolution"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        cp = lp.convolution_param
        self.cp = cp
        (self.kh, self.kw), (self.sh, self.sw), (self.ph, self.pw) = \
            resolve_conv_geometry(cp)
        self.group = int(cp.group)
        self.num_output = int(cp.num_output)
        self.bias_term = bool(cp.bias_term)
        n, c, h, w = bottom_shapes[0]
        self.in_channels = c
        self.weight_shape = (c, self.num_output // self.group, self.kh, self.kw)

    def param_shapes(self):
        mults = _param_mults(self.lp, 2 if self.bias_term else 1)
        out = [(self.weight_shape, self.cp.weight_filler, *mults[0])]
        if self.bias_term:
            out.append(((self.num_output,), self.cp.bias_filler, *mults[1]))
        return out

    def out_shapes(self):
        n, c, h, w = self.bottom_shapes[0]
        oh = self.sh * (h - 1) + self.kh - 2 * self.ph
        ow = self.sw * (w - 1) + self.kw - 2 * self.pw
        return [(n, self.num_output, oh, ow)]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        g, o_g = self.group, self.num_output // self.group
        c_g = self.in_channels // g
        w = params[0].astype(x.dtype)
        # (C_in, O/g, kh, kw) -> (O, C_in/g, kh, kw), spatially flipped:
        # forward deconv == gradient of the corresponding forward conv.
        w = w.reshape(g, c_g, o_g, self.kh, self.kw)
        w = w.transpose(0, 2, 1, 3, 4).reshape(self.num_output, c_g,
                                               self.kh, self.kw)
        w = w[:, :, ::-1, ::-1]
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(1, 1),
            padding=[(self.kh - 1 - self.ph,) * 2, (self.kw - 1 - self.pw,) * 2],
            lhs_dilation=(self.sh, self.sw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=g,
        )
        if self.bias_term:
            y = y + params[1].astype(x.dtype)[None, :, None, None]
        return [y]


@register
class Im2col(Layer):
    """Explicit im2col as a layer (reference im2col_layer.cpp) — rarely used,
    kept for parity; XLA does not need it for convs."""

    type_name = "Im2col"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        (self.kh, self.kw), (self.sh, self.sw), (self.ph, self.pw) = \
            resolve_conv_geometry(lp.convolution_param)

    def out_shapes(self):
        n, c, h, w = self.bottom_shapes[0]
        oh = (h + 2 * self.ph - self.kh) // self.sh + 1
        ow = (w + 2 * self.pw - self.kw) // self.sw + 1
        return [(n, c * self.kh * self.kw, oh, ow)]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        patches = lax.conv_general_dilated_patches(
            x, (self.kh, self.kw), (self.sh, self.sw),
            [(self.ph, self.ph), (self.pw, self.pw)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        return [patches]
