"""Fully-connected layers — the MXU-hot matmuls.

InnerProduct matches reference inner_product_layer.cpp: bottom flattened from
``axis`` onward, weight blob (num_output, K), y = x @ W^T + b. Embed matches
embed_layer.cpp: one-hot indices -> row gather, weight (input_dim, num_output).
"""

import numpy as np
import jax.numpy as jnp

from ..graph.registry import Layer, register
from .convolution import _param_mults


@register
class InnerProduct(Layer):
    type_name = "InnerProduct"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.inner_product_param
        self.p = p
        self.num_output = int(p.num_output)
        self.bias_term = bool(p.bias_term)
        self.axis = self.canonical_axis(p.axis)
        shape = bottom_shapes[0]
        self.outer = int(np.prod(shape[:self.axis], dtype=np.int64))
        self.K = int(np.prod(shape[self.axis:], dtype=np.int64))

    def param_shapes(self):
        mults = _param_mults(self.lp, 2 if self.bias_term else 1)
        out = [((self.num_output, self.K), self.p.weight_filler, *mults[0])]
        if self.bias_term:
            out.append(((self.num_output,), self.p.bias_filler, *mults[1]))
        return out

    def out_shapes(self):
        return [tuple(self.bottom_shapes[0][:self.axis]) + (self.num_output,)]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        w = params[0].astype(x.dtype)
        y = x.reshape(self.outer, self.K) @ w.T
        if self.bias_term:
            y = y + params[1].astype(x.dtype)
        return [y.reshape(self.out_shapes()[0])]


@register
class Embed(Layer):
    type_name = "Embed"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.embed_param
        self.p = p
        self.num_output = int(p.num_output)
        self.input_dim = int(p.input_dim)
        self.bias_term = bool(p.bias_term)

    def param_shapes(self):
        mults = _param_mults(self.lp, 2 if self.bias_term else 1)
        out = [((self.input_dim, self.num_output), self.p.weight_filler,
                *mults[0])]
        if self.bias_term:
            out.append(((self.num_output,), self.p.bias_filler, *mults[1]))
        return out

    def out_shapes(self):
        return [tuple(self.bottom_shapes[0]) + (self.num_output,)]

    def apply(self, params, bottoms, train, rng):
        idx = bottoms[0].astype(jnp.int32)
        y = jnp.take(params[0], idx, axis=0)
        if self.bias_term:
            y = y + params[1]
        cd = getattr(self, "compute_dtype", None)
        if cd is not None:
            # activations are born here from params alone: this cast is
            # what puts the whole downstream transformer in bf16 while
            # the embedding table itself stays an f32 master
            y = y.astype(cd)
        return [y]


@register
class PositionalEmbed(Layer):
    """sparknet_tpu extension: adds a learned (max_positions, E) table to a
    (B, S, E) activation — the positional half of a transformer's input
    embedding. Reuses embed_param: input_dim = max positions (must be >= S),
    num_output = E."""

    type_name = "PositionalEmbed"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.embed_param
        self.p = p
        b, s, e = bottom_shapes[0]
        self.max_positions = int(p.input_dim)
        if self.max_positions < s:
            raise ValueError(
                f"{lp.name}: embed_param.input_dim {self.max_positions} < "
                f"sequence length {s}")
        if int(p.num_output) != e:
            raise ValueError(
                f"{lp.name}: embed_param.num_output {p.num_output} != "
                f"embedding dim {e}")
        self.dim = int(e)

    def param_shapes(self):
        mults = _param_mults(self.lp, 1)
        return [((self.max_positions, self.dim), self.p.weight_filler,
                 *mults[0])]

    def out_shapes(self):
        return [tuple(self.bottom_shapes[0])]

    def apply(self, params, bottoms, train, rng):
        import jax.lax as lax
        from ..parallel import context
        x = bottoms[0]
        s = x.shape[1]
        seq_axis = context.axis("seq")
        if seq_axis is not None:
            # sequence-sharded (ring/Ulysses): this shard holds global
            # positions [idx*s, (idx+1)*s), not [0, s)
            start = lax.axis_index(seq_axis) * s
            rows = lax.dynamic_slice_in_dim(params[0], start, s, 0)
        else:
            rows = params[0][:s]
        return [x + rows.astype(x.dtype)[None]]
